#!/usr/bin/env python
"""Sysdump bundle schema check: a flight-recorder artifact must be
USABLE at 3am — it loads, carries every required key, and fits the
size cap it declares.

THIN SHIM: the implementation moved into the static-analysis package
(``cilium_tpu.analysis.sysdump_lint``, checker CTA007), which also
statically checks that ``SYSDUMP_REQUIRED_KEYS`` stays in sync with
the daemon's ``_sysdump_collect`` sections on every analysis pass.
This script keeps the original standalone CLI and the importable
``check_bundle`` surface (tests import it).

Usage::

    python scripts/check_sysdump_schema.py BUNDLE.json [...]
    python scripts/check_sysdump_schema.py SYSDUMP_DIR

Exit status 0 = every bundle clean; 1 = violations (one per line).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cilium_tpu.analysis.sysdump_lint import check_bundle  # noqa: E402,F401


def main(argv=None) -> int:
    args = (argv if argv is not None else sys.argv[1:])
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    paths = []
    for a in args:
        if os.path.isdir(a):
            paths.extend(
                os.path.join(a, n) for n in sorted(os.listdir(a))
                if n.startswith("sysdump-") and n.endswith(".json"))
        else:
            paths.append(a)
    if not paths:
        print("no sysdump bundles found", file=sys.stderr)
        return 1
    bad = []
    for p in paths:
        bad.extend(check_bundle(p))
    if bad:
        print("sysdump schema check FAILED:", file=sys.stderr)
        for b in bad:
            print("  " + b, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
