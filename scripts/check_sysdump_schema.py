#!/usr/bin/env python
"""Sysdump bundle schema check: a flight-recorder artifact must be
USABLE at 3am, which means three machine-checkable properties —

1. the bundle LOADS (valid JSON; a hard-truncated body fails here,
   which is the honest answer for a bundle the size bound had to
   amputate);
2. every REQUIRED top-level key is present (the key list is imported
   from ``cilium_tpu.obs.flightrec`` so this check and the writer
   cannot drift apart), and the schema version is one we know;
3. the file fits the size cap the bundle itself declares
   (``max-bytes``) — the flight recorder's own bound, re-verified
   from the outside.

Usage::

    python scripts/check_sysdump_schema.py BUNDLE.json [...]
    python scripts/check_sysdump_schema.py SYSDUMP_DIR

Exit status 0 = every bundle clean; 1 = violations (one per line).
Run standalone, or from the test suite (tests/test_flightrec.py
round-trips every bundle the incident e2e produces through
``check_bundle``).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cilium_tpu.obs.flightrec import (SYSDUMP_REQUIRED_KEYS,  # noqa: E402
                                      SYSDUMP_SCHEMA)


def check_bundle(path: str) -> list:
    """-> list of violation strings (empty = clean)."""
    bad = []
    try:
        size = os.path.getsize(path)
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    try:
        with open(path) as f:
            bundle = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: does not load as JSON ({e})"]
    if not isinstance(bundle, dict):
        return [f"{path}: top level is {type(bundle).__name__}, "
                f"not an object"]
    if bundle.get("schema") != SYSDUMP_SCHEMA:
        bad.append(f"{path}: schema {bundle.get('schema')!r} != "
                   f"{SYSDUMP_SCHEMA}")
    for key in SYSDUMP_REQUIRED_KEYS:
        if key not in bundle:
            bad.append(f"{path}: missing required key {key!r}")
    cap = bundle.get("max-bytes")
    if isinstance(cap, int) and size > cap:
        bad.append(f"{path}: {size} bytes exceeds its declared "
                   f"cap {cap}")
    return bad


def main(argv=None) -> int:
    args = (argv if argv is not None else sys.argv[1:])
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    paths = []
    for a in args:
        if os.path.isdir(a):
            paths.extend(
                os.path.join(a, n) for n in sorted(os.listdir(a))
                if n.startswith("sysdump-") and n.endswith(".json"))
        else:
            paths.append(a)
    if not paths:
        print("no sysdump bundles found", file=sys.stderr)
        return 1
    bad = []
    for p in paths:
        bad.extend(check_bundle(p))
    if bad:
        print("sysdump schema check FAILED:", file=sys.stderr)
        for b in bad:
            print("  " + b, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
