#!/usr/bin/env python
"""Encryption key-hygiene check: private keys and derived session
keys never reach a log call, an incident payload, a serializer, an
operator-visible bundle surface, or the exposition modules.

THIN SHIM: the implementation lives in the static-analysis package
(``cilium_tpu.analysis.crypto_lint``, checker CTA013) and runs on
every analysis pass / tier-1 run.  This script keeps a standalone
CLI (the check_cluster_ledger idiom).

Usage::

    python scripts/check_crypto_keys.py    # repo pass

Exit status 0 = clean; 1 = violations (one per line).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cilium_tpu.analysis.crypto_lint import check  # noqa: E402


def main(argv=None) -> int:
    from cilium_tpu.analysis import Repo, repo_root

    bad = [f.render() for f in check(Repo(repo_root()))]
    if bad:
        print("crypto key-hygiene check FAILED:", file=sys.stderr)
        for b in bad:
            print("  " + b, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
