#!/usr/bin/env python
"""SLO-plane contract check: every declared SLO references series
the registry exports AND the history ring samples, the cilium_slo_*
exposition floor stays registered, and the observability bench
artifact keeps its v2 schema.

THIN SHIM: the implementation lives in the static-analysis package
(``cilium_tpu.analysis.slo_lint``, checker CTA014) and runs on
every analysis pass / tier-1 run.  This script keeps a standalone
CLI (the check_cluster_ledger idiom) and the importable
``check_bench`` surface.

Usage::

    python scripts/check_slo.py                   # repo pass
    python scripts/check_slo.py BENCH_obs.json [...]

Exit status 0 = clean; 1 = violations (one per line).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cilium_tpu.analysis.slo_lint import (  # noqa: E402,F401
    BENCH_OBS_KEYS, check, check_bench)


def main(argv=None) -> int:
    args = (argv if argv is not None else sys.argv[1:])
    bad = []
    if args:
        for path in args:
            bad.extend(check_bench(path))
    else:
        from cilium_tpu.analysis import Repo, repo_root

        for f in check(Repo(repo_root())):
            bad.append(f.render())
    if bad:
        print("SLO contract check FAILED:", file=sys.stderr)
        for b in bad:
            print("  " + b, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
