#!/usr/bin/env python
"""The single CI lint entry point: every registered static checker
(guarded-by, thread-affinity, hot-path, sharding-spec, reason-codes,
metrics-registry, sysdump-schema) through one driver with shared
finding/suppression/baseline machinery.

Usage::

    python scripts/lint.py [--json] [--checker NAME ...] [BUNDLE...]

Exit status 0 = clean; 1 = findings; 2 = usage.  Equivalent to
``python -m cilium_tpu.analysis`` — see that package's docstring for
the annotation grammar and checker codes.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cilium_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
