#!/usr/bin/env python
"""Scenario-contract check: every registered adversarial scenario
declares docstring / name / criteria / seed, and the scenario bench
artifact keeps its schema.

THIN SHIM: the implementation lives in the static-analysis package
(``cilium_tpu.analysis.scenario_lint``, checker CTA010) and runs on
every analysis pass / tier-1 run.  This script keeps a standalone
CLI (the check_cluster_ledger idiom) and the importable
``check_bench`` surface.

Usage::

    python scripts/check_scenarios.py                    # repo pass
    python scripts/check_scenarios.py BENCH_scenarios.json [...]

Exit status 0 = clean; 1 = violations (one per line).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cilium_tpu.analysis.scenario_lint import (  # noqa: E402,F401
    BENCH_SCENARIO_KEYS, check, check_bench)


def main(argv=None) -> int:
    args = (argv if argv is not None else sys.argv[1:])
    bad = []
    if args:
        for path in args:
            bad.extend(check_bench(path))
    else:
        from cilium_tpu.analysis import Repo, repo_root

        for f in check(Repo(repo_root())):
            bad.append(f.render())
    if bad:
        print("scenario contract check FAILED:", file=sys.stderr)
        for b in bad:
            print("  " + b, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
