#!/usr/bin/env python
"""Lint: prometheus exposition text is built ONLY in the unified
registry (cilium_tpu/obs/registry.py).

Before the registry existed the /metrics body was hand-assembled in
four modules, each inventing its own `# TYPE` lines and label
formatting; this check fails the suite if that scatter regrows.  Two
things are flagged anywhere outside the registry module:

1. a ``# TYPE`` exposition header inside a string literal (the
   unmistakable signature of hand-built exposition text);
2. an f-string interpolating label values into a metric sample, i.e.
   a literal like ``some_metric_total{...="...``.

Registering a metric NAME with the registry (a plain string passed
to ``registry.counter(...)``) is fine — names must live at their
declaration sites; only the exposition *rendering* is centralized.

Additionally, REQUIRED_SERIES lists names that MUST be registered in
the registry module: the flow-analytics / flight-recorder series
(and a couple of long-standing anchors) are part of the operator
contract, and a refactor that silently drops their registration
would pass the scatter lint while still breaking every dashboard.
The check is textual on purpose — the declaration site is the
registry module, so the name literal must appear there.

Exit status 0 = clean; 1 = violations (printed one per line).
Run it standalone, or via tests/test_obs_registry.py (tier-1).
"""

from __future__ import annotations

import io
import os
import re
import sys
import tokenize

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "cilium_tpu")
# the one module allowed to build exposition text
REGISTRY_MODULE = os.path.join("cilium_tpu", "obs", "registry.py")
ALLOWED = {REGISTRY_MODULE}

# series that must be REGISTERED (their name literal present in the
# registry module) — the operator-contract floor
REQUIRED_SERIES = (
    # flow analytics plane + incident flight recorder
    "cilium_flow_agg_windows_total",
    "cilium_flow_agg_batches_dropped_total",
    "cilium_top_talkers_evictions_total",
    "cilium_incidents_total",
    "cilium_sysdump_writes_total",
    # long-standing anchors (a registry rewrite that loses these
    # fails here, not on a dashboard)
    "cilium_datapath_packets_total",
    "cilium_serving_verdicts_total",
    "cilium_ring_lost_total",
)

# exposition-text signatures inside a string literal
_TYPE_LINE = re.compile(r"#\s*TYPE\s+\w+\s+(counter|gauge|histogram)")
# metric sample with inline labels: name{key="  (catches both the
# f-string template text and fully literal lines)
_SAMPLE = re.compile(r"\b[a-z][a-z0-9_]*_(total|bucket|sum|count|"
                     r"seconds|bytes|info)\{[^}]*=")
_GENERIC_SAMPLE = re.compile(r"\b(cilium|hubble)_[a-z0-9_]+\{")


def scan_file(path: str) -> list:
    with open(path, "rb") as f:
        src = f.read()
    out = []
    try:
        toks = tokenize.tokenize(io.BytesIO(src).readline)
        for tok in toks:
            if tok.type not in (tokenize.STRING,
                                getattr(tokenize, "FSTRING_MIDDLE",
                                        -1)):
                continue
            s = tok.string
            for pat, what in ((_TYPE_LINE, "# TYPE exposition line"),
                              (_SAMPLE, "labelled metric sample"),
                              (_GENERIC_SAMPLE,
                               "labelled metric sample")):
                if pat.search(s):
                    out.append((tok.start[0], what, s.strip()[:70]))
                    break
    except tokenize.TokenError:
        pass
    return out


def check_required() -> list:
    """Every REQUIRED_SERIES name must appear in the registry
    module (i.e. still be registered)."""
    path = os.path.join(REPO, REGISTRY_MODULE)
    try:
        with open(path) as f:
            src = f.read()
    except OSError as e:
        return [f"{REGISTRY_MODULE}: unreadable ({e})"]
    return [f"{REGISTRY_MODULE}: required series {name!r} is not "
            f"registered"
            for name in REQUIRED_SERIES if f'"{name}"' not in src]


def main() -> int:
    bad = list(check_required())
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, REPO)
            if rel in ALLOWED:
                continue
            for line, what, snippet in scan_file(path):
                bad.append(f"{rel}:{line}: {what} outside the "
                           f"metrics registry: {snippet!r}")
    if bad:
        print("metrics-registry lint FAILED — exposition text must "
              "only be built in cilium_tpu/obs/registry.py (register "
              "a collector instead), and every REQUIRED_SERIES must "
              "stay registered:", file=sys.stderr)
        for b in bad:
            print("  " + b, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
