#!/usr/bin/env python
"""Lint: prometheus exposition text is built ONLY in the unified
registry (cilium_tpu/obs/registry.py).

THIN SHIM: the implementation moved into the static-analysis package
(``cilium_tpu.analysis.registry_lint``, checker CTA006) so it shares
the finding/suppression/baseline machinery with every other checker
— run ``python scripts/lint.py`` (or ``python -m
cilium_tpu.analysis``) for the full pass.  This script keeps the
original standalone CLI and the importable ``scan_file`` /
``check_required`` surface (tests import them).

Exit status 0 = clean; 1 = violations (printed one per line).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cilium_tpu.analysis.registry_lint import (  # noqa: E402,F401
    REGISTRY_MODULE,
    REQUIRED_SERIES,
    check,
    scan_file,
)
from cilium_tpu.analysis.core import Repo  # noqa: E402


def check_required() -> list:
    """Every REQUIRED_SERIES name must appear in the registry
    module (i.e. still be registered)."""
    path = os.path.join(REPO, REGISTRY_MODULE)
    try:
        with open(path) as f:
            src = f.read()
    except OSError as e:
        return [f"{REGISTRY_MODULE}: unreadable ({e})"]
    return [f"{REGISTRY_MODULE}: required series {name!r} is not "
            f"registered"
            for name in REQUIRED_SERIES if f'"{name}"' not in src]


def main() -> int:
    findings = check(Repo(REPO))
    if findings:
        print("metrics-registry lint FAILED — exposition text must "
              "only be built in cilium_tpu/obs/registry.py or "
              "cilium_tpu/obs/relay.py (register a collector "
              "instead), and every REQUIRED_SERIES must stay "
              "registered:", file=sys.stderr)
        for f in findings:
            print("  " + f.render(), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
