#!/usr/bin/env python
"""Lint: prometheus exposition text is built ONLY in the unified
registry (cilium_tpu/obs/registry.py).

Before the registry existed the /metrics body was hand-assembled in
four modules, each inventing its own `# TYPE` lines and label
formatting; this check fails the suite if that scatter regrows.  Two
things are flagged anywhere outside the registry module:

1. a ``# TYPE`` exposition header inside a string literal (the
   unmistakable signature of hand-built exposition text);
2. an f-string interpolating label values into a metric sample, i.e.
   a literal like ``some_metric_total{...="...``.

Registering a metric NAME with the registry (a plain string passed
to ``registry.counter(...)``) is fine — names must live at their
declaration sites; only the exposition *rendering* is centralized.

Exit status 0 = clean; 1 = violations (printed one per line).
Run it standalone, or via tests/test_obs_registry.py (tier-1).
"""

from __future__ import annotations

import io
import os
import re
import sys
import tokenize

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "cilium_tpu")
# the one module allowed to build exposition text
ALLOWED = {os.path.join("cilium_tpu", "obs", "registry.py")}

# exposition-text signatures inside a string literal
_TYPE_LINE = re.compile(r"#\s*TYPE\s+\w+\s+(counter|gauge|histogram)")
# metric sample with inline labels: name{key="  (catches both the
# f-string template text and fully literal lines)
_SAMPLE = re.compile(r"\b[a-z][a-z0-9_]*_(total|bucket|sum|count|"
                     r"seconds|bytes|info)\{[^}]*=")
_GENERIC_SAMPLE = re.compile(r"\b(cilium|hubble)_[a-z0-9_]+\{")


def scan_file(path: str) -> list:
    with open(path, "rb") as f:
        src = f.read()
    out = []
    try:
        toks = tokenize.tokenize(io.BytesIO(src).readline)
        for tok in toks:
            if tok.type not in (tokenize.STRING,
                                getattr(tokenize, "FSTRING_MIDDLE",
                                        -1)):
                continue
            s = tok.string
            for pat, what in ((_TYPE_LINE, "# TYPE exposition line"),
                              (_SAMPLE, "labelled metric sample"),
                              (_GENERIC_SAMPLE,
                               "labelled metric sample")):
                if pat.search(s):
                    out.append((tok.start[0], what, s.strip()[:70]))
                    break
    except tokenize.TokenError:
        pass
    return out


def main() -> int:
    bad = []
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, REPO)
            if rel in ALLOWED:
                continue
            for line, what, snippet in scan_file(path):
                bad.append(f"{rel}:{line}: {what} outside the "
                           f"metrics registry: {snippet!r}")
    if bad:
        print("metrics-registry lint FAILED — exposition text must "
              "only be built in cilium_tpu/obs/registry.py "
              "(register a collector instead):", file=sys.stderr)
        for b in bad:
            print("  " + b, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
