"""Operator: the cluster-singleton housekeeping process.

Reference: upstream cilium ``operator/`` — one replica per cluster
garbage-collects unreferenced identities, assigns cluster-pool
podCIDRs to nodes, and cleans up state of departed nodes.  The three
kvstore-riding responsibilities live on :class:`Operator`;
CiliumEndpointSlice batching (operator/pkg/ciliumendpointslice) lives
in :mod:`.ces`.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

from ..health import NODES_PREFIX
from ..ipam import ClusterPool
from ..kvstore.allocator import DEFAULT_PREFIX, KVStoreAllocatorBackend
from .ces import CES_MAX_ENDPOINTS, CESBatcher  # noqa: F401 (re-export)


class Operator:
    def __init__(self, kv, cluster_cidr: str = "10.0.0.0/8",
                 node_mask: int = 24):
        self.kv = kv
        self._alloc_gc = KVStoreAllocatorBackend(kv, node="operator")
        self.pool = ClusterPool(kv, cluster_cidr, node_mask)
        self.identities_collected = 0
        self.cidrs_collected = 0
        self.sweeps = 0

    def close(self) -> None:
        self._alloc_gc.close()

    def sweep(self) -> dict:
        """One housekeeping pass (drive from a controller):
        1. identity GC — master keys with no live node refs;
        2. podCIDR assignment for registered nodes without one;
        3. podCIDR reclamation for nodes whose lease expired."""
        collected = self._alloc_gc.gc()
        self.identities_collected += collected

        live = {n["name"] for n in self._nodes()}
        assigned = self.pool.assignments()
        cidrs_assigned = 0
        for name in live:
            if name not in assigned:
                self.pool.allocate_node_cidr(name)
                cidrs_assigned += 1
        cidrs_reclaimed = 0
        for name in list(assigned):
            if name not in live:
                self.pool.release_node_cidr(name)
                cidrs_reclaimed += 1
        self.cidrs_collected += cidrs_reclaimed
        self.sweeps += 1
        return {
            "identities-collected": collected,
            "podcidrs-assigned": cidrs_assigned,
            "podcidrs-reclaimed": cidrs_reclaimed,
        }

    def _nodes(self):
        return [json.loads(v) for v in
                self.kv.list_prefix(NODES_PREFIX + "/").values()]

    def status(self) -> dict:
        return {
            "sweeps": self.sweeps,
            "identities-collected": self.identities_collected,
            "podcidrs": self.pool.assignments(),
        }
