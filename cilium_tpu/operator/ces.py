"""CiliumEndpointSlice batching — the operator's CEP write-amortizer.

Reference: upstream cilium ``operator/pkg/ciliumendpointslice`` — the
operator (cluster singleton) watches CiliumEndpoint objects and
coalesces them into CiliumEndpointSlice objects of up to 100
endpoints each (first-come-first-served slice assignment, one
namespace per slice), so a churn of N pods costs ~N/100 apiserver
writes and every agent watches one slice stream instead of N CEP
streams.

The TPU build keeps the same economics: :class:`CESBatcher` consumes
CiliumEndpoint add/update/delete events, assigns each endpoint to a
non-full slice of its namespace (holes left by deletions are refilled
FCFS), and publishes dirty slices through a debounced
:class:`~cilium_tpu.infra.trigger.Trigger` — a burst of M endpoint
events that lands inside one sync window becomes at most
``len(touched slices)`` publishes.  ``cep_events`` / ``slice_writes``
make the amortization observable (and testable).

Agent side, :class:`~cilium_tpu.k8s.watchers.CiliumEndpointSliceWatcher`
unpacks slices back into per-endpoint ipcache upserts through the
same :class:`~cilium_tpu.k8s.watchers.CiliumEndpointWatcher` the
direct CEP path uses, so both propagation modes converge on identical
daemon state.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Set

# one key format operator- and agent-side: the slice watcher diffs
# members by the same ns/name key the batcher groups by
from ..k8s.watchers import _meta_key as _cep_key

# upstream default: maxCEPsInCES = 100
CES_MAX_ENDPOINTS = 100


def core_endpoint(cep: dict) -> dict:
    """CiliumEndpoint -> CoreCiliumEndpoint (the per-endpoint record
    embedded in a slice; reference: cilium.io/v2alpha1
    CoreCiliumEndpoint{name, id, networking})."""
    meta = cep.get("metadata") or {}
    status = cep.get("status") or {}
    return {
        "name": meta.get("name", ""),
        "id": int((status.get("identity") or {}).get("id", 0)),
        "networking": status.get("networking") or {},
    }


def expand_slice(ces: dict) -> List[dict]:
    """CiliumEndpointSlice -> synthetic CiliumEndpoint objects (what
    the agent-side watcher feeds the CEP handler)."""
    ns = ces.get("namespace", "")
    out = []
    for core in ces.get("endpoints") or ():
        out.append({
            "apiVersion": "cilium.io/v2",
            "kind": "CiliumEndpoint",
            "metadata": {"name": core.get("name", ""), "namespace": ns},
            "status": {
                "identity": {"id": int(core.get("id", 0))},
                "networking": core.get("networking") or {},
            },
        })
    return out


class _Slice:
    __slots__ = ("name", "ns", "keys", "published")

    def __init__(self, name: str, ns: str):
        self.name = name
        self.ns = ns
        self.keys: Set[str] = set()
        self.published = False  # first publish is an add, then updates


class CESBatcher:
    """FCFS CiliumEndpoint -> CiliumEndpointSlice grouping with
    debounced publishing.

    ``publish(event, obj)`` receives ``add``/``update``/``delete``
    with a CiliumEndpointSlice object — point it at a
    :class:`~cilium_tpu.testing.stub_apiserver.StubAPIServer` (via
    :meth:`publish_to`) or any store.  ``sync_interval`` is the
    debounce window a burst accumulates inside before the background
    sync thread publishes (upstream: the CES workqueue's rate
    limiter); 0 publishes synchronously on the event thread.
    """

    def __init__(self, publish: Callable[[str, dict], None],
                 max_per_slice: int = CES_MAX_ENDPOINTS,
                 sync_interval: float = 0.0):
        self._publish = publish
        self._max = int(max_per_slice)
        if self._max <= 0:
            raise ValueError("max_per_slice must be positive")
        self._lock = threading.Lock()
        self._sync_lock = threading.Lock()
        self._core: Dict[str, dict] = {}        # cep key -> core record
        self._slice_of: Dict[str, str] = {}     # cep key -> slice name
        self._slices: Dict[str, _Slice] = {}
        self._open: Dict[str, Set[str]] = {}    # ns -> non-full slices
        self._dirty: Set[str] = set()
        self._seq = 0
        self.cep_events = 0
        self.slice_writes = 0
        self._interval = float(sync_interval)
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._thread = None
        if self._interval > 0:
            self._thread = threading.Thread(target=self._loop,
                                            name="ces-sync", daemon=True)
            self._thread.start()

    @classmethod
    def publish_to(cls, store, **kw) -> "CESBatcher":
        """Batcher wired to an apiserver-shaped store with
        add/update/delete(obj) methods."""
        def pub(event: str, obj: dict) -> None:
            getattr(store, event)(obj)
        return cls(pub, **kw)

    # -- CiliumEndpoint event intake (watcher-hub shaped) --------------
    def dispatch(self, event: str, obj: dict) -> None:
        getattr(self, f"on_{event}")(obj)

    def on_add(self, obj: dict) -> None:
        key = _cep_key(obj)
        core = core_endpoint(obj)
        with self._lock:
            self.cep_events += 1
            prev = self._core.get(key)
            if prev == core:
                return  # no-op resync: don't dirty the slice
            self._core[key] = core
            name = self._slice_of.get(key)
            if name is None:
                name = self._assign_locked(key, obj)
            self._dirty.add(name)
        self._notify()

    on_update = on_add

    def on_delete(self, obj: dict) -> None:
        key = _cep_key(obj)
        with self._lock:
            self.cep_events += 1
            self._core.pop(key, None)
            name = self._slice_of.pop(key, None)
            if name is None:
                return
            sl = self._slices[name]
            sl.keys.discard(key)
            self._open.setdefault(sl.ns, set()).add(name)
            self._dirty.add(name)
        self._notify()

    def flush(self) -> None:
        """Publish everything pending now (callers that can't wait out
        the debounce window, and tests)."""
        self._sync()

    def close(self) -> None:
        self._stopped.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self._sync()

    # -- internals -----------------------------------------------------
    def _notify(self) -> None:
        if self._thread is None:
            self._sync()
        else:
            self._wake.set()

    def _loop(self) -> None:
        while not self._stopped.is_set():
            self._wake.wait()
            # debounce: let the rest of the burst land before writing
            # (stopped.wait doubles as an interruptible sleep so
            # close() never waits out a long window)
            if self._stopped.wait(self._interval):
                return
            self._wake.clear()
            self._sync()
    def _assign_locked(self, key: str, obj: dict) -> str:
        """FCFS: any non-full slice of the endpoint's namespace, else
        a new one (upstream cesManagerFcfs.getLargestAvailableCES).
        The per-namespace open-slice index keeps this O(1) — a 10k-pod
        churn must not scan the whole slice table per endpoint."""
        ns = (obj.get("metadata") or {}).get("namespace", "default")
        open_ns = self._open.setdefault(ns, set())
        while open_ns:
            name = next(iter(open_ns))
            sl = self._slices[name]
            if len(sl.keys) >= self._max:  # stale index entry
                open_ns.discard(name)
                continue
            sl.keys.add(key)
            if len(sl.keys) >= self._max:
                open_ns.discard(name)
            self._slice_of[key] = name
            return name
        self._seq += 1
        sl = _Slice(f"ces-{self._seq}", ns)
        sl.keys.add(key)
        self._slices[sl.name] = sl
        if len(sl.keys) < self._max:
            open_ns.add(sl.name)
        self._slice_of[key] = sl.name
        return sl.name

    def _sync(self) -> None:
        # serialize whole syncs: publishes happen outside _lock, and a
        # flush racing the background loop must not reorder a slice's
        # add ahead of its update
        with self._sync_lock:
            self._sync_locked()

    def _sync_locked(self) -> None:
        with self._lock:
            dirty, self._dirty = self._dirty, set()
            work = []
            for name in sorted(dirty):
                sl = self._slices.get(name)
                if sl is None:
                    continue
                if not sl.keys:
                    del self._slices[name]
                    self._open.get(sl.ns, set()).discard(name)
                    if sl.published:
                        work.append(("delete", self._obj(sl)))
                    continue
                event = "update" if sl.published else "add"
                sl.published = True
                work.append((event, self._obj(sl)))
        for event, obj in work:
            self._publish(event, obj)
            self.slice_writes += 1

    def _obj(self, sl: _Slice) -> dict:
        return {
            "apiVersion": "cilium.io/v2alpha1",
            "kind": "CiliumEndpointSlice",
            "metadata": {"name": sl.name},
            "namespace": sl.ns,
            "endpoints": [self._core[k] for k in sorted(sl.keys)],
        }

    # -- introspection -------------------------------------------------
    def slice_count(self) -> int:
        with self._lock:
            return len(self._slices)

    def slice_sizes(self) -> Dict[str, int]:
        with self._lock:
            return {n: len(s.keys) for n, s in self._slices.items()}
