"""Label model: typed key[=value] pairs with a source prefix.

Reference: upstream cilium ``pkg/labels`` (Label, Labels, NewLabel,
ParseLabel).  Labels are the unit of identity: a workload's security
identity is the numeric ID allocated for its *sorted label set*.

A label renders as ``source:key=value`` (value optional).  Sources seen
in the reference: ``k8s``, ``reserved``, ``cidr``, ``unspec``, ``any``,
``container``.  ``any`` matches every source when used in a selector.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

SOURCE_ANY = "any"
SOURCE_K8S = "k8s"
SOURCE_RESERVED = "reserved"
SOURCE_CIDR = "cidr"
SOURCE_UNSPEC = "unspec"


@dataclass(frozen=True, order=True)
class Label:
    source: str
    key: str
    value: str = ""

    @staticmethod
    def parse(s: str) -> "Label":
        """Parse ``[source:]key[=value]`` (reference: pkg/labels ParseLabel)."""
        source = SOURCE_UNSPEC
        rest = s
        if ":" in s:
            maybe_source, after = s.split(":", 1)
            # a ':' before any '=' is a source separator
            eq = s.find("=")
            if eq == -1 or s.find(":") < eq:
                source, rest = maybe_source, after
        if "=" in rest:
            key, value = rest.split("=", 1)
        else:
            key, value = rest, ""
        return Label(source=source or SOURCE_UNSPEC, key=key, value=value)

    def matches(self, other: "Label") -> bool:
        """Does *self* (a selector label) match *other* (an endpoint label)?

        ``any`` source on the selector side matches any source.
        """
        if self.source != SOURCE_ANY and self.source != other.source:
            return False
        return self.key == other.key and self.value == other.value

    def __str__(self) -> str:
        if self.value:
            return f"{self.source}:{self.key}={self.value}"
        return f"{self.source}:{self.key}"


@dataclass(frozen=True)
class LabelSet:
    """An immutable, canonically-sorted set of labels.

    Reference: pkg/labels ``Labels`` (map) + ``SortedList`` — the sorted
    rendering is the allocator key, so two workloads with the same labels
    in any order share one identity.
    """

    labels: tuple = field(default_factory=tuple)

    def __init__(self, labels: Iterable[Label] = ()):
        object.__setattr__(self, "labels", tuple(sorted(set(labels))))

    @staticmethod
    def parse(*strs: str) -> "LabelSet":
        return LabelSet(Label.parse(s) for s in strs)

    def sorted_key(self) -> str:
        """Canonical string key (the reference's Labels.SortedList)."""
        return ";".join(str(l) for l in self.labels) + ";"

    def has(self, sel: Label) -> bool:
        return any(sel.matches(l) for l in self.labels)

    def get(self, source: str, key: str) -> Optional[Label]:
        for l in self.labels:
            if l.key == key and (source == SOURCE_ANY or l.source == source):
                return l
        return None

    def union(self, other: "LabelSet") -> "LabelSet":
        return LabelSet(self.labels + other.labels)

    def __iter__(self) -> Iterator[Label]:
        return iter(self.labels)

    def __len__(self) -> int:
        return len(self.labels)

    def __contains__(self, l: Label) -> bool:
        return l in self.labels

    def __str__(self) -> str:
        return self.sorted_key()
