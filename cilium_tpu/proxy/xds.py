"""xDS-style policy push: the NPDS surface for an EXTERNAL proxy.

Reference: upstream cilium embeds Envoy and pushes per-endpoint
``cilium.NetworkPolicy`` resources over xDS (``pkg/envoy/xds/server.go``
— state-of-the-world NetworkPolicyDiscoveryService with ACK/NACK
version tracking).  This framework enforces L7 natively (SURVEY.md "no
embedded proxy"), but a deployment fronted by a real Envoy still needs
a push surface — THIS module is it: the same SotW protocol state
machine (versioned snapshot, subscribe, ACK by version echo, NACK by
error detail) over JSON-shaped resources that mirror the
cilium.NetworkPolicy schema.  Transport: the discover() long-poll is
transport-agnostic; serve_xds() wraps it in the same JSON-over-gRPC
streaming used by the observer API.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

TYPE_URL = "type.googleapis.com/cilium.NetworkPolicy"


def _l7rules_to_dict(l7) -> dict:
    """L7Rules -> schema-shaped dict (the rules an external proxy must
    enforce on the redirected port)."""
    out: dict = {}
    if l7.http:
        out["http"] = [{
            "method": h.method, "path": h.path, "host": h.host,
            "headers": list(h.headers),
        } for h in l7.http]
    if l7.dns:
        out["dns"] = [{
            "matchName": d.match_name, "matchPattern": d.match_pattern,
        } for d in l7.dns]
    if l7.kafka:
        out["kafka"] = [dict(k) for k in l7.kafka]
    for name, rules in getattr(l7, "extra", ()):
        out[name] = [dict(r) for r in rules]
    return out


def policy_resource(pol) -> dict:
    """One resolved EndpointPolicy -> a cilium.NetworkPolicy-shaped
    resource (per-direction policymap entries + per-port L7 rules)."""
    def _entries(ms) -> list:
        return [{
            "identity": k.identity,
            "proto": k.proto,
            "dport_lo": k.dport_lo,
            "dport_hi": k.dport_hi,
            "verdict": e.verdict,
            "proxy_port": e.proxy_port,
            "derived_from": list(e.derived_from),
        } for k, e in sorted(
            ms.to_entries().items(),
            key=lambda kv: (kv[0].identity, kv[0].proto,
                            kv[0].dport_lo, kv[0].dport_hi))]

    return {
        "name": str(pol.subject_labels),
        "policy_revision": pol.revision,
        "ingress_enforcing": pol.ingress.enforcing,
        "egress_enforcing": pol.egress.enforcing,
        "ingress": _entries(pol.ingress),
        "egress": _entries(pol.egress),
        "l7": [{"proxy_port": port, "rule_label": label,
                "rules": _l7rules_to_dict(l7)}
               for port, label, l7 in pol.redirects],
    }


class XDSCache:
    """State-of-the-world resource cache + subscription protocol.

    ``discover(request)`` implements one round of the SotW protocol:
    a request whose ``version_info`` equals the current version is an
    ACK (block until the snapshot changes); a request carrying
    ``error_detail`` is a NACK of that version (recorded, then block
    the same way — the reference keeps serving the last ACKed version
    and retries on the next change).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._change = threading.Condition(self._lock)
        self._version = 0
        self._resources: Dict[str, dict] = {}
        self.nacks: List[Tuple[int, str]] = []  # (version, detail)

    # -- producer side ------------------------------------------------
    def set_resources(self, resources: Dict[str, dict]) -> int:
        """Replace the snapshot; bumps the version only on change."""
        with self._change:
            if resources != self._resources:
                self._resources = dict(resources)
                self._version += 1
                self._change.notify_all()
            return self._version

    def update_from_policies(self, policies: Sequence) -> int:
        """EndpointManager attach hook: resolved policies -> snapshot
        (wired exactly like L7Proxy.update)."""
        return self.set_resources(
            {str(p.subject_labels): policy_resource(p)
             for p in policies})

    # -- consumer side ------------------------------------------------
    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def snapshot(self) -> dict:
        """Non-blocking status view (version + resource names) for the
        REST /xds endpoint — discover() long-polls when the caller is
        up to date, which must never stall a status probe."""
        with self._lock:
            return {"version": self._version,
                    "resources": sorted(self._resources),
                    "nacks": list(self.nacks[-8:])}

    def discover(self, request: Optional[dict] = None,
                 timeout: Optional[float] = None) -> Optional[dict]:
        """One DiscoveryRequest -> DiscoveryResponse (or None on
        timeout while up to date)."""
        request = request or {}
        acked = int(request.get("version_info") or 0)
        if request.get("error_detail"):
            with self._lock:
                self.nacks.append(
                    (int(request.get("response_nonce") or 0),
                     str(request["error_detail"])))
        names = request.get("resource_names") or ()
        with self._change:
            if self._version == acked:
                if not self._change.wait_for(
                        lambda: self._version != acked, timeout):
                    return None
            resources = [r for n, r in sorted(self._resources.items())
                         if not names or n in names]
            return {
                "version_info": str(self._version),
                "type_url": request.get("type_url", TYPE_URL),
                "nonce": str(self._version),
                "resources": resources,
            }


def serve_xds(cache: XDSCache, address: str):
    """Expose the cache as a JSON-over-gRPC stream (the observer API's
    wire style): /cilium.NetworkPolicyDiscoveryService/
    StreamNetworkPolicies is a bidirectional stream of
    DiscoveryRequest -> DiscoveryResponse."""
    import json
    from concurrent import futures

    import grpc

    def _loads(b: bytes):
        return json.loads(b.decode())

    def _dumps(o) -> bytes:
        return json.dumps(o).encode()

    SERVICE = "cilium.NetworkPolicyDiscoveryService"

    def stream(request_iterator, context):
        for req in request_iterator:
            # SotW: the client sends nothing further until it gets a
            # response, so a quiet long-poll must RE-ARM with the same
            # request — returning to request_iterator after a timeout
            # would leave an idle subscriber watching nothing and
            # enforcing stale policy forever
            while context.is_active():
                resp = cache.discover(req, timeout=5.0)
                if resp is not None:
                    yield resp
                    break

    handler = grpc.method_handlers_generic_handler(SERVICE, {
        "StreamNetworkPolicies": grpc.stream_stream_rpc_method_handler(
            stream, request_deserializer=_loads,
            response_serializer=_dumps),
    })
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((handler,))
    server.add_insecure_port(address)
    server.start()
    return server
