"""L7 request enforcement: the Envoy/proxylib plane, TPU-first.

Reference: upstream cilium redirects L7-policied connections to an
Envoy listener (``pkg/proxy`` allocates the ports, ``pkg/envoy`` pushes
``NetworkPolicy`` over xDS, ``proxylib/`` parses requests and returns
per-request verdicts, the DNS proxy lives in ``pkg/fqdn``).  TPU-first
redesign (SURVEY.md §2a rows 5-6): parsers become *featurizers* that
emit fixed-width L7 feature rows; ``L7Rules`` compile into per-rule
match tensors; request verdicts are one batched masked-compare on
device, with a host fallback only for regex/glob rules that cannot
compile to exact hashes.
"""

from .l7policy import (
    L7PolicyTensors,
    METHOD_IDS,
    compile_l7,
    l7_verdict,
)
from .featurize import featurize_dns, featurize_http, fnv64
from .proxy import L7Proxy, L7Record
from .registry import L7Protocol, register
from . import plugins  # noqa: F401 — registers cassandra/memcached

__all__ = [
    "L7Protocol",
    "register",
    "L7PolicyTensors",
    "METHOD_IDS",
    "compile_l7",
    "l7_verdict",
    "featurize_http",
    "featurize_dns",
    "fnv64",
    "L7Proxy",
    "L7Record",
]
