"""L7 protocol plugin registry — the proxylib plugin seam.

Reference: upstream ``proxylib/`` loads protocol parsers (cassandra,
memcached, r2d2, ...) as Go plugins behind one interface
(``proxylib/proxylib/parserfactory.go``); a new protocol registers a
factory and the policy schema key follows.  TPU-first equivalent: a
protocol plugin maps its requests onto the SHARED feature-row layout
(featurize.py L7_* columns — method id in one word, two 64-bit string
hashes) and its rules onto rows of the SAME match tensor, so every
protocol's verdict rides the one fused tensor compare in
``l7policy.l7_verdict`` with zero per-protocol device code.

A fourth protocol therefore needs ONLY a registration call — no edits
to featurize.py, l7policy.py, or proxy.py (see plugins.py for the
cassandra/memcached proofs).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .featurize import (
    L7_COLS,
    L7_HOST_H0,
    L7_HOST_H1,
    L7_KIND,
    L7_METHOD,
    L7_PATH_H0,
    L7_PATH_H1,
    L7_PORT,
    L7_SRC_ROW,
    fnv64,
)

# kinds 0..2 are the built-in HTTP/DNS/Kafka featurizers
_FIRST_PLUGIN_KIND = 16


@dataclass(frozen=True)
class L7Protocol:
    """One pluggable protocol.

    ``featurize(requests, port, src_row) -> (rows, raw)`` maps request
    dicts onto the shared feature columns; ``compile_rule(rule) ->
    ("row", [method, f0_lo, f0_hi, f1_lo, f1_hi]) | ("matcher", fn)``
    maps one policy rule onto a match-tensor row (exact fields) or a
    host-side matcher (regex/prefix fields); ``record_fields(raw) ->
    (method_str, path_str)`` feeds the access log."""

    name: str  # the L7Rules schema key, e.g. "cassandra"
    kind: int
    featurize: Callable[[Sequence[dict], int, int],
                        Tuple[np.ndarray, List]]
    compile_rule: Callable[[dict], Tuple[str, object]]
    record_fields: Callable[[dict], Tuple[str, str]] = \
        lambda r: (str(r.get("method", "")), str(r.get("path", "")))
    # optional wire-facing half: raw payload bytes -> request dicts
    # (proxylib OnData analogue; parsers without it accept structured
    # requests only)
    parse_bytes: Optional[Callable[[Sequence[bytes]], List[dict]]] = None


_registry: Dict[str, L7Protocol] = {}


def register(proto: L7Protocol) -> L7Protocol:
    """Add a protocol to the registry (idempotent by name+kind;
    conflicting re-registration raises)."""
    prev = _registry.get(proto.name)
    if prev is not None and prev.kind != proto.kind:
        raise ValueError(
            f"L7 protocol {proto.name!r} already registered as kind "
            f"{prev.kind}")
    for other in _registry.values():
        if other.kind == proto.kind and other.name != proto.name:
            raise ValueError(
                f"kind {proto.kind} already taken by {other.name!r}")
    _registry[proto.name] = proto
    return proto


def next_kind() -> int:
    """Allocate the next free plugin kind id."""
    taken = {p.kind for p in _registry.values()}
    k = _FIRST_PLUGIN_KIND
    while k in taken:
        k += 1
    return k


def get(name: str) -> Optional[L7Protocol]:
    return _registry.get(name)


def names() -> Tuple[str, ...]:
    return tuple(sorted(_registry))


# -- per-plugin parse latency -------------------------------------------
# Upstream's Envoy proxy exports per-listener histogram stats; here the
# registry is the shared seam every plugin's parse+verdict rides
# through, so the parse-latency histograms live beside it.  Keyed by
# plugin/kind NAME ("http", "dns", "kafka", "cassandra", ...).  The L7
# workers record into these from the ``l7`` domain; snapshots feed
# ``proxy stats`` / GET /proxy/stats / BENCH_l7.json percentiles.
_lat_lock = threading.Lock()
_latency: Dict[str, object] = {}


def observe_parse(name: str, us: float) -> None:
    # thread-affinity: any
    """Record one parse+verdict latency (µs) for plugin ``name``."""
    from ..serving.stats import LatencyHistogram

    h = _latency.get(name)
    if h is None:
        with _lat_lock:
            h = _latency.setdefault(name, LatencyHistogram())
    h.record(us)


def latency_snapshot() -> Dict[str, dict]:
    """Per-plugin parse-latency percentiles (p50/p95/p99/max/count)."""
    with _lat_lock:
        items = list(_latency.items())
    # lint: disable=CTA002 -- .snapshot here is LatencyHistogram's, not FlowAnalytics'
    return {name: h.snapshot() for name, h in items}


def latency_histogram(name: str):
    """The live histogram for ``name`` (created on first use) — the
    obs registry collects these directly."""
    from ..serving.stats import LatencyHistogram

    with _lat_lock:
        return _latency.setdefault(name, LatencyHistogram())


def featurize_generic(kind: int, requests: Sequence[dict], port: int,
                      src_row: int,
                      method_of: Callable[[dict], int],
                      f0_of: Callable[[dict], str],
                      f1_of: Callable[[dict], str] = lambda r: ""
                      ) -> Tuple[np.ndarray, List[dict]]:
    """The standard featurizer shape: a method id + two hashed string
    fields (what HTTP/Kafka/cassandra/memcached all reduce to)."""
    n = len(requests)
    out = np.zeros((n, L7_COLS), dtype=np.uint32)
    out[:, L7_PORT] = port
    out[:, L7_KIND] = kind
    out[:, L7_SRC_ROW] = src_row
    for i, r in enumerate(requests):
        out[i, L7_METHOD] = method_of(r)
        lo, hi = fnv64(f0_of(r))
        out[i, L7_PATH_H0], out[i, L7_PATH_H1] = lo, hi
        lo, hi = fnv64(f1_of(r))
        out[i, L7_HOST_H0], out[i, L7_HOST_H1] = lo, hi
    return out, list(requests)
