"""Byte-level proxy listeners: the transparent socket splice.

Reference: upstream cilium's proxy plane terminates redirected
connections on a real listener (Envoy, or the Go DNS proxy), parses
requests off the socket, verdicts them against L7 policy, and splices
allowed traffic to the original destination (``pkg/proxy`` +
``proxylib`` OnData).  This module is that last mile for the TPU
framework: a TCP listener per redirect port that reads HTTP/1.x
requests off the wire (``featurize.parse_http_bytes``), runs them
through :class:`~cilium_tpu.proxy.proxy.L7Proxy` (device match
tensors + host fallback + access records), and either splices
request+response bytes to the upstream or answers 403 — closing
DIVERGENCES #12 (the byte-level splice used to be left to the
deployment's ingest adapter).
"""

from __future__ import annotations

import socket
import threading
from typing import Callable, Optional, Tuple

_MAX_HEADER = 64 * 1024
_MAX_BODY = 8 * 1024 * 1024  # enforcement listener: bound memory
_REJECT = "reject"  # _read_request sentinel: close with 400, not EOF
_DENIED = (b"HTTP/1.1 403 Forbidden\r\n"
           b"content-length: 15\r\n"
           b"connection: close\r\n\r\n"
           b"Access denied\r\n")


def _read_request(conn: socket.socket, buf: bytes
                  ):
    """Read one HTTP/1.x request (head + body per content-length) ->
    (head_bytes, body_bytes, leftover_bytes), None on EOF, or
    ``_REJECT`` for a request this listener refuses to frame (header
    overflow, body over ``_MAX_BODY``, negative/conflicting
    Content-Length, chunked transfer) — ambiguous framing on an
    enforcement listener is a smuggling vector, so anything not
    unambiguously framed closes with 400 at the caller.

    ``buf`` carries bytes already received past the previous request
    (pipelined clients) — leftover MUST round-trip through the caller
    or pipelined requests would be silently dropped."""
    while b"\r\n\r\n" not in buf:
        chunk = conn.recv(4096)
        if not chunk:
            return None
        buf += chunk
        if len(buf) > _MAX_HEADER:
            return _REJECT
    head, rest = buf.split(b"\r\n\r\n", 1)
    clen = 0
    seen_clen = False
    for line in head.split(b"\r\n")[1:]:
        if line[:1] in (b" ", b"\t"):
            # obs-fold continuation: an upstream may splice it into
            # the previous value — framing headers hiding in a fold
            # are exactly the listener/upstream disagreement to refuse
            return _REJECT
        name, _, value = line.partition(b":")
        name = name.strip().lower()
        if name == b"content-length":
            value = value.strip()
            # strictly digits: int() also takes '+52'/'5_2', which
            # compliant upstreams reject — no disagreement allowed
            if not value.isdigit():
                return _REJECT
            v = int(value)
            if seen_clen and v != clen:
                return _REJECT
            clen, seen_clen = v, True
        elif name == b"transfer-encoding":
            return _REJECT  # chunked would reframe as pipelined reqs
    if clen > _MAX_BODY:
        return _REJECT
    while len(rest) < clen:
        chunk = conn.recv(4096)
        if not chunk:
            return None
        rest += chunk
    return head + b"\r\n\r\n", rest[:clen], rest[clen:]


class HTTPListener:
    """One redirect port's socket listener + splice loop.

    ``upstream`` is the original destination ``(host, port)`` — in a
    full deployment the datapath's REDIRECT verdict delivers the
    connection here and the original destination rides the NAT record;
    tests pass it explicitly.  Without an upstream, allowed requests
    get a synthesized 200 (the DNS-proxy-style terminating mode)."""

    def __init__(self, proxy, port: int,
                 upstream: Optional[Tuple[str, int]] = None,
                 host: str = "127.0.0.1", src_row: int = 0,
                 upstream_of: Optional[Callable] = None):
        self.proxy = proxy
        self.port = port
        self.upstream = upstream
        self.upstream_of = upstream_of  # fn(request dict) -> (h, p)
        self.src_row = src_row
        self._sock = socket.create_server((host, 0))
        self.address = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _peer = self._sock.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    # -- per-connection splice ----------------------------------------
    def _serve_conn(self, conn: socket.socket) -> None:
        from .featurize import parse_http_bytes

        with conn:
            leftover = b""
            while not self._stop.is_set():
                req = _read_request(conn, leftover)
                if req is None:
                    return
                if req is _REJECT:
                    try:
                        conn.sendall(b"HTTP/1.1 400 Bad Request\r\n"
                                     b"content-length: 0\r\n"
                                     b"connection: close\r\n\r\n")
                    except OSError:
                        pass
                    return
                head, body, leftover = req
                [parsed] = parse_http_bytes([head])
                if not parsed:  # unparseable: reject before policy
                    try:  # (Envoy 400s malformed requests pre-filter)
                        conn.sendall(b"HTTP/1.1 400 Bad Request\r\n"
                                     b"content-length: 0\r\n"
                                     b"connection: close\r\n\r\n")
                    except OSError:
                        pass
                    return
                allow = self.proxy.handle_http(self.port, [parsed],
                                               self.src_row)
                if not int(allow[0]):
                    try:
                        conn.sendall(_DENIED)
                    except OSError:
                        pass
                    return  # deny closes, like an Envoy 403 + reset
                wants_close = b"connection: close" in head.lower()
                if not self._splice_one(conn, head + body, parsed):
                    return
                if wants_close:
                    return

    def _splice_one(self, conn: socket.socket, request: bytes,
                    parsed: dict) -> bool:
        """Forward one allowed request upstream and stream the response
        back; returns False when the connection should close."""
        upstream = (self.upstream_of(parsed) if self.upstream_of
                    else self.upstream)
        if upstream is None:
            # terminating mode keeps the connection alive (the DNS-
            # proxy-style loop); pipelined requests continue via the
            # caller's leftover buffer
            conn.sendall(b"HTTP/1.1 200 OK\r\ncontent-length: 0\r\n\r\n")
            return True
        try:
            with socket.create_connection(upstream, timeout=10) as up:
                up.sendall(request)
                up.shutdown(socket.SHUT_WR)
                while True:
                    chunk = up.recv(65536)
                    if not chunk:
                        break
                    conn.sendall(chunk)
        except OSError:
            try:
                conn.sendall(b"HTTP/1.1 502 Bad Gateway\r\n"
                             b"content-length: 0\r\n\r\n")
            except OSError:
                pass
            return False
        return False  # one-shot upstream splice closes the connection


class ListenerManager:
    """Redirect ports -> live listeners (pkg/proxy redirect lifecycle
    at the SOCKET level: update() reconciles listeners with the
    proxy's compiled redirect set)."""

    def __init__(self, proxy, upstream_of: Optional[Callable] = None):
        self.proxy = proxy
        self.upstream_of = upstream_of
        self._listeners: dict = {}

    def reconcile(self) -> dict:
        wanted = {l["proxy-port"] for l in self.proxy.listeners()}
        for port in list(self._listeners):
            if port not in wanted:
                self._listeners.pop(port).close()
        for port in wanted:
            if port not in self._listeners:
                self._listeners[port] = HTTPListener(
                    self.proxy, port, upstream_of=self.upstream_of)
        return {p: l.address for p, l in self._listeners.items()}

    def close(self) -> None:
        for l in self._listeners.values():
            l.close()
        self._listeners.clear()
