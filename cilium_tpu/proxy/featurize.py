"""L7 featurizers: requests -> fixed-width feature rows.

Reference: ``proxylib/`` parsers (Go, loaded into Envoy via cgo) parse
protocol payloads and hand structured requests to the policy filter.
TPU-first: the parser's output is a ``[N, L7_COLS] uint32`` tensor —
string fields ride as 64-bit FNV-1a hashes (two u32 words) so the
policy match is pure tensor compares; the raw strings travel alongside
only for (a) regex-rule host fallback and (b) access-log records.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

# Feature row columns.
L7_PORT = 0  # proxy port the request arrived on
L7_KIND = 1  # 0 = HTTP, 1 = DNS
L7_METHOD = 2  # dense method id (HTTP) / query type (DNS)
L7_PATH_H0 = 3  # FNV-64 low word of path (HTTP) / qname (DNS)
L7_PATH_H1 = 4  # FNV-64 high word
L7_HOST_H0 = 5  # FNV-64 low word of Host header
L7_HOST_H1 = 6
L7_SRC_ROW = 7  # source identity row (for per-peer L7 policy + logs)
L7_COLS = 8

KIND_HTTP = 0
KIND_DNS = 1
KIND_KAFKA = 2
# rule-tensor-only kind: an HTTP PREFIX rule row (matches KIND_HTTP
# requests through the rolling prefix-hash tensor; l7policy.py)
KIND_HTTP_PREFIX = 3

# longest path prefix that can match on device (longer prefixes fall
# back to host matchers); bounds the rolling-hash tensor
MAX_PREFIX = 48

# Kafka api keys the policy schema names (reference: proxylib kafka
# parser + api.PortRuleKafka role/apiKey)
KAFKA_API_IDS = {"produce": 1, "fetch": 2, "consume": 2,
                 "metadata": 3, "offsets": 4, "offsetcommit": 8,
                 "offsetfetch": 9}

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def fnv64(s: str) -> Tuple[int, int]:
    """FNV-1a 64-bit of the utf-8 bytes -> (lo32, hi32); ('' -> (0,0)).

    The empty string maps to (0, 0) = the wildcard marker, so policy
    fields left blank mean "any" (upstream: empty method/path/host
    fields are unconstrained)."""
    if not s:
        return 0, 0
    h = _FNV_OFFSET
    for b in s.encode():
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    lo, hi = h & 0xFFFFFFFF, h >> 32
    if lo == 0 and hi == 0:  # never collide with the wildcard marker
        lo = 1
    return lo, hi


def _norm_dns(name: str) -> str:
    return name.rstrip(".").lower()


def path_prefix_hashes(paths: Sequence[str],
                       lengths: Optional[Sequence[int]] = None
                       ) -> np.ndarray:
    """Rolling FNV-64 of each path, sampled at prefix lengths.

    ``lengths=None`` samples EVERY position: [N, MAX_PREFIX, 2] u32
    with column j holding fnv64(path[:j+1]) (bit-equal to
    :func:`fnv64`, same zero-avoidance).  With ``lengths`` (sorted,
    ascending — the lengths the compiled prefix rules actually probe)
    the output is the compact [N, len(lengths), 2] and the rolling
    loop stops at max(lengths) — the serving-path shape.  Prefixes
    past a path's end are (0, 0), the "no such prefix" sentinel that
    doubles as the length check."""
    n = len(paths)
    if lengths is None:
        sample = list(range(1, MAX_PREFIX + 1))
    else:
        sample = [int(x) for x in lengths]
    upto = sample[-1] if sample else 0
    arr = np.zeros((n, max(upto, 1)), dtype=np.uint8)
    lens = np.zeros(n, dtype=np.int32)
    for i, p in enumerate(paths):
        b = p.encode()[:upto]
        lens[i] = len(b)
        arr[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
    # vectorized rolling FNV: one pass over positions, whole batch per
    # step (uint64 wraps mod 2^64 natively)
    out = np.zeros((n, len(sample), 2), dtype=np.uint32)
    h = np.full(n, _FNV_OFFSET, dtype=np.uint64)
    prime = np.uint64(_FNV_PRIME)
    col = {L: k for k, L in enumerate(sample)}
    with np.errstate(over="ignore"):
        for j in range(upto):
            h = (h ^ arr[:, j].astype(np.uint64)) * prime
            k = col.get(j + 1)
            if k is None:
                continue
            lo = (h & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            hi = (h >> np.uint64(32)).astype(np.uint32)
            lo = np.where((lo | hi) == 0, np.uint32(1), lo)
            alive = j < lens
            out[:, k, 0] = np.where(alive, lo, 0)
            out[:, k, 1] = np.where(alive, hi, 0)
    return out


def featurize_http(requests: Sequence[dict], port: int,
                   src_row: int = 0) -> Tuple[np.ndarray, List[dict]]:
    """Structured HTTP requests ({method, path, host}) -> feature rows.

    Returns (rows [N, L7_COLS], the requests echoed back — callers keep
    them for regex fallback + access logs)."""
    from .l7policy import METHOD_IDS

    n = len(requests)
    out = np.zeros((n, L7_COLS), dtype=np.uint32)
    out[:, L7_PORT] = port
    out[:, L7_KIND] = KIND_HTTP
    out[:, L7_SRC_ROW] = src_row
    for i, r in enumerate(requests):
        out[i, L7_METHOD] = METHOD_IDS.get(r.get("method", "").upper(), 0)
        lo, hi = fnv64(r.get("path", ""))
        out[i, L7_PATH_H0], out[i, L7_PATH_H1] = lo, hi
        lo, hi = fnv64(r.get("host", ""))
        out[i, L7_HOST_H0], out[i, L7_HOST_H1] = lo, hi
    return out, list(requests)


def featurize_dns(qnames: Sequence[str], port: int,
                  src_row: int = 0) -> Tuple[np.ndarray, List[str]]:
    """DNS query names -> feature rows (qname hash in the path words)."""
    n = len(qnames)
    out = np.zeros((n, L7_COLS), dtype=np.uint32)
    out[:, L7_PORT] = port
    out[:, L7_KIND] = KIND_DNS
    out[:, L7_SRC_ROW] = src_row
    names = [_norm_dns(q) for q in qnames]
    for i, q in enumerate(names):
        lo, hi = fnv64(q)
        out[i, L7_PATH_H0], out[i, L7_PATH_H1] = lo, hi
    return out, names


def featurize_kafka(requests: Sequence[dict], port: int,
                    src_row: int = 0) -> Tuple[np.ndarray, List[dict]]:
    """Kafka requests ({api_key, topic, client_id}) -> feature rows:
    api id in the method column, topic hash in the path words."""
    n = len(requests)
    out = np.zeros((n, L7_COLS), dtype=np.uint32)
    out[:, L7_PORT] = port
    out[:, L7_KIND] = KIND_KAFKA
    out[:, L7_SRC_ROW] = src_row
    for i, r in enumerate(requests):
        out[i, L7_METHOD] = KAFKA_API_IDS.get(
            str(r.get("api_key", "")).lower(), 0)
        lo, hi = fnv64(r.get("topic", ""))
        out[i, L7_PATH_H0], out[i, L7_PATH_H1] = lo, hi
        lo, hi = fnv64(r.get("client_id", ""))
        out[i, L7_HOST_H0], out[i, L7_HOST_H1] = lo, hi
    return out, list(requests)


def parse_http_bytes(payloads: Iterable[bytes]) -> List[dict]:
    """Minimal HTTP/1.x request parser: request line + Host header.

    The wire-facing half of the featurizer (reference: proxylib's HTTP
    parser); malformed requests become empty dicts, which match no
    rule and are therefore denied by an enforcing L7 policy."""
    out = []
    for raw in payloads:
        try:
            head = raw.split(b"\r\n\r\n", 1)[0].decode("latin-1")
            lines = head.split("\r\n")
            method, path, _ = lines[0].split(" ", 2)
            host = ""
            for ln in lines[1:]:
                if ln.lower().startswith("host:"):
                    host = ln.split(":", 1)[1].strip()
                    break
            out.append({"method": method, "path": path, "host": host})
        except (ValueError, IndexError):
            out.append({})
    return out
