"""L7Rules -> per-rule match tensors + host fallback matchers.

Reference: upstream cilium pushes ``api.L7Rules`` to Envoy as an xDS
``NetworkPolicy`` (``pkg/envoy``); the cilium Envoy filter evaluates
each request against the rule list (an unmatched request on an
L7-policied port gets 403 / a refused DNS answer — L7 default deny).

TPU-first: each HTTP/DNS rule row compiles to one row of a match
tensor; a request matches a rule iff every constrained field agrees
(method id, 64-bit path/qname hash, host hash).  The batched verdict
is one masked compare over [N requests x R rules] on device.  Rules
whose fields are regexes/globs (not expressible as exact hashes)
compile to *host matchers* instead; a port is host-evaluated only for
requests no exact rule already admitted.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..policy.api import L7Rules
from .registry import get as registry_get
from .featurize import (
    KAFKA_API_IDS,
    KIND_DNS,
    KIND_HTTP,
    KIND_HTTP_PREFIX,
    KIND_KAFKA,
    L7_COLS,
    L7_HOST_H0,
    L7_HOST_H1,
    L7_KIND,
    L7_METHOD,
    L7_PATH_H0,
    L7_PATH_H1,
    L7_PORT,
    MAX_PREFIX,
    fnv64,
)

METHOD_IDS: Dict[str, int] = {
    "GET": 1, "POST": 2, "PUT": 3, "DELETE": 4, "HEAD": 5,
    "OPTIONS": 6, "PATCH": 7, "CONNECT": 8, "TRACE": 9,
}

# rule tensor columns
R_PORT = 0
R_KIND = 1
R_METHOD = 2  # 0 == any
R_PATH_H0 = 3  # (0,0) == any
R_PATH_H1 = 4
R_HOST_H0 = 5
R_HOST_H1 = 6
R_COLS = 7

_REGEX_CHARS = re.compile(r"[.*+?^$()\[\]{}|\\]")


def _is_literal(path: str) -> bool:
    """True when the rule's path regex is a plain literal (the common
    case), so it compiles to an exact hash."""
    return not _REGEX_CHARS.search(path)


def _prefix_form(path: str):
    """``LITERAL.*`` / ``LITERAL.+`` -> (literal, min_extra) or None.

    The overwhelmingly common regex-path shape compiles to a device
    prefix row (rolling prefix-hash compare); anything else stays a
    host matcher.  The literal must fit the rolling-hash window."""
    if len(path) < 3 or path[-1] not in "*+" or path[-2] != ".":
        return None
    lit = path[:-2]
    if not lit or not _is_literal(lit) or len(lit) > MAX_PREFIX - 1:
        return None
    return lit, (1 if path[-1] == "+" else 0)


@dataclass
class L7PolicyTensors:
    """Compiled L7 policy: device rule tensor + host fallback."""

    rules: np.ndarray  # [R, R_COLS] uint32 (exact rules)
    # port -> [fn(request_dict) -> bool] for regex/glob rules
    host_matchers: Dict[int, List[Callable]] = field(default_factory=dict)
    # every L7-policied port (requests on other ports bypass the proxy)
    ports: frozenset = frozenset()
    # port -> original L7Rules (for xDS-style display / DNS observers)
    by_port: Dict[int, L7Rules] = field(default_factory=dict)

    # sorted prefix lengths the rules probe (incl. L+1 for .+ rules);
    # the featurizer samples the rolling hash at exactly these
    prefix_lengths: Tuple[int, ...] = ()

    @property
    def n_prefix(self) -> int:
        """Device prefix rows (callers compute the rolling-hash tensor
        only when some rule consumes it)."""
        if self.rules.shape[0] == 0:
            return 0
        return int((self.rules[:, R_KIND] == KIND_HTTP_PREFIX).sum())


def compile_l7(redirects: Sequence[Tuple[int, str, L7Rules]]
               ) -> L7PolicyTensors:
    """Compile ``EndpointPolicy.redirects`` into match tensors.

    ``redirects`` is the resolver's (proxy_port, rule_label, L7Rules)
    list; one listener per port (reference: pkg/proxy redirect
    lifecycle)."""
    rows: List[List[int]] = []
    host_matchers: Dict[int, List[Callable]] = {}
    by_port: Dict[int, L7Rules] = {}
    ports = set()

    for port, _label, l7 in redirects:
        ports.add(port)
        by_port[port] = l7
        # regex-PATH-only http rules group into ONE alternation per
        # (method, host): the fallback then runs one fullmatch per
        # request instead of one per rule (the bench's 200-rule config
        # showed the per-rule loop dominating the fallback path)
        path_groups: Dict[Tuple[str, str], List[str]] = {}
        for h in l7.http:
            # 0 in the method column means "any"; a method OUTSIDE the
            # dense id table (PURGE, custom verbs) must NOT compile to
            # 0 — that would widen the rule — so it takes the host
            # matcher path, which compares method strings.
            method_id = (0 if not h.method
                         else METHOD_IDS.get(h.method.upper()))
            literal = ((not h.path or _is_literal(h.path))
                       and not h.headers and method_id is not None
                       and _is_literal(h.host))
            if literal:
                p_lo, p_hi = fnv64(h.path)
                ho_lo, ho_hi = fnv64(h.host)
                rows.append([
                    port, KIND_HTTP, method_id,
                    p_lo, p_hi, ho_lo, ho_hi,
                ])
                continue
            pref = (_prefix_form(h.path)
                    if h.path and not h.headers and method_id is not None
                    and _is_literal(h.host) else None)
            if pref is not None:
                # LITERAL.* rides the device prefix-hash compare: the
                # method word carries len(prefix) (bits 8..15) and the
                # .+ at-least-one-more-byte flag (bit 16)
                lit, extra = pref
                p_lo, p_hi = fnv64(lit)
                ho_lo, ho_hi = fnv64(h.host)
                rows.append([
                    port, KIND_HTTP_PREFIX,
                    method_id | (len(lit) << 8) | (extra << 16),
                    p_lo, p_hi, ho_lo, ho_hi,
                ])
                continue
            if (h.path and not h.headers and _is_literal(h.host)
                    and _groupable(h.path)):
                path_groups.setdefault(
                    (h.method.upper(), h.host), []).append(h.path)
                continue
            host_matchers.setdefault(port, []).append(
                _http_matcher(h))
        for (meth, host), paths in path_groups.items():
            host_matchers.setdefault(port, []).append(
                _http_group_matcher(meth, host, paths))
        for d in l7.dns:
            if d.match_name:
                lo, hi = fnv64(d.match_name.rstrip(".").lower())
                rows.append([port, KIND_DNS, 0, lo, hi, 0, 0])
            if d.match_pattern:
                pat = d.match_pattern.rstrip(".").lower()
                host_matchers.setdefault(port, []).append(
                    _dns_matcher(pat))
        for k in l7.kafka:
            # reference: api.PortRuleKafka {role|apiKey, topic,
            # clientID}; role produce/consume maps onto api ids
            api = str(k.get("apiKey") or k.get("role") or "").lower()
            api_id = KAFKA_API_IDS.get(api, 0) if api else 0
            topic = str(k.get("topic") or "")
            client = str(k.get("clientID") or "")
            if api and api_id == 0:
                # unknown api name: host matcher compares strings
                host_matchers.setdefault(port, []).append(
                    _kafka_matcher(k))
                continue
            t_lo, t_hi = fnv64(topic)
            c_lo, c_hi = fnv64(client)
            rows.append([port, KIND_KAFKA, api_id,
                         t_lo, t_hi, c_lo, c_hi])
        # plugin protocols (registry.py): each rule compiles to a row
        # of the SAME tensor or a host matcher — no per-protocol code
        # here.  Rules for an UNREGISTERED parser compile to nothing,
        # which under L7 default deny means such requests are denied
        # (the reference fails policy push when the parser is missing).
        for name, extra_rules in getattr(l7, "extra", ()):
            plugin = registry_get(name)
            if plugin is None:
                continue
            for rule in extra_rules:
                what, val = plugin.compile_rule(rule)
                if what == "row":
                    m, f0l, f0h, f1l, f1h = val
                    rows.append([port, plugin.kind, m,
                                 f0l, f0h, f1l, f1h])
                else:
                    host_matchers.setdefault(port, []).append(val)

    rules = (np.asarray(rows, dtype=np.uint32) if rows
             else np.zeros((0, R_COLS), dtype=np.uint32))
    plens = set()
    for row in rows:
        if row[R_KIND] == KIND_HTTP_PREFIX:
            L = (row[R_METHOD] >> 8) & 0xFF
            plens.add(L)
            if (row[R_METHOD] >> 16) & 1:
                plens.add(L + 1)  # the .+ at-least-one-more check
    return L7PolicyTensors(rules=rules, host_matchers=host_matchers,
                           ports=frozenset(ports), by_port=by_port,
                           prefix_lengths=tuple(sorted(plens)))


_BACKREF = re.compile(
    r"\\[1-9]|\(\?P=|\(\?P?<|\((?!\?)|\(\?[aiLmsux-]+\)")


def _groupable(path: str) -> bool:
    """A path regex joins the (method, host) alternation only if it
    carries no capturing groups, backreferences, or global inline
    flags — the alternation renumbers groups (``(a)\\1`` would match
    different text once other patterns precede it), and a ``(?i)``
    would either fail to compile mid-pattern or leak onto every
    grouped rule."""
    return _BACKREF.search(path) is None


def _http_group_matcher(meth: str, host: str,
                        paths: Sequence[str]) -> Callable:
    """One matcher for EVERY regex-path rule sharing (method, host):
    a single compiled alternation replaces the per-rule loop."""
    try:
        combined = re.compile("|".join(f"(?:{p})" for p in paths))
    except re.error:
        # a construct _groupable didn't anticipate: never let one
        # pattern take down the whole redirect set — match per rule
        singles = [re.compile(p) for p in paths]

        class combined:  # noqa: N801 — duck-typed fallback
            @staticmethod
            def fullmatch(s):
                return next(
                    (m for r in singles if (m := r.fullmatch(s))), None)

    def match(req) -> bool:
        if not isinstance(req, dict):
            return False
        if meth and req.get("method", "").upper() != meth:
            return False
        if host and req.get("host", "") != host:
            return False
        return combined.fullmatch(req.get("path", "")) is not None

    return match


def _http_matcher(h) -> Callable:
    meth = h.method.upper()
    path_re = re.compile(h.path) if h.path else None
    host_re = re.compile(h.host) if h.host else None

    def match(req) -> bool:
        if not isinstance(req, dict):
            return False  # a DNS qname on a mixed-rule port
        if meth and req.get("method", "").upper() != meth:
            return False
        if path_re and not path_re.fullmatch(req.get("path", "")):
            return False
        if host_re and not host_re.fullmatch(req.get("host", "")):
            return False
        if h.headers:
            have = {x.strip() for x in req.get("headers", ())}
            if not set(h.headers).issubset(have):
                return False
        return True

    return match


def _kafka_matcher(rule: dict) -> Callable:
    api = str(rule.get("apiKey") or rule.get("role") or "").lower()
    topic = str(rule.get("topic") or "")
    client = str(rule.get("clientID") or "")

    def match(req) -> bool:
        if not isinstance(req, dict):
            return False  # a DNS qname on a mixed-rule port
        if api and str(req.get("api_key", "")).lower() != api:
            return False
        if topic and req.get("topic", "") != topic:
            return False
        if client and req.get("client_id", "") != client:
            return False
        return True

    return match


def _dns_matcher(pattern: str) -> Callable:
    from ..fqdn.matchpattern import matches

    def match(req) -> bool:
        name = req if isinstance(req, str) else req.get("qname", "")
        return matches(pattern, name)

    return match


def l7_verdict(rules: jnp.ndarray, rows: jnp.ndarray,
               pref: jnp.ndarray = None,
               pref_lengths: jnp.ndarray = None) -> jnp.ndarray:
    """Batched request verdict: [N, L7_COLS] x [R, R_COLS] -> [N] bool.

    A request is admitted iff SOME rule row matches on every
    constrained field (L7 default deny otherwise).  One fused masked
    compare — no per-request control flow.

    ``pref`` ([N, MAX_PREFIX, 2] rolling path prefix hashes,
    featurize.path_prefix_hashes) serves the KIND_HTTP_PREFIX rows:
    a ``LITERAL.*`` rule matches when the request's rolling hash at
    ``len(LITERAL)`` equals the rule's prefix hash (and for ``.+``,
    a hash exists one byte further — i.e. the path is longer)."""
    if rules.shape[0] == 0:
        return jnp.zeros(rows.shape[0], dtype=bool)
    r = rules[None, :, :].astype(jnp.uint32)  # [1, R, C]
    q = rows[:, None, :].astype(jnp.uint32)  # [N, 1, C]
    is_pref = r[:, :, R_KIND] == KIND_HTTP_PREFIX
    port_ok = q[:, :, L7_PORT] == r[:, :, R_PORT]
    kind_ok = jnp.where(is_pref, q[:, :, L7_KIND] == KIND_HTTP,
                        q[:, :, L7_KIND] == r[:, :, R_KIND])
    meth_id = jnp.where(is_pref, r[:, :, R_METHOD] & 0xFF,
                        r[:, :, R_METHOD])
    meth_ok = (meth_id == 0) | (q[:, :, L7_METHOD] == meth_id)
    path_any = (r[:, :, R_PATH_H0] == 0) & (r[:, :, R_PATH_H1] == 0)
    path_ok = path_any | ((q[:, :, L7_PATH_H0] == r[:, :, R_PATH_H0])
                          & (q[:, :, L7_PATH_H1] == r[:, :, R_PATH_H1]))
    if pref is not None:
        rp = rules.astype(jnp.uint32)
        plen = ((rp[:, R_METHOD] >> 8) & 0xFF).astype(jnp.int32)  # [R]
        extra = (rp[:, R_METHOD] >> 16) & 1
        pq = pref.astype(jnp.uint32)
        if pref_lengths is None:  # full sampling: column j = length j+1
            pref_lengths = jnp.arange(1, pq.shape[1] + 1,
                                      dtype=jnp.int32)
        K = pq.shape[1]
        # per-rule column selection via one-hot over the (tiny) K axis
        # — a [N, R] middle-axis gather compiles to a pathologically
        # slow scatter on the CPU backend this kernel serves from
        ks = jnp.arange(K, dtype=jnp.int32)
        col = jnp.minimum(jnp.searchsorted(pref_lengths, plen), K - 1)
        ncol = jnp.minimum(jnp.searchsorted(pref_lengths, plen + 1),
                           K - 1)
        onehot = ks[None, :] == col[:, None]  # [R, K]
        nhot = ks[None, :] == ncol[:, None]
        eq = ((pq[:, None, :, 0] == rp[None, :, None, R_PATH_H0])
              & (pq[:, None, :, 1] == rp[None, :, None, R_PATH_H1]))
        ph_hit = jnp.any(eq & onehot[None, :, :], axis=2)  # [N, R]
        nonempty = (pq[:, :, 0] | pq[:, :, 1]) != 0  # [N, K]
        beyond_ok = jnp.any(nonempty[:, None, :] & nhot[None, :, :],
                            axis=2)
        pref_hit = ph_hit & ((extra[None, :] == 0) | beyond_ok)
        path_ok = jnp.where(is_pref, pref_hit, path_ok)
    else:
        path_ok = path_ok & ~is_pref  # no prefix tensor: can't match
    host_any = (r[:, :, R_HOST_H0] == 0) & (r[:, :, R_HOST_H1] == 0)
    host_ok = host_any | ((q[:, :, L7_HOST_H0] == r[:, :, R_HOST_H0])
                          & (q[:, :, L7_HOST_H1] == r[:, :, R_HOST_H1]))
    hit = port_ok & kind_ok & meth_ok & path_ok & host_ok
    return jnp.any(hit, axis=1)


l7_verdict_jit = jax.jit(l7_verdict)
