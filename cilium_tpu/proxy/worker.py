"""The L7 worker pool: bounded-queue proxy workers off the event plane.

Reference: upstream cilium redirects matched flows to a userspace
proxy (Envoy via ``pkg/proxy``, proxylib parsers for the long tail of
protocols) running in its own threads — the packet path's only cost
is the REDIRECT verdict and the proxy does payload work at its own
cadence.  TPU-first equivalent: the device emits ``VERDICT_REDIRECT``
rows into the monitor ring; the event plane's join worker fans those
rows out (never the drain thread — the same separation the event
plane itself exists for) into THIS pool, whose workers parse payloads
via the plugin registry and evaluate L7 policy through the fused
tensor compare in ``l7policy.l7_verdict``.

Loss discipline — the no-silent-loss contract, applied to the proxy
plane's own machinery, in ROWS (redirected packets), not windows::

    redirected == l7_allowed + l7_denied + l7_shed + l7_failed

- bounded-queue OVERFLOW drops the OLDEST queued task, its rows
  counted ``l7_shed`` — a stalled proxy keeps the freshest redirects;
- a task whose handling RAISES is contained: its rows count
  ``l7_failed``, the worker lives on;
- worker DEATH (an exception outside the per-task containment, e.g.
  the ``l7.parse`` fault site) claims the in-flight task — its rows
  count ``l7_failed`` — and the thread restarts under a POOL-WIDE
  restart budget (the drain-loop watchdog idiom); terminal once
  exhausted (new submissions shed, surviving workers keep draining);
- ``stop(drain=True)`` handles everything queued before returning,
  so the ledger closes exactly afterwards.

The counters are declared in ``L7WorkerPool.__init__`` and surfaced
verbatim through ``stats()`` → serving stats → ``GET /proxy/stats`` /
``cilium-tpu proxy stats`` / the ``cilium_l7_*`` metrics series;
CTA012 (analysis/proxy_lint.py) pins the declaration/export chain.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..infra import faults
from ..serving.stats import LatencyHistogram

_IDLE_WAIT_S = 0.05
DEFAULT_L7_WORKERS = 2
DEFAULT_L7_QUEUE = 128


class L7Task:
    """One redirected row-group in flight between the event plane and
    an L7 worker: every redirect row of one (proxy_port, batch) pair,
    with the header columns the parse leg needs to synthesize /
    attribute requests.  ``rows`` is the ledger unit — the task is
    accounted in rows whatever happens to it."""

    __slots__ = ("port", "rows", "hdr", "identities", "meta",
                 "t_submit")

    def __init__(self, port: int, rows: int, hdr=None,
                 identities=None, meta=None):
        self.port = int(port)
        self.rows = int(rows)
        self.hdr = hdr  # per-row header columns (dict of np arrays)
        self.identities = identities  # per-row source identity ids
        self.meta = meta  # owner context (plane's request source etc.)
        self.t_submit = 0.0


class L7WorkerPool:
    """N worker threads popping :class:`L7Task` off one bounded queue
    and running ``handle_fn(task) -> (n_allowed, n_denied)`` (the L7
    plane's parse + verdict + DNS-observe leg).  Rows the handler does
    not account for (``allowed + denied < task.rows``) count
    ``l7_failed`` — the ledger closes no matter what a handler does."""

    def __init__(self, handle_fn: Callable[[L7Task], tuple],
                 workers: int = DEFAULT_L7_WORKERS,
                 queue_depth: int = DEFAULT_L7_QUEUE,
                 restart_budget: int = 3,
                 on_terminal: Optional[Callable[[str], None]] = None):
        self._handle_fn = handle_fn
        # INCIDENT HOOK POINT (obs/flightrec.py): fires once, from the
        # dying worker thread, when the pool-wide restart budget
        # exhausts — a terminal proxy pool means redirected traffic is
        # shedding, which is exactly when an operator wants a bundle.
        self._on_terminal = on_terminal
        self.n_workers = max(1, int(workers))
        self.queue_depth = max(1, int(queue_depth))
        self._budget = max(0, int(restart_budget))
        self._cv = threading.Condition()
        # guarded-by: _cv: _q, _current, _stop, error, restarts,
        # guarded-by: _cv: tasks_submitted, tasks_done, tasks_dropped,
        # guarded-by: _cv: overflows, redirected, l7_allowed,
        # guarded-by: _cv: l7_denied, l7_shed, l7_failed, parse_lag,
        # guarded-by: _cv: last_drop_cause
        self._q: List[L7Task] = []
        # one in-flight slot per worker: death/stop sweeps claim them
        # under the lock so a wedged handler can never double-count
        self._current: List[Optional[L7Task]] = \
            [None] * self.n_workers
        self._stop = False
        self._threads: List[Optional[threading.Thread]] = \
            [None] * self.n_workers
        self.error: Optional[str] = None  # terminal fault
        # the proxy-plane ledger (rows):
        #   redirected == l7_allowed + l7_denied + l7_shed + l7_failed
        # exact once pending reaches 0 (post-stop it always does)
        self.redirected = 0
        self.l7_allowed = 0
        self.l7_denied = 0
        self.l7_shed = 0
        self.l7_failed = 0
        self.tasks_submitted = 0
        self.tasks_done = 0
        self.tasks_dropped = 0
        self.overflows = 0  # ...of the dropped, at the bounded queue
        self.restarts = 0  # pool-wide, against one shared budget
        self.parse_lag = LatencyHistogram()  # submit -> handled, µs
        self.last_drop_cause = ""

    # -- producer side (the event-join worker) -------------------------
    def submit(self, task: L7Task) -> bool:
        # thread-affinity: any
        """Offer one task; never blocks.  A full queue sheds the
        OLDEST queued task (counted) to admit the new one; a
        terminal/stopped pool sheds the offered task instead.
        Returns False when the offered task itself was shed."""
        victim = drop_cause = None
        task.t_submit = time.monotonic()
        with self._cv:
            self.tasks_submitted += 1
            # the rows entered the proxy plane regardless of what
            # happens to the task now — that is what keeps the ledger
            # exact under trace-sampling upstream and shedding here
            self.redirected += task.rows
            if self.error is not None:
                drop_cause = "pool terminal"
            elif self._stop:
                drop_cause = "pool stopped"
            else:
                if len(self._q) >= self.queue_depth:
                    self.overflows += 1
                    victim = self._q.pop(0)
                self._q.append(task)
                self._cv.notify()
        if victim is not None:
            self._shed(victim, "task queue full")
            return True
        if drop_cause is not None:
            self._shed(task, drop_cause)
            return False
        return True

    @property
    def pending(self) -> int:
        # thread-affinity: any
        with self._cv:
            return (len(self._q)
                    + sum(1 for c in self._current if c is not None))

    def _stopping(self) -> bool:
        """Locked read of the stop-and-drained predicate (the
        ``l7.parse`` hang site's abort hook)."""
        with self._cv:
            return self._stop and not self._q

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        # thread-affinity: api
        assert all(t is None for t in self._threads), \
            "pool already started"
        for i in range(self.n_workers):
            t = threading.Thread(target=self._run, args=(i,),
                                 daemon=True,
                                 name=f"serving-l7-w{i}")
            self._threads[i] = t
            t.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> dict:
        # thread-affinity: api
        """Stop the pool.  With ``drain`` (default) every queued task
        is handled first — the ``stop_serving`` contract; the sweep
        below only fires for dead/terminal workers or a timeout, and
        it COUNTS what it sweeps."""
        with self._cv:
            self._stop = True
            if not drain:
                swept, self._q = self._q, []
            self._cv.notify_all()
        if not drain:
            for t in swept:
                self._shed(t, "stopped without drain")
        deadline = time.monotonic() + timeout
        for i in range(self.n_workers):
            t = self._threads[i]
            while (t is not None and t.is_alive()
                   and time.monotonic() < deadline):
                t.join(timeout=0.1)
                t = self._threads[i]  # follow restart successors
        with self._cv:
            swept, self._q = self._q, []
            # claim every in-flight task too: a handler hung past the
            # timeout must still land in the ledger.  Claiming under
            # the lock transfers ownership — if the wedged handler
            # eventually returns, _run_body sees it lost the claim
            # and does NOT also count the task done.
            curs = [c for c in self._current if c is not None]
            self._current = [None] * self.n_workers
            sweep_cause = self.error or "pool did not drain in time"
        for t in swept:
            self._shed(t, sweep_cause)
        for t in curs:
            self._fail(t, t.rows, "handler hung past stop timeout")
        return self.stats()

    # -- the worker threads --------------------------------------------
    def _run(self, slot: int) -> None:
        # thread-affinity: l7
        try:
            self._run_body(slot)
        except BaseException as e:  # noqa: BLE001 — death path: the
            # in-flight task's rows are a counted l7_failed loss, and
            # the slot restarts under the pool budget (the drain-loop
            # watchdog discipline applied to the proxy plane).  Claim
            # under the lock — stop()'s sweep may have taken it.
            with self._cv:
                cur, self._current[slot] = self._current[slot], None
            if cur is not None:
                self._fail(cur, cur.rows, f"worker died: {e}")
            went_terminal = fire = False
            err = None
            with self._cv:
                if self._stop or self.restarts >= self._budget:
                    went_terminal = True
                    # a worker dying DURING stop() is the sweep's
                    # business, not an incident
                    fire = not self._stop and self.error is None
                    if self.error is None:
                        self.error = (
                            f"l7 worker died ({type(e).__name__}: "
                            f"{e}); restart budget "
                            f"{self.restarts}/{self._budget} exhausted")
                    err = self.error
                    self._cv.notify_all()
                else:
                    self.restarts += 1
                    n = self.restarts
            if went_terminal:
                if fire and self._on_terminal is not None:
                    try:  # contained: a failing hook must not mask
                        # the terminal error it reports
                        self._on_terminal(err)
                    except Exception:  # noqa: BLE001
                        pass
                return
            t = threading.Thread(target=self._run, args=(slot,),
                                 daemon=True,
                                 name=f"serving-l7-w{slot}-r{n}")
            self._threads[slot] = t
            t.start()

    def _run_body(self, slot: int) -> None:
        # thread-affinity: l7
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(_IDLE_WAIT_S)
                if self._q:
                    task = self._q.pop(0)
                    self._current[slot] = task
                else:  # stopped AND drained
                    return
            # the injection site: a raise here kills the worker
            # mid-parse (restart-on-death, rows counted l7_failed); a
            # ~S hang stalls the pool so the bounded queue's shed
            # accounting can be proven
            faults.check(faults.SITE_L7_PARSE, abort=self._stopping)
            try:
                allowed, denied = self._handle_fn(task)
                allowed = max(0, int(allowed))
                denied = max(0, int(denied))
            except Exception as e:  # noqa: BLE001 — contained: one
                # task's rows lost (counted), the worker lives on
                with self._cv:
                    owned = self._current[slot] is task
                    self._current[slot] = None
                if owned:
                    self._fail(task, task.rows,
                               f"handler failed: "
                               f"{type(e).__name__}: {e}")
                continue
            with self._cv:
                if self._current[slot] is not task:
                    # stop()'s timeout sweep claimed this task and
                    # already counted it while the handler hung —
                    # never double-count it
                    continue
                self._current[slot] = None
                # rows the handler left unaccounted are failures, so
                # the ledger closes no matter what a handler returns
                short = task.rows - min(task.rows, allowed + denied)
                if allowed + denied > task.rows:
                    allowed = min(allowed, task.rows)
                    denied = task.rows - allowed
                self.l7_allowed += allowed
                self.l7_denied += denied
                self.l7_failed += short
                self.tasks_done += 1
                self.parse_lag.record(
                    (time.monotonic() - task.t_submit) * 1e6)
                self._cv.notify_all()

    def _shed(self, task: L7Task, cause: str) -> None:
        # thread-affinity: any
        with self._cv:
            self.tasks_dropped += 1
            self.l7_shed += task.rows
            self.last_drop_cause = (cause or "")[:200]
            self._cv.notify_all()

    def _fail(self, task: L7Task, rows: int, cause: str) -> None:
        # thread-affinity: any
        with self._cv:
            self.tasks_dropped += 1
            self.l7_failed += rows
            self.last_drop_cause = (cause or "")[:200]
            self._cv.notify_all()

    # -- reading (API/CLI threads) -------------------------------------
    def stats(self) -> Dict[str, object]:
        # thread-affinity: any
        with self._cv:
            pending = (len(self._q)
                       + sum(1 for c in self._current
                             if c is not None))
            accounted = (self.l7_allowed + self.l7_denied
                         + self.l7_shed + self.l7_failed)
            out = {
                "workers": self.n_workers,
                "queue-depth": self.queue_depth,
                "tasks-pending": pending,
                "tasks-submitted": self.tasks_submitted,
                "tasks-done": self.tasks_done,
                "tasks-dropped": self.tasks_dropped,
                "queue-overflows": self.overflows,
                "redirected": self.redirected,
                "l7-allowed": self.l7_allowed,
                "l7-denied": self.l7_denied,
                "l7-shed": self.l7_shed,
                "l7-failed": self.l7_failed,
                # exact once nothing is in flight (post-stop always)
                "ledger-exact": (pending == 0
                                 and self.redirected == accounted),
                "worker-restarts": self.restarts,
                "parse-lag-us": self.parse_lag.snapshot(),
            }
            if self.last_drop_cause:
                out["last-drop-cause"] = self.last_drop_cause
            if self.error is not None:
                out["error"] = self.error
            return out
