"""The L7 proxy: redirect listeners, request verdicts, access records.

Reference: upstream cilium's proxy plane — ``pkg/proxy`` (redirect
lifecycle: one listener per allocated proxy port), the Envoy cilium
filter (per-request policy verdicts), and Hubble's ``parser/seven``
records (access logs).  TPU-first: requests batch through the
featurizer + the compiled match tensors (``l7policy``); only
regex/glob rules drop to host matchers, and only for requests the
exact tensor pass didn't already admit.

An unmatched request on an L7-policied port is DENIED (HTTP 403 /
refused DNS) — L7 default deny, matching the reference's filter
behavior on ports carrying ``rules``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .featurize import (
    KIND_DNS,
    KIND_HTTP,
    featurize_dns,
    featurize_http,
)
from .l7policy import L7PolicyTensors, compile_l7, l7_verdict_jit

VERDICT_FORWARDED = 1
VERDICT_DENIED = 0


@dataclass
class L7Record:
    """One access-log record (the hubble "seven" flow's source)."""

    kind: int  # KIND_HTTP | KIND_DNS
    verdict: int  # VERDICT_FORWARDED | VERDICT_DENIED
    proxy_port: int
    src_row: int
    timestamp: float
    # HTTP: method/path/host + synthetic status; DNS: qname
    method: str = ""
    path: str = ""
    host: str = ""
    qname: str = ""
    status: int = 0


# fn(qname, ips, ttl) — the fqdn subsystem subscribes to observed DNS
# answers (reference: pkg/fqdn's DNS proxy feeds the name manager)
DNSAnswerFn = Callable[[str, Sequence[str], int], None]


class L7Proxy:
    def __init__(self):
        self._lock = threading.Lock()
        self._tensors: L7PolicyTensors = compile_l7([])
        self._records: List[Callable[[L7Record], None]] = []
        self._dns_observers: List[DNSAnswerFn] = []
        self.requests_total = 0
        self.requests_denied = 0
        # host-fallback accounting: requests the device tensors did NOT
        # admit that were re-checked against regex/glob host matchers
        # (the per-request Python cost center — the bench reports the
        # hit fraction so the device-tensor coverage is visible)
        self.host_fallback_checked = 0
        self.host_fallback_allowed = 0

    # -- wiring -------------------------------------------------------
    def update(self, policies) -> None:
        """Recompile listeners from the resolved policies' redirects
        (called on attach/regeneration; reference: pkg/proxy
        UpdateRedirect on endpoint regeneration)."""
        redirects = []
        seen = set()
        for pol in policies:
            for port, label, l7 in pol.redirects:
                if port not in seen:
                    seen.add(port)
                    redirects.append((port, label, l7))
        tensors = compile_l7(redirects)
        with self._lock:
            self._tensors = tensors

    def on_record(self, fn: Callable[[L7Record], None]) -> None:
        self._records.append(fn)

    def observe_dns(self, fn: DNSAnswerFn) -> None:
        self._dns_observers.append(fn)

    @property
    def ports(self) -> frozenset:
        with self._lock:
            return self._tensors.ports

    def listeners(self) -> list:
        """Redirect listeners + their rule shapes (GET /proxy; the
        xDS NetworkPolicy view)."""
        with self._lock:
            by_port = dict(self._tensors.by_port)
        return [{
            "proxy-port": port,
            "http-rules": len(l7.http),
            "dns-rules": len(l7.dns),
            "kafka-rules": len(l7.kafka),
            **{f"{name}-rules": len(rules)
               for name, rules in getattr(l7, "extra", ())},
        } for port, l7 in sorted(by_port.items())]

    # -- request paths ------------------------------------------------
    def _verdicts(self, rows: np.ndarray, port: int,
                  raw: Sequence) -> np.ndarray:
        with self._lock:
            t = self._tensors
        if port not in t.ports:
            # no listener: the datapath never redirects here; treat as
            # pass-through (reference: proxy without policy forwards)
            return np.ones(len(raw), dtype=np.uint8)
        if t.rules.shape[0]:
            import jax
            import jax.numpy as jnp

            from .featurize import path_prefix_hashes

            # prefix rows consume the rolling path-hash tensor; it is
            # only computed when some rule needs it
            pref = None
            if t.n_prefix:
                pref = path_prefix_hashes(
                    [r.get("path", "") if isinstance(r, dict) else ""
                     for r in raw], t.prefix_lengths)
            # the proxy lives host-side (requests arrive here); the
            # match tensor is tiny, so it runs on the LOCAL cpu
            # backend — a per-request-batch round trip to a remote/
            # tunneled accelerator would be pure latency (measured
            # ~180ms/batch through the harness tunnel).  EVERY input
            # must materialize inside this scope: one device-committed
            # operand drags the whole computation onto the tunnel.
            with jax.default_device(jax.devices("cpu")[0]):
                allow = np.array(l7_verdict_jit(
                    jnp.asarray(t.rules), jnp.asarray(rows),
                    None if pref is None else jnp.asarray(pref),
                    None if pref is None else jnp.asarray(
                        np.asarray(t.prefix_lengths, dtype=np.int32))))
        else:
            allow = np.zeros(len(raw), dtype=bool)
        matchers = t.host_matchers.get(port)
        if matchers:
            pending = np.nonzero(~allow)[0]
            self.host_fallback_checked += len(pending)
            for i in pending:
                if any(m(raw[i]) for m in matchers):
                    allow[i] = True
                    self.host_fallback_allowed += 1
        return allow.astype(np.uint8)

    def handle_http(self, port: int, requests: Sequence[dict],
                    src_row: int = 0) -> np.ndarray:
        """Verdict a batch of HTTP requests on one listener port.

        Returns [N] uint8 (1 = forward, 0 = 403)."""
        rows, raw = featurize_http(requests, port, src_row)
        allow = self._verdicts(rows, port, raw)
        now = time.time()
        self.requests_total += len(raw)
        self.requests_denied += int((allow == 0).sum())
        for i, req in enumerate(raw):
            self._emit(L7Record(
                kind=KIND_HTTP, verdict=int(allow[i]), proxy_port=port,
                src_row=src_row, timestamp=now,
                method=req.get("method", ""), path=req.get("path", ""),
                host=req.get("host", ""),
                status=200 if allow[i] else 403))
        return allow

    def handle(self, kind_name: str, port: int,
               requests: Sequence[dict],
               src_row: int = 0) -> np.ndarray:
        """Verdict requests of a PLUGIN protocol (registry.py) — the
        generic path a fourth parser rides without proxy edits."""
        from . import registry

        plugin = registry.get(kind_name)
        if plugin is None:
            raise KeyError(f"no L7 parser registered for {kind_name!r}")
        rows, raw = plugin.featurize(requests, port, src_row)
        allow = self._verdicts(rows, port, raw)
        now = time.time()
        self.requests_total += len(raw)
        self.requests_denied += int((allow == 0).sum())
        for i, req in enumerate(raw):
            m, p = plugin.record_fields(req)
            self._emit(L7Record(
                kind=plugin.kind, verdict=int(allow[i]),
                proxy_port=port, src_row=src_row, timestamp=now,
                method=m, path=p))
        return allow

    def handle_bytes(self, kind_name: str, port: int,
                     payloads: Sequence[bytes],
                     src_row: int = 0) -> np.ndarray:
        """Verdict RAW payloads of a plugin protocol that ships a
        wire parser (proxylib OnData analogue)."""
        from . import registry

        plugin = registry.get(kind_name)
        if plugin is None or plugin.parse_bytes is None:
            raise KeyError(
                f"no byte-level L7 parser registered for {kind_name!r}")
        return self.handle(kind_name, port, plugin.parse_bytes(payloads),
                           src_row)

    def handle_kafka(self, port: int, requests: Sequence[dict],
                     src_row: int = 0) -> np.ndarray:
        """Verdict Kafka requests ({api_key, topic, client_id});
        1 = forward, 0 = topic-authorization-failed."""
        from .featurize import KIND_KAFKA, featurize_kafka

        rows, raw = featurize_kafka(requests, port, src_row)
        allow = self._verdicts(rows, port, raw)
        now = time.time()
        self.requests_total += len(raw)
        self.requests_denied += int((allow == 0).sum())
        for i, req in enumerate(raw):
            self._emit(L7Record(
                kind=KIND_KAFKA, verdict=int(allow[i]),
                proxy_port=port, src_row=src_row, timestamp=now,
                method=str(req.get("api_key", "")),
                path=str(req.get("topic", ""))))
        return allow

    def handle_dns(self, port: int, qnames: Sequence[str],
                   src_row: int = 0) -> np.ndarray:
        """Verdict a batch of DNS queries (1 = forward, 0 = refused)."""
        rows, names = featurize_dns(qnames, port, src_row)
        allow = self._verdicts(rows, port, names)
        now = time.time()
        self.requests_total += len(names)
        self.requests_denied += int((allow == 0).sum())
        for i, q in enumerate(names):
            self._emit(L7Record(
                kind=KIND_DNS, verdict=int(allow[i]), proxy_port=port,
                src_row=src_row, timestamp=now, qname=q))
        return allow

    def observe_answer(self, qname: str, ips: Sequence[str],
                       ttl: int = 60) -> None:
        """Feed an observed DNS answer to the fqdn subsystem
        (reference: the DNS proxy snoops responses and updates the
        name manager -> new fqdn identities -> ipcache)."""
        name = qname.rstrip(".").lower()
        for fn in list(self._dns_observers):
            fn(name, ips, ttl)

    def _emit(self, rec: L7Record) -> None:
        for fn in list(self._records):
            fn(rec)
