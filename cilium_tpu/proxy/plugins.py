"""Built-in L7 protocol plugins: cassandra + memcached.

Reference: ``proxylib/cassandra`` (parses CQL query strings, matches
``query_action`` + ``query_table``) and ``proxylib/memcache`` (matches
command + keyExact/keyPrefix).  Each plugin here registers through the
generic seam in registry.py — NO code in featurize.py / l7policy.py /
proxy.py knows these protocols exist, which is the point: a fourth
protocol is a registration, not an edit.
"""

from __future__ import annotations

import re
from typing import Tuple

from .featurize import fnv64
from .registry import L7Protocol, featurize_generic, register

# -- cassandra ---------------------------------------------------------

# CQL actions the policy schema names (reference: proxylib/cassandra
# cassandraparser.go action table)
CQL_ACTIONS = {"select": 1, "insert": 2, "update": 3, "delete": 4,
               "create-table": 5, "drop-table": 6, "alter-table": 7,
               "truncate": 8, "use": 9, "batch": 10}

_CQL_RE = re.compile(
    r"^\s*(select|insert|update|delete|truncate|use|batch)\b"
    r"(?:.*?\b(?:from|into|update)\s+([\w.\"]+))?",
    re.IGNORECASE | re.DOTALL)


def parse_cql(query: str) -> dict:
    """CQL query string -> {action, table} (the wire-facing half;
    reference: proxylib/cassandra parses the QUERY frame body)."""
    m = _CQL_RE.match(query or "")
    if not m:
        return {}
    action = m.group(1).lower()
    table = (m.group(2) or "").replace('"', "").lower()
    if action == "update":  # UPDATE <table> SET ...
        m2 = re.match(r"\s*update\s+([\w.\"]+)", query, re.IGNORECASE)
        table = (m2.group(1).replace('"', "").lower() if m2 else table)
    return {"action": action, "table": table}


def _cass_featurize(requests, port, src_row=0):
    # requests: {action, table} (or {query} parsed on the fly)
    reqs = [parse_cql(r["query"]) if "query" in r else r
            for r in requests]
    return featurize_generic(
        CASSANDRA.kind, reqs, port, src_row,
        method_of=lambda r: CQL_ACTIONS.get(
            str(r.get("action", "")).lower(), 0),
        f0_of=lambda r: str(r.get("table", "")).lower())


# regex metacharacters EXCEPT '.', which in a table rule is the
# keyspace.table separator (the overwhelmingly common literal case);
# patterns carrying real regex operators still get regex semantics
_TABLE_REGEX_CHARS = re.compile(r"[*+?^$()\[\]{}|\\]")


def _cass_compile(rule: dict):
    """{queryAction, queryTable} -> tensor row; a regex table (like
    upstream's query_table regex) -> host matcher."""
    action = str(rule.get("queryAction") or rule.get("action") or
                 "").lower()
    table = str(rule.get("queryTable") or rule.get("table") or "")
    action_id = CQL_ACTIONS.get(action, 0) if action else 0
    literal = not _TABLE_REGEX_CHARS.search(table)
    if (action and action_id == 0) or not literal:
        table_re = re.compile(table.lower()) if table else None

        def match(req) -> bool:
            if not isinstance(req, dict):
                return False
            if "query" in req:
                req = parse_cql(req["query"])
            if action and str(req.get("action", "")).lower() != action:
                return False
            if table_re and not table_re.fullmatch(
                    str(req.get("table", "")).lower()):
                return False
            return True

        return "matcher", match
    lo, hi = fnv64(table.lower())
    return "row", [action_id, lo, hi, 0, 0]


def parse_cql_frames(payloads) -> list:
    """CQL native-protocol frames -> request dicts (the wire-facing
    half; reference: proxylib/cassandra parses the 9-byte frame
    header + QUERY long-string body).  Non-QUERY opcodes pass through
    as {} (matched by no rule -> denied under enforcing policy);
    malformed frames likewise."""
    import struct

    out = []
    for raw in payloads:
        try:
            if len(raw) < 9:
                out.append({})
                continue
            opcode = raw[4]
            if opcode != 0x07:  # QUERY
                out.append({"opcode": int(opcode)})
                continue
            (qlen,) = struct.unpack_from(">i", raw, 9)
            query = raw[13:13 + qlen].decode("utf-8", "replace")
            out.append(parse_cql(query))
        except (struct.error, IndexError):
            out.append({})
    return out


CASSANDRA = register(L7Protocol(
    name="cassandra", kind=16,
    featurize=_cass_featurize,
    compile_rule=_cass_compile,
    record_fields=lambda r: (str(r.get("action", "")),
                             str(r.get("table", ""))),
    parse_bytes=parse_cql_frames,
))

# -- memcached ---------------------------------------------------------

MEMCACHE_COMMANDS = {"get": 1, "gets": 1, "set": 2, "add": 3,
                     "replace": 4, "append": 5, "prepend": 6, "cas": 7,
                     "delete": 8, "incr": 9, "decr": 10, "touch": 11,
                     "flush_all": 12, "stats": 13}


def _mc_featurize(requests, port, src_row=0):
    return featurize_generic(
        MEMCACHED.kind, requests, port, src_row,
        method_of=lambda r: MEMCACHE_COMMANDS.get(
            str(r.get("command", "")).lower(), 0),
        f0_of=lambda r: str(r.get("key", "")))


def _mc_compile(rule: dict):
    """{command, keyExact} -> tensor row; {command, keyPrefix} ->
    host matcher (a prefix is not an exact hash)."""
    cmd = str(rule.get("command") or "").lower()
    cmd_id = MEMCACHE_COMMANDS.get(cmd, 0) if cmd else 0
    prefix = rule.get("keyPrefix")
    exact = rule.get("keyExact")
    if (cmd and cmd_id == 0) or prefix is not None:
        def match(req) -> bool:
            if not isinstance(req, dict):
                return False
            if cmd and str(req.get("command", "")).lower() != cmd:
                return False
            key = str(req.get("key", ""))
            if prefix is not None and not key.startswith(str(prefix)):
                return False
            if exact is not None and key != str(exact):
                return False
            return True

        return "matcher", match
    lo, hi = fnv64(str(exact or ""))
    return "row", [cmd_id, lo, hi, 0, 0]


def parse_memcache_lines(payloads) -> list:
    """Memcached TEXT protocol request lines -> request dicts
    (reference: proxylib/memcache; the command word + first key).
    Multi-key gets emit one dict per key is NOT done here — the
    policy unit is the request line, matching upstream's per-request
    verdict."""
    out = []
    for raw in payloads:
        try:
            line = raw.split(b"\r\n", 1)[0].decode("ascii", "replace")
            parts = line.split()
            if not parts:
                out.append({})
                continue
            cmd = parts[0].lower()
            req = {"command": cmd}
            if len(parts) > 1 and cmd in MEMCACHE_COMMANDS:
                req["key"] = parts[1]
            out.append(req)
        except (IndexError, ValueError):
            out.append({})
    return out


MEMCACHED = register(L7Protocol(
    name="memcached", kind=17,
    featurize=_mc_featurize,
    compile_rule=_mc_compile,
    record_fields=lambda r: (str(r.get("command", "")),
                             str(r.get("key", ""))),
    parse_bytes=parse_memcache_lines,
))
