"""The DNS proxy — wire-level UDP interception (reference: upstream
``pkg/fqdn/dnsproxy``).

Upstream runs a transparent DNS proxy: pod DNS queries redirect to it,
the qname is verdicted against the endpoint's ``rules.dns`` L7 policy
(matchName/matchPattern), allowed queries forward to the real
resolver, and the ANSWERS feed the fqdn cache — which mints the
identities ``toFQDNs`` selectors match.  Denied queries answer
REFUSED (rcode 5) so clients fail fast instead of timing out.

This module is the same loop over a real UDP socket: parse the query
off the wire, verdict through the compiled DNS L7 tensors
(``L7Proxy.handle_dns``), forward/refuse, parse A/AAAA answers
(including name compression) and hand them to ``observe`` — closing
the toFQDNs loop at the byte level exactly like the HTTP splice
listeners close HTTP's.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, List, Optional, Tuple

RCODE_REFUSED = 5
TYPE_A = 1
TYPE_AAAA = 28


class DNSParseError(ValueError):
    pass


def _read_name(buf: bytes, off: int, depth: int = 0
               ) -> Tuple[str, int]:
    """Decode a (possibly compressed) DNS name.  Returns (name, next
    offset); for compressed tails the returned offset is past the
    POINTER, not the target."""
    if depth > 16:
        raise DNSParseError("compression loop")
    labels: List[str] = []
    while True:
        if off >= len(buf):
            raise DNSParseError("truncated name")
        n = buf[off]
        if n == 0:
            return ".".join(labels), off + 1
        if n & 0xC0 == 0xC0:  # compression pointer
            if off + 2 > len(buf):
                raise DNSParseError("truncated pointer")
            ptr = ((n & 0x3F) << 8) | buf[off + 1]
            if ptr >= off:
                raise DNSParseError("forward pointer")
            tail, _ = _read_name(buf, ptr, depth + 1)
            return ".".join(labels + [tail]) if labels else tail, \
                off + 2
        off += 1
        if off + n > len(buf):
            raise DNSParseError("truncated label")
        labels.append(buf[off:off + n].decode("ascii",
                                              errors="replace"))
        off += n


def parse_query(buf: bytes) -> Tuple[int, str, int]:
    """-> (txn id, qname, qtype) of the FIRST question."""
    if len(buf) < 12:
        raise DNSParseError("short header")
    txid, flags, qd = struct.unpack("!HHH", buf[:6])
    if qd < 1:
        raise DNSParseError("no question")
    name, off = _read_name(buf, 12)
    if off + 4 > len(buf):
        raise DNSParseError("truncated question")
    qtype = struct.unpack("!H", buf[off:off + 2])[0]
    return txid, name.lower(), qtype


def parse_answers(buf: bytes) -> List[Tuple[str, str, int]]:
    """-> [(owner name, ip, ttl)] for every A/AAAA answer RR."""
    if len(buf) < 12:
        raise DNSParseError("short header")
    qd, an = struct.unpack("!HH", buf[4:8])
    off = 12
    for _ in range(qd):
        _, off = _read_name(buf, off)
        off += 4
    out: List[Tuple[str, str, int]] = []
    for _ in range(an):
        name, off = _read_name(buf, off)
        if off + 10 > len(buf):
            raise DNSParseError("truncated RR")
        rtype, _cls, ttl, rdlen = struct.unpack(
            "!HHIH", buf[off:off + 10])
        off += 10
        rdata = buf[off:off + rdlen]
        off += rdlen
        if rtype == TYPE_A and rdlen == 4:
            out.append((name.lower(), socket.inet_ntoa(rdata),
                        int(ttl)))
        elif rtype == TYPE_AAAA and rdlen == 16:
            out.append((name.lower(),
                        socket.inet_ntop(socket.AF_INET6, rdata),
                        int(ttl)))
    return out


def refused_response(query: bytes) -> bytes:
    """Echo the question back with QR=1 RCODE=REFUSED (what upstream's
    proxy answers for policy-denied names — fail fast, not timeout)."""
    txid = query[:2]
    # QR=1, opcode from query, RD preserved, RCODE=5
    flags = struct.unpack("!H", query[2:4])[0]
    flags = 0x8000 | (flags & 0x7900) | RCODE_REFUSED
    qd = query[4:6]
    # body: just the question section(s)
    _, off = _read_name(query, 12)
    body = query[12:off + 4]
    return txid + struct.pack("!H", flags) + qd + b"\x00\x00" * 3 \
        + body


class DNSProxyListener:
    """One DNS redirect port's UDP proxy loop.

    ``resolver`` is the upstream (host, port) queries forward to;
    ``observe`` receives (name, [ips], ttl) per allowed answer —
    wire it to ``FQDNCache.observe`` and toFQDNs selectors update
    from live traffic."""

    def __init__(self, proxy, proxy_port: int,
                 resolver: Tuple[str, int],
                 observe: Optional[Callable] = None,
                 host: str = "127.0.0.1", src_row: int = 0,
                 timeout: float = 2.0):
        self.proxy = proxy
        self.proxy_port = proxy_port
        self.resolver = resolver
        self.observe = observe
        self.src_row = src_row
        self.timeout = timeout
        self.queries = 0
        self.refused = 0
        self.errors = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, 0))
        self._sock.settimeout(0.2)
        self.address = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                buf, client = self._sock.recvfrom(4096)
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_one,
                             args=(buf, client), daemon=True).start()

    def _serve_one(self, buf: bytes, client) -> None:
        try:
            _txid, qname, _qtype = parse_query(buf)
        except DNSParseError:
            self.errors += 1
            return  # unparseable: drop silently (upstream logs+drops)
        self.queries += 1
        verdicts = self.proxy.handle_dns(self.proxy_port, [qname],
                                         self.src_row)
        if not int(verdicts[0]):
            self.refused += 1
            try:
                self._sock.sendto(refused_response(buf), client)
            except (OSError, DNSParseError):
                self.errors += 1
            return
        # forward to the real resolver, relay the answer back
        try:
            with socket.socket(socket.AF_INET,
                               socket.SOCK_DGRAM) as up:
                up.settimeout(self.timeout)
                up.sendto(buf, self.resolver)
                resp, _ = up.recvfrom(4096)
        except OSError:
            self.errors += 1
            return  # resolver unreachable: client retries
        try:
            answers = parse_answers(resp)
        except DNSParseError:
            answers = []
        if self.observe is not None:
            by_name: dict = {}
            for name, ip, ttl in answers:
                by_name.setdefault(name, ([], [0]))[0].append(ip)
                by_name[name][1][0] = max(by_name[name][1][0], ttl)
            for name, (ips, ttl_box) in by_name.items():
                self.observe(name, ips, ttl_box[0])
        try:
            self._sock.sendto(resp, client)
        except OSError:
            self.errors += 1
