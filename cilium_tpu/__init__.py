"""cilium-tpu: a TPU-native network-policy and flow-analytics framework.

A ground-up rebuild of the capabilities of Cilium's per-packet hot path
(reference: ``bpf/bpf_lxc.c`` verdict pipeline + ``pkg/hubble`` flow
parsing + the Go control plane under ``pkg/policy`` / ``pkg/identity`` /
``pkg/ipcache``) as a batched header-tensor pipeline under JAX/XLA/Pallas.

Layer map (mirrors SURVEY.md §1, re-drawn TPU-first):

- ``core``      packet/header tensor schema, pcap ingest (host side)
- ``native``    C++ host runtime (ingest parser), g++-compiled at import
- ``datapath``  the verdict pipeline + Loader seam (tpu / interpreter).
                Kernels are XLA gather/scatter programs, not pallas: the
                pipeline is gather-bound and XLA's fused gathers already
                saturate it (datapath/verdict.py); pallas is reserved
                for the day a probe kernel beats the fused gather
- ``policy``    rule schema, repository, selector cache, MapState compiler
- ``identity``  label->numeric identity allocation, reserved identities
- ``ipcache``   IP/CIDR -> identity store, compiled to DIR-24-8 tensors
- ``flow``      hubble-equivalent: threefour parser, observer, metrics
- ``monitor``   event vocabulary (drop/trace/policy-verdict) + agent
- ``ml``        learned flow classifier (embedding from identity labels)
- ``parallel``  device-mesh sharding of batch + replicated tables
- ``kvstore``   in-memory kvstore + distributed allocator
- ``api``/``cli`` REST-ish control API and cilium-style CLI
- ``utils``     controller/trigger/eventqueue/logging/metrics/config
"""

__version__ = "0.1.0"
