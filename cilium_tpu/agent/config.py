"""Config resolution: defaults < config-dir files < env < flags.

Reference: upstream cilium's option system (``pkg/option`` +
``pkg/defaults``): ~300 viper/cobra flags whose values resolve from
CLI flags, environment (``CILIUM_*``), and a config directory — in
k8s, the ``cilium-config`` ConfigMap mounted as one file per key.
This module gives :class:`~cilium_tpu.agent.daemon.DaemonConfig` the
same resolution order; the flag registry derives from the dataclass
fields so a new config field is automatically a flag, an env var, and
a config-dir key.
"""

from __future__ import annotations

import dataclasses
import os
import typing
from typing import Dict, Optional, Tuple

from .daemon import DaemonConfig

ENV_PREFIX = "CILIUM_TPU_"

# CILIUM_TPU_* vars that are NOT DaemonConfig flags: debug/harness
# switches read directly by other modules (infra/lockdebug.py,
# __graft_entry__.py).  The env loop must skip them — a documented
# debug var crashing `daemon run` with "unknown config option" is
# worse than the typo it guards against.
ENV_NON_CONFIG = {"LOCKDEBUG", "DRYRUN_CHILD", "CIC_PCAP",
                  "CIC_LABELS"}

_TRUE = {"true", "1", "yes", "on"}
_FALSE = {"false", "0", "no", "off"}


def _cast_for(tp):
    """Build a string-parser for one DaemonConfig field from its
    RESOLVED type (Optional[X] unwraps to X; tuples split on
    commas)."""
    origin = typing.get_origin(tp)
    args = typing.get_args(tp)
    if origin is typing.Union and type(None) in args:  # Optional[X]
        inner = [a for a in args if a is not type(None)][0]
        base = _scalar_cast(inner)

        def cast(raw: str):
            s = str(raw).strip()
            return None if s in ("", "none", "None") else base(s)

        return cast
    if origin in (tuple, Tuple):
        return lambda raw: tuple(
            s.strip() for s in str(raw).split(",") if s.strip())
    return _scalar_cast(tp)


def _scalar_cast(tp):
    if tp is bool:
        def cast(raw: str) -> bool:
            s = str(raw).strip().lower()
            if s in _TRUE:
                return True
            if s in _FALSE:
                return False
            raise ValueError(f"not a boolean: {raw!r}")

        return cast
    if tp in (int, float, str):
        return tp
    return str


def flag_registry() -> Dict[str, tuple]:
    """kebab-case flag name -> (attr, cast) for every DaemonConfig
    field (the viper-registry analogue).  Types resolve through
    ``typing.get_type_hints`` so a NEW field's annotation (whatever it
    is) parses correctly without touching this module."""
    hints = typing.get_type_hints(DaemonConfig)
    out: Dict[str, tuple] = {}
    for f in dataclasses.fields(DaemonConfig):
        out[f.name.replace("_", "-")] = (f.name,
                                         _cast_for(hints[f.name]))
    return out


def load_config(config_dir: Optional[str] = None,
                env: Optional[Dict[str, str]] = None,
                **overrides) -> DaemonConfig:
    """Resolve a DaemonConfig.

    Order (weakest first): dataclass defaults, then one-file-per-key
    ``config_dir`` entries (the mounted-ConfigMap layout; file name =
    flag name, content = value), then ``CILIUM_TPU_<NAME>`` env vars,
    then explicit keyword ``overrides`` (CLI flags).  Unknown config
    keys raise — a typo'd option must not silently fall back to its
    default (upstream: viper unknown-flag error)."""
    registry = flag_registry()
    values: Dict[str, object] = {}

    def apply(flag: str, raw, source: str):
        spec = registry.get(flag)
        if spec is None:
            raise ValueError(f"unknown config option {flag!r} "
                             f"(from {source})")
        attr, cast = spec
        try:
            values[attr] = cast(raw)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"bad value for {flag!r} (from {source}): {e}") from None

    if config_dir and os.path.isdir(config_dir):
        for name in sorted(os.listdir(config_dir)):
            path = os.path.join(config_dir, name)
            if name.startswith(".") or not os.path.isfile(path):
                continue  # ConfigMap mounts hide ..data symlink dirs
            with open(path) as f:
                apply(name.strip(), f.read().strip(),
                      f"config-dir {path}")
    for key, raw in (env if env is not None else os.environ).items():
        if not key.startswith(ENV_PREFIX):
            continue
        if key[len(ENV_PREFIX):] in ENV_NON_CONFIG:
            continue
        flag = key[len(ENV_PREFIX):].lower().replace("_", "-")
        # a CILIUM_TPU_* var naming no flag is a typo (MASQUERDE=true
        # silently doing nothing is the failure mode this loader
        # exists to prevent), same as the config-dir/override layers
        apply(flag, raw, f"env {key}")
    for key, raw in overrides.items():
        flag = key.replace("_", "-")
        spec = registry.get(flag)
        if spec is None:
            raise ValueError(f"unknown config option {flag!r} "
                             "(from overrides)")
        # overrides arrive typed (CLI layer already parsed) OR as
        # strings; cast only strings
        attr, cast = spec
        values[attr] = cast(raw) if isinstance(raw, str) else raw

    return DaemonConfig(**values)
