"""EndpointManager: registry + the regeneration pipeline.

Reference: upstream cilium ``pkg/endpointmanager`` (registry, bulk
regeneration triggers) + the regeneration flow of
``pkg/endpoint/bpf.go`` (SURVEY.md §3.3): policy resolve ->
policy-map/datapath update.

TPU-first: all endpoints on the node share one compiled tensor set, so
regeneration is: resolve one EndpointPolicy per DISTINCT subject
identity (the distillery/PolicyCache sharing), assemble the policy
list + endpoint->row map + ipcache view, and swap via the Loader.
Bursts coalesce through a Trigger.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..datapath.loader import Loader
from ..infra.trigger import Trigger
from ..ipcache import IPCache
from ..labels import LabelSet
from ..policy.compiler import IdentityRowMap
from ..policy.repository import PolicyRepository
from .endpoint import Endpoint, EndpointState


class EndpointManager:
    def __init__(self, repo: PolicyRepository, ipcache: IPCache,
                 loader: Loader, row_capacity: int = 1 << 14):
        self._lock = threading.RLock()
        self._endpoints: Dict[int, Endpoint] = {}
        self._next_id = 1
        self.repo = repo
        self.ipcache = ipcache
        self.loader = loader
        self.row_capacity = row_capacity
        self.regenerations = 0
        repo.peer_named_ports_getter = self.named_ports_multimap
        # persistent identity->row map: rows are stable across identity
        # churn so incremental tensor patches address the same row the
        # attached tensors were compiled with (rows are never reused;
        # released identities leave unreferenced rows behind)
        self.row_map = IdentityRowMap(capacity=row_capacity)
        self._attached_policies: List = []
        self._attach_hooks: List = []  # fn(policies) after every attach
        self._ep_hooks: List = []  # fn(kind, ep) on add/remove
        self._regen_trigger = Trigger(self._regenerate_all,
                                      name="endpoint-regeneration")
        self._event_options_cache: Optional[Dict] = None

    def named_ports_multimap(self) -> Dict[str, frozenset]:
        """name -> EVERY port number bound to that name by any
        endpoint (the NamedPortMultiMap analogue).  Egress rules with
        named ports expand over all bindings — the destination could
        be any pod, and last-registered-wins would silently judge one
        endpoint under another's port."""
        out: Dict[str, set] = {}
        with self._lock:
            for ep in self._endpoints.values():
                for name, port in ep.named_ports.items():
                    out.setdefault(name, set()).add(int(port))
        return {n: frozenset(s) for n, s in out.items()}

    def on_attach(self, fn) -> None:
        """Register fn(policies), called after every successful attach
        (the L7 proxy re-syncs its listeners here, the way pkg/proxy
        updates redirects on endpoint regeneration)."""
        self._attach_hooks.append(fn)

    def on_endpoint_change(self, fn) -> None:
        """Register fn(kind, ep) for endpoint add/remove (clustermesh
        publishes endpoint IPs here)."""
        self._ep_hooks.append(fn)

    def _fire_ep(self, kind: str, ep: Endpoint) -> None:
        for fn in list(self._ep_hooks):
            fn(kind, ep)

    # -- registry ----------------------------------------------------
    def add(self, name: str, ips: Tuple[str, ...], labels: LabelSet,
            ep_id: Optional[int] = None,
            named_ports: Optional[Dict[str, int]] = None,
            restoring: bool = False,
            defer_regen: bool = False,
            enforcement: str = "default",
            options: Optional[Dict[str, bool]] = None) -> Endpoint:
        """``ep_id`` pins a checkpointed id on restore so COL_EP
        tagging, policy rows, and the CT snapshot stay coherent.
        ``named_ports`` (name -> number) feeds the policy resolver's
        named-port registry.  ``restoring`` marks checkpoint-restore
        endpoints (state RESTORING until their first regeneration);
        ``defer_regen`` lets the restore loop batch one regeneration
        for all endpoints instead of one each.  ``enforcement`` /
        ``options`` restore per-endpoint config (checkpoint round
        trip)."""
        from ..datapath.verdict import MAX_ENDPOINTS
        from ..policy.resolve import ENFORCEMENT_MODES

        if enforcement not in ENFORCEMENT_MODES:
            raise ValueError(f"enforcement mode {enforcement!r} not "
                             f"in {ENFORCEMENT_MODES}")
        with self._lock:
            if ep_id is None:
                ep_id = self._next_id
            elif ep_id in self._endpoints:
                raise ValueError(f"endpoint id {ep_id} already in use")
            if not 0 < ep_id < MAX_ENDPOINTS:
                raise ValueError(
                    f"endpoint id {ep_id} out of range (1.."
                    f"{MAX_ENDPOINTS - 1}); the ep_policy table is "
                    f"fixed at {MAX_ENDPOINTS} rows")
            self._next_id = max(self._next_id, ep_id + 1)
            ep = Endpoint(id=ep_id, name=name, ips=tuple(ips),
                          labels=labels,
                          named_ports=dict(named_ports or {}),
                          enforcement=enforcement)
            if options:
                ep.options.update({k: bool(v)
                                   for k, v in options.items()
                                   if k in ep.options})
            if restoring:
                ep.state = EndpointState.RESTORING
            self._endpoints[ep_id] = ep
            self._event_options_cache = None
        try:
            ident = self.repo.allocator.allocate(labels)
        except Exception:
            # kvstore outage / id-space pressure: the endpoint exists
            # but cannot enforce yet — it waits (reference: the
            # waiting-for-identity endpoint state) and the retry
            # controller re-attempts until allocation succeeds
            ep.state = EndpointState.WAITING_FOR_IDENTITY
            return ep
        self._bind_identity(ep, ident)
        self._fire_ep("add", ep)
        if not defer_regen:
            self.regenerate()
        return ep

    def _bind_identity(self, ep: Endpoint, ident) -> None:
        ep.identity = ident
        for ip in ep.ips:
            suffix = "/128" if ":" in ip else "/32"
            self.ipcache.upsert(ip + suffix, ident.numeric_id,
                                source="endpoint")
        if ep.named_ports:
            # named-port bindings change what rules resolve to; cached
            # resolutions at the current revision are stale
            self.repo.invalidate()

    def retry_pending_identities(self) -> int:
        """Re-attempt allocation for waiting-for-identity endpoints;
        returns how many advanced (controller-driven)."""
        with self._lock:
            pending = [ep for ep in self._endpoints.values()
                       if ep.identity is None
                       and ep.state == EndpointState.WAITING_FOR_IDENTITY]
        advanced = 0
        for ep in pending:
            try:
                ident = self.repo.allocator.allocate(ep.labels)
            except Exception:
                continue
            self._bind_identity(ep, ident)
            # the add-time hook was skipped while waiting (no identity
            # to publish); fire it now so clustermesh/watchers see the
            # endpoint exactly once it can enforce
            self._fire_ep("add", ep)
            advanced += 1
        if advanced:
            self.regenerate()
        return advanced

    def remove(self, ep_id: int) -> bool:
        with self._lock:
            ep = self._endpoints.pop(ep_id, None)
            self._event_options_cache = None
        if ep is None:
            return False
        ep.state = EndpointState.DISCONNECTING
        for ip in ep.ips:
            suffix = "/128" if ":" in ip else "/32"
            self.ipcache.delete(ip + suffix)
        if ep.identity is not None:
            self.repo.allocator.release(ep.identity)
        if ep.named_ports:
            self.repo.invalidate()
        self._fire_ep("remove", ep)
        self.regenerate()
        return True

    def update_config(self, ep_id: int,
                      enforcement: Optional[str] = None,
                      options: Optional[Dict[str, bool]] = None) -> bool:
        """PATCH /endpoint/{id}/config: change the enforcement mode
        and/or runtime options.  A mode change regenerates through
        the shared trigger (synchronous when idle; folded into the
        in-flight run otherwise — never two interleaved
        regenerations); option changes are host-side event filters
        and need no regen."""
        from ..policy.resolve import ENFORCEMENT_MODES

        # validate EVERYTHING before applying anything: a bad mode
        # must not leave options half-applied behind a 400 (same
        # stage-then-apply rule as Daemon.patch_config)
        if enforcement is not None and enforcement not in \
                ENFORCEMENT_MODES:
            raise ValueError(f"enforcement mode {enforcement!r} not "
                             f"in {ENFORCEMENT_MODES}")
        with self._lock:
            ep = self._endpoints.get(ep_id)
            if ep is None:
                return False
            if options:
                unknown = set(options) - set(ep.options)
                if unknown:
                    raise ValueError(f"unknown endpoint options "
                                     f"{sorted(unknown)}")
                ep.options.update({k: bool(v) for k, v in options.items()})
            mode_changed = (enforcement is not None
                            and enforcement != ep.enforcement)
            if mode_changed:
                ep.enforcement = enforcement
            self._event_options_cache = None
        if mode_changed:
            self._regen_trigger.trigger()
        return True

    def event_options(self) -> Dict[int, Dict[str, bool]]:
        """{ep_id: options} for endpoints with NON-DEFAULT options —
        the monitor's per-endpoint event filter input.  Cached (and
        invalidated on add/remove/update_config) so the per-batch hot
        path is one attribute read in the all-default case."""
        cached = self._event_options_cache
        if cached is not None:
            return cached
        out: Dict[int, Dict[str, bool]] = {}
        with self._lock:
            for ep in self._endpoints.values():
                if (ep.options.get("Debug")
                        or not ep.options.get("DropNotification", True)
                        or not ep.options.get("TraceNotification", True)):
                    out[ep.id] = dict(ep.options)
            self._event_options_cache = out
        return out

    def get(self, ep_id: int) -> Optional[Endpoint]:
        with self._lock:
            return self._endpoints.get(ep_id)

    def list(self) -> List[Endpoint]:
        with self._lock:
            return sorted(self._endpoints.values(), key=lambda e: e.id)

    def lookup_by_ip(self, ip: str) -> Optional[Endpoint]:
        with self._lock:
            for ep in self._endpoints.values():
                if ip in ep.ips:
                    return ep
        return None

    # -- regeneration ------------------------------------------------
    def regenerate(self) -> None:
        """Trigger regeneration (coalesces bursts)."""
        self._regen_trigger.trigger()

    def _regenerate_all(self) -> None:
        with self._lock:
            # endpoints without an identity cannot enforce yet: they
            # keep waiting (their state machine advances when the
            # retry controller lands an allocation)
            eps = [ep for ep in self._endpoints.values()
                   if ep.identity is not None]
        for ep in eps:
            ep.state = EndpointState.REGENERATING
        revision = self.repo.revision
        # distillery: one resolved policy per distinct (subject
        # identity, enforcement mode) — non-default modes derive their
        # own variant from the shared resolve (pkg/policy distillery +
        # pkg/option per-endpoint enforcement)
        from ..policy.resolve import with_enforcement

        policies = []
        row_of: Dict[tuple, int] = {}
        ep_policy: Dict[int, int] = {}
        resolved: Dict[tuple, object] = {}
        for ep in eps:
            # named ports resolve PER ENDPOINT (reference: container
            # ports belong to the pod) — the distillery key carries the
            # bindings, so only endpoints that actually differ split
            np_key = tuple(sorted(ep.named_ports.items()))
            lkey = (ep.labels.sorted_key(), np_key)
            key = (lkey, ep.enforcement)
            if key not in row_of:
                if lkey not in resolved:
                    resolved[lkey] = self.repo.resolve(
                        ep.labels, named_ports=ep.named_ports)
                row_of[key] = len(policies)
                policies.append(with_enforcement(resolved[lkey],
                                                 ep.enforcement))
            ep_policy[ep.id] = row_of[key]
            ep.policy_row = row_of[key]
        if not policies:
            # no endpoints: an empty permissive policy keeps the
            # datapath well-formed
            policies = [self.repo.resolve(LabelSet.parse("reserved:init"))]
        for ident in self.repo.allocator.all_identities():
            self.row_map.add(ident.numeric_id)
        self.loader.attach(policies, self.ipcache.to_identity_map(),
                           ep_policy, self.row_map)
        with self._lock:
            self._attached_policies = policies
        for fn in list(self._attach_hooks):
            fn(policies)
        for ep in eps:
            ep.state = EndpointState.READY
            ep.policy_revision = revision
        self.regenerations += 1

    # -- incremental identity churn (SURVEY.md §7 hard part #3) -------
    def patch_identity(self, kind: str, ident) -> bool:
        """Apply one identity add/remove as an in-place tensor patch
        (no re-resolve, no recompile, no re-attach).  Returns False
        when the caller must fall back to full regeneration."""
        from ..policy.incremental import update_contributions

        with self._lock:
            policies = self._attached_policies
        if not policies:
            return False
        # peer sets first (keeps the oracle/MapState view and any later
        # full recompile consistent with the patched tensors) ...
        update_contributions(policies, kind, ident.numeric_id,
                             ident.labels)
        # ... then the device row
        return self.loader.patch_identity(kind, ident.numeric_id,
                                          policies)

    def patch_ipcache(self, cidr: str, numeric_id: int) -> bool:
        return self.loader.patch_ipcache(cidr, numeric_id)
