"""Mutual authentication manager (reference: upstream ``pkg/auth``,
cilium 1.14+).

Upstream flow: a policy entry carrying ``authentication.mode:
required`` makes the datapath drop un-authenticated NEW flows with
``DROP_POLICY_AUTH_REQUIRED`` and queue an auth request; the agent's
auth manager runs a mutual-TLS handshake between the two identities'
SPIFFE certificates (SPIRE-issued) and writes the negotiated
expiration into the BPF authmap; retried traffic forwards until the
entry expires, and a GC job sweeps expired/orphaned entries.

Here the same loop rides the batch world: the daemon hands every
``REASON_AUTH_REQUIRED`` drop batch to :meth:`AuthManager.observe`,
the configured provider performs the handshake (the default validates
both identities against the live allocator — the certificate-issuance
analogue in a sandbox with no SPIRE; providers are pluggable exactly
so a real mTLS implementation can slot in), and the grant lands in
the loader's auth table (``Loader.auth_upsert``) keyed (subject
identity, remote identity) with ``now + ttl``.  Failed handshakes are
counted and retried no sooner than ``retry_s``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np


class AuthError(Exception):
    """Handshake failure (unknown identity, provider refusal)."""


class MutualAuthProvider:
    """The default provider: both identities must be LIVE in the
    allocator (the 'both sides hold a valid certificate' check —
    identity liveness is what SPIRE attestation derives from here).
    Reserved identities (world, host...) hold no workload certificate
    upstream and fail the handshake."""

    name = "mutual-identity"

    def __init__(self, allocator, ttl: int = 3600):
        self.allocator = allocator
        self.ttl = ttl

    def handshake(self, subject_id: int, remote_id: int) -> int:
        from ..identity import RESERVED_LABELSETS

        for num in (subject_id, remote_id):
            if num in RESERVED_LABELSETS:
                raise AuthError(
                    f"identity {num} is reserved: no workload "
                    "certificate to handshake with")
            if self.allocator.lookup_by_id(num) is None:
                raise AuthError(f"identity {num} unknown to the "
                                "allocator (no live certificate)")
        return self.ttl


class DenyAuthProvider:
    """Test/fail-safe provider: every handshake fails."""

    name = "deny"

    def __init__(self, *_a, **_kw):
        pass

    def handshake(self, subject_id: int, remote_id: int) -> int:
        raise AuthError("auth provider denies all handshakes")


class AuthManager:
    """Observes AUTH_REQUIRED drops, handshakes, grants.

    ``observe`` is synchronous by design: the batch that dropped is
    gone either way (upstream drops too while the handshake runs);
    the grant is live before the next batch, which is this world's
    'retried traffic forwards'."""

    def __init__(self, daemon, provider=None, retry_s: int = 30):
        self.daemon = daemon
        self.provider = provider or MutualAuthProvider(
            daemon.allocator, ttl=daemon.config.auth_ttl)
        self.retry_s = retry_s
        self.granted = 0
        self.failed = 0
        self.deferred = 0  # handshake OK but device apply deferred
        self._lock = threading.Lock()
        # (ep, remote) -> earliest retry time, for failed handshakes
        self._backoff: Dict[Tuple[int, int], int] = {}

    def observe(self, ev, now: int) -> int:
        """Handshake every distinct (endpoint, remote identity) pair
        that dropped AUTH_REQUIRED in this batch.  Returns grants."""
        from ..core.packets import COL_EP
        from ..datapath.verdict import REASON_AUTH_REQUIRED

        rows = np.flatnonzero(ev.reason == REASON_AUTH_REQUIRED)
        if rows.size == 0:
            return 0
        pairs = {(int(ev.hdr[i, COL_EP]), int(ev.identity[i]))
                 for i in rows}
        n = 0
        for ep_id, remote in sorted(pairs):
            if self._grant(ep_id, remote, now):
                n += 1
        return n

    def _grant(self, ep_id: int, remote: int, now: int) -> bool:
        with self._lock:
            if self._backoff.get((ep_id, remote), 0) > now:
                return False
        ep = self.daemon.endpoints.get(ep_id)
        subject = ep.identity.numeric_id if ep is not None else 0
        try:
            ttl = self.provider.handshake(subject, remote)
        except AuthError:
            with self._lock:
                self.failed += 1
                self._backoff[(ep_id, remote)] = now + self.retry_s
            return False
        ok = self.daemon.loader.auth_upsert(ep_id, remote, now + ttl)
        with self._lock:
            if ok:
                self.granted += 1
                self._backoff.pop((ep_id, remote), None)
            else:
                # handshake succeeded but the loader could not apply
                # (endpoint/identity row gone or not yet attached):
                # damp retries like a failure, count separately
                self.deferred += 1
                self._backoff[(ep_id, remote)] = now + self.retry_s
        return ok

    def gc(self, now: int) -> int:
        """Sweep expired grants + stale backoff entries (upstream:
        the authmap GC job)."""
        with self._lock:
            for k in [k for k, t in self._backoff.items() if t <= now]:
                del self._backoff[k]
        return self.daemon.loader.auth_gc(now)

    def status(self) -> dict:
        with self._lock:
            return {"provider": self.provider.name,
                    "granted": self.granted, "failed": self.failed,
                    "deferred": self.deferred,
                    "pending-backoff": len(self._backoff)}
