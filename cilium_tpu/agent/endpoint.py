"""Endpoint: one managed workload and its lifecycle state machine.

Reference: upstream cilium ``pkg/endpoint`` — an endpoint owns its
identity, datapath config, and policy realization, moving through
restoring -> waiting-for-identity -> regenerating -> ready (SURVEY.md
§2b).  Regeneration itself is centralized in the EndpointManager here
(the whole node shares one set of device tensors, so "regenerate" is a
node-level tensor swap, not a per-endpoint program compile).
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..labels import LabelSet
from ..identity.identity import Identity


class EndpointState(str, enum.Enum):
    # reference: pkg/endpoint state constants
    WAITING_FOR_IDENTITY = "waiting-for-identity"
    REGENERATING = "regenerating"
    READY = "ready"
    DISCONNECTING = "disconnecting"
    RESTORING = "restoring"


@dataclass
class Endpoint:
    id: int
    name: str
    ips: Tuple[str, ...]
    labels: LabelSet
    identity: Optional[Identity] = None
    state: EndpointState = EndpointState.WAITING_FOR_IDENTITY
    policy_revision: int = 0  # realized revision
    created_at: float = field(default_factory=time.time)
    policy_row: int = 0  # row into the loader's policy list
    # container port names (reference: pod spec containerPort names;
    # named ports in policy resolve against these)
    named_ports: Dict[str, int] = field(default_factory=dict)
    # policy enforcement mode (reference: pkg/option per-endpoint
    # PolicyEnforcement): "default" | "always" | "never"
    enforcement: str = "default"
    # per-endpoint runtime options (reference: pkg/option endpoint
    # options Debug / DropNotification / TraceNotification).  Debug
    # exempts this endpoint from monitor trace aggregation.
    options: Dict[str, bool] = field(default_factory=lambda: {
        "Debug": False,
        "DropNotification": True,
        "TraceNotification": True,
    })

    def to_dict(self) -> dict:
        """API rendering (GET /endpoint/{id})."""
        return {
            "id": self.id,
            "name": self.name,
            "ips": list(self.ips),
            "labels": [str(l) for l in self.labels],
            "identity": (self.identity.numeric_id if self.identity
                         else None),
            "state": self.state.value,
            "policy-revision": self.policy_revision,
            "policy-enforcement": self.enforcement,
            "options": dict(self.options),
            **({"named-ports": dict(self.named_ports)}
               if self.named_ports else {}),
        }
