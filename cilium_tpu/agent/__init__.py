"""The agent: per-node control plane (the cilium-agent analogue).

Reference: upstream cilium ``daemon/`` + ``pkg/endpoint`` +
``pkg/endpointmanager`` — process lifecycle, endpoint state machines,
policy regeneration, and the wiring of every subsystem (SURVEY.md
§3.1/§3.3 call stacks).
"""

from .endpoint import Endpoint, EndpointState  # noqa: F401
from .endpointmanager import EndpointManager  # noqa: F401
from .daemon import Daemon, DaemonConfig  # noqa: F401
