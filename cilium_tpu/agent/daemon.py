"""Daemon: the full agent wiring (NewDaemon, SURVEY.md §3.1).

Reference: upstream cilium ``daemon/cmd`` — config parse, state
restore, identity allocator, policy repository, datapath init,
endpoint restore/regeneration, monitor + Hubble, API serve.

Lifecycle here: construct -> (optionally) ``restore(dir)`` ->
add endpoints / import policy -> ``process_batch`` per packet tensor ->
``checkpoint(dir)`` on shutdown.  Background work (CT GC) runs in
named controllers; identity churn invalidates resolve caches and
coalesces into one regeneration (the SelectorCache-notification
analogue).
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..datapath.loader import InterpreterLoader, Loader, TPULoader
from ..flow import FlowExporter, FlowMetrics, Observer, ThreeFourParser
from ..identity.allocator import CachingIdentityAllocator
from ..infra.controller import ControllerManager
from ..ipcache import IPCache
from ..kvstore import InMemoryKVStore
from ..labels import LabelSet, SOURCE_CIDR
from ..monitor import MonitorAgent, decode_out
from ..monitor.api import EventBatch
from ..policy.api import rule_to_dict
from ..policy.repository import PolicyRepository
from .endpoint import Endpoint
from .endpointmanager import EndpointManager

VERSION = "0.1.0"


@dataclass
class DaemonConfig:
    """Reference: pkg/option.DaemonConfig (the ~300 viper flags; the
    subset that matters here)."""

    node_name: str = "node0"
    backend: str = "tpu"  # "tpu" | "interpreter"
    ct_capacity: int = 1 << 20
    ct_gc_interval: float = 30.0
    flow_ring_capacity: int = 4096
    export_path: Optional[str] = None
    state_dir: Optional[str] = None
    enable_hubble: bool = True
    anomaly_model_path: Optional[str] = None  # trained AnomalyModel .npz
    anomaly_threshold: float = 0.8
    fqdn_gc_interval: float = 15.0  # pkg/fqdn TTL sweep cadence
    # gRPC Observer address ("unix:///run/hubble.sock" or "host:port");
    # None = in-process only (REST /flows still serves)
    hubble_listen: Optional[str] = None
    # AF_UNIX path of this agent's REST API socket; advertised in the
    # node registry so peers' health meshes can probe it
    api_socket_path: Optional[str] = None
    health_probe_interval: float = 10.0
    # mutual authentication (pkg/auth): the manager observes
    # AUTH_REQUIRED drops and handshakes via the provider; TTL is the
    # grant lifetime (upstream: derived from certificate expiry)
    mesh_auth: bool = True
    auth_ttl: int = 3600
    auth_gc_interval: float = 30.0
    # transparent encryption (pkg/wireguard analogue): node keypair
    # published via the node registry; node-to-node batch transport
    # seals with ChaCha20-Poly1305 (cilium_tpu/encryption)
    enable_encryption: bool = False
    encryption_key_path: Optional[str] = None
    # egress masquerade (bpf/lib/nat.h analogue; service/nat.py)
    masquerade: bool = False
    node_ip: Optional[str] = None
    # additional node addresses nodePort frontends bind, beyond
    # node_ip (reference: --nodeport-addresses)
    nodeport_addresses: Tuple[str, ...] = ()
    non_masquerade_cidrs: Tuple[str, ...] = ("10.0.0.0/8",)
    # identity value-ref lease (reference: etcd lease on pkg/allocator
    # slave keys): None = unleased refs (single-process tests); set it
    # when the kvstore is networked so a crashed agent's refs expire
    # and identity GC can sweep.  A keepalive controller refreshes at
    # ttl/3.
    identity_lease_ttl: Optional[float] = None
    # policy-audit-mode (reference: --policy-audit-mode): policy
    # denials forward while verdict events keep the would-be reason
    policy_audit_mode: bool = False
    # monitor trace aggregation (reference: --monitor-aggregation):
    # "none" emits a TraceNotify per forwarded packet; "medium" only
    # for flow-state-changing packets (non-TCP, or TCP SYN/FIN/RST).
    # Per-endpoint Debug=True exempts an endpoint from aggregation.
    monitor_aggregation: str = "none"
    # serving front end (cilium_tpu/serving; the XDP/RSS + per-CPU
    # ring analogue).  Validated at construction — see
    # serving.validate_serving_config for the rules.
    # admission queue capacity in PACKETS; overflow sheds by policy
    serving_queue_depth: int = 1 << 16
    # power-of-two padding buckets, strictly ascending: each distinct
    # batch shape is one XLA compile, so the ladder bounds recompiles
    serving_bucket_ladder: Tuple[int, ...] = (1024, 4096, 16384,
                                              65536)
    # max microseconds a queued packet waits before a partial bucket
    # flushes (tail-latency bound at low load)
    serving_max_wait_us: float = 2000.0
    # "drop-tail" (arriving overflow sheds) | "drop-oldest" (stale
    # queued rows shed to admit the arrival)
    serving_overflow_policy: str = "drop-tail"
    # ship eligible IPv4 single-stream batches as the packed
    # 16 B/packet h2d wire format (core/packets.py PACKED_*) instead
    # of wide 64 B/packet rows; ineligible traffic (IPv6, mixed
    # ep/dir streams) keeps the wide fallback shape either way.
    # start_serving(packed=...) overrides per session.
    serving_packed_ingest: bool = False
    # K-batch superbatch dispatch (ISSUE 11): fuse up to K ready
    # batches into ONE device dispatch — a lax.scan runs datapath +
    # ring append for all K steps, so the drain loop's per-dispatch
    # Python cost (lock window, arena bookkeeping, one jit call) is
    # paid once per K batches.  Power of two; 1 disables.  K is a
    # fallback-ladder rung property: demotion shrinks K before it
    # would ever change mode.  Interaction with serving_max_wait_us:
    # assembly never WAITS for K batches — it takes what is already
    # queued (single-batch fallback below two full buckets), so tail
    # latency at low offered load is unchanged; the tradeoff it DOES
    # buy is generation-pinning latency (one dispatch pins one table
    # generation for K batches — BENCH_churn measures it at K>1).
    # start_serving(superbatch_k=...) overrides per session.
    serving_superbatch_k: int = 1
    # -- the async event plane (serving/eventplane.py).  How many
    # drain windows the event-join worker's bounded queue may hold;
    # overflow drops the OLDEST offered window, counted
    # (windows-dropped), never silently.  Also sizes the batcher
    # arena's recycling horizon: header slots must outlive every
    # window in flight on the worker, so arena memory scales with
    # (depth + 2) * drain_every slots per bucket shape
    serving_window_queue_depth: int = 4
    # -- the L7 proxy plane (serving/l7plane.py + proxy/worker.py):
    # redirected rows fan out of the event-join worker into a bounded
    # pool of L7 workers (upstream: the Envoy/proxylib userspace
    # proxy).  Worker count and task-queue depth; overflow sheds the
    # OLDEST queued task, counted l7_shed, never silently.  The pool
    # shares serving_restart_budget for its restart-on-death budget
    l7_workers: int = 2
    l7_queue_depth: int = 128
    # occupancy-bounded ring drain: fetch a power-of-two-rung device
    # GATHER of just the window's occupied slots instead of the full
    # ring — d2h bytes scale with events appended, not ring capacity.
    # False falls back to the full-capacity copy (the pre-PR5 wire)
    serving_event_gather: bool = True
    # -- serving fault tolerance (cilium_tpu/serving runtime watchdog
    # + degraded-mode ladder; the cilium-health / endpoint-
    # regeneration analogue for the serving plane).  Validated at
    # construction like the knobs above.
    # per-batch dispatch deadline in ms; a dispatch exceeding it is
    # declared hung, its rows counted as REASON_DISPATCH_TIMEOUT
    # drops, and the drain loop restarted.  0 disables hang detection
    serving_dispatch_deadline_ms: float = 1000.0
    # how many drain-loop restarts the watchdog may spend before the
    # runtime goes terminal (0 disables supervision entirely: a dead
    # drain loop stays a visible corpse, the pre-PR3 behavior)
    serving_restart_budget: int = 8
    # initial restart backoff in ms (doubles per consecutive restart,
    # capped at 1s; resets after a healthy interval)
    serving_restart_backoff_ms: float = 10.0
    # consecutive dispatch failures on one ladder rung before the
    # serving session demotes (sharded -> single-chip -> wide); one
    # success resets the streak
    serving_demote_threshold: int = 3
    # consecutive healthy batches before a degraded session promotes
    # one rung back up...
    serving_promote_after: int = 64
    # ...and the minimum seconds since the last rung change (the
    # hysteresis half: a flapping shard burns a full cooldown per
    # re-promotion attempt)
    serving_promote_cooldown_s: float = 5.0
    # periodic CT snapshot cadence in seconds (0 = only on demotion /
    # checkpoint): the last snapshot rides recovery paths where the
    # live device CT is unreadable, so a loader rebuild keeps
    # established flows
    ct_snapshot_interval: float = 0.0
    # deterministic fault injection (infra/faults.py spec string,
    # e.g. "serving.dispatch=1x1~0.3"); armed process-global at
    # construction, disarmed on shutdown.  For chaos testing — leave
    # None in production
    fault_injection: Optional[str] = None
    fault_seed: int = 0
    # -- observability (cilium_tpu/obs; the Hubble/pkg/monitor-depth
    # introspection layer for the serving plane).
    # sampled per-packet trace spans: 1-in-N admitted packets get a
    # span carried admission -> batcher -> staging -> dispatch ->
    # verdict join with six monotonic stage timestamps (GET
    # /debug/traces, `cilium-tpu trace`).  0 = off = zero overhead
    serving_trace_sample: int = 0
    # jax.profiler capture window: trace the first profile_batches
    # serving dispatches into this directory, then stop (viewable in
    # TensorBoard/Perfetto).  None = off
    profile_dir: Optional[str] = None
    profile_batches: int = 16
    # -- flow analytics plane (obs/analytics.py): windowed
    # per-identity aggregation, top-K talkers, drop-spike detection
    # over the decoded event stream.  The aggregation work runs on
    # the event-join worker and query threads, NEVER the serving
    # drain thread (publishing threads only pay an O(1) reference
    # park) — disabling it removes already-off-path work only
    flow_agg_enabled: bool = True
    # rolling window width in seconds, and how many CLOSED windows
    # the ring-of-windows retains behind the open one
    flow_agg_window_s: float = 1.0
    flow_agg_windows: int = 8
    # space-saving sketch capacity K (top talkers by flow 4-tuple
    # and by identity pair): any key with true count > N/K is
    # guaranteed retained, every estimate overshoots by <= N/K
    flow_agg_topk: int = 32
    # decoded batches parked between monitor publish and the
    # worker-side drain; overflow drops the OLDEST pending batch,
    # counted (the event plane's drop-oldest discipline)
    flow_agg_queue_depth: int = 16
    # aggregation duty-cycle cap (fraction of wall time per rolling
    # second the worker may spend aggregating): "off the dispatch
    # path" must also mean "not eating the dispatch path's machine"
    # on CPU hosts (python-held segments contend on the GIL with the
    # drain loop), so past the budget pending batches become counted
    # drops instead of stolen cycles.  0.1 = 100 ms/s — ample for
    # 1-in-N sampled traffic plus drop-storm accounting
    flow_agg_max_duty: float = 0.1
    # drop-spike detector: a closed window whose drop count crosses
    # max(spike_min_drops, spike_factor * trailing-baseline) raises
    # ONE drop-spike incident; hysteresis holds the state until
    # drops fall back to baseline, and spike windows are excluded
    # from the baseline (a burst must not teach itself normal)
    spike_factor: float = 4.0
    spike_min_drops: int = 64
    spike_baseline_windows: int = 4
    # -- incident flight recorder (obs/flightrec.py).  Where sysdump
    # bundles land; None records incident history but captures no
    # bundles.  Incidents that fire a capture: drop-spike, watchdog
    # restart/terminal, ladder demotion, terminal event-join worker,
    # and the manual API/CLI trigger
    sysdump_dir: Optional[str] = None
    # bundles kept on disk (oldest pruned past this)
    sysdump_retention: int = 8
    # bundle size cap; oversize bundles shed their largest optional
    # sections (metrics text, flows, traces...) until they fit
    sysdump_max_bytes: int = 1 << 20
    # auto-captures inside this interval are skipped (counted) so a
    # restart storm cannot write a bundle per restart; manual
    # triggers bypass the limit
    sysdump_min_interval_s: float = 1.0
    # last-N Observer flows included per bundle
    sysdump_flows: int = 128
    # -- clustermesh serving tier (cilium_tpu/cluster): N in-process
    # daemon replicas behind one flow-affine front-end router, built
    # by start_cluster_serving(nodes=N, config=...).  Validated at
    # ClusterServing construction (cluster.validate_cluster_config).
    # per-node forward queue capacity in PACKETS between the router
    # and a replica's admission queue; overflow sheds drop-tail as
    # counted REASON_CLUSTER_OVERFLOW drops
    cluster_forward_depth: int = 1 << 15
    # membership liveness sweep cadence...
    cluster_probe_interval_s: float = 0.5
    # ...and how many CONSECUTIVE failed probes declare a node dead
    # (then: CT-replay failover onto the designated peer)
    cluster_death_threshold: int = 2
    # how long identity/policy mutations may take to reach every
    # replica over the kvstore before wait_identity / wait_policy
    # report divergence
    cluster_convergence_deadline_s: float = 5.0
    # "remote" serves the shared store over a real socket
    # (kvstore/remote.py — one client per replica, the deployment
    # shape); "memory" shares the InMemoryKVStore object (cheapest
    # tests)
    cluster_kvstore: str = "remote"
    # "thread" = in-process replicas (the PR 8 shape: cheapest tests,
    # N nodes share one GIL); "process" = one spawned worker PROCESS
    # per node (cluster/nodehost.py) with row forwarding over real
    # sockets (cluster/transport.py) — the upstream per-node-agent
    # shape where N nodes buy N cores.  Process mode requires
    # cluster_kvstore="remote"
    cluster_mode: str = "thread"
    # slots per INITIAL node in the router's fixed flow-hash space:
    # the re-pin granularity for failover and live scale-out (a
    # joining node steals ~1/new_n of the slots, nobody else's flows
    # move)
    cluster_slot_factor: int = 16
    # -- cluster observability relay (obs/relay.py; ISSUE 14).  The
    # parent-side scrape loop's cadence in seconds: every tick pulls
    # each node's registry exposition, flow-ring tail, analytics
    # top-K, tracer stats, and incident list into the merged cluster
    # views (GET /cluster/metrics, flows --cluster, top --cluster,
    # cluster sysdump).  0 disables the periodic loop — queries then
    # scrape on demand
    cluster_obs_interval_s: float = 1.0
    # a node whose scrape fails keeps serving its last-known-good
    # snapshot this long; past the bound its per-node series drop
    # (only the relay's scrape_ok/age meta-series remain)
    cluster_obs_stale_after_s: float = 30.0
    # cross-process trace stitching: every Nth forwarded chunk
    # carries (trace_id, router stamps) through the data channel and
    # the worker's stage stamps ride the ack back — one stitched span
    # per sample (router-queue -> forward -> worker-admit -> ack).
    # 0 = off (the hot-path cost when off is one int compare)
    cluster_trace_sample: int = 0
    # -- pipelined data channel (ISSUE 17).  Frames a process-mode
    # forwarder may have ON THE WIRE (sent, not yet cumulatively
    # acked) per node before it blocks for credit.  1 = the PR 13
    # synchronous per-frame-ack protocol, byte-identical on the wire;
    # >= 2 switches to sequenced frames + cumulative acks and pays
    # the round trip once per window
    cluster_forward_window: int = 8
    # worker-side ack coalescer: one cumulative ack per this many
    # admitted frames (or immediately when the channel drains —
    # nothing else buffered after an admit — so low-load frames ack
    # sync-like)...
    cluster_ack_every: int = 4
    # ...or after this many ms of quiet (the flush-on-idle timer that
    # bounds the tail latency coalescing could otherwise add)
    cluster_ack_flush_ms: float = 2.0
    # -- encrypted data channel (ISSUE 18).  When ON (process mode),
    # every router->worker data frame AND every worker->router ack
    # travels as one AEAD seal (EncryptedChannel; X25519 session keys
    # exchanged through the spawn handshake + node registry), decrypt
    # failures are counted + contained (typed reject record, never a
    # worker crash), and ClusterServing.rotate_epoch() rotates every
    # channel live.  OFF = byte-identical to the plaintext wire.
    # Thread mode has no sockets, so the knob is a no-op there.
    cluster_encrypt: bool = False
    # how long the receive side keeps the PREVIOUS epoch's key alive
    # after rotate_epoch() (its own replay window), so in-flight
    # frames sealed pre-rotation still open; past the grace they
    # reject as epoch-old
    cluster_epoch_grace_s: float = 2.0
    # -- queue-depth autoscale (cluster/scale.py ClusterAutoscaler).
    # When ON, a named controller samples the router's forward queues
    # and add_node()s after `ticks` consecutive samples over
    # `high_frac * cluster_forward_depth`, up to `max_nodes`;
    # when `low_frac` > 0 it also remove_node()s after `ticks`
    # consecutive samples under `low_frac * cluster_forward_depth`,
    # down to `min_nodes` (scale-in, ISSUE 17)
    cluster_autoscale: bool = False
    cluster_autoscale_max_nodes: int = 8
    cluster_autoscale_high_frac: float = 0.5
    cluster_autoscale_ticks: int = 3
    cluster_autoscale_interval_s: float = 0.5
    cluster_autoscale_min_nodes: int = 1
    cluster_autoscale_low_frac: float = 0.0
    # -- live policy churn (datapath/tables.py table versioning;
    # ISSUE 10).  Delta attach: repaint only fingerprint-changed
    # policies on a re-attach instead of recompiling the world
    # (policy.incremental.delta_compile); False forces every attach
    # down the full-compile path (debug / A-B comparison)
    policy_delta_compile: bool = True
    # warn when a table publish holds the dispatch lock longer than
    # this many ms (the flip is supposed to be a pointer swap; a slow
    # one means device work leaked inside the lock).  0 = off
    policy_swap_warn_ms: float = 0.0
    # -- map-pressure graceful degradation (datapath/pressure.py;
    # ISSUE 12 — the ctmap adaptive-GC / map-pressure-gauge
    # analogue).  A named controller samples CT occupancy, insert-
    # drop rate, and NAT pool failures off the drain thread; crossing
    # a threshold accelerates the CT aging sweep, records ONE
    # `map-pressure` incident per episode (sysdump capture), and
    # surfaces the state in serving stats / GET /serving / CLI.
    # sample cadence in seconds; 0 disables the monitor entirely
    map_pressure_interval: float = 5.0
    # CT occupancy fraction (occupied slots / capacity) entering the
    # pressure state...
    ct_pressure_threshold: float = 0.85
    # ...and the hysteresis exit: pressure clears only once occupancy
    # falls back under this AND a sample window sees no new insert
    # drops / NAT failures (a storm cannot flap incidents)
    ct_pressure_clear: float = 0.70
    # the ACCELERATED CT aging-sweep cadence while under pressure
    # (ct_gc_interval is the normal cadence it returns to)
    ct_gc_pressure_interval: float = 1.0
    # SNAT port-pool size (service/nat.py NATTable): power of two,
    # pool must fit the port space above NAT_PORT_MIN.  None = the
    # NAT_DEFAULT_CAPACITY (1 << 14).  Small pools are the
    # nat_exhaustion scenario's pressure shape
    nat_pool_capacity: Optional[int] = None
    # -- adaptive GC relaxation (ISSUE 19 satellite; the other half
    # of ctmap's adaptive interval: the sweep accelerates under
    # pressure AND relaxes back out when the map stays calm).  After
    # every ct_gc_relax_after seconds of CONTINUOUS calm (state ok,
    # occupancy under the clear bound, no new drops/failures) the
    # monitor stretches the normal CT-GC cadence by
    # ct_gc_relax_factor, compounding up to the ct_gc_relax_max
    # multiplier; any pressure episode snaps the multiplier back to
    # 1 — relaxation can never fire mid-episode.  0 = off
    ct_gc_relax_after: float = 300.0
    ct_gc_relax_factor: float = 2.0
    ct_gc_relax_max: float = 4.0
    # -- SLO plane (obs/history.py + obs/slo.py; ISSUE 19).  One
    # sampler thread (CTA002 domain `slo`, duty-governed) retains a
    # declared registry subset in two fixed-memory ring tiers and
    # evaluates the shipped SLO set with fast+slow burn rates; a
    # page-severity burn opens a `slo-burn` incident episode.
    # sampler cadence in seconds; 0 disables history AND SLO
    # evaluation entirely
    history_interval: float = 10.0
    # fast-tier ring slots (span = history_interval * slots)
    history_slots: int = 360
    # every Nth sample also lands in the slow tier...
    history_slow_every: int = 30
    # ...whose ring holds this many slots (default 5 min x 288 = 24 h)
    history_slow_slots: int = 288
    # the multi-window burn evaluation windows (seconds); both must
    # fit the rings' span to ever leave no-data
    slo_fast_window: float = 60.0
    slo_slow_window: float = 600.0
    # burn-rate thresholds: PAGE when both windows burn at/over
    # slo_page_burn (opens the incident episode), WARN at
    # slo_warn_burn
    slo_page_burn: float = 10.0
    slo_warn_burn: float = 2.0
    # hysteresis: an episode closes only after this many consecutive
    # calm evaluations (both windows under the warn burn)
    slo_clear_ticks: int = 3
    # the sampler's duty-governor ceiling (the flow-analytics
    # max_duty idiom): sampling+evaluation time stays under this
    # fraction of wall clock by stretching the cadence. 0 = fixed
    slo_max_duty: float = 0.05


class Daemon:
    def __init__(self, config: Optional[DaemonConfig] = None,
                 kvstore: Optional[InMemoryKVStore] = None,
                 encryption_keypair=None):
        """``kvstore``: pass one shared store to multiple daemons and
        they agree on identity numerics through the distributed
        allocator protocol AND replicate each other's allocations by
        watch (reference: pkg/kvstore + pkg/allocator + clustermesh).
        Without it the daemon allocates locally.

        ``encryption_keypair``: inject the node's Curve25519 identity
        instead of generating/loading one — the process-per-node
        worker hands over the keypair it already introduced in its
        spawn handshake, so the registry-advertised pubkey and the
        cluster data channel's key are the SAME identity."""
        from ..kvstore import ClusterIdentitySync, KVStoreAllocatorBackend
        from ..serving import (validate_recovery_config,
                               validate_serving_config,
                               validate_superbatch_config)

        self.config = config or DaemonConfig()
        # serving knobs fail at CONSTRUCTION (config resolution hands
        # them over as strings from env/config-dir): a typo'd policy
        # or non-power-of-two bucket must not surface as a recompile
        # storm under load.  Normalized values write back so the
        # /config surface shows what actually runs.
        (self.config.serving_queue_depth,
         self.config.serving_bucket_ladder,
         self.config.serving_max_wait_us,
         self.config.serving_overflow_policy) = validate_serving_config(
            self.config.serving_queue_depth,
            self.config.serving_bucket_ladder,
            self.config.serving_max_wait_us,
            self.config.serving_overflow_policy)
        (self.config.serving_dispatch_deadline_ms,
         self.config.serving_restart_budget,
         self.config.serving_restart_backoff_ms,
         self.config.serving_demote_threshold,
         self.config.serving_promote_after,
         self.config.serving_promote_cooldown_s
         ) = validate_recovery_config(
            self.config.serving_dispatch_deadline_ms,
            self.config.serving_restart_budget,
            self.config.serving_restart_backoff_ms,
            self.config.serving_demote_threshold,
            self.config.serving_promote_after,
            self.config.serving_promote_cooldown_s)
        self.config.serving_superbatch_k, _ = (
            validate_superbatch_config(
                self.config.serving_superbatch_k))
        if self.config.ct_snapshot_interval < 0:
            raise ValueError("ct_snapshot_interval must be >= 0")
        self.config.serving_window_queue_depth = int(
            self.config.serving_window_queue_depth)
        if self.config.serving_window_queue_depth < 1:
            raise ValueError(
                "serving_window_queue_depth must be >= 1 (the "
                "event-join worker's bounded window queue)")
        self.config.l7_workers = int(self.config.l7_workers)
        if self.config.l7_workers < 1:
            raise ValueError(
                "l7_workers must be >= 1 (the L7 proxy worker pool)")
        self.config.l7_queue_depth = int(self.config.l7_queue_depth)
        if self.config.l7_queue_depth < 1:
            raise ValueError(
                "l7_queue_depth must be >= 1 (the L7 pool's bounded "
                "task queue)")
        from ..obs import validate_obs_config

        (self.config.serving_trace_sample,
         self.config.profile_dir,
         self.config.profile_batches) = validate_obs_config(
            self.config.serving_trace_sample,
            self.config.profile_dir,
            self.config.profile_batches)
        # deterministic fault injection (chaos testing): arm the
        # process-global injector; spec typos fail here, not as a
        # silently-inert chaos run.  shutdown() disarms what we armed
        self._fault_injector = None
        if self.config.fault_injection:
            from ..infra import faults

            self._fault_injector = faults.arm(
                self.config.fault_injection,
                seed=self.config.fault_seed)
        # last CT snapshot (periodic controller / demotion / on
        # demand): recovery paths restore from it when the live
        # device CT is unreadable
        self._ct_snap: Optional[dict] = None
        self.kvstore = kvstore if kvstore is not None else InMemoryKVStore()
        backend = None
        if kvstore is not None:
            backend = KVStoreAllocatorBackend(
                self.kvstore, node=self.config.node_name,
                lease_ttl=self.config.identity_lease_ttl)
        self.allocator = CachingIdentityAllocator(backend=backend)
        self.identity_sync: Optional[ClusterIdentitySync] = None
        self.repo = PolicyRepository(self.allocator)
        self.ipcache = IPCache()
        self.config.policy_swap_warn_ms = float(
            self.config.policy_swap_warn_ms)
        if self.config.policy_swap_warn_ms < 0:
            raise ValueError("policy_swap_warn_ms must be >= 0")
        # map-pressure knobs (datapath/pressure.py) + the SNAT pool
        # size: fail at construction like every serving knob
        from ..datapath.pressure import validate_pressure_config

        (self.config.map_pressure_interval,
         self.config.ct_pressure_threshold,
         self.config.ct_pressure_clear,
         self.config.ct_gc_pressure_interval
         ) = validate_pressure_config(
            self.config.map_pressure_interval,
            self.config.ct_pressure_threshold,
            self.config.ct_pressure_clear,
            self.config.ct_gc_pressure_interval)
        from ..datapath.pressure import validate_relax_config

        (self.config.ct_gc_relax_after,
         self.config.ct_gc_relax_factor,
         self.config.ct_gc_relax_max) = validate_relax_config(
            self.config.ct_gc_relax_after,
            self.config.ct_gc_relax_factor,
            self.config.ct_gc_relax_max)
        # SLO-plane knobs (obs/history.py + obs/slo.py): same
        # fail-at-construction contract
        from ..obs import validate_history_config, validate_slo_config

        (self.config.history_interval,
         self.config.history_slots,
         self.config.history_slow_every,
         self.config.history_slow_slots) = validate_history_config(
            self.config.history_interval,
            self.config.history_slots,
            self.config.history_slow_every,
            self.config.history_slow_slots)
        (self.config.slo_fast_window,
         self.config.slo_slow_window,
         self.config.slo_page_burn,
         self.config.slo_warn_burn,
         self.config.slo_clear_ticks,
         self.config.slo_max_duty) = validate_slo_config(
            self.config.slo_fast_window,
            self.config.slo_slow_window,
            self.config.slo_page_burn,
            self.config.slo_warn_burn,
            self.config.slo_clear_ticks,
            self.config.slo_max_duty)
        if self.config.nat_pool_capacity is not None:
            # NAT_PORT_MIN is the single pool-base authority
            # (service/nat.py); NATTable.create re-validates — this
            # check exists so the failure names the config knob, not
            # a lazy first-masquerade deep in a serving leg
            from ..service.nat import NAT_PORT_MIN

            cap = int(self.config.nat_pool_capacity)
            if cap < 8 or cap & (cap - 1) \
                    or NAT_PORT_MIN + cap > 65536:
                raise ValueError(
                    f"nat_pool_capacity must be a power of two with "
                    f"NAT_PORT_MIN + capacity <= 65536 (the pool is "
                    f"[{NAT_PORT_MIN}, {NAT_PORT_MIN} + capacity) "
                    f"node ports)")
            self.config.nat_pool_capacity = cap
        if self.config.backend == "tpu":
            self.loader: Loader = TPULoader(
                self.config.ct_capacity,
                delta_compile=self.config.policy_delta_compile,
                swap_warn_ms=self.config.policy_swap_warn_ms,
                nat_capacity=self.config.nat_pool_capacity)
        else:
            self.loader = InterpreterLoader(
                nat_capacity=self.config.nat_pool_capacity)
        self.endpoints = EndpointManager(self.repo, self.ipcache,
                                         self.loader)
        self.monitor = MonitorAgent()
        self.controllers = ControllerManager()
        self.encryption = None  # set below when enabled + kvstore
        self._dns_listeners: Dict[int, object] = {}
        self._boot_time = time.time()
        self._started = False

        # L7 proxy plane: listeners follow the resolved redirects
        # (reference: pkg/proxy redirect lifecycle + Envoy filter);
        # created before hubble so the seven parser can subscribe
        from ..proxy import L7Proxy

        self.proxy = L7Proxy()
        self.endpoints.on_attach(self.proxy.update)

        # xDS push surface for an EXTERNAL proxy (reference: pkg/envoy
        # NPDS) — the native L7 path above stays the default; the
        # cache just tracks every attach so a fronting Envoy can
        # subscribe via proxy/xds.serve_xds
        from ..proxy.xds import XDSCache

        self.xds = XDSCache()
        self.endpoints.on_attach(self.xds.update_from_policies)

        # the live L7 proxy plane (serving/l7plane.py): constructed
        # per serving session in start_serving, read lock-free from
        # the event-join worker via this attribute (NEVER through
        # self._serving — _emit_ring_rows is contractually barred
        # from touching the session dict).  _l7_last keeps the final
        # stats of the most recent session for post-stop reads.
        self._l7plane = None
        self._l7_last: Optional[dict] = None
        # embedder seams for the plane's parse leg: a request source
        # (port, kind, task) -> requests, and a DNS resolver
        # (qname) -> (ips, ttl) feeding live FQDN identity mints
        self.l7_request_source = None
        self.l7_dns_resolver = None

        # hubble plane
        self.observer = Observer(
            capacity=self.config.flow_ring_capacity,
            identity_getter=self._identity_labels,
            endpoint_getter=self._endpoint_info)
        self.parser = ThreeFourParser(self.observer)
        self.flow_metrics = FlowMetrics()
        self.exporter: Optional[FlowExporter] = None
        if self.config.enable_hubble:
            self.monitor.register("hubble", self.parser.consume)
            self.monitor.register("metrics", self.flow_metrics.consume)
            # the seven parser: proxy access records -> L7 flows in
            # the same ring (reference: pkg/hubble/parser/seven)
            from ..flow.seven import SevenParser

            self.seven = SevenParser(
                self.observer,
                numeric_of_row=lambda r: (
                    self.loader.row_map.numeric(r)
                    if self.loader.row_map else 0))
            self.proxy.on_record(self.seven.consume)
        if self.config.export_path:
            self.exporter = FlowExporter(
                self.config.export_path, self.config.node_name,
                identity_getter=self._identity_labels,
                endpoint_getter=self._endpoint_info)
            self.monitor.register("exporter", self.exporter.consume)
        # learned path: advisory anomaly scores on the monitor stream
        self.anomaly = None
        if self.config.anomaly_model_path:
            from ..ml import AnomalyScorer, load_model

            self.anomaly = AnomalyScorer(
                load_model(self.config.anomaly_model_path),
                lambda numeric: (self.loader.row_map.row(numeric)
                                 if self.loader.row_map else 0),
                threshold=self.config.anomaly_threshold)
            self.monitor.register("anomaly", self.anomaly.consume)

        # flow analytics + incident flight recorder (obs/analytics,
        # obs/flightrec): the analytics engine rides the monitor
        # stream as one O(1) reference-park consumer and aggregates
        # on the event-join worker / query threads; incidents —
        # spike, watchdog restart, ladder demotion, terminal event
        # worker, manual — capture a sysdump bundle when a dir is
        # configured
        from ..obs import (FlightRecorder, FlowAnalytics,
                           validate_analytics_config,
                           validate_flightrec_config)

        (self.config.flow_agg_window_s,
         self.config.flow_agg_windows,
         self.config.flow_agg_topk,
         self.config.flow_agg_queue_depth,
         self.config.spike_factor,
         self.config.spike_min_drops,
         self.config.spike_baseline_windows,
         self.config.flow_agg_max_duty
         ) = validate_analytics_config(
            self.config.flow_agg_window_s,
            self.config.flow_agg_windows,
            self.config.flow_agg_topk,
            self.config.flow_agg_queue_depth,
            self.config.spike_factor,
            self.config.spike_min_drops,
            self.config.spike_baseline_windows,
            self.config.flow_agg_max_duty)
        (self.config.sysdump_dir,
         self.config.sysdump_retention,
         self.config.sysdump_max_bytes,
         self.config.sysdump_min_interval_s,
         self.config.sysdump_flows) = validate_flightrec_config(
            self.config.sysdump_dir,
            self.config.sysdump_retention,
            self.config.sysdump_max_bytes,
            self.config.sysdump_min_interval_s,
            self.config.sysdump_flows)
        self.flightrec = FlightRecorder(
            self._sysdump_collect,
            sysdump_dir=self.config.sysdump_dir,
            retention=self.config.sysdump_retention,
            max_bytes=self.config.sysdump_max_bytes,
            min_interval_s=self.config.sysdump_min_interval_s,
            node=self.config.node_name)
        self.analytics = FlowAnalytics(
            window_s=self.config.flow_agg_window_s,
            retention=self.config.flow_agg_windows,
            topk=self.config.flow_agg_topk,
            queue_depth=self.config.flow_agg_queue_depth,
            spike_factor=self.config.spike_factor,
            spike_min_drops=self.config.spike_min_drops,
            spike_baseline_windows=self.config.spike_baseline_windows,
            max_duty=self.config.flow_agg_max_duty,
            ep_identity=self._endpoint_identity,
            on_incident=self.record_incident,
            enabled=self.config.flow_agg_enabled)
        self.monitor.register("analytics", self.analytics.submit)
        # map-pressure monitor + graceful degradation
        # (datapath/pressure.py): samples CT occupancy / insert-drop
        # rate / NAT pool failures on a named controller (started in
        # start()), accelerates the CT aging sweep under pressure,
        # and records a `map-pressure` incident per episode
        from ..datapath.pressure import MapPressureMonitor

        self.pressure = MapPressureMonitor(
            sample_fn=lambda: self.loader.map_pressure(self._now()),
            on_accelerate=self._ct_gc_accelerate,
            on_restore=self._ct_gc_restore,
            record_incident=self.record_incident,
            ct_threshold=self.config.ct_pressure_threshold,
            ct_clear=self.config.ct_pressure_clear,
            gc_pressure_interval_s=self.config
            .ct_gc_pressure_interval,
            relax_after_s=self.config.ct_gc_relax_after,
            relax_factor=self.config.ct_gc_relax_factor,
            relax_max=self.config.ct_gc_relax_max,
            on_relax=self._ct_gc_relax)
        # hubble-relay analogue: add_relay_peer() builds it lazily;
        # when peers exist the sysdump bundle carries a relay-merged
        # flow sample stamped with node names
        self.relay = None

        # service LB: VIP -> Maglev backend selection, applied before
        # the policy pipeline (reference: pkg/service + bpf/lib/lb.h)
        from ..service import ServiceManager

        self.services = ServiceManager()
        self._serving = None  # start_serving() installs the ring path
        # set by ClusterServing on every member replica: the back
        # reference the Cluster serving-stats block, GET
        # /cluster/status and the cilium_cluster_* registry series
        # read (None = not part of a cluster serving tier)
        self._cluster = None
        # bandwidth manager (pkg/bandwidth analogue): per-endpoint
        # egress rates; None until some endpoint is limited
        self._bw = None
        self._bw_rates = None
        self._bw_limits: Dict[int, int] = {}
        # egress-gateway policies (name -> spec); endpoint churn
        # re-expands the pod selectors over local endpoints
        self._egress_policies: Dict[str, dict] = {}
        self._egress_rules_cache = None  # last expanded rule tuple
        self.endpoints.on_attach(
            lambda _pols: (self._recompile_nat()
                           if self._egress_policies else None))
        # connect-time LB flow cache (service/socklb.py, the bpf_sock
        # analogue): created on first service traffic
        self._socklb = None
        self._svc_version_seen = None  # affinity prune bookkeeping
        # mutual auth (pkg/auth): drop-observing handshake manager.
        # Fed explicitly where the batch's LOGICAL clock is in hand
        # (process_batch / the serving-path drain) — grants must be
        # stamped on the same clock the datapath compares against
        if self.config.mesh_auth:
            from .auth import AuthManager
            self.auth_manager = AuthManager(self)
        else:
            self.auth_manager = None
        # egress masquerade (applies after LB, before the datapath, so
        # CT tracks the post-NAT tuple)
        self.nat = None
        if self.config.masquerade:
            if not self.config.node_ip:
                # silently running WITHOUT masquerade when the operator
                # asked for it would leak pod source IPs
                raise ValueError(
                    "masquerade=True requires node_ip to be set")
            from ..service.nat import NATConfig

            self.nat = NATConfig(
                node_ip=self.config.node_ip,
                non_masquerade_cidrs=self.config.non_masquerade_cidrs,
            ).compile()

        # fqdn loop: DNS answers observed by the proxy become
        # identities + ipcache entries (reference: pkg/fqdn)
        from ..fqdn import NameManager

        self.fqdn = NameManager(self.allocator, self.delete_ipcache)
        self.proxy.observe_dns(self.fqdn.observe)

        # recorder: FlowFilter-gated pcap capture off the monitor
        # stream (reference: pkg/hubble/recorder)
        from ..flow.recorder import Recorder

        self.recorder = Recorder()
        self.monitor.register("recorder", self.recorder.consume)

        # clustermesh: remote clusters mirror in as incremental
        # identity/ipcache patches (reference: pkg/clustermesh)
        from ..clustermesh import (ClusterMesh, publish_endpoint_ip,
                                   withdraw_endpoint_ip)

        self.clustermesh = ClusterMesh(self.allocator,
                                       self.upsert_ipcache,
                                       self.delete_ipcache)
        if kvstore is not None:
            # agent side of the ipcache shared store: announce local
            # endpoint IPs for remote clusters/nodes to mirror
            def _publish_ep(kind: str, ep) -> None:
                if ep.identity is None:
                    return
                for ip in ep.ips:
                    if kind == "add":
                        publish_endpoint_ip(self.kvstore, ip,
                                            ep.identity.numeric_id)
                    else:
                        withdraw_endpoint_ip(self.kvstore, ip)

            self.endpoints.on_endpoint_change(_publish_ep)

        # ipcache catch-all: IPs no entry covers belong to WORLD
        # (reference: ipcache misses resolve to the world identity, so
        # toEntities:[world] policies see all external traffic)
        world = self.allocator.allocate(LabelSet.parse("reserved:world"))
        self.ipcache.upsert("0.0.0.0/0", world.numeric_id,
                            source="reserved")
        self.ipcache.upsert("::/0", world.numeric_id, source="reserved")

        # wiring: rule changes and identity churn both end in one
        # coalesced regeneration (SURVEY.md §3.3)
        self.repo.on_change(lambda rev: self.endpoints.regenerate())
        self.allocator.observe(self._on_identity_change)

        # initial empty attach so the datapath is live pre-endpoints
        self.endpoints.regenerate()

        # join the cluster identity plane LAST (the watch replays every
        # existing remote identity through the observer->patch chain,
        # which needs the wiring above in place)
        self.health = None
        if kvstore is not None:
            self.identity_sync = ClusterIdentitySync(self.kvstore,
                                                     self.allocator)
            # node registry + probe mesh (reference: cilium-health)
            from ..health import HealthMesh, NodeRegistry

            self.node_registry = NodeRegistry(self.kvstore)
            info = {}
            if self.config.api_socket_path:
                info["api_socket"] = self.config.api_socket_path
            if self.config.enable_encryption:
                from ..encryption import EncryptionManager

                self.encryption = EncryptionManager(
                    self.config.node_name, self.node_registry,
                    key_path=self.config.encryption_key_path,
                    keypair=encryption_keypair)
                info = self.encryption.advertise(info)
            self.node_registry.register(self.config.node_name, info)
            self.health = HealthMesh(self.node_registry,
                                     self.config.node_name)

        # the unified metrics registry (obs/registry.py): every
        # prometheus series GET /metrics serves is declared here —
        # collectors are lazy closures over this daemon, so
        # registration costs the hot path nothing
        from ..obs import build_daemon_registry

        self.registry = build_daemon_registry(self)
        # the SLO plane (ISSUE 19): history rings + burn-rate engine,
        # constructed AFTER the registry because the sampler pulls
        # registry.sample() — the registry's own cilium_slo_*
        # collectors resolve this attribute lazily for the same
        # reason.  The engine exists even with the sampler disabled
        # (history_interval 0): tests and operators can drive
        # tick() synchronously
        from ..obs import SLOEngine, SeriesHistory, default_slos
        from ..obs.slo import HISTORY_SERIES

        self.history = SeriesHistory(
            sample_fn=lambda: self.registry.sample(HISTORY_SERIES),
            kinds={name: kind for name in HISTORY_SERIES
                   if (kind := self.registry.kind(name)) is not None},
            interval_s=self.config.history_interval,
            slots=self.config.history_slots,
            slow_every=self.config.history_slow_every,
            slow_slots=self.config.history_slow_slots)
        self.slo = SLOEngine(
            self.history, default_slos(),
            record_incident=self.record_incident,
            interval_s=self.config.history_interval,
            fast_window_s=self.config.slo_fast_window,
            slow_window_s=self.config.slo_slow_window,
            page_burn=self.config.slo_page_burn,
            warn_burn=self.config.slo_warn_burn,
            clear_ticks=self.config.slo_clear_ticks,
            max_duty=self.config.slo_max_duty)

    # -- getters for flow enrichment ---------------------------------
    def _identity_labels(self, numeric: int) -> Tuple[str, ...]:
        ident = self.allocator.lookup_by_id(numeric)
        return tuple(str(l) for l in ident.labels) if ident else ()

    def _endpoint_info(self, ep_id: int) -> Tuple[str, int]:
        ep = self.endpoints.get(ep_id)
        return (ep.name, ep.id) if ep else ("", ep_id)

    def _endpoint_identity(self, ep_id: int) -> int:
        """ep id -> LOCAL numeric identity (the analytics plane's
        src/dst attribution for the local side of a flow)."""
        ep = self.endpoints.get(ep_id)
        if ep is not None and ep.identity is not None:
            return int(ep.identity.numeric_id)
        return 0

    # -- incidents + flight recorder -----------------------------------
    # thread-affinity: any
    def record_incident(self, kind: str, detail=None,
                        capture: bool = True):
        """The one incident entry every hook funnels through: spike
        detection (analytics, worker thread), watchdog restart /
        terminal (serving/runtime.py on_restart, watchdog thread),
        ladder demotion (_serving_demote, drain thread), terminal
        event-join worker (serving/eventplane.py on_terminal), and
        the manual API/CLI trigger.  Never raises — incident
        recording must not take down whatever plane just faulted."""
        try:
            return self.flightrec.record_incident(kind, detail,
                                                  capture=capture)
        except Exception:  # noqa: BLE001
            # hot-path-ok: the incident-recorder-itself-broke path —
            # by definition not steady state, and swallowing it
            # silently would hide a dead flight recorder
            logging.getLogger(__name__).warning(
                "incident recording failed (kind=%s)", kind,
                exc_info=True)
            return None

    def _serving_restart_incident(self, cause: str,
                                  terminal: bool) -> None:
        """ServingRuntime's on_restart hook (watchdog thread)."""
        from ..obs.flightrec import KIND_RESTART, KIND_TERMINAL

        self.record_incident(
            KIND_TERMINAL if terminal else KIND_RESTART,
            {"cause": cause})

    def _eventworker_incident(self, error: str) -> None:
        """EventJoinWorker's on_terminal hook (worker thread)."""
        from ..obs.flightrec import KIND_EVENTWORKER

        self.record_incident(KIND_EVENTWORKER, {"error": error})

    def _l7pool_incident(self, error: str) -> None:
        """L7WorkerPool's on_terminal hook (dying l7 thread)."""
        from ..obs.flightrec import KIND_L7POOL

        self.record_incident(KIND_L7POOL, {"error": error})

    def sysdump_now(self, trigger: str = "manual") -> dict:
        """The manual trigger (``GET /debug/sysdump?trigger=1``,
        ``cilium-tpu sysdump``): records a manual incident and
        captures OUTSIDE the auto rate limit.  A disabled recorder
        declines WITHOUT recording — a probe polling the 400-ing
        endpoint must not evict real incidents from the bounded
        history."""
        from ..obs.flightrec import KIND_MANUAL

        if not self.flightrec.enabled:
            return {"written": None, "enabled": False,
                    "bundles": [], "stats": self.flightrec.stats()}
        inc = self.flightrec.record_incident(KIND_MANUAL,
                                             {"trigger": trigger},
                                             capture=False)
        path = self.flightrec.capture(trigger=KIND_MANUAL,
                                      incident=inc, manual=True)
        return {"written": path,
                "enabled": self.flightrec.enabled,
                "bundles": self.flightrec.list_bundles(),
                "stats": self.flightrec.stats()}

    def flows_aggregate(self, top: int = 16) -> dict:
        """``GET /flows/aggregate``: the analytics snapshot (drains
        pending batches on THIS thread — query threads are off the
        dispatch path by definition)."""
        return self.analytics.snapshot(top=top)

    def obs_scrape_snapshot(self, cursor: int = 0, flows: int = 512,
                            top: int = 16) -> dict:
        """One relay scrape (ISSUE 14): registry exposition + the
        flow-ring tail since the caller's cursor + analytics top-K +
        tracer stats + the incident list, in one round trip —
        everything the parent-side ``ClusterObsRelay`` merges into
        the cluster views.  The ONE definition behind BOTH node
        modes (``ClusterNode.obs_scrape`` in-process and the
        ``nodehost`` ``obs_scrape`` control op): a field added to a
        single copy would silently diverge thread-mode and
        process-mode merged views (the PR 12 warm-recipe regression
        class)."""
        from ..proxy import registry as l7registry

        fls, new_cursor = self.observer.flows_since(int(cursor),
                                                    limit=int(flows))
        s = self._serving
        tr = s.get("tracer") if s is not None else None
        return {
            "metrics-text": self.registry.render(),
            "flows": [f.to_dict() for f in fls],
            "cursor": new_cursor,
            "top": self.flows_aggregate(top=int(top)),
            "trace": tr.stats() if tr is not None else None,
            "incidents": self.flightrec.incidents(),
            # per-plugin L7 parse latency (ISSUE 17 — PR 16 residue
            # c): the relay renders it node+plugin-labeled in the
            # merged exposition instead of summed across plugins
            "l7-by-plugin": l7registry.latency_snapshot(),
        }

    def slo_snapshot(self) -> dict:
        """``GET /slo`` body, node-stamped.  The ONE definition
        behind BOTH node modes (``ClusterNode.slo`` in-process and
        the ``nodehost`` ``slo`` control op) — the
        obs_scrape_snapshot contract."""
        out = self.slo.snapshot()
        out["node"] = self.config.node_name
        return out

    def history_snapshot(self, series=None, since: float = 0.0
                         ) -> dict:
        """``GET /metrics/history`` body, node-stamped — the one
        definition behind both node modes, like
        :meth:`slo_snapshot`."""
        out = self.history.query(series=series, since=float(since))
        out["node"] = self.config.node_name
        return out

    def add_relay_peer(self, name: str, observer) -> None:
        """Register a peer agent's Observer(-protocol object) for
        relay-merged flow views (the hubble-relay analogue; prep for
        the clustermesh serving tier).  Once any peer is registered,
        sysdump bundles include a relay flow sample stamped with
        node_name."""
        from ..flow.relay import Relay

        if self.relay is None:
            self.relay = Relay({self.config.node_name: self.observer})
        self.relay.add_peer(name, observer)

    def _sysdump_collect(self) -> dict:
        """The flight recorder's section collector.  Each section is
        INDIVIDUALLY contained — incident time is exactly when
        subsystems misbehave, and one failing snapshot must not cost
        the whole artifact."""
        from dataclasses import asdict

        out: dict = {}

        def section(name, fn):
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001
                out[name] = {"error": f"{type(e).__name__}: {e}"}

        cfg = self.config
        section("config", lambda: asdict(cfg))
        section("serving", self.serving_stats)
        section("compile",
                lambda: (self.loader.compile_log.snapshot()
                         if getattr(self.loader, "compile_log", None)
                         is not None else None))
        section("traces", lambda: self.debug_traces(limit=16))
        section("flows",
                lambda: [f.to_dict() for f in self.observer.get_flows(
                    number=cfg.sysdump_flows)])
        section("flow-aggregation",
                lambda: self.analytics.snapshot(top=16))
        section("metrics", self.registry.render)
        section("ct-snapshot", self.ct_snapshot_info)
        section("pressure", self.pressure.stats)
        # the SLO plane (ISSUE 19): a slo-burn capture must carry
        # the evidence — the full evaluation/episode state plus the
        # retained series window the burn was computed over
        section("slo", self.slo.snapshot)
        section("history", self.history.query)
        if self.relay is not None:
            section("relay-flows", lambda: self.relay.get_flows(
                number=min(cfg.sysdump_flows, 64)))
        return out

    # -- identity churn ----------------------------------------------
    def _on_identity_change(self, kind: str, ident) -> None:
        # CIDR-derived identities feed the ipcache (reference: ipcache
        # CIDR entries appear when policy references them).  Only the
        # MOST SPECIFIC cidr label is the identity's prefix — the
        # parent-prefix labels (r05, label-selecting fromCIDR) are
        # selection metadata; upserting them would route the whole
        # parent range onto this identity.
        cidr_labels = []
        if kind == "add":
            cidrs = [l.key for l in ident.labels
                     if l.source == SOURCE_CIDR]
            if cidrs:
                exact = max(cidrs,
                            key=lambda c: int(c.rsplit("/", 1)[1]))
                self.ipcache.upsert(exact, ident.numeric_id,
                                    source="generated")
                cidr_labels.append(exact)
        if not self._started:
            # no serve loop to patch yet, but cached resolutions are
            # STALE (peer sets freeze at resolve time) — without this,
            # an endpoint added after a policy import resolves against
            # the pre-churn peer sets and its traffic default-denies
            # until an unrelated revision bump (r04 endpoint-after-
            # policy ordering bug).  Cache-only clear: the
            # regeneration add_endpoint triggers re-resolves fresh,
            # and a full invalidate() would regen once per replayed
            # identity at startup.
            self.repo.invalidate_cache()
            # ...EXCEPT a CIDR/fqdn identity minted into a LIVE
            # pre-start world (the DNS proxy observes answers before
            # start()): its ipcache upsert must reach the datapath
            # NOW — no later regeneration is coming, so the cache-only
            # shortcut left toFQDNs traffic default-denying until an
            # unrelated revision bump.  Startup replay keeps the
            # cache-only path: identities restore before any endpoint
            # registers, so the gate below stays closed there.
            if not (kind == "add" and cidr_labels
                    and self.endpoints.list()):
                return
        # Incremental fast path (SURVEY.md §7 hard part #3): patch the
        # identity's verdict row + LPM slots in place — no re-resolve,
        # no compile_policy, no re-attach.  Falls back to a full
        # regeneration when the backend can't express the patch.
        if self.endpoints.patch_identity(kind, ident):
            ok = all(self.endpoints.patch_ipcache(c, ident.numeric_id)
                     for c in cidr_labels)
            if ok:
                return
        self.repo.invalidate()  # also triggers regeneration

    # -- graceful degradation (datapath/pressure.py hooks) -------------
    def _ct_gc_schedule(self, interval: float) -> None:
        """(Re-)register the CT aging sweep at ``interval`` — ONE
        definition for start(), patch_config, and the pressure
        monitor's accelerate/restore transitions."""
        self.controllers.update(
            "ct-gc", lambda: self.loader.gc(self._now()), interval)

    def _ct_gc_accelerate(self, interval: float) -> None:
        # thread-affinity: api -- the map-pressure controller thread
        """Pressure entered: accelerate the aging sweep and run one
        NOW (the ctmap adaptive-GC response)."""
        if not self._started:
            return
        self._ct_gc_schedule(interval)
        c = self.controllers.get("ct-gc")
        if c is not None:
            c.trigger()

    def _ct_gc_restore(self) -> None:
        # thread-affinity: api -- the map-pressure controller thread
        """Pressure cleared: back to the configured cadence."""
        if not self._started:
            return
        self._ct_gc_schedule(self.config.ct_gc_interval)

    def _ct_gc_relax(self, multiplier: float) -> None:
        # thread-affinity: api -- the map-pressure controller thread
        """A sustained-calm relax step (ISSUE 19 satellite): stretch
        the normal cadence by the monitor's bounded multiplier."""
        if not self._started:
            return
        self._ct_gc_schedule(self.config.ct_gc_interval * multiplier)

    # -- lifecycle ----------------------------------------------------
    def start(self) -> None:
        """Start background controllers (CT GC, fqdn TTL GC)."""
        self._started = True
        self._ct_gc_schedule(self.config.ct_gc_interval)
        if self.config.map_pressure_interval > 0:
            # one SYNCHRONOUS warm sample before the controller
            # ticks: compiles the occupancy executable while no
            # serving session's compile-count freeze is live, and
            # seeds the insert-drop/NAT-failure delta baselines
            self.pressure.sample()
            self.controllers.update(
                "map-pressure", self.pressure.sample,
                self.config.map_pressure_interval)
        # the SLO plane's sampler thread (obs/slo.py `slo-sampler`,
        # CTA002 domain `slo`): history sampling + burn evaluation,
        # duty-governed, never the drain thread.  start() is a no-op
        # when history_interval is 0
        self.slo.start()
        self.controllers.update(
            "fqdn-gc", self.fqdn.gc, self.config.fqdn_gc_interval)
        if self.auth_manager is not None:
            self.controllers.update(
                "auth-gc",
                lambda: self.auth_manager.gc(self._now()),
                self.config.auth_gc_interval)
        if self.config.hubble_listen:
            from ..flow.grpc_server import serve as hubble_serve

            self.hubble_server = hubble_serve(
                self.observer, self.config.hubble_listen,
                node_name=self.config.node_name)
        if self.health is not None:
            def _health_sweep():
                self.node_registry.heartbeat(self.config.node_name)
                # advertise the serving plane's fault state alongside
                # reachability (reference: cilium-health carries more
                # than liveness) — peers see a degraded/restarting
                # node in their node info, not just "reachable"
                self.node_registry.annotate(self.config.node_name,
                                            self._node_fault_info())
                self.health.probe_all()

            self.controllers.update(
                "health-probe", _health_sweep,
                self.config.health_probe_interval)
        if self.config.ct_snapshot_interval > 0:
            # periodic CT snapshots (the pinned-map persistence
            # analogue, but in-memory + on a cadence): recovery and
            # loader rebuilds restore established flows from the
            # last one when the live CT is gone
            self.controllers.update(
                "ct-snapshot",
                lambda: self.ct_snapshot_now(trigger="interval"),
                self.config.ct_snapshot_interval)
        if self.config.flow_agg_enabled:
            # close aggregation windows on WALL time: a drop burst
            # followed by total silence must still reach the spike
            # detector (ingest-driven rolls need a later batch that
            # may never come).  Controller thread = off the dispatch
            # path, like every other drain() caller
            self.controllers.update(
                "flow-agg-roll", self.analytics.drain,
                self.config.flow_agg_window_s)
        # endpoints whose identity allocation failed (kvstore outage)
        # retry here until they leave waiting-for-identity
        self.controllers.update(
            "identity-retry", self.endpoints.retry_pending_identities,
            5.0)
        # leased identity refs need a heartbeat (reference: etcd lease
        # keepalive on allocator slave keys)
        ttl = self.config.identity_lease_ttl
        backend = self.allocator._backend
        if ttl and backend is not None and hasattr(backend,
                                                   "refresh_refs"):
            self.controllers.update(
                "identity-keepalive", backend.refresh_refs,
                max(ttl / 3.0, 0.05))

    hubble_server = None

    def shutdown(self) -> None:
        self.slo.stop()
        self.controllers.stop_all()
        self.stop_serving()  # no-op when idle; drains in-flight work
        self.stop_dns_proxy()
        if self.hubble_server is not None:
            self.hubble_server.stop(grace=0.5)
        if self.exporter:
            self.exporter.close()
        if self.config.state_dir:
            self.checkpoint(self.config.state_dir)
        # unsubscribe kvstore watchers: a shared store outliving this
        # daemon would otherwise keep invoking (and retaining) it
        if self.identity_sync is not None:
            self.identity_sync.close()
        self.allocator.close()
        if self._fault_injector is not None:
            from ..infra import faults

            faults.disarm(self._fault_injector)
            self._fault_injector = None

    def _now(self) -> int:
        return int(time.time() - self._boot_time) + 1

    # -- egress gateway (CiliumEgressGatewayPolicy analogue) -----------
    def add_egress_gateway(self, name: str, selector: dict,
                           dest_cidrs, egress_ip: str) -> None:
        """Pods matching ``selector`` (a k8s LabelSelector dict) SNAT
        via ``egress_ip`` toward ``dest_cidrs`` (reference:
        CiliumEgressGatewayPolicy; single-node scope — the designated
        gateway is this node).

        Validates BEFORE storing: a malformed policy must raise here
        (and be skipped by the watcher), never poison every later
        regeneration's recompile."""
        import ipaddress as _ip

        eip = _ip.IPv4Address(egress_ip)  # raises on v6/garbage
        cidrs = []
        for c in dest_cidrs:
            net = _ip.ip_network(c, strict=False)
            if net.version != 4:
                raise ValueError(
                    f"egress gateway destinationCIDR {c!r}: the SNAT "
                    "path is v4-only")
            cidrs.append(str(net))
        if not cidrs:
            raise ValueError("egress gateway needs destinationCIDRs")
        selectors = (selector if isinstance(selector, (list, tuple))
                     else (selector,))
        if not selectors:
            raise ValueError("egress gateway needs a selector")
        # selectors must PARSE before the store: a stored-but-invalid
        # policy would raise from every later recompile (the
        # regeneration hook), breaking endpoint churn node-wide
        from ..policy.api import EndpointSelector

        for sel in selectors:
            EndpointSelector.from_dict(sel)  # raises on bad operators
        self._egress_policies[name] = {
            "selectors": tuple(selectors),
            "dest_cidrs": tuple(cidrs),
            "egress_ip": str(eip),
        }
        self._recompile_nat()

    def remove_egress_gateway(self, name: str) -> bool:
        if self._egress_policies.pop(name, None) is None:
            return False
        self._recompile_nat()
        return True

    def _egress_rules(self):
        """Expand the policies over the CURRENT local endpoints:
        (pod IP, destination CIDR, egress IP) triples."""
        from ..policy.api import EndpointSelector

        rules = []
        for pol in self._egress_policies.values():
            sels = [EndpointSelector.from_dict(s)
                    for s in pol["selectors"]]
            for ep in self.endpoints.list():
                if not any(s.matches(ep.labels) for s in sels):
                    continue
                for ip in ep.ips:
                    if ":" in ip:
                        continue  # v4-only SNAT path
                    for cidr in pol["dest_cidrs"]:
                        rules.append((ip, cidr, pol["egress_ip"]))
        return tuple(rules)

    def _recompile_nat(self) -> None:
        """Rebuild the NAT tensors from masquerade config + egress
        policies (endpoint churn re-expands the selectors — wired to
        the regeneration hook).  Skips the rebuild when the expanded
        rule set is unchanged (most regenerations don't touch the
        selected endpoints)."""
        from ..service.nat import NATConfig

        rules = self._egress_rules()
        if rules == self._egress_rules_cache:
            return
        self._egress_rules_cache = rules
        if self.config.masquerade:
            self.nat = NATConfig(
                node_ip=self.config.node_ip,
                non_masquerade_cidrs=self.config.non_masquerade_cidrs,
                egress_rules=rules,
            ).compile()
        elif rules:
            # egress gateway without masquerade: the exemption list
            # covers everything, so ONLY policy-matched rows SNAT
            self.nat = NATConfig(
                node_ip=self.config.node_ip or "0.0.0.0",
                non_masquerade_cidrs=("0.0.0.0/0",),
                egress_rules=rules,
            ).compile()
        else:
            self.nat = None

    # -- bandwidth manager (pkg/bandwidth / EDT analogue) --------------
    def set_bandwidth(self, ep_id: int,
                      bytes_per_sec: Optional[int]) -> None:
        """Set (or clear with None/0) an endpoint's egress rate limit
        in bytes/s (reference: kubernetes.io/egress-bandwidth pod
        annotation -> pkg/bandwidth -> EDT in bpf_lxc)."""
        import jax.numpy as jnp

        from ..datapath.bandwidth import (BandwidthState, rates_array)

        if bytes_per_sec:
            if self._bw_limits.get(int(ep_id)) == int(bytes_per_sec):
                return  # unchanged: skip the tensor rebuild
            self._bw_limits[int(ep_id)] = int(bytes_per_sec)
        else:
            if self._bw_limits.pop(int(ep_id), None) is None:
                return  # nothing was limited: nothing to rebuild
        if self._bw_limits:
            self._bw_rates = jnp.asarray(rates_array(self._bw_limits))
            if self._bw is None:
                self._bw = BandwidthState.create()
        else:
            self._bw_rates = None
            self._bw = None

    def _bw_police(self, hdr, now: int):
        """-> per-row REASON codes for the datapath's
        ``pre_drop_reason`` (None when no endpoint is limited)."""
        if self._bw_rates is None:
            return None
        import jax.numpy as jnp

        from ..datapath.bandwidth import bw_stage_jit

        if isinstance(hdr, np.ndarray):
            hdr = jnp.asarray(np.ascontiguousarray(hdr))
        reasons, self._bw = bw_stage_jit(self._bw, hdr,
                                         jnp.uint32(now),
                                         self._bw_rates)
        return reasons

    # -- the serve loop ----------------------------------------------
    def process_batch(self, hdr: np.ndarray,
                      now: Optional[int] = None) -> EventBatch:
        # thread-affinity: offline, api, cli
        """One packet tensor through LB -> datapath -> monitor."""
        if now is None:
            now = self._now()
        if (len(self.services) or self.nat is not None
                or self._bw_rates is not None):
            import jax.numpy as jnp

            # hdr stays ON DEVICE across the LB -> SNAT -> datapath
            # stages (loader.step accepts device arrays); the one host
            # fetch below feeds event decode, which needed the
            # rewritten rows anyway
            hdr_dev = hdr
            if len(self.services):
                # connect-time translation with a per-flow cache
                # (socket-LB analogue): established flows ride a
                # window probe; only genuinely-new flows pay the
                # frontend compare + Maglev
                from ..service.socklb import (SockLBTable,
                                              socklb_stage_jit)

                if self._socklb is None:
                    self._socklb = SockLBTable.create()
                svc_ver = self.services.version
                if self._svc_version_seen != svc_ver:
                    # backend-set change: expire ClientIP affinity
                    # pins whose backend no longer exists anywhere.
                    # Gated on affinity actually being in use — the
                    # sweep pays a d2h fetch of the pin table
                    if self.services.any_affinity:
                        self._socklb = self._socklb.prune_affinity(
                            self.services.backend_set())
                    self._svc_version_seen = svc_ver
                hdr_dev, _hits, svc_nobe, self._socklb = \
                    socklb_stage_jit(
                        self._socklb, self.services.tensors(),
                        jnp.asarray(np.ascontiguousarray(hdr_dev)),
                        jnp.uint32(now))
                t6 = self.services.tensors6()
                if t6 is not None:
                    # dual-stack: v6 frontends ride the per-packet
                    # pass (socklb judged only v4 rows)
                    from ..service import lb6_stage_jit

                    hdr_dev, _h6, nobe6 = lb6_stage_jit(t6, hdr_dev)
                    svc_nobe = svc_nobe | nobe6
            else:
                svc_nobe = None
            nat_drop = None
            if self.nat is not None:
                # conntrack-aware egress SNAT with port allocation
                # (service.nat.snat_egress): inbound-connection
                # replies keep their source; pool exhaustion marks the
                # row for a REASON_NAT_EXHAUSTED drop in the step
                hdr_dev, nat_drop = self.loader.masquerade(
                    self.nat, hdr_dev, now)
            bw_reasons = self._bw_police(hdr_dev, now)
            # svc_nobe (frontend hit, no backend) rides the dedicated
            # lb_drop channel: upstream's LB lookup runs BEFORE the
            # endpoint program, so NO_SERVICE wins over policy too
            out, row_map = self.loader.step(
                hdr_dev, now, pre_drop=nat_drop,
                pre_drop_reason=bw_reasons, lb_drop=svc_nobe,
                audit=self.config.policy_audit_mode)
            if self.nat is not None:
                # reverse translation AFTER the verdict (CT/policy see
                # the wire tuple; delivery + events see the restored
                # pod destination)
                hdr_dev = self.loader.reverse_nat(self.nat, hdr_dev,
                                                  now)
            hdr = np.asarray(hdr_dev)
            return self._finish_batch(out, hdr, row_map, now)
        out, row_map = self.loader.step(
            hdr, now, audit=self.config.policy_audit_mode)
        return self._finish_batch(out, hdr, row_map, now)

    def _finish_batch(self, out, hdr: np.ndarray, row_map,
                      now: int) -> EventBatch:
        # thread-affinity: offline, api, cli
        """The shared process_batch tail: decode -> auth observe ->
        monitor publish (ONE definition; a per-batch hook added here
        reaches both the routed and the plain path)."""
        batch = decode_out(out, hdr, row_map.numeric_array(),
                           timestamp=time.time())
        if self.auth_manager is not None:
            self.auth_manager.observe(batch, now)
        self.monitor.publish(self._filter_events(batch))
        # offline path: aggregate inline on the CALLER's thread (the
        # serving path instead drains on the event-join worker)
        self.analytics.drain()
        return batch

    def _filter_events(self, batch: EventBatch) -> EventBatch:
        """Per-endpoint event options + monitor aggregation (reference:
        pkg/option endpoint options DropNotification/TraceNotification/
        Debug and --monitor-aggregation).  Filters what the MONITOR
        plane sees; the caller's EventBatch (and metrics) keep every
        row — the reference likewise only gates event emission."""
        from ..core.packets import (COL_EP, COL_FLAGS, COL_PROTO,
                                    TCP_FIN, TCP_RST, TCP_SYN)
        from ..monitor.api import MSG_DROP, MSG_TRACE

        opts = self.endpoints.event_options()
        aggregate = self.config.monitor_aggregation == "medium"
        if not opts and not aggregate:
            return batch
        keep = np.ones(len(batch), dtype=bool)
        ep_col = batch.hdr[:, COL_EP]
        if aggregate:
            proto = batch.hdr[:, COL_PROTO]
            flags = batch.hdr[:, COL_FLAGS]
            boring = ((proto == 6)
                      & ((flags & (TCP_SYN | TCP_FIN | TCP_RST)) == 0)
                      & (batch.msg_type == MSG_TRACE))
            debug_eps = [e for e, o in opts.items() if o.get("Debug")]
            for e in debug_eps:
                boring &= ep_col != e
            keep &= ~boring
        for ep_id, o in opts.items():
            m = ep_col == ep_id
            if not o.get("DropNotification", True):
                keep &= ~(m & (batch.msg_type == MSG_DROP))
            if not o.get("TraceNotification", True):
                keep &= ~(m & (batch.msg_type == MSG_TRACE))
        if keep.all():
            return batch
        return EventBatch(
            msg_type=batch.msg_type[keep], verdict=batch.verdict[keep],
            reason=batch.reason[keep], ct_state=batch.ct_state[keep],
            identity=batch.identity[keep],
            proxy_port=batch.proxy_port[keep], hdr=batch.hdr[keep],
            timestamp=batch.timestamp)

    # -- policy API ---------------------------------------------------
    def policy_import(self, obj) -> int:
        return self.repo.add_obj(obj)

    def policy_delete(self, labels: List[str]) -> int:
        return self.repo.delete_by_labels(labels)

    def policy_get(self) -> dict:
        return {"revision": self.repo.revision,
                "rules": [rule_to_dict(r) for r in self.repo.rules()]}

    # -- endpoint API --------------------------------------------------
    def add_endpoint(self, name: str, ips: Tuple[str, ...],
                     labels: List[str],
                     named_ports: Optional[Dict[str, int]] = None
                     ) -> Endpoint:
        return self.endpoints.add(name, ips, LabelSet.parse(*labels),
                                  named_ports=named_ports)

    # -- L7 proxy API (the listener-facing entry) ----------------------
    def handle_l7_http(self, proxy_port: int, requests,
                       src_identity: int = 0) -> np.ndarray:
        """Verdict HTTP requests arriving on a redirect listener
        (1 = forward, 0 = 403)."""
        row = (self.loader.row_map.row(src_identity)
               if self.loader.row_map else 0)
        return self.proxy.handle_http(proxy_port, requests, row)

    def handle_l7_dns(self, proxy_port: int, qnames,
                      src_identity: int = 0) -> np.ndarray:
        row = (self.loader.row_map.row(src_identity)
               if self.loader.row_map else 0)
        return self.proxy.handle_dns(proxy_port, qnames, row)

    def handle_l7_kafka(self, proxy_port: int, requests,
                        src_identity: int = 0) -> np.ndarray:
        row = (self.loader.row_map.row(src_identity)
               if self.loader.row_map else 0)
        return self.proxy.handle_kafka(proxy_port, requests, row)

    def handle_l7(self, kind: str, proxy_port: int, requests,
                  src_identity: int = 0) -> np.ndarray:
        """Verdict requests of a PLUGIN protocol (cassandra,
        memcached, or anything proxy/registry.py knows)."""
        row = (self.loader.row_map.row(src_identity)
               if self.loader.row_map else 0)
        return self.proxy.handle(kind, proxy_port, requests, row)

    def proxy_stats(self) -> dict:
        """``GET /proxy/stats`` / ``cilium-tpu proxy stats``: the
        proxy plane's full picture — listener table, offline proxy
        counters, the LIVE L7 worker-pool ledger (or the last
        session's final one), per-plugin parse latency."""
        from ..proxy import registry as l7registry

        l7 = self._l7plane
        out = {
            "listeners": self.proxy.listeners(),
            "requests-total": self.proxy.requests_total,
            "requests-denied": self.proxy.requests_denied,
            "plane-active": l7 is not None,
            "parse-latency-by-plugin": l7registry.latency_snapshot(),
        }
        if l7 is not None:
            out["plane"] = l7.stats()
        elif self._l7_last is not None:
            out["plane"] = self._l7_last
        return out

    # -- k8s integration ----------------------------------------------
    _k8s_hub = None

    def k8s_watchers(self):
        """The k8s watcher aggregate (pkg/k8s/watchers analogue);
        drive it from an informer stream or fixture replay."""
        if self._k8s_hub is None:
            from ..k8s.watchers import K8sWatcherHub

            self._k8s_hub = K8sWatcherHub(self)
        return self._k8s_hub

    # -- clustermesh API ----------------------------------------------
    def connect_cluster(self, name: str, cluster_id: int, kv):
        """Join a remote cluster's store (reference: clustermesh
        config per remote cluster)."""
        return self.clustermesh.connect(name, cluster_id, kv)

    # -- serving path: device event ring -> monitor plane --------------
    def start_serving(self, ring_capacity: int = 1 << 15,
                      drain_every: int = 4,
                      trace_sample: int = 1024,
                      ingress: bool = False,
                      packed: Optional[bool] = None,
                      mesh=None,
                      shard_headroom: int = 2,
                      span_sample: Optional[int] = None,
                      window_queue_depth: Optional[int] = None,
                      event_gather: Optional[bool] = None,
                      superbatch_k: Optional[int] = None) -> None:
        """Switch to the SERVING monitor path: batches run through the
        fused datapath + device event-ring append (one dispatch, no
        per-packet host fetch), and only the compacted events cross to
        the host at the drain cadence — upstream's perf-ring economics
        (the kernel streams events, not packets).  :meth:`serve_batch`
        feeds it; :meth:`stop_serving` drains what is in flight.

        ``ingress=True`` additionally starts the serving FRONT END
        (cilium_tpu/serving): a bounded admission queue + adaptive
        batcher + drain loop, configured by the DaemonConfig
        ``serving_*`` knobs.  :meth:`submit` then feeds a packet
        STREAM; batches assemble, pad to the bucket ladder, and
        dispatch through :meth:`serve_batch` with sheds surfaced as
        monitor DROP events (``REASON_INGRESS_OVERFLOW``).

        ``packed=True`` (default: the ``serving_packed_ingest``
        config knob) ships eligible IPv4 single-stream buckets as the
        packed 16 B/packet wire format — 4x fewer h2d bytes — through
        :meth:`TPULoader.serve_packed`; ineligible traffic falls back
        to the wide shape per batch.

        ``span_sample`` (default: the ``serving_trace_sample``
        config knob) arms PER-PACKET TRACE SPANS on the ingress
        path: 1-in-N admitted packets carry a span through admission
        -> dequeue -> staging -> dispatch -> device -> verdict join
        (six monotonic stage timestamps + batch/bucket/mode
        annotations), surfaced via ``GET /debug/traces`` and
        ``cilium-tpu trace``.  0 = off = zero overhead; sampling is
        deterministic over the admitted-packet sequence.

        ``window_queue_depth`` / ``event_gather`` (defaults: the
        ``serving_window_queue_depth`` / ``serving_event_gather``
        config knobs) shape the ASYNC EVENT PLANE
        (serving/eventplane.py): drained windows are handed to a
        dedicated event-join worker over a bounded queue (overflow
        drops the offered window, COUNTED) and the fetch ships an
        occupancy-bounded device gather — d2h bytes scale with the
        events a window appended, not the ring capacity.  The drain
        thread's only event work is the 8-byte cursor sync + a queue
        push; decode / wide-column join / monitor fan-out all run on
        the worker.

        ``superbatch_k`` (default: the ``serving_superbatch_k``
        config knob) arms K-BATCH SUPERBATCH DISPATCH on the ingress
        path: the drain loop fuses up to K ready batches into one
        device dispatch (``lax.scan`` over the K steps — datapath +
        ring append per step, one cursor sync per drain tick), so
        per-dispatch Python cost is paid once per K batches.  K is a
        fallback-ladder rung property (demotion shrinks K before
        changing mode); assembly never waits for K batches, so
        low-load latency is unchanged.  1 disables.

        ``mesh=...`` (a ``jax.sharding.Mesh`` or a device count)
        switches to MULTI-CHIP serving: each assembled bucket is
        flow-routed (``parallel.route_by_flow`` — the RSS analogue)
        into per-shard blocks and dispatched through the sharded
        serve step (CT private per chip, policy/ipcache replicated,
        per-chip event rings drained round-robin).  Router overflow
        is counted in the metricsmap as ``REASON_ROUTE_OVERFLOW`` and
        surfaced as monitor DROP events.  ``shard_headroom`` sizes
        each shard's block at ``headroom * bucket / n_shards`` — the
        RSS ring-sizing trade-off: headroom 1 ships the fewest bytes
        but a full bucket of uniform flows overflows ~every shard
        (block == fair share, zero slack); the default 2 makes skew
        loss negligible for ~2x link/lane padding, and every drop is
        counted either way.

        Requires the tpu backend (the interpreter loader has no device
        ring).  Redirect events carry their proxy port as an index
        into the CURRENT listener table (monitor/ring.py); listeners
        added later stream as port 0 until serving is restarted."""
        import jax.numpy as jnp

        from ..datapath.loader import TPULoader
        from ..monitor.ring import (AsyncRingDrainer, MAX_PROXY_PORTS,
                                    ShardedAsyncRingDrainer)
        from ..serving import (BucketArena, ServingAlreadyActiveError,
                               ServingBackendError)

        if not isinstance(self.loader, TPULoader):
            raise ServingBackendError(
                "serving path requires backend='tpu'")
        if self._serving is not None:
            # silently replacing the drainer would drop its in-flight
            # window without any loss accounting
            raise ServingAlreadyActiveError(
                "already serving; stop_serving() first")
        if packed is None:
            packed = self.config.serving_packed_ingest
        if span_sample is None:
            span_sample = self.config.serving_trace_sample
        span_sample = int(span_sample)
        if span_sample < 0:
            # the whole obs-knob contract, applied to the explicit
            # argument: reject here, before self._serving is
            # assigned or the loader re-sharded — a raise below
            # would wedge the daemon in a phantom "already serving"
            # state
            raise ValueError("span_sample must be >= 0 "
                             "(0 disables span tracing)")
        if span_sample and not ingress:
            # validate BEFORE any side effect: below this point the
            # loader may already be re-sharded, and an error path
            # that leaves mutated placement behind is worse than the
            # misconfiguration it reports.  The config knob resolves
            # first so a daemon armed with serving_trace_sample fails
            # just as loudly as an explicit span_sample= argument
            # instead of silently tracing nothing
            raise ValueError(
                "span_sample tracing needs ingress=True: spans are "
                "allocated at IngressQueue admission")
        if window_queue_depth is None:
            window_queue_depth = self.config.serving_window_queue_depth
        window_queue_depth = int(window_queue_depth)
        if window_queue_depth < 1:
            raise ValueError("window_queue_depth must be >= 1")
        if event_gather is None:
            event_gather = self.config.serving_event_gather
        event_gather = bool(event_gather)
        # K-batch superbatch dispatch (ISSUE 11): validate the
        # per-session override exactly like the config knob — before
        # any side effect below
        from ..serving import validate_superbatch_config

        if superbatch_k is None:
            superbatch_k = self.config.serving_superbatch_k
        superbatch_k, k_ladder = validate_superbatch_config(
            superbatch_k)
        table = np.asarray(sorted(self.proxy.ports)[:MAX_PROXY_PORTS],
                           dtype=np.uint32)
        n_shards = 0
        if mesh is not None:
            from ..parallel import make_mesh, make_sharded_ring

            if isinstance(mesh, int):
                mesh = make_mesh(mesh)
            if "data" not in mesh.axis_names:
                # the sharded serving stack steers over the "data"
                # axis end-to-end (shard_state, make_sharded_ring,
                # make_sharded_serve_step); a differently-named axis
                # would die deep inside jax with an unbound-axis error
                raise ValueError(
                    f"serving mesh must have a 'data' axis, got "
                    f"axis_names={mesh.axis_names} (make_mesh builds "
                    f"the right one)")
            n_shards = int(mesh.devices.size)
            ladder = self.config.serving_bucket_ladder
            if ladder[0] % n_shards:
                raise ValueError(
                    f"sharded serving needs every ladder bucket "
                    f"divisible by the {n_shards}-chip mesh; smallest "
                    f"bucket is {ladder[0]}")
            if shard_headroom < 1:
                raise ValueError("shard_headroom must be >= 1")
            self.loader.serving_shard(mesh)
            drainer = ShardedAsyncRingDrainer(
                ring_capacity, n_shards,
                fresh_fn=lambda: make_sharded_ring(mesh,
                                                   ring_capacity),
                proxy_ports=table, gather=event_gather,
                compile_log=self.loader.compile_log)
        else:
            drainer = AsyncRingDrainer(
                ring_capacity, proxy_ports=table,
                gather=event_gather,
                compile_log=self.loader.compile_log)
        # the degraded-mode ladder (serving/ladder.py): rungs this
        # session can actually run — no mesh, no "sharded" rung; no
        # packing, no "single" rung; "wide" is always the floor
        from ..serving.ladder import (FallbackLadder, RUNG_SHARDED,
                                      RUNG_SINGLE, RUNG_WIDE)

        rungs = ([RUNG_SHARDED] if mesh is not None else []) \
            + ([RUNG_SINGLE] if packed else []) + [RUNG_WIDE]
        cfg = self.config
        # arena recycling horizon (batcher.py ownership-handoff
        # contract), EXTENDED to cover the async event plane: a
        # header slot must outlive the batches filling the next
        # window (drain_every) plus every window in flight on the
        # worker (bounded queue + the one being joined, +1 window of
        # mid-join slack) — windows keep their records by REFERENCE
        # (the swap-time snapshot), so the slot count is the only
        # thing that scales.  The worker REFUSES joins older than
        # join_horizon batches (counted drops), which is what makes
        # this depth a guarantee rather than a hope when the plane
        # stalls: a window's records span [seq-2*drain_every, seq),
        # slots recycle depth batches after allocation, so joins are
        # safe while (live seq - window seq) < depth - 2*drain_every;
        # the horizon keeps one extra drain_every of slack under that
        arena_depth = (window_queue_depth + 3) * drain_every + 2
        join_horizon = window_queue_depth * drain_every + 2
        # the event-join worker: the drain thread's only event work
        # becomes swap (cursor sync + async occupancy-bounded copy)
        # + one bounded-queue push; THIS thread finishes the
        # transfer, decodes, joins, and emits — restart-on-death
        # under the serving restart budget, terminal once exhausted
        from ..serving.eventplane import EventJoinWorker

        worker = EventJoinWorker(
            self._event_join, drop_fn=self._event_drop,
            queue_depth=window_queue_depth,
            restart_budget=cfg.serving_restart_budget,
            on_terminal=self._eventworker_incident)
        # the L7 proxy plane (serving/l7plane.py): redirected rows fan
        # out of the event-join worker into the bounded worker pool.
        # Held as a daemon ATTRIBUTE, not a _serving key —
        # _emit_ring_rows (event-worker thread) is contractually
        # barred from touching the session dict, and an atomic
        # attribute read is all the fan-out needs
        from ..serving.l7plane import L7Plane

        l7plane = L7Plane(
            self.proxy,
            workers=cfg.l7_workers,
            queue_depth=cfg.l7_queue_depth,
            restart_budget=cfg.serving_restart_budget,
            on_terminal=self._l7pool_incident,
            request_source=self.l7_request_source,
            dns_resolver=self.l7_dns_resolver)
        self._serving = {
            "drainer": drainer,
            "ring": drainer.fresh(),
            "table_dev": jnp.asarray(table) if len(table) else None,
            "proxy_table": table,  # host copy: demotion rebuilds the
            "ring_capacity": ring_capacity,  # drainer from these
            "trace_sample": trace_sample,
            "drain_every": drain_every,
            "seq": 0,
            "packed": bool(packed),
            "packed_pref": bool(packed),  # survives wide demotion
            "mesh": mesh,
            "mesh_pref": mesh,  # survives sharded demotion
            "n_shards": n_shards,
            "headroom": int(shard_headroom),
            "route_overflow": 0,
            "ladder": FallbackLadder(
                rungs,
                demote_threshold=cfg.serving_demote_threshold,
                promote_after=cfg.serving_promote_after,
                cooldown_s=cfg.serving_promote_cooldown_s,
                k_ladder=k_ladder),
            # superbatch dispatch: the configured K ceiling (the
            # ladder's live K can sit below it after demotions) —
            # also stretches the drain tick's window retention
            "superbatch_k": superbatch_k,
            # packed re-staging arena for the sharded path; same
            # recycling horizon as the batcher arena (routed/valid/
            # orig buffers ride windows onto the worker too)
            "route_arena": BucketArena(arena_depth),
            # batch_id (wrapped) -> (kind, host rows, (ep, dirn) or
            # None, numeric ids, timestamp); kind "wide" | "packed"
            "window": {},
            # the async event plane: worker + the spans accumulated
            # since the last drain tick (bid -> tuple[TraceSpan];
            # drain-thread-only, snapshotted into each DrainWindow)
            "eventplane": worker,
            "gather": event_gather,
            "join_horizon": join_horizon,
            "spans": {},
            # seq at the last drain tick: serve_batch ticks when
            # drain_every batches have dispatched since; the idle
            # hook ticks whenever ANY have (so windows flush when
            # traffic pauses instead of waiting for a batch that may
            # never come)
            "last_tick": 0,
            "tracer": None,
        }
        l7plane.start()
        self._l7plane = l7plane
        worker.start()
        if ingress:
            from ..core.packets import N_COLS
            from ..serving import ServingRuntime

            tracer = None
            if span_sample:
                from ..obs import SpanTracer

                tracer = SpanTracer(span_sample, seed=cfg.fault_seed)
            self._serving["tracer"] = tracer
            deadline_s = cfg.serving_dispatch_deadline_ms * 1e-3
            runtime = ServingRuntime(
                dispatch=self._serving_dispatch,
                # the K-batch leg: the ladder's CURRENT (mode, K)
                # rung decides the live K (sharded rungs pin K=1 —
                # superbatching is a single-chip dispatch shape)
                dispatch_super=self._serving_dispatch_super,
                superbatch_k=self._serving["ladder"].k,
                on_shed=self._publish_sheds,
                on_recovery_drop=self._publish_recovery_drops,
                queue_depth=cfg.serving_queue_depth,
                bucket_ladder=cfg.serving_bucket_ladder,
                max_wait_us=cfg.serving_max_wait_us,
                overflow_policy=cfg.serving_overflow_policy,
                expected_cols=N_COLS,
                # sharded dispatch flow-routes WIDE rows and re-packs
                # after routing, so the batcher packs only when the
                # bucket goes straight to the single-chip device leg
                pack=bool(packed) and mesh is None,
                # arena slots outlive every window in flight on the
                # event-join worker — the ownership handoff contract
                # in serving/batcher.py, sized above
                arena_depth=arena_depth,
                # fault tolerance: watchdog deadline + restart budget
                # from the serving_* knobs; the consumer-idle tick is
                # DERIVED from the deadline so sub-50ms deadlines are
                # honorable (a loop asleep in a 50ms wait cannot
                # notice churn faster than the wait)
                dispatch_deadline_s=deadline_s,
                restart_budget=cfg.serving_restart_budget,
                restart_backoff_s=cfg.serving_restart_backoff_ms
                * 1e-3,
                idle_wait_s=(min(0.05, deadline_s / 4)
                             if deadline_s > 0 else 0.05),
                # obs plane: span tracer + the batch-scoped
                # jax.profiler capture window.  No gauge_fn: the
                # registry reads the in-flight window live at scrape
                # (an idle-tick copy would disagree with /metrics
                # during sustained load, when the idle tick never
                # fires)
                tracer=tracer,
                # the async event plane owns sampled spans from the
                # dispatch return on: device/join stamp at true
                # window-join time on the worker
                span_sink=self._serving_span_sink,
                # idle-cadence drain tick: flush the pending window
                # when traffic pauses (the worker then joins it off
                # the dispatch path as usual)
                idle_fn=self._serving_event_idle_tick,
                # flight recorder: every watchdog restart (and the
                # terminal transition) is a named incident with an
                # auto-captured sysdump bundle
                on_restart=self._serving_restart_incident,
                profile_dir=cfg.profile_dir,
                profile_batches=cfg.profile_batches)
            self._serving["runtime"] = runtime
            runtime.start()

    def _serving_dispatch(self, hdr: np.ndarray, valid: np.ndarray,
                          n_valid: int, packed_meta=None):
        # thread-affinity: drain, api -- the ServingRuntime dispatch
        # callback; stop()'s final drain also lands here
        """The runtime's device leg: one padded bucket through
        serve_batch (padding masked out of CT/metrics/events).
        ``hdr`` arrives as a batcher arena slot whose recycling
        horizon outlives serve_batch's retain-by-reference window
        join (arena_depth above), so no copy is needed.

        Wide batches keep the legacy 3-arg serve_batch call shape —
        tests (and operators) wrap serve_batch with spies that only
        know (hdr, now, valid).

        The DEGRADED-MODE LADDER wraps the device leg: a dispatch
        failure counts toward the rung's demotion threshold; at the
        threshold the session demotes (sharded -> single-chip ->
        wide, CT carried via snapshot + restore) and the TRIGGERING
        batch retries on the demoted rung — it has not been recorded
        anywhere yet, so nothing double-counts.  Below the threshold
        the failure is CONTAINED (DispatchFailedError): the runtime
        accounts the batch as counted recovery drops and keeps the
        loop alive.  At the ladder floor failures escalate raw —
        burning the runtime's restart budget until terminal.
        Sustained health re-promotes after the cooldown."""
        from ..serving import DispatchFailedError

        s = self._serving
        try:
            info = self._serving_device_leg(hdr, valid, packed_meta)
        except Exception as e:  # noqa: BLE001 — any device-leg fault
            lad = s.get("ladder")
            if lad is None:
                raise
            cause = f"{type(e).__name__}: {e}"
            if not lad.record_failure(cause):
                if lad.at_floor:
                    raise  # not containable: escalate to the watchdog
                raise DispatchFailedError(
                    f"dispatch failed on rung {lad.rung!r} "
                    f"({lad.fail_streak}/{lad.demote_threshold}): "
                    f"{cause}") from e
            self._serving_demote(cause)
            # retry the triggering batch on the demoted rung: a
            # sharded-mode bucket is wide (the batcher never packs
            # under a mesh), and a packed bucket demoting to wide
            # unpacks host-side first
            if packed_meta is not None and not s["packed"]:
                from ..core.packets import unpack_rows_np

                hdr = unpack_rows_np(np.asarray(hdr), *packed_meta)
                packed_meta = None
            info = self._serving_device_leg(hdr, valid, packed_meta)
            if isinstance(info, dict):
                # obs plane: this batch CROSSED the demotion — its
                # sampled spans carry the flag (a trace through a
                # ladder transition is exactly what the span ring
                # exists to explain after the fact)
                info["demoted"] = True
        lad = s.get("ladder")
        if (lad is not None and lad.record_success()
                and s.get("runtime") is not None):
            self._serving_promote()
        return info

    def _serving_device_leg(self, hdr, valid, packed_meta):
        # thread-affinity: drain, api
        if packed_meta is None:
            return self.serve_batch(hdr, valid=valid)
        return self.serve_batch(hdr, valid=valid,
                                packed_meta=packed_meta)

    def _serving_dispatch_super(self, sb):
        # thread-affinity: drain
        """The runtime's K-BATCH device leg (ISSUE 11) with the same
        degraded-mode ladder wrap as :meth:`_serving_dispatch`: a
        failure counts toward the rung's demotion threshold; at the
        threshold the session demotes — shrinking K BEFORE changing
        mode — and the triggering K batches retry ONE BY ONE on the
        demoted rung (nothing recorded yet, so nothing
        double-counts).  Below the threshold the failure is contained
        exactly like a single-batch one."""
        from ..serving import DispatchFailedError

        s = self._serving
        try:
            info = self.serve_superbatch(sb)
        except Exception as e:  # noqa: BLE001 — any device-leg fault
            lad = s.get("ladder")
            if lad is None:
                raise
            cause = f"{type(e).__name__}: {e}"
            if not lad.record_failure(cause):
                if lad.at_floor:
                    raise  # not containable: escalate to the watchdog
                raise DispatchFailedError(
                    f"superbatch dispatch failed on rung "
                    f"{lad.rung!r} k={lad.k} "
                    f"({lad.fail_streak}/{lad.demote_threshold}): "
                    f"{cause}") from e
            self._serving_demote(cause)
            info = self._serving_retry_super_steps(sb)
            info["demoted"] = True
        lad = s.get("ladder")
        if (lad is not None and lad.record_success()
                and s.get("runtime") is not None):
            self._serving_promote()
        return info

    def _serving_retry_super_steps(self, sb) -> dict:
        # thread-affinity: drain
        """Retry a failed superbatch's steps one-by-one through the
        single-batch device leg (the most conservative rung of the K
        ladder) — a packed step unpacks host-side first when the
        demotion also left packed mode."""
        s = self._serving
        bids, total_h2d, mode = [], 0, None
        for k in range(sb.k):
            hdr = sb.hdr[k]
            meta = ((int(sb.eps[k]), int(sb.dirns[k]))
                    if sb.packed else None)
            if meta is not None and not s["packed"]:
                from ..core.packets import unpack_rows_np

                hdr = unpack_rows_np(np.asarray(hdr), *meta)
                meta = None
            info = self._serving_device_leg(hdr, sb.valid[k], meta)
            if isinstance(info, dict):
                bids.append(int(info.get("batch_id", -1)))
                total_h2d += int(info.get("h2d_bytes", 0))
                mode = info.get("mode", mode)
            else:
                bids.append(-1)
        return {"h2d_bytes": total_h2d,
                "mode": mode or ("packed" if s["packed"] else "wide"),
                "bids": bids,
                # K single dispatches actually ran — the dispatch
                # scoreboard must not count the retry as one fused
                # superbatch
                "dispatches": sb.k}

    def _serving_demote(self, cause: str) -> None:
        # thread-affinity: drain, api
        """One rung down (drain-thread context).  sharded -> single:
        drain the per-chip rings, SNAPSHOT the (sharded) CT, rebuild
        the single-device placement, and ct_restore the snapshot so
        established flows survive — the endpoint-regeneration
        discipline applied to the serving plane.  single -> wide:
        stop packing (both the batcher and the per-batch eligibility
        path)."""
        import logging

        s = self._serving
        lad = s["ladder"]
        old, old_k = lad.rung, lad.k
        new = lad.demote()
        from ..obs.flightrec import KIND_DEMOTION

        if new == old:
            # K-ONLY shrink (ISSUE 11): the mode keeps its
            # capability, only the superbatch amortization drops —
            # no ring/CT/placement mechanics, no warm-shape reset
            # (each K is its own executable shape; the smaller K is
            # already warm from before superbatching engaged, or
            # compiles under the cold-shape deadline exemption)
            # hot-path-ok: a LADDER DEMOTION is a rare contained-
            # failure event, never per-batch
            logging.getLogger(__name__).warning(
                "serving ladder shrinks superbatch K %d -> %d on "
                "rung %s: %s", old_k, lad.k, new, cause)
            self.record_incident(KIND_DEMOTION,
                                 {"from": f"{old}@k{old_k}",
                                  "to": f"{new}@k{lad.k}",
                                  "cause": cause})
            runtime = s.get("runtime")
            if runtime is not None:
                runtime.superbatch_k = lad.k
                # the triggering superbatch retries its steps
                # one-by-one: a single-batch shape that never
                # dispatched this session pays its XLA compile
                # during the retry, and the in-flight registration
                # (computed from the SUPERBATCH shape) would not be
                # deadline-exempt — the watchdog would deadline the
                # retry mid-flight and double-account rows whose
                # device effects already landed.  Same discipline
                # as the mode-demotion path below.
                runtime.reset_warm_shapes()
            return
        # hot-path-ok: a LADDER DEMOTION is a rare contained-failure
        # event (>= demote_threshold consecutive dispatch failures) —
        # the warning is part of the incident record, never per-batch
        logging.getLogger(__name__).warning(
            "serving ladder demotes %s -> %s: %s", old, new, cause)
        self.record_incident(KIND_DEMOTION,
                             {"from": old, "to": new, "cause": cause})
        if old == "sharded":
            from ..monitor.ring import AsyncRingDrainer

            # flush what the per-chip rings already hold onto the
            # event plane (the window keeps its own buffer/record
            # references, so rebuilding the drainer below is safe
            # while the worker is still joining it); best effort —
            # the ledger counts anything a wedged swap abandons
            try:
                self._serving_drain_tick(s)
            except Exception:  # noqa: BLE001
                # hot-path-ok: demotion failure path only (see above)
                logging.getLogger(__name__).warning(
                    "sharded ring drain failed during demotion; "
                    "in-flight window events lost (counted)")
            # CT continuity: snapshot (gathers every chip's private
            # shard), unshard, restore into the single-device
            # placement.  A wedged gather falls back to the last
            # periodic snapshot rather than dropping all flows.
            ct, fresh = None, False
            try:
                ct = self.loader.ct_snapshot()
                fresh = True
            except Exception:  # noqa: BLE001
                if self._ct_snap is not None:
                    ct = self._ct_snap["rows"]
                    # hot-path-ok: demotion failure path only
                    logging.getLogger(__name__).warning(
                        "live CT unreadable during demotion; "
                        "restoring the %.1fs-old periodic snapshot",
                        time.time() - self._ct_snap["taken-at"])
            self.loader.serving_unshard()
            if ct is not None:
                if fresh:
                    # a STALE fallback keeps its original taken-at:
                    # re-stamping it would zero the age every
                    # telemetry surface reports and hide how old a
                    # later restore really is
                    self._store_ct_snapshot(ct, trigger="demotion")
                self.loader.ct_restore(ct)
            s["mesh"] = None
            s["n_shards"] = 0
            d = AsyncRingDrainer(s["ring_capacity"],
                                 proxy_ports=s["proxy_table"],
                                 gather=s["gather"],
                                 compile_log=self.loader.compile_log)
            s["drainer"] = d
            s["ring"] = d.fresh()
            s["window"].clear()
        s["packed"] = (new == "single") and s["packed_pref"]
        runtime = s.get("runtime")
        if runtime is not None:
            # single-chip rungs pack in the batcher; wide never does
            runtime.batcher.pack = s["packed"] and s["mesh"] is None
            # a mode demotion enters the new mode at ITS best K
            runtime.superbatch_k = lad.k
            # the demoted mode's executables compile on first
            # dispatch — not a hang
            runtime.reset_warm_shapes()

    def _serving_promote(self) -> None:
        # thread-affinity: drain, api
        """One rung back up after sustained health + cooldown
        (drain-thread context).  wide -> single re-enables packing;
        single -> sharded re-places the live state on the mesh and
        swaps back to per-chip rings.  NOTE: re-sharding scatters CT
        rows by position, not flow hash — flows whose entry lands on
        a different chip than their flow route re-establish on their
        next packet (counted as NEW, never dropped); demotion is the
        direction that must be lossless, and is."""
        import logging

        s = self._serving
        lad = s["ladder"]
        old, old_k = lad.rung, lad.k
        new = lad.promote()
        if new == old:
            # K-ONLY growth: re-arm the superbatch amortization on
            # the same mode — no placement/ring mechanics
            # hot-path-ok: promotions happen at most once per
            # cooldown_s (hysteresis-gated recovery)
            logging.getLogger(__name__).info(
                "serving ladder grows superbatch K %d -> %d on "
                "rung %s", old_k, lad.k, new)
            runtime = s.get("runtime")
            if runtime is not None:
                runtime.superbatch_k = lad.k
            return
        # hot-path-ok: promotions happen at most once per cooldown_s
        # (hysteresis-gated recovery, not steady state)
        logging.getLogger(__name__).info(
            "serving ladder promotes %s -> %s", old, new)
        if new == "sharded":
            from ..monitor.ring import ShardedAsyncRingDrainer
            from ..parallel import make_sharded_ring

            mesh = s["mesh_pref"]
            try:
                self._serving_drain_tick(s)
            except Exception:  # noqa: BLE001
                pass
            self.loader.serving_shard(mesh)
            s["mesh"] = mesh
            s["n_shards"] = int(mesh.devices.size)
            cap = s["ring_capacity"]
            s["drainer"] = ShardedAsyncRingDrainer(
                cap, s["n_shards"],
                fresh_fn=lambda: make_sharded_ring(mesh, cap),
                proxy_ports=s["proxy_table"], gather=s["gather"],
                compile_log=self.loader.compile_log)
            s["ring"] = s["drainer"].fresh()
            s["window"].clear()
            s["packed"] = False
        else:  # -> single
            s["packed"] = s["packed_pref"]
        runtime = s.get("runtime")
        if runtime is not None:
            runtime.batcher.pack = s["packed"] and s["mesh"] is None
            # a mode promotion enters the better mode at its
            # SMALLEST K (the inverse of demote's entry-at-best-K)
            runtime.superbatch_k = lad.k
            runtime.reset_warm_shapes()

    def _publish_recovery_drops(self, rows: Optional[np.ndarray],
                                count: int, reason: int) -> None:
        # thread-affinity: drain, watchdog, api
        """Recovery-plane drops (dead/hung/failed dispatch, dead-loop
        stop sweep) -> metricsmap + decoded monitor DROP events —
        the same double surfacing REASON_ROUTE_OVERFLOW gets, so the
        loss is visible both to counters and to flow consumers."""
        from ..monitor.api import synth_drop_batch

        self.loader.add_host_drops(reason, count)
        if rows is None or not len(rows):
            return
        batch = synth_drop_batch(rows, reason, time.time())
        self.monitor.publish(self._filter_events(batch))

    def _publish_sheds(self, rows: Optional[np.ndarray],
                       count: int) -> None:
        # thread-affinity: drain, api
        """Admission sheds -> monitor DROP events.  ``rows`` is the
        bounded retained subset; ``count`` is exact (the counter in
        serving stats carries the difference when retention capped)."""
        from ..datapath.verdict import REASON_INGRESS_OVERFLOW
        from ..monitor.api import synth_drop_batch

        if rows is None or not len(rows):
            return
        batch = synth_drop_batch(rows, REASON_INGRESS_OVERFLOW,
                                 time.time())
        self.monitor.publish(self._filter_events(batch))

    def _publish_cluster_drops(self, rows: Optional[np.ndarray],
                               count: int) -> None:
        # thread-affinity: router, api
        """Cluster-router sheds -> metricsmap + decoded monitor DROP
        events on THIS node (the flow's owner, or a surviving peer
        when the owner died) — the same double surfacing every other
        host-side drop gets.  ``rows`` is the bounded retained
        subset; ``count`` is exact."""
        from ..datapath.verdict import REASON_CLUSTER_OVERFLOW
        from ..monitor.api import synth_drop_batch

        self.loader.add_host_drops(REASON_CLUSTER_OVERFLOW, count)
        if rows is None or not len(rows):
            return
        batch = synth_drop_batch(rows, REASON_CLUSTER_OVERFLOW,
                                 time.time())
        self.monitor.publish(self._filter_events(batch))

    def submit(self, rows: np.ndarray,
               t: Optional[float] = None) -> int:
        # thread-affinity: any
        """Offer a chunk of header rows to the serving front end
        (requires ``start_serving(ingress=True)``); returns how many
        were admitted.  Never blocks — overflow sheds by the
        configured policy and surfaces as counted DROP events."""
        from ..serving import ServingNotStartedError

        s = self._serving
        runtime = s.get("runtime") if s is not None else None
        if runtime is None:
            raise ServingNotStartedError(
                "call start_serving(ingress=True) first")
        return runtime.submit(rows, t)

    # -- CT snapshots (periodic + on-demotion + on-demand) -------------
    def ct_snapshot_now(self, trigger: str = "manual") -> dict:
        """Take and retain a CT snapshot (dense portable rows).  The
        retained copy rides recovery paths — a demotion or loader
        rebuild whose live CT is unreadable restores from it instead
        of dropping every established flow."""
        rows = self.loader.ct_snapshot()
        return self._store_ct_snapshot(rows, trigger)

    def _store_ct_snapshot(self, rows: np.ndarray,
                           trigger: str) -> dict:
        s = self._serving
        lad = s.get("ladder") if s is not None else None
        self._ct_snap = {
            "rows": np.array(rows, copy=True),
            "taken-at": time.time(),
            "trigger": trigger,
            "mode": lad.rung if lad is not None else "offline",
            "revision": self.repo.revision,
        }
        return self.ct_snapshot_info()

    def ct_snapshot_info(self) -> Optional[dict]:
        """Metadata of the retained CT snapshot (None before the
        first one) — surfaced via serving stats / status /
        prometheus so operators can see how stale a recovery
        restore would be."""
        snap = self._ct_snap
        if snap is None:
            return None
        return {
            "age-seconds": round(time.time() - snap["taken-at"], 3),
            "entries": int(len(snap["rows"])),
            "trigger": snap["trigger"],
            "mode": snap["mode"],
            "revision": snap["revision"],
        }

    def restore_ct_snapshot(self) -> bool:
        """Restore the retained snapshot into the live loader (the
        recovery entry for an operator-driven or rebuild-driven CT
        reload).  False when no snapshot has been taken."""
        if self._ct_snap is None:
            return False
        self.loader.ct_restore(self._ct_snap["rows"])
        return True

    def _node_fault_info(self) -> dict:
        """The serving fault state advertised in the node registry
        (health plane): enough for a peer (or operator sweep) to see
        a degraded/restarting node without scraping its API."""
        out = {}
        s = self._serving
        if s is not None:
            lad = s.get("ladder")
            if lad is not None:
                out["serving-mode"] = lad.rung
                out["serving-degraded"] = lad.degraded
            runtime = s.get("runtime")
            if runtime is not None:
                out["serving-restarts"] = runtime.stats.restarts
        snap = self.ct_snapshot_info()
        if snap is not None:
            out["ct-snapshot-age-seconds"] = snap["age-seconds"]
        return out

    def serving_stats(self) -> dict:
        """GET /serving — front-end telemetry + ring-drain counters +
        the fault-tolerance plane (mode/ladder, restarts, recovery
        drops, CT-snapshot age)."""
        s = self._serving
        if s is None:
            return {"active": False}
        d = s["drainer"]
        out = {"active": True,
               "ring": {"windows": d.windows, "events": d.events,
                        "lost": d.lost},
               "event-plane": s["eventplane"].stats(),
               "analytics": self.analytics.stats(),
               # the map-pressure block (datapath/pressure.py):
               # cached last sample + state machine — never touches
               # the device at render time
               "pressure": self.pressure.stats(),
               # the SLO block (obs/slo.py): verdict + per-SLO
               # states off the engine's cached last evaluation —
               # a stats render never evaluates
               "slo": self.slo.stats(),
               "history": self.history.stats()}
        if s["n_shards"]:
            out["shards"] = s["n_shards"]
            out["route-overflow"] = s["route_overflow"]
        lad = s.get("ladder")
        if lad is not None:
            out["mode"] = lad.rung
            out["ladder"] = lad.to_dict()
        runtime = s.get("runtime")
        if runtime is not None:
            out.update(runtime.snapshot())
        snap = self.ct_snapshot_info()
        if snap is not None:
            out["ct-snapshot"] = snap
        log = getattr(self.loader, "compile_log", None)
        if log is not None:
            out["compile"] = log.summary()
        # live-churn plane (datapath/tables.py): published generation,
        # swap/update latency, delta-compile scoreboard
        tstats = getattr(self.loader, "table_stats", None)
        if tstats is not None:
            out["tables"] = tstats()
        if self._cluster is not None:
            # the Cluster block: tier-level counters only (router,
            # membership, failovers) — cheap by contract, because
            # every member node renders it per scrape
            out["cluster"] = self._cluster.summary()
        l7 = self._l7plane
        if l7 is not None:
            out["l7"] = l7.stats()
        return out

    def debug_traces(self, limit: int = 64) -> dict:
        """``GET /debug/traces``: the sampled span plane (per-stage
        aggregate histograms, recent + slowest completed traces) plus
        the compile-event log — the introspection surfaces an
        operator reaches for when a latency histogram says "slow"
        but not "where"."""
        out = {"enabled": False}
        s = self._serving
        tracer = s.get("tracer") if s is not None else None
        if tracer is not None:
            out = tracer.snapshot(limit=limit)
            out["enabled"] = True
        lad = s.get("ladder") if s is not None else None
        if lad is not None:
            out["mode"] = lad.rung
        log = getattr(self.loader, "compile_log", None)
        if log is not None:
            out["compile"] = log.snapshot()
        runtime = s.get("runtime") if s is not None else None
        if runtime is not None:
            prof = runtime.profile_status()
            if prof is not None:
                out["profile"] = prof
        return out

    def serve_batch(self, hdr: np.ndarray,
                    now: Optional[int] = None,
                    valid: Optional[np.ndarray] = None,
                    packed_meta=None) -> Optional[dict]:
        # thread-affinity: drain, api
        """One serving-path batch: dispatch, retain the host header
        rows for the event join, drain/emit every ``drain_every``
        batches.  ``hdr`` must be HOST memory (the serving path never
        fetches it back).  ``valid`` masks the adaptive batcher's
        padding rows (they touch neither CT, metrics, nor the ring).

        ``packed_meta=(ep, dirn)`` marks ``hdr`` as PACKED [N, 4]
        wire rows (16 B/packet h2d) with the stream-metadata scalars;
        the fused packed step unpacks on device and the event join
        reconstructs wide columns host-side only for the few rows the
        ring kept.  Under ``start_serving(mesh=...)`` the batch is
        flow-routed into per-shard blocks first (wide input only —
        the router needs wide columns; the 16 B format then ships the
        ROUTED rows).  Returns link accounting ({"h2d_bytes",
        "mode"}) for the runtime's telemetry."""
        from ..serving import ServingNotStartedError

        s = self._serving
        if s is None:
            raise ServingNotStartedError("call start_serving() first")
        if now is None:
            now = self._now()
        # drain tick BEFORE the dispatch (not after, as pre-PR5): the
        # window then covers exactly the batches dispatched since the
        # previous tick, every one of which has already handed its
        # sampled spans to _serving_span_sink — so the swap-time
        # snapshot is complete and the worker can stamp device/join
        # at true window-join time with no cross-thread rendezvous
        if s["seq"] - s["last_tick"] >= s["drain_every"]:
            self._serving_drain_tick(s)
        bid = s["seq"] & 0x1FFF  # ring batch field width
        if s["mesh"] is not None:
            if packed_meta is not None:
                raise ValueError(
                    "sharded serving routes wide rows (packing "
                    "happens after flow routing); submit wide "
                    "batches")
            info = self._serve_batch_sharded(s, hdr, now, bid, valid)
        elif packed_meta is not None:
            ep, dirn = packed_meta
            s["ring"], row_map = self.loader.serve_packed(
                s["ring"], hdr, now, bid, ep, dirn,
                trace_sample=s["trace_sample"],
                proxy_ports=s["table_dev"],
                audit=self.config.policy_audit_mode,
                valid=valid)
            self._serving_snapshot_numerics(s, row_map)
            s["window"][bid] = ("packed", np.asarray(hdr),
                                (int(ep), int(dirn)), s["numerics"],
                                time.time())
            info = {"h2d_bytes": hdr.nbytes, "mode": "packed",
                    "batch_id": bid}
        else:
            s["ring"], row_map = self.loader.serve(
                s["ring"], hdr, now, bid,
                trace_sample=s["trace_sample"],
                proxy_ports=s["table_dev"],
                audit=self.config.policy_audit_mode,
                valid=valid)
            self._serving_snapshot_numerics(s, row_map)
            # retained by REFERENCE: callers must not mutate hdr
            # until its window drains (the ingress runtime satisfies
            # this via the batcher arena's recycling horizon)
            s["window"][bid] = ("wide", np.asarray(hdr), None,
                                s["numerics"], time.time())
            info = {"h2d_bytes": hdr.nbytes, "mode": "wide",
                    "batch_id": bid}
        s["seq"] += 1
        return info

    def _serving_snapshot_numerics(self, s, row_map) -> None:
        # thread-affinity: drain, api
        # numeric_array() copies the whole row->numeric table; the map
        # only changes on identity churn, so snapshot per
        # (object, version) — the map object is REUSED and mutated
        # across regenerations (object identity alone would serve
        # stale numerics forever), and the retained REFERENCE keeps
        # the comparison sound if the loader ever swaps in a fresh
        # map (an id() of a collected object can false-match)
        if (s.get("row_map") is not row_map
                or s.get("row_map_version") != row_map.version):
            s["row_map"] = row_map
            s["row_map_version"] = row_map.version
            s["numerics"] = row_map.numeric_array()

    def serve_superbatch(self, sb, now: Optional[int] = None) -> dict:
        # thread-affinity: drain, api
        """K batches in ONE device dispatch (ISSUE 11): ``sb`` is the
        batcher's :class:`~..serving.batcher.SuperBatch` — [K, bucket,
        cols] rows + [K, bucket] valid masks.  Each inner step gets
        its own batch id (``seq + k``, the same 13-bit wrap the ring
        uses) and its own retained window record, so the event-join
        worker decodes a superbatch window exactly like K single
        batches; the drain tick still fires per DISPATCH, which is
        the one-cursor-sync-per-K-batches the amortization buys.
        Returns link accounting plus the per-step ``bids`` the
        runtime's span sink needs."""
        from ..serving import ServingNotStartedError

        s = self._serving
        if s is None:
            raise ServingNotStartedError("call start_serving() first")
        if s["mesh"] is not None:
            # the sharded session's ring is per-chip and its state
            # mesh-placed: feeding them to the single-chip superbatch
            # executable would crash opaquely (or worse) — mirror
            # serve_batch's explicit rejection.  The ladder pins K=1
            # on the sharded rung, so the drain loop never gets here;
            # this guards direct callers (warm-up scripts, operators)
            raise ValueError(
                "superbatch dispatch is a single-chip shape; "
                "sharded serving flow-routes per batch (the ladder "
                "pins K=1 on the sharded rung)")
        if now is None:
            now = self._now()
        if s["seq"] - s["last_tick"] >= s["drain_every"]:
            self._serving_drain_tick(s)
        bid0 = s["seq"] & 0x1FFF
        s["ring"], row_map = self.loader.serve_superbatch(
            s["ring"], sb.hdr, now, bid0, eps=sb.eps, dirns=sb.dirns,
            trace_sample=s["trace_sample"],
            proxy_ports=s["table_dev"],
            audit=self.config.policy_audit_mode,
            valid=sb.valid, packed=sb.packed)
        self._serving_snapshot_numerics(s, row_map)
        ts = time.time()
        kind = "packed" if sb.packed else "wide"
        bids = []
        for k in range(sb.k):
            bid = (s["seq"] + k) & 0x1FFF
            meta = ((int(sb.eps[k]), int(sb.dirns[k]))
                    if sb.packed else None)
            # per-step records retained by REFERENCE (views into the
            # superbatch arena slot, whose per-dispatch recycling
            # horizon spans K times more batches than a single slot)
            s["window"][bid] = (kind, sb.hdr[k], meta, s["numerics"],
                                ts)
            bids.append(bid)
        s["seq"] += sb.k
        return {"h2d_bytes": sb.hdr.nbytes, "mode": f"super-{kind}",
                "batch_id0": bid0, "bids": bids, "k": sb.k}

    def _serve_batch_sharded(self, s, hdr: np.ndarray, now: int,
                             bid: int, valid) -> dict:
        # thread-affinity: drain, api
        """The multi-chip leg: flow-route the bucket into per-shard
        blocks (the RSS analogue), account router overflow as
        REASON_ROUTE_OVERFLOW (metricsmap + synthesized DROP events),
        re-pack eligible routed batches to 16 B/packet, and dispatch
        the sharded serve step (CT private per chip, per-chip rings)."""
        from ..core.packets import (N_COLS, PACKED_COLS,
                                    pack_eligibility, pack_rows)
        from ..datapath.verdict import REASON_ROUTE_OVERFLOW
        from ..monitor.api import synth_drop_batch
        from ..parallel import route_by_flow

        S = s["n_shards"]
        hdr = np.asarray(hdr)
        if valid is None:
            rows = hdr
        else:
            n_valid = int(valid.sum())
            # the batcher always produces prefix-valid buckets (slice
            # = view, no copy); a direct caller may pass an arbitrary
            # mask — honor the holes (fancy-index copy) rather than
            # silently routing masked-out rows
            if valid[:n_valid].all():
                rows = hdr[:n_valid]
            else:
                rows = hdr[valid]
        bucket = max(len(hdr), S)
        # ONE routed shape per ladder rung: block is fixed at
        # headroom * bucket / S across batches of this rung (a
        # data-dependent block would retrace the sharded step every
        # batch); the headroom slack absorbs flow skew — see
        # start_serving.  Routed/valid/orig buffers come from the
        # serving arena (same recycling-horizon contract as the
        # batcher slots), keeping this leg allocation-free too.
        block = s["headroom"] * bucket // S
        arena = s["route_arena"]
        out = (arena.slot(S * block, N_COLS),
               arena.slot(S * block, 0, dtype=bool),
               arena.slot(S * block, 0, dtype=np.int64))
        routed, rvalid, orig, n_ovf = route_by_flow(rows, S, block,
                                                    out=out)
        if n_ovf:
            # a shard's block overflowed (flow skew): the loss is
            # counted where operators look (metricsmap) AND each
            # overflowed packet surfaces as a DROP event, exactly
            # like admission sheds
            s["route_overflow"] += n_ovf
            self.loader.add_route_overflow(n_ovf)
            dropped = np.ones(len(rows), dtype=bool)
            dropped[orig[orig >= 0]] = False
            batch = synth_drop_batch(rows[dropped],
                                     REASON_ROUTE_OVERFLOW,
                                     time.time())
            self.monitor.publish(self._filter_events(batch))
        ship, meta, kind = routed, None, "wide"
        if s["packed"]:
            ok, ep, dirn = pack_eligibility(rows)
            if ok:
                ship = pack_rows(
                    routed, out=s["route_arena"].slot(len(routed),
                                                      PACKED_COLS))
                meta, kind = (ep, dirn), "packed"
        s["ring"], row_map = self.loader.serve_sharded(
            s["ring"], ship, now, bid,
            trace_sample=s["trace_sample"],
            proxy_ports=s["table_dev"],
            audit=self.config.policy_audit_mode,
            valid=rvalid, packed_meta=meta)
        self._serving_snapshot_numerics(s, row_map)
        s["window"][bid] = (kind, ship, meta, s["numerics"],
                            time.time())
        info = {"h2d_bytes": ship.nbytes,
                "mode": f"sharded-{kind}", "batch_id": bid}
        if s.get("tracer") is not None:
            # per-shard span attribution: invert the router's
            # orig-index map into batch_pos -> owning shard (routed
            # position // block); -1 marks a route-overflow drop.
            # Only paid while tracing is armed, O(routed) per batch
            shard_of = np.full(len(rows), -1, dtype=np.int64)
            p = np.flatnonzero(orig >= 0)
            shard_of[orig[p]] = p // block
            info["shard_of"] = shard_of
        return info

    def _serving_drain_tick(self, s) -> None:
        # thread-affinity: drain, api
        """The drain thread's ENTIRE event leg after the async event
        plane (PR 5): block on the 8-byte cursor, start the
        occupancy-bounded async copy (``swap_window``), and push the
        window handle + its join context — the retained batch records
        and the spans accumulated since the last tick — onto the
        worker's bounded queue.  No d2h buffer wait, no decode, no
        wide-column join, no monitor fan-out here; a queue overflow
        drops the window COUNTED (never silently)."""
        from ..serving.eventplane import DrainWindow

        window, s["ring"] = s["drainer"].swap_window(s["ring"])
        s["last_tick"] = s["seq"]
        spans, s["spans"] = s["spans"], {}
        # shallow snapshot: the window keeps the records (arena slot
        # + numerics references) alive on the worker regardless of
        # the pruning below — zero copy, the ownership-horizon shape
        records = dict(s["window"])
        s["eventplane"].submit(DrainWindow(
            window, records, spans, s["n_shards"],
            tracer=s.get("tracer"), seq=s["seq"]))
        # retain headers for the batches filling the next window plus
        # one horizon of slack; in-flight windows hold their own refs.
        # A superbatch advances seq by K in one dispatch, so a window
        # spans up to drain_every + K - 1 batch records — the
        # retention stretches by the configured K ceiling
        live = {(s["seq"] - 1 - i) & 0x1FFF
                for i in range(2 * (s["drain_every"]
                                    + s.get("superbatch_k", 1)))}
        for b in list(s["window"]):
            if b not in live:
                del s["window"][b]

    def _serving_event_idle_tick(self) -> None:
        # thread-affinity: drain
        """ServingRuntime's idle hook (drain-thread context, queue
        empty): if any batch dispatched since the last drain tick,
        tick now — a traffic pause must flush the pending window to
        the event plane instead of letting its events (and sampled
        spans) wait for a drain_every-th batch that may never come.
        The monitor plane drains at its own cadence, as the
        reference's userspace perf-ring reader does."""
        s = self._serving
        if s is None or s["seq"] <= s["last_tick"]:
            return
        try:
            self._serving_drain_tick(s)
        except Exception:  # noqa: BLE001 — an idle-cadence swap
            # failure must not kill the drain loop; the dispatch-path
            # tick keeps the fault-propagation discipline
            # hot-path-ok: failure path of the IDLE tick — the queue
            # is empty by definition when this fires
            logging.getLogger(__name__).warning(
                "idle event-plane drain tick failed", exc_info=True)

    def _serving_span_sink(self, bid: int, spans: tuple) -> bool:
        # thread-affinity: drain, api
        """The runtime hands a dispatched batch's sampled spans to
        the event plane (drain-thread context).  Returns False — the
        runtime falls back to completion-boundary stamping — when the
        worker is terminal, so tracing degrades instead of leaking
        spans into a queue nobody drains."""
        s = self._serving
        if s is None:
            return False
        worker = s.get("eventplane")
        if worker is None or worker.error is not None:
            return False
        cur = s["spans"].get(bid)
        s["spans"][bid] = (cur + spans) if cur else spans
        return True

    def _event_join(self, dw) -> None:
        # thread-affinity: event-worker
        """The worker's join leg (eventplane thread, NEVER the drain
        thread): finish the d2h transfer + decode, join packed rows
        back to wide columns, emit to monitor/hubble consumers, and
        stamp sampled spans at TRUE window-join time — device work is
        provably complete once the window's fetch lands."""
        self._event_check_horizon(dw, self._serving)
        rows, shards, _appended, _lost = dw.ring.fetch()
        t_dev = time.monotonic()
        try:
            # the fetch itself can stall (tunneled d2h): the producer
            # may have dispatched past the recycling horizon while it
            # waited, so a window admitted inside the horizon can
            # still reference recycled slots by the time the rows
            # land — re-check before publishing anything
            self._event_check_horizon(dw, self._serving)
            self._emit_ring_rows(rows, shards, dw.records, dw.n_shards)
        except Exception:
            # fetch() already credited the drainer's delivered
            # counters; a refuse/emit failure means the monitor plane
            # got NOTHING, and the worker will count the whole window
            # dropped — roll the credit back so the ring ledger and
            # the event-plane ledger never double-count the same
            # events (single-writer: this thread owns the window)
            d = dw.ring.drainer
            if d is not None:
                d.windows -= 1
                d.events -= dw.appended - dw.lost
                d.lost -= dw.lost
            raise
        if dw.spans and dw.tracer is not None:
            from ..obs.trace import STAGE_DEVICE, STAGE_JOIN

            t_join = time.monotonic()
            flat = [sp for spans in dw.spans.values() for sp in spans]
            for i, sp in enumerate(flat):
                sp.ts[STAGE_DEVICE] = t_dev
                sp.ts[STAGE_JOIN] = t_join
                try:
                    dw.tracer.commit(sp)
                except Exception:  # noqa: BLE001 — the events WERE
                    # delivered above, so a tracer failure must not
                    # recount the window as a drop (the drainer credit
                    # stands); evict the uncommitted remainder so the
                    # span ledger stays exact and join normally
                    dw.tracer.evict(flat[i:])
                    logging.getLogger(__name__).warning(
                        "span commit failed at window join",
                        exc_info=True)
                    break
        # the flow analytics plane drains HERE — on the event-join
        # worker, never the drain thread.  Contained: the window's
        # events were already delivered above, so an analytics fault
        # must not recount the window as a drop
        try:
            self.analytics.drain()
        except Exception:  # noqa: BLE001
            logging.getLogger(__name__).warning(
                "flow-analytics drain failed at window join",
                exc_info=True)

    @staticmethod
    def _event_check_horizon(dw, s) -> None:
        # thread-affinity: event-worker
        """Refuse a window the producer has dispatched past the arena
        recycling horizon (stalled plane): its record references may
        point at RECYCLED slots, so a join would publish corrupted
        events.  Raising makes it a contained, COUNTED drop — never
        silent corruption.  (After stop_serving, ``s`` is None and no
        check is needed: the runtime stops dispatching before the
        worker drains.)"""
        if (s is not None and dw.seq is not None
                and s["seq"] - dw.seq > s.get("join_horizon", 1 << 30)):
            raise RuntimeError(
                f"arena horizon exceeded: window is "
                f"{s['seq'] - dw.seq} batches stale "
                f"(horizon {s['join_horizon']})")

    def _event_drop(self, dw) -> None:
        # thread-affinity: any
        """A window the event plane LOST (queue overflow, contained
        join failure, worker death, stop sweep): its spans are
        counted tracer drops — never left incomplete."""
        if dw.tracer is not None:
            for spans in dw.spans.values():
                dw.tracer.evict(spans)

    def stop_serving(self) -> dict:
        # thread-affinity: api -- `api` covers every control-plane
        # caller (API handlers, CLI shutdown, tests' main thread);
        # what matters is that it is never the drain/worker threads
        """Drain everything in flight and emit it; returns serving
        stats (windows/events/lost per the drainer's accounting, plus
        the front-end snapshot when ingress mode was on).  Idempotent:
        stopping an idle daemon is a no-op returning zero counters."""
        s = self._serving
        if s is None:
            return {"windows": 0, "events": 0, "lost": 0}
        runtime = s.get("runtime")
        front = None
        if runtime is not None:
            # stop the front end FIRST: its drain flushes every queued
            # row through serve_batch before the ring drains below
            front = runtime.stop(drain=True)
        d = s["drainer"]
        # the final window (everything appended since the last tick)
        # rides the event plane like any other, then the worker is
        # drained BEFORE the sweep: every queued window joins, and
        # anything a dead/terminal worker left behind is swept as a
        # COUNTED drop — submitted == joined + dropped holds exactly
        self._serving_drain_tick(s)
        ev = s["eventplane"].stop(drain=True)
        # the worker is drained: aggregate whatever it published
        # (caller-thread context — the drain loop has stopped)
        self.analytics.drain()
        # the L7 plane stops AFTER the event plane: the join worker
        # above was still fanning redirect rows into the pool until
        # its drain completed.  Drain the pool, keep the final stats
        # for post-stop reads (proxy stats / metrics), then detach
        l7 = None
        if self._l7plane is not None:
            l7 = self._l7plane.stop(drain=True)
            self._l7_last = l7
            self._l7plane = None
        if s["mesh"] is not None:
            # leave the loader in the default single-device placement
            # (subsequent step()/process_batch callers expect it)
            self.loader.serving_unshard()
        self._serving = None
        out = {"windows": d.windows, "events": d.events,
               "lost": d.lost, "event-plane": ev}
        if s["n_shards"]:
            out["shards"] = s["n_shards"]
            out["route-overflow"] = s["route_overflow"]
        lad = s.get("ladder")
        if lad is not None and (lad.demotions or lad.promotions):
            out["ladder"] = lad.to_dict()
        if front is not None:
            out["front-end"] = front
        if l7 is not None:
            out["l7"] = l7
        return out

    def _emit_ring_rows(self, rows: np.ndarray,
                        shards: Optional[np.ndarray],
                        records: dict, n_shards: int) -> None:
        # thread-affinity: event-worker
        """Join decoded ring rows back to their retained batch
        records and publish (event-join WORKER context: ``records``
        is the window's swap-time snapshot, so this never touches
        ``self._serving`` — which the drain thread may be mutating,
        or stop_serving may already have cleared)."""
        from ..core.packets import unpack_rows_np
        from ..monitor.api import decode_ring_rows
        from ..monitor.ring import COL_BATCH, COL_PKT_IDX

        if rows is None or not len(rows):
            return
        for b in np.unique(rows[:, COL_BATCH]):
            rec = records.get(int(b))
            if rec is None:
                continue  # header window expired (overrun drain lag)
            kind, hdr, meta, numerics, ts = rec
            m = rows[:, COL_BATCH] == b
            rows_b = rows[m]
            pkt = rows_b[:, COL_PKT_IDX].astype(np.int64)
            if shards is not None:
                # per-chip rings carry shard-LOCAL packet indices;
                # the retained window is the ROUTED tensor, shard s
                # owning rows [s*block, (s+1)*block)
                pkt = shards[m] * (len(hdr) // n_shards) + pkt
            sel = hdr[pkt]
            if kind == "packed":
                # wide columns reconstructed host-side ONLY for the
                # rows the ring compaction kept — the whole point of
                # retaining the 4x smaller packed window
                sel = unpack_rows_np(sel, *meta)
            batch = decode_ring_rows(rows_b, sel, numerics, ts,
                                     aligned=True)
            # redirect fan-out: the L7 plane's bounded submit (never
            # blocks, shed is counted).  Attribute read, not a
            # _serving key — see the contract in the docstring; a
            # racing stop_serving already drained what we submitted
            # or sheds it counted, either way the ledger closes
            l7 = self._l7plane
            if l7 is not None:
                l7.ingest(batch)
            if self.auth_manager is not None:
                # the drained window's logical now is gone; the
                # serving loop stamps batches with _now(), so grants
                # land on the same clock
                self.auth_manager.observe(batch, self._now())
            self.monitor.publish(self._filter_events(batch))

    # -- DNS proxy (pkg/fqdn/dnsproxy) --------------------------------
    def start_dns_proxy(self, resolver, host: str = "127.0.0.1"
                        ) -> Dict[int, tuple]:
        """Spawn a wire-level UDP DNS proxy per DNS redirect port
        (reference: the transparent dnsproxy pods resolve through).
        Allowed answers feed the fqdn cache, so toFQDNs identities
        mint from LIVE traffic.  Returns {proxy_port: (host, port)}
        bind addresses."""
        from ..proxy.dnslistener import DNSProxyListener

        out: Dict[int, tuple] = {}
        for l in self.proxy.listeners():
            port = l["proxy-port"]
            if l.get("dns-rules") and port not in self._dns_listeners:
                self._dns_listeners[port] = DNSProxyListener(
                    self.proxy, port, resolver,
                    observe=self.fqdn.observe, host=host)
            if port in self._dns_listeners:
                out[port] = self._dns_listeners[port].address
        return out

    def stop_dns_proxy(self) -> dict:
        stats = {p: {"queries": l.queries, "refused": l.refused,
                     "errors": l.errors}
                 for p, l in self._dns_listeners.items()}
        for l in self._dns_listeners.values():
            l.close()
        self._dns_listeners.clear()
        return stats

    # -- transparent encryption (pkg/wireguard analogue) --------------
    def seal_batch(self, peer: str, frames: bytes) -> bytes:
        """Seal a packed wire-frame buffer for ``peer`` — the egress
        half of node-to-node transparent encryption (the cilium_wg0
        transmit leg; one AEAD per batch)."""
        if self.encryption is None:
            raise RuntimeError("encryption disabled "
                               "(DaemonConfig.enable_encryption)")
        return self.encryption.channel(peer).seal(frames)

    def ingest_encrypted(self, peer: str, frame: bytes, ep: int = 0,
                         direction: int = 0,
                         now: Optional[int] = None) -> EventBatch:
        """The ingress half: open a sealed batch from ``peer``, parse
        the wire frames through the native packed path, and verdict
        them — decrypt-then-datapath, exactly the wg-device receive
        leg.  Raises encryption.DecryptError on tamper/replay."""
        if self.encryption is None:
            raise RuntimeError("encryption disabled "
                               "(DaemonConfig.enable_encryption)")
        wire = self.encryption.channel(peer).open(frame)
        from .. import native

        got = native.parse_frames_packed(wire)
        if got is None:
            got = native.parse_frames_packed_py(wire)
        rows, n, _skipped = got
        import jax.numpy as jnp

        from ..core.packets import unpack_hdr

        hdr = np.asarray(unpack_hdr(jnp.asarray(rows[:n]),
                                    jnp.uint32(ep),
                                    jnp.uint32(direction)))
        return self.process_batch(hdr, now=now)

    def socklb_entries(self, limit: int = 1000) -> list:
        """Decode the socket-LB flow cache for GET /map/lb
        (`cilium-tpu bpf lb list`).  ``socklb_stage_jit`` DONATES the
        table every batch, so a snapshot raced by process_batch can
        find its buffer deleted — retry on the replacement table
        rather than serializing the API against the hot path."""
        from ..service.socklb import socklb_entries_from_snapshot

        for _ in range(4):
            tbl = self._socklb
            if tbl is None:
                return []
            try:
                snap = np.asarray(tbl.table)
            except RuntimeError:  # donated mid-read
                continue
            return socklb_entries_from_snapshot(snap, self._now(),
                                                limit)
        return []

    # -- ipcache API (the k8s-watcher/clustermesh-facing entry) --------
    def upsert_ipcache(self, cidr: str, numeric_id: int,
                       source: str = "k8s") -> None:
        """Map a prefix to an identity; patches the device LPM in
        place when possible, else falls back to regeneration."""
        self.ipcache.upsert(cidr, numeric_id, source=source)
        if self.endpoints.patch_ipcache(cidr, numeric_id):
            return
        self.endpoints.regenerate()

    def delete_ipcache(self, cidr: str) -> None:
        self.ipcache.delete(cidr)
        if self.loader.delete_ipcache(cidr):
            return
        self.endpoints.regenerate()

    # -- runtime config mutation (PATCH /config) -----------------------
    # the mutable subset of DaemonConfig; everything else (backend,
    # capacities) is construction-time (reference: option.DaemonConfig
    # runtime-mutable options like MonitorAggregation/PolicyEnforcement)
    @staticmethod
    def _cast_aggregation(raw) -> str:
        v = str(raw)
        if v not in ("none", "medium"):
            raise ValueError(f"monitor-aggregation must be none|medium,"
                             f" got {v!r}")
        return v

    _MUTABLE_CONFIG = {
        "ct-gc-interval": ("ct_gc_interval", float),
        "fqdn-gc-interval": ("fqdn_gc_interval", float),
        "health-probe-interval": ("health_probe_interval", float),
        "anomaly-threshold": ("anomaly_threshold", float),
        "monitor-aggregation": ("monitor_aggregation",
                                _cast_aggregation.__func__),
    }

    def patch_config(self, body: Dict[str, object]) -> Dict[str, object]:
        """Apply runtime-mutable option changes; returns what changed.
        Unknown or immutable keys raise (reference: PATCH /config
        rejects non-mutable options)."""
        # validate + cast EVERYTHING first: a bad key must not leave
        # earlier keys half-applied behind a 400
        staged: Dict[str, tuple] = {}
        for key, raw in body.items():
            spec = self._MUTABLE_CONFIG.get(key)
            if spec is None:
                raise ValueError(f"option {key!r} is not runtime-"
                                 "mutable (or unknown)")
            attr, cast = spec
            staged[key] = (attr, cast(raw))
        changed: Dict[str, object] = {}
        for key, (attr, value) in staged.items():
            setattr(self.config, attr, value)
            changed[key] = value
        if not changed:
            return changed
        # re-arm controllers whose cadence changed
        if self._started:
            if "ct-gc-interval" in changed:
                # serialized against the monitor's state transitions
                # (monitor lock): a LIVE pressure episode keeps the
                # accelerated cadence — the monitor only accelerates
                # on the OK->PRESSURE edge, so an unsynchronized
                # reset here would silently cancel the response for
                # the rest of the episode; the new normal cadence
                # applies once the episode exits
                self.pressure.resync(self.config.ct_gc_interval,
                                     self._ct_gc_schedule)
            if "fqdn-gc-interval" in changed:
                self.controllers.update(
                    "fqdn-gc", self.fqdn.gc,
                    self.config.fqdn_gc_interval)
            if ("health-probe-interval" in changed
                    and self.health is not None):
                def _health_sweep():
                    self.node_registry.heartbeat(self.config.node_name)
                    self.health.probe_all()

                self.controllers.update(
                    "health-probe", _health_sweep,
                    self.config.health_probe_interval)
        if "anomaly-threshold" in changed and self.anomaly is not None:
            self.anomaly.threshold = self.config.anomaly_threshold
        return changed

    # -- status --------------------------------------------------------
    def status(self) -> dict:
        m = self.loader.metrics()
        mesh = self.clustermesh.status()
        return {
            "version": VERSION,
            "node": self.config.node_name,
            "backend": self.config.backend,
            "uptime-seconds": round(time.time() - self._boot_time, 1),
            "policy-revision": self.repo.revision,
            "endpoints": {
                "total": len(self.endpoints.list()),
                "by-state": self._eps_by_state(),
            },
            "identities": len(self.allocator.all_identities()),
            "ipcache-entries": len(self.ipcache.entries()),
            "fqdn-entries": len(self.fqdn.entries()),
            "l7-requests": self.proxy.requests_total,
            "regenerations": self.endpoints.regenerations,
            "forwarded": int(m[0].sum()),
            "dropped": int(m[1:].sum()),
            "monitor-events": self.monitor.published,
            "flows-seen": self.observer.seq,
            # via stats(): the sum happens under the recorder's lock
            # (an unlocked dict iteration races first-of-a-kind
            # incident insertion on worker/watchdog threads)
            "incidents": self.flightrec.stats()["incidents"],
            "flow-aggregation": self.analytics.stats(),
            "map-pressure": self.pressure.stats(),
            "controllers": {
                n: {"success": s.success_count, "failure": s.failure_count,
                    "last-error": s.last_error.splitlines()[-1]
                    if s.last_error else ""}
                for n, s in self.controllers.statuses().items()},
            **({"cluster-health": self.health.to_dict()}
               if self.health is not None else {}),
            **({"serving": {
                k: v for k, v in self._node_fault_info().items()}}
               if (self._serving is not None
                   or self._ct_snap is not None) else {}),
            **({"clustermesh": mesh} if mesh else {}),
            **({"nat": nat_st} if (nat_st := (
                self.loader.nat_status(self._now())
                if self.nat is not None
                and hasattr(self.loader, "nat_status") else None))
               else {}),
            **({"auth": self.auth_manager.status()}
               if self.auth_manager is not None else {}),
            **({"encryption": self.encryption.status()}
               if self.encryption is not None else {}),
        }

    def _eps_by_state(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for ep in self.endpoints.list():
            out[ep.state.value] = out.get(ep.state.value, 0) + 1
        return out

    # -- checkpoint / restore -----------------------------------------
    def checkpoint(self, state_dir: str) -> None:
        """Persist control-plane state + CT snapshot (reference:
        /var/run/cilium/state + pinned maps, SURVEY.md §5)."""
        os.makedirs(state_dir, exist_ok=True)
        ids = [{"id": i.numeric_id,
                "labels": [str(l) for l in i.labels]}
               for i in self.allocator.all_identities()]
        eps = [ep.to_dict() for ep in self.endpoints.list()]
        meta = {
            "version": VERSION,
            "node": self.config.node_name,
            "revision": self.repo.revision,
            "identities": ids,
            "endpoints": eps,
            "ipcache": [
                {"cidr": e.cidr, "identity": e.identity,
                 "source": e.source}
                for e in self.ipcache.entries()
                if e.source not in ("endpoint", "generated")],
            "rules": [rule_to_dict(r) for r in self.repo.rules()],
            # bandwidth limits survive restart (upstream re-derives
            # them from pod annotations; restore-without-k8s must not
            # silently unthrottle endpoints)
            "bandwidth": {str(k): v for k, v in self._bw_limits.items()},
            # egress policies likewise: the restored NAT snapshot's
            # mappings carry their egress IPs, and NEW flows must not
            # silently fall back to node_ip masquerade
            "egress-gateways": {
                name: {"selectors": list(p["selectors"]),
                       "dest_cidrs": list(p["dest_cidrs"]),
                       "egress_ip": p["egress_ip"]}
                for name, p in self._egress_policies.items()},
        }
        # ct.npz first, state.json LAST: state.json is the commit point
        # of the checkpoint pair, so a crash between the two renames
        # can never pair new control-plane state with a stale CT
        # snapshot (stale CT would resurrect established flows admitted
        # under since-revoked policy).  The CT snapshot additionally
        # carries the policy revision it was taken under: the INVERSE
        # crash ordering (new ct.npz + old state.json) is caught at
        # restore time by the revision mismatch and the snapshot is
        # skipped.
        ct = self.loader.ct_snapshot()
        self._store_ct_snapshot(ct, trigger="checkpoint")
        ct_tmp = os.path.join(state_dir, "ct.npz.tmp")
        extra = {}
        nat = getattr(self.loader, "nat_snapshot", lambda: None)()
        if nat is not None:
            # NAT entries pair with the CT snapshot (both carry the
            # post-NAT tuples); riding the same file keeps them atomic
            extra["nat"] = nat
        with open(ct_tmp, "wb") as f:
            np.savez_compressed(
                f, table=ct,
                revision=np.int64(self.repo.revision), **extra)
        os.replace(ct_tmp, os.path.join(state_dir, "ct.npz"))
        tmp = os.path.join(state_dir, "state.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(tmp, os.path.join(state_dir, "state.json"))

    def restore(self, state_dir: str) -> bool:
        """Reload a checkpoint (the agent-restart path: datapath state
        survives; endpoints re-register and regenerate)."""
        path = os.path.join(state_dir, "state.json")
        if not os.path.exists(path):
            return False
        with open(path) as f:
            meta = json.load(f)
        for rec in meta["identities"]:
            self.allocator.restore_identity(
                rec["id"], LabelSet.parse(*rec["labels"]))
        for rec in meta["ipcache"]:
            self.ipcache.upsert(rec["cidr"], rec["identity"],
                                rec["source"])
        if meta["rules"]:
            self.repo.add_obj(meta["rules"])
        for rec in meta["endpoints"]:
            # RESTORING until the batched regeneration below realizes
            # their policy (reference: the endpoint restore state).
            # Enforcement mode + options round-trip — silently
            # resetting a "never"/"always" endpoint to "default" on
            # restart would change verdicts.
            self.endpoints.add(rec["name"], tuple(rec["ips"]),
                               LabelSet.parse(*rec["labels"]),
                               ep_id=rec["id"],
                               named_ports=rec.get("named-ports"),
                               restoring=True, defer_regen=True,
                               enforcement=rec.get("policy-enforcement",
                                                   "default"),
                               options=rec.get("options"))
        self.endpoints.regenerate()
        for ep_id, bps in (meta.get("bandwidth") or {}).items():
            self.set_bandwidth(int(ep_id), int(bps))
        for name, p in (meta.get("egress-gateways") or {}).items():
            self.add_egress_gateway(name, p["selectors"],
                                    p["dest_cidrs"], p["egress_ip"])
        ct_path = os.path.join(state_dir, "ct.npz")
        if os.path.exists(ct_path):
            try:
                snap = np.load(ct_path)
                # revision stamp: a CT snapshot taken under a different
                # policy revision than state.json records is the torn-
                # checkpoint case (crash between the two renames) —
                # skip it rather than resurrect flows admitted under
                # policy that is absent from the restored ruleset.
                # Pre-stamp snapshots (no "revision" key) restore as
                # before.
                snap_rev = (int(snap["revision"])
                            if "revision" in snap.files else None)
                if snap_rev is not None and snap_rev != meta["revision"]:
                    import logging

                    logging.getLogger(__name__).warning(
                        "CT snapshot revision %s != checkpoint revision "
                        "%s (torn checkpoint); skipping connection "
                        "state", snap_rev, meta["revision"])
                else:
                    self.loader.ct_restore(snap["table"])
                    if "nat" in snap.files and hasattr(
                            self.loader, "nat_restore"):
                        # replies to allocated node ports must keep
                        # reverse-translating across restarts
                        self.loader.nat_restore(snap["nat"])
            except Exception as e:  # corrupt snapshot: identities/
                # rules/endpoints above are already restored; losing
                # live connections is the lesser failure
                import logging

                logging.getLogger(__name__).warning(
                    "CT snapshot restore failed (%s); continuing "
                    "without connection state", e)
        return True
