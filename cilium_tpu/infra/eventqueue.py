"""EventQueue: serialized, droppable event processing per owner.

Reference: upstream cilium ``pkg/eventqueue`` — each endpoint owns a
queue; events (regenerations, policy recalculations) execute strictly
in order on one consumer goroutine, can be waited on, and a closed
queue drains deterministically.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional


class Event:
    """One queued unit of work; ``wait()`` blocks until it ran (or the
    queue closed underneath it)."""

    def __init__(self, fn: Callable[[], Any]):
        self._fn = fn
        self._done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.dropped = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def _run(self) -> None:
        try:
            self.result = self._fn()
        except BaseException as e:  # surfaced via .error, never lost
            self.error = e
        finally:
            self._done.set()

    def _drop(self) -> None:
        self.dropped = True
        self._done.set()


class EventQueue:
    def __init__(self, name: str = "", maxsize: int = 0):
        self.name = name
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue(maxsize)
        self._closed = threading.Event()
        self._drained = threading.Event()
        # serializes the closed-check-then-put against close()'s
        # set-then-sentinel: without it an event can slip in BEHIND
        # the sentinel after the drain finished — neither run nor
        # dropped, and its wait() would hang forever
        self._enqueue_mutex = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"eventq-{name or id(self)}")
        self._thread.start()

    def enqueue(self, fn: Callable[[], Any]) -> Event:
        """Queue fn; returns its Event.  A closed queue drops
        immediately (event.dropped = True), like the reference's
        nil-return after Close."""
        ev = Event(fn)
        with self._enqueue_mutex:
            if self._closed.is_set():
                ev._drop()
                return ev
            try:
                self._q.put_nowait(ev)
            except queue.Full:
                ev._drop()
        return ev

    def _loop(self) -> None:
        while True:
            ev = self._q.get()
            if ev is None:
                break
            ev._run()
        # anything that slipped in behind the close sentinel drops
        while not self._q.empty():
            ev = self._q.get_nowait()
            if ev is not None:
                ev._drop()
        self._drained.set()

    def close(self, wait: bool = True,
              timeout: Optional[float] = 10.0) -> None:
        """Stop accepting NEW events; everything already queued runs
        to completion first (reference: eventqueue Stop + drain)."""
        # set closed UNDER the mutex (no enqueue can pass the check
        # afterwards), but put the sentinel OUTSIDE it: a bounded full
        # queue would otherwise deadlock against a worker whose event
        # callback calls enqueue() (blocked on the mutex)
        with self._enqueue_mutex:
            self._closed.set()
        self._q.put(None)
        if wait:
            self._drained.wait(timeout)
