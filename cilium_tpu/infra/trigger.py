"""Debounced trigger: coalesce bursts of requests into one run.

Reference: upstream cilium ``pkg/trigger`` — endpoint regeneration and
policy recalculation are triggered many times in a burst (k8s event
storms) but must run serialized with a minimum interval.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class Trigger:
    def __init__(self, fn: Callable[[], None],
                 min_interval: float = 0.0, name: str = "trigger"):
        self._fn = fn
        self._min_interval = min_interval
        self.name = name
        self._lock = threading.Lock()
        self._pending = False
        self._running = False
        self._last_run = 0.0
        self.run_count = 0
        self.fold_count = 0  # requests coalesced into an already-pending run

    def trigger(self) -> None:
        """Request a run.  Synchronous when idle (runs on the calling
        thread); folds into the pending run otherwise."""
        with self._lock:
            if self._running:
                if not self._pending:
                    self._pending = True
                else:
                    self.fold_count += 1
                return
            self._running = True
        while True:
            wait = self._min_interval - (time.time() - self._last_run)
            if wait > 0:
                time.sleep(wait)
            self._fn()
            with self._lock:
                self.run_count += 1
                self._last_run = time.time()
                if self._pending:
                    self._pending = False
                    continue  # somebody asked again while we ran
                self._running = False
                return
