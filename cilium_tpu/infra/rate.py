"""Rate limiting: token bucket + the api-rate-limit style gate.

Reference: upstream cilium ``pkg/rate`` (golang.org/x/time/rate
wrapper) — API calls and reconciliations pass through named limiters
with burst + sustained-rate knobs, surfaced in metrics.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class TokenBucket:
    def __init__(self, rate: float, burst: int):
        """``rate`` tokens/second sustained, up to ``burst`` stored."""
        if rate <= 0 or burst < 1:
            raise ValueError("rate must be > 0 and burst >= 1")
        self.rate = rate
        self.burst = burst
        self._tokens = float(burst)
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self) -> None:
        now = time.monotonic()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now

    def allow(self, n: int = 1) -> bool:
        """Non-blocking: take n tokens if available."""
        with self._lock:
            self._refill()
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def wait(self, n: int = 1, timeout: Optional[float] = None) -> bool:
        """Blocking acquire; False on timeout.  n > burst can never be
        satisfied (tokens cap at burst) and is an error, matching
        golang.org/x/time/rate."""
        if n > self.burst:
            raise ValueError(f"wait({n}) exceeds burst {self.burst}")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                self._refill()
                if self._tokens >= n:
                    self._tokens -= n
                    return True
                need = (n - self._tokens) / self.rate
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                need = min(need, remaining)
            time.sleep(min(need, 0.1))


class LimiterSet:
    """Named limiters (the api-rate-limit map); unknown names pass."""

    def __init__(self):
        self._limiters: Dict[str, TokenBucket] = {}
        self._stats: Dict[str, Dict[str, int]] = {}
        self._lock = threading.Lock()

    def configure(self, name: str, rate: float, burst: int) -> None:
        with self._lock:
            self._limiters[name] = TokenBucket(rate, burst)
            self._stats.setdefault(name, {"allowed": 0, "limited": 0})

    def allow(self, name: str) -> bool:
        # stats increments stay under the set lock (lost updates would
        # underreport the metric surface); TokenBucket.allow is
        # non-blocking and lock-ordered set -> bucket consistently
        with self._lock:
            lim = self._limiters.get(name)
            st = self._stats.setdefault(name,
                                        {"allowed": 0, "limited": 0})
            if lim is None or lim.allow():
                st["allowed"] += 1
                return True
            st["limited"] += 1
            return False

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}
