"""Cross-cutting plumbing mirrored from the reference's pkg/ utilities.

Reference: ``pkg/controller`` (named retry loops with backoff, surfaced
in ``cilium status``), ``pkg/trigger`` (debounced triggers serializing
expensive work like endpoint regeneration).
"""

from .controller import Controller, ControllerManager  # noqa: F401
from .trigger import Trigger  # noqa: F401
