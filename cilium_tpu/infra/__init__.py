"""Cross-cutting plumbing mirrored from the reference's pkg/ utilities.

Reference: ``pkg/controller`` (named retry loops with backoff, surfaced
in ``cilium status``), ``pkg/trigger`` (debounced triggers serializing
expensive work like endpoint regeneration), plus the datapath fault
injector (``faults``) the chaos suite drives the serving plane with.
"""

from .controller import Controller, ControllerManager  # noqa: F401
from .faults import FaultInjector, InjectedFault  # noqa: F401
from .trigger import Trigger  # noqa: F401
