"""Lock-order debugging: the pkg/lock + go-deadlock analogue.

Reference: upstream cilium builds with a ``lockdebug`` tag wrapping
every mutex in go-deadlock, which reports lock-order inversions and
too-long holds in CI.  Here: :class:`DebugLock` records the global
acquisition-order graph; acquiring B while holding A adds edge A->B,
and an edge that closes a cycle is a potential deadlock, reported
immediately with both stacks' names.  Zero overhead when disabled —
:func:`make_lock` returns a plain ``threading.Lock`` unless
``CILIUM_TPU_LOCKDEBUG=1`` (tests enable it explicitly).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple


class LockOrderError(RuntimeError):
    pass


class _Registry:
    """Process-global acquisition-order graph."""

    def __init__(self):
        self._lock = threading.Lock()
        # guarded-by: _lock: _edges, violations
        self._edges: Dict[str, Set[str]] = {}  # held -> then-acquired
        self.violations: List[Tuple[str, str]] = []

    def record(self, held: List[str], acquiring: str,
               raise_on_cycle: bool) -> None:
        with self._lock:
            for h in held:
                if h == acquiring:
                    continue
                self._edges.setdefault(h, set()).add(acquiring)
                # does acquiring -> ... -> h already exist?  Then the
                # new edge h -> acquiring closes an order cycle.
                if self._reachable(acquiring, h):
                    self.violations.append((h, acquiring))
                    if raise_on_cycle:
                        raise LockOrderError(
                            f"lock-order inversion: {acquiring!r} is "
                            f"acquired while holding {h!r}, but the "
                            f"reverse order exists elsewhere")

    def _reachable(self, src: str, dst: str) -> bool:
        # holds: _lock -- only called from record()'s locked region
        seen: Set[str] = set()
        stack = [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self._edges.get(n, ()))
        return False

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self.violations.clear()


REGISTRY = _Registry()
_held = threading.local()


class DebugLock:
    """A named lock that reports order inversions (reentrant-safe via
    the per-thread held list)."""

    def __init__(self, name: str, raise_on_cycle: bool = True):
        self.name = name
        self._lock = threading.Lock()
        self._raise = raise_on_cycle

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        held = getattr(_held, "names", None)
        if held is None:
            held = _held.names = []
        REGISTRY.record(list(held), self.name, self._raise)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            held.append(self.name)
        return ok

    def release(self) -> None:
        held = getattr(_held, "names", [])
        if self.name in held:
            held.remove(self.name)
        self._lock.release()

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()


def enabled() -> bool:
    return os.environ.get("CILIUM_TPU_LOCKDEBUG", "") == "1"


def make_lock(name: str):
    """Factory the subsystems use: plain Lock in production, DebugLock
    under CILIUM_TPU_LOCKDEBUG=1 (CI)."""
    if enabled():
        return DebugLock(name)
    return threading.Lock()
