"""Named retry-with-backoff reconciliation loops.

Reference: upstream cilium ``pkg/controller`` — every background
reconciliation (CT GC, kvstore sync, ipcache sync...) runs in a named
``Controller`` with exponential backoff on failure, and their health is
reported in ``cilium status --verbose``.
"""

from __future__ import annotations

import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


@dataclass
class ControllerStatus:
    name: str
    success_count: int = 0
    failure_count: int = 0
    consecutive_failures: int = 0
    last_error: str = ""
    last_success: float = 0.0


class Controller:
    def __init__(self, name: str, fn: Callable[[], None],
                 interval: float, backoff_max: float = 60.0):
        self.status = ControllerStatus(name)
        self._fn = fn
        self._interval = interval
        self._backoff_max = backoff_max
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"ctrl-{self.status.name}")
        self._thread.start()

    def trigger(self) -> None:
        """Run now instead of waiting out the interval."""
        self._wake.set()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=5)

    def run_once(self) -> bool:
        """Synchronous single run (tests; also used by the loop)."""
        try:
            self._fn()
        except Exception:
            self.status.failure_count += 1
            self.status.consecutive_failures += 1
            self.status.last_error = traceback.format_exc(limit=3)
            return False
        self.status.success_count += 1
        self.status.consecutive_failures = 0
        self.status.last_error = ""
        self.status.last_success = time.time()
        return True

    def _run(self) -> None:
        while not self._stop.is_set():
            ok = self.run_once()
            wait = self._interval if ok else min(
                self._interval * (2 ** self.status.consecutive_failures),
                self._backoff_max)
            self._wake.wait(timeout=wait)
            self._wake.clear()


class ControllerManager:
    def __init__(self):
        self._controllers: Dict[str, Controller] = {}
        _MANAGERS.add(self)

    def update(self, name: str, fn: Callable[[], None],
               interval: float) -> Controller:
        self.remove(name)
        c = Controller(name, fn, interval)
        self._controllers[name] = c
        c.start()
        return c

    def get(self, name: str) -> Optional[Controller]:
        return self._controllers.get(name)

    def remove(self, name: str) -> None:
        c = self._controllers.pop(name, None)
        if c:
            c.stop()

    def stop_all(self) -> None:
        for name in list(self._controllers):
            self.remove(name)

    def statuses(self) -> Dict[str, ControllerStatus]:
        return {n: c.status for n, c in self._controllers.items()}


# Controllers run device work (CT GC) on daemon threads; a thread
# caught mid-XLA-dispatch while the interpreter tears down crashes the
# runtime's C++ destructors (std::terminate).  Stop every live
# controller at interpreter exit — also the correct agent-shutdown
# order (background reconciliation quiesces before the datapath).
import atexit
import weakref

_MANAGERS: "weakref.WeakSet[ControllerManager]" = weakref.WeakSet()


def _stop_all_at_exit() -> None:
    for mgr in list(_MANAGERS):
        try:
            mgr.stop_all()
        except Exception:
            pass


atexit.register(_stop_all_at_exit)
