"""Deterministic fault injection for the serving datapath.

Reference: upstream cilium treats failure as an input it must keep
working through — ``cilium-health`` probes every node, endpoints
REGENERATE after datapath faults, the kvstore layer fails over.  The
control-plane half of that discipline already exists here
(``testing/chaos.ChaosKVStore``); this module is the DATAPATH half: a
seeded injector with NAMED SITES threaded through the serving hot
path, so the watchdog / fallback-ladder / recovery machinery
(serving/runtime.py, agent/daemon.py) can be proven against the
failures it exists for — deterministically, on CPU, in tier-1.

Sites are LOCATIONS (where the fault fires); the armed spec picks the
BEHAVIOR per site — raise (the code path dies there) or hang (the
call stalls, simulating a wedged device dispatch / stuck d2h fetch).

Spec grammar (one string, config/env-friendly)::

    spec  := entry (";" entry)*
    entry := site "=" rate ["x" count] ["@" skip] ["~" seconds]

- ``rate``: fire probability per pass through the site (1 = always).
- ``xN``: fire at most N times total (the usual test shape: ``x1``
  kills exactly one dispatch; ``x3`` drives a demotion threshold).
- ``@K``: stay inert for the first K passes through the site (skip
  the warmup dispatches that pay XLA compiles, then strike).
- ``~S``: HANG for S seconds instead of raising (interruptible: the
  site's ``abort`` callback — e.g. "my generation was abandoned" —
  ends the stall early, like a cancelled RPC).

Examples: ``serving.dispatch=1x1`` (one dispatch raises),
``serving.dispatch=1x1@2~0.3`` (the third dispatch hangs 300 ms),
``loader.serve_sharded=1x3`` (three sharded dispatches fail — a shard
gone unavailable), ``serving.queue.take=0.01`` (1% of dequeue memcpys
fault).

Arming is PROCESS-GLOBAL (the sites live in hot paths that cannot
thread an injector object through every layer): ``arm()`` installs an
injector, ``disarm()`` removes it, and the disarmed fast path is one
module-global load + None check — zero-cost in production.  The agent
arms from ``DaemonConfig.fault_injection`` (so ``daemon run
--fault-injection ...`` / ``CILIUM_TPU_FAULT_INJECTION`` work) and
disarms on shutdown.  Draws are seeded per (seed, site) so a fault
schedule replays exactly.
"""

from __future__ import annotations

import re
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Dict, Optional

import numpy as np

# -- the named sites ---------------------------------------------------
# serving/runtime.py — the drain thread, just before the device leg:
# a raise here kills the drain thread (dead-thread recovery); a hang
# simulates a wedged dispatch the watchdog must deadline.
SITE_SERVING_DISPATCH = "serving.dispatch"
# serving/ingress.py — the dequeue memcpy inside take_into(): the
# queue is exception-atomic (nothing is popped until every copy
# landed), so this kills the drain thread WITHOUT losing rows.
SITE_QUEUE_TAKE = "serving.queue.take"
# datapath/loader.py — the single-chip wide / packed serve dispatch
# and the sharded serve dispatch (a shard dropping off the mesh).
SITE_LOADER_SERVE = "loader.serve"
SITE_LOADER_SERVE_PACKED = "loader.serve_packed"
SITE_LOADER_SERVE_SHARDED = "loader.serve_sharded"
# ...and the K-batch superbatch dispatch (ISSUE 11): a raise fails
# the whole K-batch dispatch, which is exactly how the ladder's
# K-shrink demotion path is exercised.
SITE_LOADER_SERVE_SUPER = "loader.serve_super"
# monitor/ring.py — the window swap / collect of the async drainer
# (arm with ``~S`` for the ring-drain stall failure mode).
SITE_RING_SWAP = "ring.swap"
SITE_RING_COLLECT = "ring.collect"
# serving/eventplane.py — the event-join worker, just before it joins
# a popped window: a raise KILLS the worker thread (restart-on-death
# under its budget); a ``~S`` hang stalls the join plane so windows
# pile up against the bounded queue (overflow accounting).
SITE_EVENT_JOIN = "eventplane.join"
# cluster/membership.py — fired per node probe (fixed sweep order):
# a raise CRASHES the probed node (its serving runtime is
# crash-stopped, queued rows counted) and fails the probe, so
# ``cluster.probe=1x1@K`` is a deterministic "kill the K-th probed
# node" — the injected-node-death entry for cluster failover chaos.
SITE_CLUSTER_PROBE = "cluster.probe"
# datapath/loader.py table versioning (datapath/tables.py) — the
# mid-swap crash/hang sites of the churn chaos gate.  ``churn.build``
# fires in the BUILDER, after the successor tables are assembled but
# before publication: a raise abandons the build (the published
# generation and its tables stay untouched); a ``~S`` hang stalls the
# builder with only the build lock held, proving serving dispatches
# keep flowing through a slow rebuild.  ``churn.swap`` fires INSIDE
# the dispatch lock immediately before the generation flip: a raise
# proves a crash at the last possible instant still publishes
# nothing; a ``~S`` hang holds the dispatch lock (the worst-case
# swap stall the watchdog's deadline machinery must tolerate).
SITE_CHURN_BUILD = "churn.build"
SITE_CHURN_SWAP = "churn.swap"
# proxy/worker.py — an L7 worker, just before it parses a redirected
# task's payloads: a raise KILLS the worker mid-parse (the pool's
# watchdog restarts it under the budget and the task's rows are
# counted l7_failed, keeping the redirect ledger exact); a ``~S``
# hang stalls the pool so redirected tasks pile against the bounded
# queue (shed accounting).
SITE_L7_PARSE = "l7.parse"
# encryption/__init__.py — the AEAD legs of the encrypted cluster
# data channel.  ``crypto.seal`` fires in EncryptedChannel.seal just
# before the AEAD: a raise on the parent's forward path drops the
# frame BEFORE it reaches the wire (rows requeue through the window's
# drop accounting, ledger exact).  ``crypto.open`` fires in
# EncryptedChannel.open before verification: the frame arrived but
# cannot be opened — the receiver must count it rejected and reply
# with the typed crypto-reject record, never die.
SITE_CRYPTO_SEAL = "crypto.seal"
SITE_CRYPTO_OPEN = "crypto.open"

SITES = frozenset({
    SITE_SERVING_DISPATCH,
    SITE_QUEUE_TAKE,
    SITE_LOADER_SERVE,
    SITE_LOADER_SERVE_PACKED,
    SITE_LOADER_SERVE_SHARDED,
    SITE_LOADER_SERVE_SUPER,
    SITE_RING_SWAP,
    SITE_RING_COLLECT,
    SITE_EVENT_JOIN,
    SITE_CLUSTER_PROBE,
    SITE_CHURN_BUILD,
    SITE_CHURN_SWAP,
    SITE_L7_PARSE,
    SITE_CRYPTO_SEAL,
    SITE_CRYPTO_OPEN,
})


class InjectedFault(RuntimeError):
    """An armed site fired.  Deliberately a plain RuntimeError
    subclass: recovery code must treat it exactly like the organic
    failure it stands in for."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at {site}")
        self.site = site


_ENTRY_RE = re.compile(
    r"^(?P<site>[a-z][a-z0-9_.]*)=(?P<rate>[0-9.]+)"
    r"(?:x(?P<count>[0-9]+))?(?:@(?P<skip>[0-9]+))?"
    r"(?:~(?P<hang>[0-9.]+))?$")


@dataclass
class _Site:
    rate: float
    remaining: Optional[int]  # None = unlimited
    skip: int  # inert passes before the site goes live
    hang_s: Optional[float]  # None = raise


class FaultInjector:
    """A parsed, armed fault plan.  Thread-safe; draws are seeded per
    (seed, site) so one spec replays the same schedule."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self._sites: Dict[str, _Site] = {}
        self._rngs: Dict[str, np.random.Generator] = {}
        self.fired: Dict[str, int] = {}
        self._lock = threading.Lock()
        for entry in re.split(r"[;\s]+", spec.strip()):
            if not entry:
                continue
            m = _ENTRY_RE.match(entry)
            if m is None:
                raise ValueError(
                    f"bad fault spec entry {entry!r} (want "
                    f"site=rate[xcount][~seconds])")
            site = m.group("site")
            if site not in SITES:
                raise ValueError(
                    f"unknown fault site {site!r}; sites: "
                    f"{', '.join(sorted(SITES))}")
            rate = float(m.group("rate"))
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"fault rate {rate} not in [0, 1]")
            self._sites[site] = _Site(
                rate=rate,
                remaining=(int(m.group("count"))
                           if m.group("count") else None),
                skip=(int(m.group("skip"))
                      if m.group("skip") else 0),
                hang_s=(float(m.group("hang"))
                        if m.group("hang") else None))
            # crc32, not hash(): str hashes are salted per process and
            # the whole point is a replayable schedule
            self._rngs[site] = np.random.default_rng(
                (self.seed << 32) ^ zlib.crc32(site.encode()))
            self.fired[site] = 0

    def check(self, site: str,
              abort: Optional[Callable[[], bool]] = None) -> None:
        # thread-affinity: any
        """Fire the site per its armed spec: raise
        :class:`InjectedFault`, or stall ``~S`` seconds (ended early
        when ``abort()`` turns True).  No-op for unarmed sites."""
        sp = self._sites.get(site)
        if sp is None:
            return
        with self._lock:
            if sp.skip > 0:
                sp.skip -= 1
                return
            if sp.remaining == 0:
                return
            if sp.rate < 1.0 and self._rngs[site].random() >= sp.rate:
                return
            if sp.remaining is not None:
                sp.remaining -= 1
            self.fired[site] += 1
        if sp.hang_s is None:
            raise InjectedFault(site)
        t_end = time.monotonic() + sp.hang_s
        while True:
            left = t_end - time.monotonic()
            if left <= 0:
                return
            if abort is not None and abort():
                return
            # hot-path-ok: the ~S HANG INJECTION itself — only
            # reachable while a fault site is armed (tests/chaos);
            # disarmed cost is one global load + None check
            time.sleep(min(0.005, left))


# -- the process-global arm point --------------------------------------
_active: Optional[FaultInjector] = None


def arm(spec: str, seed: int = 0) -> FaultInjector:
    """Parse ``spec`` and install it as THE active injector (last arm
    wins); returns it so the owner can :func:`disarm` exactly what it
    armed and read ``fired`` counts."""
    global _active
    inj = FaultInjector(spec, seed)
    _active = inj
    return inj


def disarm(injector: Optional[FaultInjector] = None) -> None:
    """Remove the active injector.  Passing the injector ``arm()``
    returned makes disarm ownership-safe: a daemon shutting down after
    another one re-armed leaves the newer plan in place."""
    global _active
    if injector is None or injector is _active:
        _active = None


def active() -> Optional[FaultInjector]:
    return _active


def check(site: str,
          abort: Optional[Callable[[], bool]] = None) -> None:
    """The hot-path entry: one global load + None check when disarmed."""
    inj = _active
    if inj is None:
        return
    inj.check(site, abort)
