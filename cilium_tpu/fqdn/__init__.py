"""fqdn: DNS-aware policy — observed names become identities.

Reference: upstream cilium ``pkg/fqdn`` — the DNS proxy snoops
responses, the NameManager maps name->IPs with TTLs, IPs get
CIDR-derived identities carrying fqdn metadata, the ipcache learns the
mapping, and ``toFQDNs`` selectors start matching.  TPU-first: the
whole loop rides the incremental-patch path — a DNS answer costs one
verdict-row patch + one /32 LPM slot patch, never a recompile.

Identity shape: one identity per IP, labeled with EVERY name observed
for that IP (``fqdn:<name>``), ``cidr:<ip>/32``, and
``reserved:world`` — so exact ``toFQDNs`` selectors match by label,
``matchPattern`` globs match via the contribution's fqdn_patterns, and
the daemon's CIDR hook feeds the ipcache automatically.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..identity import Identity
from ..labels import Label, LabelSet


@dataclass
class _IPEntry:
    names: Dict[str, float]  # name -> expiry (unix time)
    identity: Identity


class NameManager:
    def __init__(self, allocator, delete_ipcache: Callable[[str], None],
                 min_ttl: int = 60):
        """``allocator`` allocates/releases identities (the daemon's);
        ``delete_ipcache(cidr)`` removes an expired mapping (the add
        side happens automatically through the daemon's CIDR-label
        hook on identity allocation)."""
        self._lock = threading.Lock()
        self._allocator = allocator
        self._delete_ipcache = delete_ipcache
        self.min_ttl = min_ttl
        self._by_ip: Dict[str, _IPEntry] = {}

    # -- the observe loop (DNS proxy -> here) -------------------------
    def observe(self, name: str, ips: Sequence[str],
                ttl: int = 60) -> None:
        """One observed DNS answer: name resolved to ips with ttl."""
        name = name.rstrip(".").lower()
        expires = time.time() + max(int(ttl), self.min_ttl)
        for ip in ips:
            self._observe_ip(name, ip, expires)

    def _observe_ip(self, name: str, ip: str, expires: float) -> None:
        with self._lock:
            e = self._by_ip.get(ip)
            if e is not None and name in e.names:
                e.names[name] = max(e.names[name], expires)
                return
            names = dict(e.names) if e else {}
            names[name] = expires
            old = e.identity if e else None
            ident = self._allocate(ip, names)
            self._by_ip[ip] = _IPEntry(names=names, identity=ident)
        # release OUTSIDE the lock: the allocator observer chain runs
        # tensor patches that must not nest under our lock
        if old is not None:
            self._allocator.release(old)

    def _allocate(self, ip: str, names: Dict[str, float]) -> Identity:
        from ..identity.allocator import cidr_labels

        suffix = "/128" if ":" in ip else "/32"
        # full parent-prefix label set (r05): a fromCIDR range
        # label-selects fqdn-minted /32s inside it
        labels = LabelSet(
            [Label("fqdn", n) for n in sorted(names)]
            + cidr_labels(ip + suffix) + [Label("reserved", "world")])
        return self._allocator.allocate(labels)

    # -- TTL expiry (controller cadence) ------------------------------
    def gc(self, now: Optional[float] = None) -> int:
        """Expire stale names; returns the number of IPs released.

        Reference: pkg/fqdn TTL GC — expired name->IP associations are
        dropped; an IP with no live names loses its identity and its
        ipcache entry."""
        now = time.time() if now is None else now
        released: List[Tuple[str, Identity, Dict[str, float]]] = []
        with self._lock:
            for ip, e in list(self._by_ip.items()):
                live = {n: exp for n, exp in e.names.items() if exp > now}
                if len(live) == len(e.names):
                    continue
                if live:
                    ident = self._allocate(ip, live)
                    old = e.identity
                    self._by_ip[ip] = _IPEntry(names=live, identity=ident)
                    released.append(("", old, {}))
                else:
                    del self._by_ip[ip]
                    released.append((ip, e.identity, e.names))
        n_dropped = 0
        for ip, ident, _names in released:
            if ip:
                suffix = "/128" if ":" in ip else "/32"
                self._delete_ipcache(ip + suffix)
                n_dropped += 1
            self._allocator.release(ident)
        return n_dropped

    # -- introspection (cilium fqdn cache list) -----------------------
    def entries(self) -> List[dict]:
        with self._lock:
            return [{
                "ip": ip,
                "names": sorted(e.names),
                "identity": e.identity.numeric_id,
                "expires": max(e.names.values()),
            } for ip, e in sorted(self._by_ip.items())]
