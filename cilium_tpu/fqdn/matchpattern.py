"""toFQDNs / DNS-rule ``matchPattern`` grammar.

Reference: upstream cilium ``pkg/fqdn/matchpattern`` — ``*`` expands
to ``[-a-zA-Z0-9_]*`` (a run of DNS-label characters), so a wildcard
NEVER crosses a dot: ``*.example.com`` matches ``sub.example.com``
but NOT ``deep.sub.example.com``.  A lone ``*`` matches every name.
Names and patterns compare case-insensitively with the trailing dot
stripped (FQDN-normalized).

This closes DIVERGENCES #9 (the old fnmatch semantics spanned dots —
a security-relevant SUPERSET of the upstream matches: an operator's
rule admitted names upstream would deny).
"""

from __future__ import annotations

import re
from functools import lru_cache
from typing import Pattern

# one DNS-label character (upstream: allowedDNSCharsREGroup)
_LABEL_CHARS = "[-a-z0-9_]"


def normalize(name: str) -> str:
    """FQDN-normalize for matching: lowercase, trailing dot stripped."""
    return name.strip().rstrip(".").lower()


@lru_cache(maxsize=4096)
def to_regex(pattern: str) -> Pattern[str]:
    """Compile a matchPattern to its anchored regex."""
    pat = normalize(pattern)
    if pat == "*":
        # the match-all case: any well-formed name
        return re.compile(rf"(?:{_LABEL_CHARS}+\.)*{_LABEL_CHARS}+")
    parts = [re.escape(p) for p in pat.split("*")]
    return re.compile(f"{_LABEL_CHARS}*".join(parts))


def matches(pattern: str, name: str) -> bool:
    """Does ``name`` match ``pattern`` under the per-label grammar?"""
    return to_regex(pattern).fullmatch(normalize(name)) is not None
