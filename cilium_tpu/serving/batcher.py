"""Adaptive batcher: stream -> fixed-shape device batches.

The device path wants large fixed shapes (every distinct batch shape
is one XLA compile); the stream wants low latency.  The batcher pads
to a small LADDER of power-of-two bucket sizes — bounding the set of
compiled shapes to ``len(ladder)`` — and flushes on bucket-full OR a
max-wait deadline, so tail latency is bounded at low load and
throughput is maximized at high load (the continuous-batching
trade-off every serving stack makes; upstream's analogue is NAPI
polling — batch what arrived, don't wait for a full ring).

Padding rows are ZEROS carried with a ``valid`` mask: the datapath
masks them out of CT and metrics (``datapath_step(valid=...)``) and
the event ring never emits them, so a padded batch is
indistinguishable from its real rows downstream.

Two staging disciplines:

- **Arena (the production hot path).** Buffers come from a
  preallocated per-bucket :class:`BucketArena` recycled round-robin —
  no per-batch allocation, queue rows memcpy straight into the slot
  (``IngressQueue.take_into``).  OWNERSHIP HANDOFF RULE: a slot handed
  out with batch N of bucket B is reused by batch N + ``depth`` of
  the SAME bucket; the consumer (the daemon retains ``hdr`` for the
  drain-time event join, and may still be feeding an async h2d copy)
  must be done with it by then.  ``Daemon.start_serving`` sizes
  ``depth`` to its retention window, which is the only consumer
  contract.  Since the async event plane (PR 5,
  ``serving/eventplane.py``) that horizon covers WINDOWS IN FLIGHT
  ON THE EVENT-JOIN WORKER too: each drain window snapshots its
  batch records (arena-slot ``hdr`` references included) at swap
  time and rides a bounded queue until the worker joins it, so a
  slot may be live for up to (window_queue_depth [queued] + 1
  [joining] + 1 [accumulating] + 1 [mid-join slack]) * drain_every
  batches after dispatch — the ``(window_queue_depth + 3) *
  drain_every + 2`` depth ``start_serving`` passes.  The depth is a
  GUARANTEE, not a hope: the worker refuses joins older than the
  matching join horizon (``Daemon._event_join``) as counted drops,
  so a stalled plane can never join against a recycled slot.  A
  dropped window releases its references when the worker counts the
  drop; nothing extends the horizon past stop() because
  ``stop_serving`` drains the worker before the runtime sweeps.
- **``pack=...`` (the 16 B/packet h2d format).** When a batch's rows
  are IPv4 with one (ep, dir) stream (``core.packets.
  pack_eligibility``), the batcher emits PACKED [bucket, 4] rows
  (``AssembledBatch.packed`` True, ``ep``/``dirn`` carried as stream
  metadata) — 4x fewer bytes on the host->device link.  Ineligible
  traffic (IPv6, mixed streams, out-of-width fields) keeps the wide
  [bucket, N_COLS] fallback shape, so each ladder rung compiles at
  most one packed and one wide executable.
"""

from __future__ import annotations

import time
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from .ingress import IngressQueue

# default arena depth: enough slots that a consumer retaining a
# handful of in-flight windows (async h2d + event join) never sees a
# slot recycled under it; Daemon.start_serving overrides to match its
# actual retention horizon
DEFAULT_ARENA_DEPTH = 16


class AssembledBatch(NamedTuple):
    hdr: np.ndarray  # [bucket, N_COLS] u32, or [bucket, 4] when packed
    valid: np.ndarray  # [bucket] bool
    n_valid: int
    arrivals: List[Tuple[int, float]]  # (count, t_arrival) chunks
    packed: bool = False  # hdr is the 16 B/packet wire format
    ep: int = 0  # stream metadata scalars (packed batches only)
    dirn: int = 0
    # sampled obs spans riding this batch (obs/trace.py; empty when
    # tracing is off) — the runtime stamps dispatch/device/join
    spans: tuple = ()


class SuperBatch(NamedTuple):
    """K batches fused into ONE device dispatch (ISSUE 11): the
    drain loop pays its per-dispatch Python cost (lock window, arena
    bookkeeping, one jit call) once per K batches.  Every step is a
    FULL top-rung bucket — :meth:`AdaptiveBatcher.assemble_super`
    rounds the ready-batch count DOWN to a power-of-two K, so no
    device math is wasted on empty steps and per-step valid masks are
    all-true (they still ship: one compiled shape per (bucket, K)).

    ``hdr``/``valid`` are ``steps=K`` arena slots under the same
    recycling-horizon contract as single batches — a superbatch slot
    is handed out per DISPATCH, so it recycles after ``depth`` more
    superbatches of the same shape, which is K times LONGER in batch
    units than the single-batch horizon the consumer is sized for."""

    hdr: np.ndarray  # [K, bucket, N_COLS] u32, or [K, bucket, 4]
    valid: np.ndarray  # [K, bucket] bool
    bucket: int
    arrivals: List[Tuple[int, float]]  # merged (count, t) chunks
    packed: bool = False
    eps: Optional[np.ndarray] = None  # [K] u32 per-step stream meta
    dirns: Optional[np.ndarray] = None  # (packed superbatches only)
    # per-step span tuples, len K (empty tuple when tracing is off)
    spans: tuple = ()

    @property
    def k(self) -> int:
        return self.hdr.shape[0]

    @property
    def n_valid(self) -> int:
        # every step is a full bucket (assemble_super's contract)
        return self.hdr.shape[0] * self.bucket


class BucketArena:
    """Preallocated per-(bucket, width) staging slots, recycled
    round-robin.  Slots allocate lazily on first use of a shape, so
    an all-packed session never pays for wide slots at the big rungs
    (and vice versa)."""

    def __init__(self, depth: int = DEFAULT_ARENA_DEPTH):
        assert depth >= 2, "arena depth < 2 would alias consecutive batches"
        self.depth = int(depth)
        self._slots: Dict[tuple, np.ndarray] = {}
        self._next: Dict[tuple, int] = {}

    def slot(self, bucket: int, cols: int,
             dtype=np.uint32, steps: int = 0) -> np.ndarray:
        # thread-affinity: drain, api
        """Next staging buffer for this shape ([bucket, cols], or
        [bucket] when cols is 0; ``steps=K`` prepends a superbatch
        axis: [K, bucket, cols]).  The caller owns it for the next
        ``depth - 1`` requests of the SAME shape (see module doc) —
        superbatch slots are requested per DISPATCH, so their horizon
        in batch units is K times the single-batch one."""
        key = (int(steps), int(bucket), int(cols),
               np.dtype(dtype).str)
        pool = self._slots.get(key)
        if pool is None:
            shape = (bucket, cols) if cols else (bucket,)
            if steps:
                shape = (steps,) + shape
            pool = np.zeros((self.depth,) + shape, dtype=dtype)
            self._slots[key] = pool
        i = self._next.get(key, 0)
        self._next[key] = (i + 1) % self.depth
        return pool[i]

    def occupancy(self) -> Dict[str, int]:
        # thread-affinity: drain
        """Allocated staging footprint (shapes lazily materialize on
        first use) — the obs plane's arena-occupancy gauge.  DRAIN
        THREAD ONLY: iterating the lazily-growing slot dict is only
        safe on the thread that grows it (runtime._sample_gauges)."""
        return {"shapes": len(self._slots),
                "bytes": sum(p.nbytes for p in self._slots.values())}


class AdaptiveBatcher:
    def __init__(self, bucket_ladder, max_wait_us: float,
                 pack: bool = False,
                 arena_depth: int = DEFAULT_ARENA_DEPTH):
        self.ladder = tuple(int(b) for b in bucket_ladder)
        assert self.ladder == tuple(sorted(set(self.ladder))), \
            "ladder must be validated (ascending, unique) upstream"
        self.max_wait_s = float(max_wait_us) * 1e-6
        self.pack = bool(pack)
        self.arena = BucketArena(arena_depth)
        # wide dequeue scratch, reused EVERY batch: rows land here
        # from the queue, then one copy moves them to their arena slot
        # (wide) or packs them 4x smaller (packed) — never handed out
        self._scratch: Optional[np.ndarray] = None

    def bucket_for(self, n: int) -> int:
        """Smallest ladder bucket holding ``n`` rows (the largest
        bucket when ``n`` exceeds it — callers take at most that)."""
        for b in self.ladder:
            if n <= b:
                return b
        return self.ladder[-1]

    def due(self, queue: IngressQueue,
            now: Optional[float] = None) -> bool:
        # thread-affinity: drain, api
        """Is a flush warranted right now?  Full-bucket OR deadline."""
        pending = queue.pending
        if pending == 0:
            return False
        if pending >= self.ladder[-1]:
            return True
        return queue.oldest_age(now) >= self.max_wait_s

    def assemble(self, queue: IngressQueue,
                 now: Optional[float] = None,
                 force: bool = False) -> Optional[AssembledBatch]:
        # thread-affinity: drain, api
        """Dequeue one batch if a flush is due; None otherwise.
        ``force`` flushes whatever is queued regardless of deadline
        (the stop/drain path).

        The returned ``hdr``/``valid`` buffers are ARENA slots —
        ownership transfers to the dispatcher under the recycling
        horizon documented in the module header: the dispatcher may
        retain ``hdr`` for the drain-time event join and feed an
        async h2d copy, and the slot is not touched again until
        ``depth`` more batches of the same shape have assembled.

        The ``valid`` mask is passed even for full buckets so each
        bucket size stays ONE compiled shape (a with-mask and a
        without-mask variant would double the compile count)."""
        if now is None:
            now = time.monotonic()
        if not force and not self.due(queue, now):
            return None
        cap = self.ladder[-1]
        if self._scratch is None or self._scratch.shape[0] < cap:
            w = queue.row_width()
            if w is None:  # force-flush of an empty queue
                return None
            # one scratch per session: the queue admits a single row
            # schema (submit() width-checks), so the first chunk's
            # width is THE width
            self._scratch = np.zeros((cap, w), dtype=np.uint32)
        n, arrivals = queue.take_into(self._scratch)
        if n == 0:
            return None
        # claim the dequeued spans IMMEDIATELY: if any staging work
        # below raises (injected or organic), they are evicted with
        # the batch instead of sitting in the queue's dequeued list
        # to be popped by — and corrupt — a later batch after a
        # drain-loop restart
        deq = (queue.pop_dequeued_spans()
               if queue.tracer is not None else [])
        try:
            bucket = self.bucket_for(n)
            rows = self._scratch[:n]
            packed, ep, dirn = False, 0, 0
            if self.pack:
                from ..core.packets import (PACKED_COLS,
                                            pack_eligibility,
                                            pack_rows)

                packed, ep, dirn = pack_eligibility(rows)
            if packed:
                hdr = self.arena.slot(bucket, PACKED_COLS)
                pack_rows(rows, out=hdr)
            else:
                hdr = self.arena.slot(bucket,
                                      self._scratch.shape[1])
                hdr[:n] = rows
            # recycled-slot hygiene, shared by both wire formats:
            # the tail may hold a previous batch's rows
            hdr[n:] = 0
            valid = self.arena.slot(bucket, 0, dtype=bool)
            valid[:n] = True
            valid[n:] = False
        except BaseException:
            if deq:
                queue.tracer.evict(sp for _pos, sp in deq)
            raise
        spans = ()
        if deq:
            from ..obs.trace import STAGE_STAGED

            t_staged = time.monotonic()
            for pos, sp in deq:
                sp.ts[STAGE_STAGED] = t_staged
                sp.batch_pos = pos
                sp.bucket = bucket
                sp.n_valid = n
            spans = tuple(sp for _pos, sp in deq)
        return AssembledBatch(hdr=hdr, valid=valid, n_valid=n,
                              arrivals=arrivals, packed=packed,
                              ep=ep, dirn=dirn, spans=spans)

    def assemble_super(self, queue: IngressQueue, k_max: int,
                       now: Optional[float] = None,
                       force: bool = False):
        # thread-affinity: drain, api
        """Multi-batch assembly (ISSUE 11): when at least TWO full
        top-rung buckets are pending, dequeue K of them — K rounded
        DOWN to the largest power of two <= min(k_max, ready) so no
        step is ever padded whole — in ONE exception-atomic
        ``take_into`` against a ``steps=K`` arena slot, and return a
        :class:`SuperBatch` for the fused K-batch dispatch.

        Anything less rides the single-batch path unchanged (the
        adaptive K=1 fallback): a partial bucket keeps its own
        deadline semantics and per-batch pack eligibility, so low
        offered load sees byte-identical behavior to ``assemble`` —
        superbatching only engages when the queue is deep enough that
        dispatch amortization is the binding constraint.

        Packed wire format: the K steps dequeue into the WIDE slot
        first (it doubles as staging), each step's eligibility is
        checked independently, and only an all-eligible superbatch
        re-packs into the 16 B/packet slot — per-step ``eps``/
        ``dirns`` ride along, so steps need not share a stream."""
        if now is None:
            now = time.monotonic()
        if not force and not self.due(queue, now):
            return None
        cap = self.ladder[-1]
        ready = queue.pending // cap
        if int(k_max) < 2 or ready < 2:
            return self.assemble(queue, now=now, force=force)
        K = 1
        while K * 2 <= min(int(k_max), ready):
            K *= 2
        w = queue.row_width()
        if w is None:
            return None
        wide = self.arena.slot(cap, w, steps=K)
        # ONE locked, exception-atomic dequeue for all K steps: the
        # drain thread is the only consumer, so the K*cap rows seen
        # pending above cannot shrink before the take
        n, arrivals = queue.take_into(wide.reshape(K * cap, w))
        assert n == K * cap, f"superbatch dequeue got {n}/{K * cap}"
        deq = (queue.pop_dequeued_spans()
               if queue.tracer is not None else [])
        try:
            packed, eps, dirns, hdr = False, None, None, wide
            if self.pack:
                from ..core.packets import (PACKED_COLS,
                                            pack_eligibility,
                                            pack_rows)

                metas = [pack_eligibility(wide[k]) for k in range(K)]
                if all(m[0] for m in metas):
                    hdr = self.arena.slot(cap, PACKED_COLS, steps=K)
                    for k in range(K):
                        pack_rows(wide[k], out=hdr[k])
                    packed = True
                    eps = np.fromiter((m[1] for m in metas),
                                      dtype=np.uint32, count=K)
                    dirns = np.fromiter((m[2] for m in metas),
                                        dtype=np.uint32, count=K)
            valid = self.arena.slot(cap, 0, dtype=bool, steps=K)
            valid[:] = True  # every step is a full bucket
        except BaseException:
            if deq:
                queue.tracer.evict(sp for _pos, sp in deq)
            raise
        spans: tuple = ()
        if deq:
            from ..obs.trace import STAGE_STAGED

            t_staged = time.monotonic()
            per_step: List[list] = [[] for _ in range(K)]
            for pos, sp in deq:
                sp.ts[STAGE_STAGED] = t_staged
                sp.batch_pos = pos % cap
                sp.bucket = cap
                sp.n_valid = cap
                per_step[pos // cap].append(sp)
            spans = tuple(tuple(s) for s in per_step)
        return SuperBatch(hdr=hdr, valid=valid, bucket=cap,
                          arrivals=arrivals, packed=packed,
                          eps=eps, dirns=dirns, spans=spans)

    def time_to_deadline(self, queue: IngressQueue,
                         now: Optional[float] = None) -> float:
        # thread-affinity: drain, api
        """Seconds until the head-of-line chunk's deadline expires
        (max_wait when empty) — the runtime's idle-wait bound."""
        if queue.pending == 0:
            return self.max_wait_s
        return max(0.0, self.max_wait_s - queue.oldest_age(now))
