"""Adaptive batcher: stream -> fixed-shape device batches.

The device path wants large fixed shapes (every distinct batch shape
is one XLA compile); the stream wants low latency.  The batcher pads
to a small LADDER of power-of-two bucket sizes — bounding the set of
compiled shapes to ``len(ladder)`` — and flushes on bucket-full OR a
max-wait deadline, so tail latency is bounded at low load and
throughput is maximized at high load (the continuous-batching
trade-off every serving stack makes; upstream's analogue is NAPI
polling — batch what arrived, don't wait for a full ring).

Padding rows are ZEROS carried with a ``valid`` mask: the datapath
masks them out of CT and metrics (``datapath_step(valid=...)``) and
the event ring never emits them, so a padded batch is
indistinguishable from its real rows downstream.
"""

from __future__ import annotations

import time
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

from .ingress import IngressQueue


class AssembledBatch(NamedTuple):
    hdr: np.ndarray  # [bucket, N_COLS] uint32 (padded)
    valid: np.ndarray  # [bucket] bool
    n_valid: int
    arrivals: List[Tuple[int, float]]  # (count, t_arrival) chunks


class AdaptiveBatcher:
    def __init__(self, bucket_ladder, max_wait_us: float):
        self.ladder = tuple(int(b) for b in bucket_ladder)
        assert self.ladder == tuple(sorted(set(self.ladder))), \
            "ladder must be validated (ascending, unique) upstream"
        self.max_wait_s = float(max_wait_us) * 1e-6

    def bucket_for(self, n: int) -> int:
        """Smallest ladder bucket holding ``n`` rows (the largest
        bucket when ``n`` exceeds it — callers take at most that)."""
        for b in self.ladder:
            if n <= b:
                return b
        return self.ladder[-1]

    def due(self, queue: IngressQueue,
            now: Optional[float] = None) -> bool:
        """Is a flush warranted right now?  Full-bucket OR deadline."""
        pending = queue.pending
        if pending == 0:
            return False
        if pending >= self.ladder[-1]:
            return True
        return queue.oldest_age(now) >= self.max_wait_s

    def assemble(self, queue: IngressQueue,
                 now: Optional[float] = None,
                 force: bool = False) -> Optional[AssembledBatch]:
        """Dequeue one batch if a flush is due; None otherwise.
        ``force`` flushes whatever is queued regardless of deadline
        (the stop/drain path).

        The returned ``hdr``/``valid`` arrays are FRESH per batch —
        ownership transfers to the dispatcher, which retains ``hdr``
        for the drain-time event join and may still be feeding an
        async h2d copy when the next batch assembles.  One bucket
        write per batch either way; reusable buffers would force the
        dispatcher to copy anyway.

        The ``valid`` mask is passed even for full buckets so each
        bucket size stays ONE compiled shape (a with-mask and a
        without-mask variant would double the compile count)."""
        if now is None:
            now = time.monotonic()
        if not force and not self.due(queue, now):
            return None
        rows, arrivals = queue.take(self.ladder[-1])
        n = len(rows)
        if n == 0:
            return None
        bucket = self.bucket_for(n)
        hdr = np.zeros((bucket, rows.shape[1]), dtype=np.uint32)
        hdr[:n] = rows
        valid = np.zeros(bucket, dtype=bool)
        valid[:n] = True
        return AssembledBatch(hdr=hdr, valid=valid, n_valid=n,
                              arrivals=arrivals)

    def time_to_deadline(self, queue: IngressQueue,
                         now: Optional[float] = None) -> float:
        """Seconds until the head-of-line chunk's deadline expires
        (max_wait when empty) — the runtime's idle-wait bound."""
        if queue.pending == 0:
            return self.max_wait_s
        return max(0.0, self.max_wait_s - queue.oldest_age(now))
