"""The serving drain loop: ingress stream -> device batches.

Reference: upstream cilium's NAPI-ish consumption of the XDP/RSS
front end — a poll loop takes what arrived (up to the ring budget),
runs it through the datapath, and surfaces sheds as counted drops.
Production inference stacks call the same shape "continuous
batching".

Double buffering: ``dispatch`` (``Daemon.serve_batch`` under the
hood) ENQUEUES the device work and returns — jax dispatch is async —
so while batch N executes on device, this loop is already draining
the queue and padding batch N+1 on the host.  hdr/valid buffers come
from the batcher's preallocated arena (ownership transfers to the
dispatcher under the recycling horizon documented in batcher.py), so
assembly is allocation-free AND never touches pages an in-flight h2d
copy or the drain-time event join may still be reading.

The loop owns all dispatch: ``submit()`` (any thread) only offers
rows to the bounded ingress queue, which is the backpressure point —
overflow sheds by policy, sheds surface through ``on_shed`` as
monitor DROP events, and nothing ever blocks the producer.

Fault tolerance (the cilium-health / endpoint-regeneration analogue
for the serving plane): with ``restart_budget > 0`` a WATCHDOG thread
supervises the drain loop —

- a DEAD drain thread (any uncaught exception) is restarted with
  exponential backoff, its in-flight batch accounted as counted
  recovery drops (``REASON_RECOVERY_DROP``);
- a HUNG dispatch is deadlined (``dispatch_deadline_s``): the wedged
  generation is ABANDONED (a bumped generation counter makes the old
  thread exit without dispatching or double-recording when it ever
  wakes), its batch accounted as ``REASON_DISPATCH_TIMEOUT`` drops,
  and a fresh drain thread takes over.  A REAL hang inside a device
  call cannot be cancelled from Python — if it eventually completes,
  its device side effects land but its host accounting is discarded
  (the restart budget bounds how often this can happen);
- a dispatch that raises :class:`~..serving.DispatchFailedError`
  (the degraded-mode ladder's "contained failure") costs neither a
  thread death nor a restart: the batch's rows become recovery drops
  and the loop continues;
- the restart budget caps recovery: once exhausted the runtime goes
  TERMINAL (submit() raises, the error rides every snapshot) —
  exactly the pre-watchdog corpse, but only after the budget proved
  the fault persistent.

The no-silent-loss ledger holds throughout:
``submitted == verdicts + shed + recovery_dropped`` after a drained
stop, with every recovery drop ALSO surfaced as a decoded monitor
DROP event via ``on_recovery_drop`` (retention-bounded, counter
exact) — the same contract admission sheds have.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from . import ServingAlreadyActiveError, validate_serving_config
from ..infra import faults
from .batcher import AdaptiveBatcher, AssembledBatch
from .ingress import IngressQueue
from .stats import ServingStats

# dispatch(hdr [bucket, N_COLS], valid [bucket] bool, n_valid) -> any;
# packed batches (pack=True and the rows were eligible) add a
# packed_meta=(ep, dirn) kwarg and ship hdr as [bucket, 4] wire rows.
# A dispatcher may return a dict with "h2d_bytes" to override the
# link accounting (the sharded path re-routes and re-packs, so the
# bytes that actually crossed differ from the assembled hdr's size).
DispatchFn = Callable[[np.ndarray, np.ndarray, int], Optional[dict]]
# on_shed(retained header rows or None, exact shed count) -> None
ShedFn = Callable[[Optional[np.ndarray], int], None]
# on_recovery_drop(wide rows or None, exact count, REASON_*) -> None:
# the recovery plane's event + metricsmap surfacing (rows may be
# fewer than count when a lost batch could not be reconstructed)
RecoveryFn = Callable[[Optional[np.ndarray], int, int], None]

# idle wait granularity: how long the loop sleeps when rows are
# pending but neither bucket-full nor deadline has fired yet.  Small
# enough that a max-wait deadline is honored within ~1ms.
_TICK_S = 0.001
# default consumer-idle wait (queue empty).  Overridable per runtime:
# the daemon derives it from the dispatch deadline so watchdog
# deadlines shorter than this are actually honorable — a loop asleep
# in a 50ms wait cannot notice stop/generation churn any faster.
DEFAULT_IDLE_WAIT_S = 0.05
_BACKOFF_CAP_S = 1.0


class ServingRuntime:
    """start() -> submit() from any thread -> stop(drain=True).

    ``dispatch`` is the device leg (``Daemon.serve_batch``); the
    runtime never imports the agent so the serving plane stays a
    leaf package."""

    def __init__(self, dispatch: DispatchFn, queue_depth: int,
                 bucket_ladder, max_wait_us: float,
                 overflow_policy: str = "drop-tail",
                 on_shed: Optional[ShedFn] = None,
                 expected_cols: Optional[int] = None,
                 pack: bool = False,
                 arena_depth: Optional[int] = None,
                 dispatch_deadline_s: float = 0.0,
                 restart_budget: int = 0,
                 restart_backoff_s: float = 0.01,
                 idle_wait_s: float = DEFAULT_IDLE_WAIT_S,
                 on_recovery_drop: Optional[RecoveryFn] = None,
                 tracer=None,
                 span_sink: Optional[Callable[[int, tuple], bool]]
                 = None,
                 gauge_fn: Optional[Callable[[], dict]] = None,
                 idle_fn: Optional[Callable[[], None]] = None,
                 on_restart: Optional[Callable[[str, bool], None]]
                 = None,
                 profile_dir: Optional[str] = None,
                 profile_batches: int = 0,
                 dispatch_super: Optional[Callable] = None,
                 superbatch_k: int = 1):
        from .batcher import DEFAULT_ARENA_DEPTH

        depth, ladder, wait, policy = validate_serving_config(
            queue_depth, bucket_ladder, max_wait_us, overflow_policy)
        self.queue = IngressQueue(depth, policy)
        # pack: assemble eligible IPv4 single-stream batches as the
        # 16 B/packet wire format; arena_depth: the staging-slot
        # recycling horizon — MUST exceed however many in-flight
        # batches the dispatcher retains (batcher.py module doc)
        self.batcher = AdaptiveBatcher(
            ladder, wait, pack=pack,
            arena_depth=arena_depth or DEFAULT_ARENA_DEPTH)
        self.stats = ServingStats()
        self._dispatch = dispatch
        # K-batch superbatch dispatch (ISSUE 11): when armed
        # (dispatch_super given AND superbatch_k > 1) the drain loop
        # assembles up to K ready batches per device dispatch —
        # Python dispatch cost amortized K-fold.  superbatch_k is
        # MUTABLE from the ladder (a K-shrink demotion writes it, the
        # drain loop reads it once per assembly — benign int race,
        # next assembly sees the new K)
        self._dispatch_super = dispatch_super
        self.superbatch_k = max(int(superbatch_k), 1)
        self._on_shed = on_shed
        self._on_recovery_drop = on_recovery_drop
        # row width the datapath expects (N_COLS): a malformed chunk
        # must bounce off submit() with a ValueError, not detonate
        # inside the drain thread batches later
        self._expected_cols = expected_cols
        # fault-tolerance knobs (module doc): budget 0 = unsupervised
        # (legacy: a dead loop is a terminal, visible corpse)
        self._deadline_s = max(float(dispatch_deadline_s), 0.0)
        self._budget = max(int(restart_budget), 0)
        self._backoff_s = max(float(restart_backoff_s), 0.0)
        self._idle_wait_s = max(float(idle_wait_s), _TICK_S)
        self._supervised = self._budget > 0
        self._error: Optional[str] = None  # drain-loop fault (the
        # watchdog clears it on recovery; terminal once the budget is
        # exhausted or when unsupervised)
        self._killed = False  # kill() crash stop: terminal, no drain
        self._stop = threading.Event()
        # serializes submit() against stop()'s final drain: a chunk
        # offered after the drain swept the queue would sit there
        # forever — neither dispatched nor shed-counted
        self._submit_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        # recovery bookkeeping, guarded by _rec_lock: the drain-thread
        # GENERATION (an abandoned generation exits without touching
        # stats), the IN-FLIGHT batch (registered before the device
        # leg so a death/hang between "rows left the queue" and "stats
        # recorded" can always be accounted), and the restart count.
        self._rec_lock = threading.Lock()
        # guarded-by: _rec_lock: _gen, _inflight, _warm_shapes, _warm_gen
        self._gen = 0
        # (gen, t0, batch, deadline_exempt, warm_gen)
        self._inflight: Optional[tuple] = None
        # shapes that completed a dispatch: the FIRST dispatch of a
        # (bucket, format) pays its XLA compile — unbounded wall time
        # that must not read as a hung device (the watchdog would
        # restart-storm through the budget deadlining compiles).  A
        # hang on a genuinely cold shape is the one blind spot; every
        # warm-shape dispatch is deadlined.  _warm_gen invalidates the
        # set on a mode change — see reset_warm_shapes.
        self._warm_shapes: set = set()
        self._warm_gen = 0
        self.restarts = 0
        # arrivals of the batch currently executing on device: its
        # end-to-end completion is stamped when the NEXT dispatch
        # returns (the device runs batches in order, so by then batch
        # N's events have been appended)
        self._prev_arrivals: List[Tuple[int, float]] = []
        # obs plane (obs/trace.py): the tracer rides the queue (span
        # allocation at admission) and this loop (dispatch/device/
        # join stamps); None costs one branch per batch.  The spans
        # of the batch on device complete WITH its arrivals — same
        # drain-boundary clock as the latency histogram.
        self._tracer = tracer
        self.queue.tracer = tracer
        # span_sink(batch_id, spans) -> bool: the owner's ASYNC event
        # plane takes over device/join stamping (the worker stamps at
        # true window-join time).  When absent — or when it declines —
        # the legacy fallback stamps device/join at the completion
        # boundary the latency histogram uses
        self._span_sink = span_sink
        self._prev_spans: tuple = ()
        # idle-tick gauges (arena occupancy + whatever the owner's
        # gauge_fn adds) land in stats.gauges; gauges that must stay
        # fresh under load (queue backlog, in-flight window) are read
        # live by the metrics registry instead — the idle tick only
        # fires when the queue is empty
        self._gauge_fn = gauge_fn
        # idle_fn runs in the drain loop's queue-empty branch (drain-
        # thread context, same as dispatch): the owner's chance to
        # tick work that otherwise only advances per-dispatch — the
        # daemon drains pending event windows here, so ring events
        # and sampled spans flush when traffic PAUSES instead of
        # waiting for the next drain_every-th batch that may never
        # come
        self._idle_fn = idle_fn
        # INCIDENT HOOK POINT (obs/flightrec.py): on_restart(cause,
        # terminal) fires from the WATCHDOG thread on every
        # drain-loop restart (terminal=False) and once more when the
        # restart budget exhausts (terminal=True) — the daemon wires
        # it to the flight recorder so each recovery event leaves a
        # sysdump bundle behind.  Contained: a failing hook must not
        # cost the restart it describes
        self._on_restart = on_restart
        # optional jax.profiler capture window: trace the first
        # profile_batches dispatches into profile_dir, then stop —
        # the batch-scoped sibling of GET /debug/profile's
        # wall-clock window
        self._profile_dir = profile_dir
        self._profile_batches = int(profile_batches)
        self._profile_state = "armed" if profile_dir else "off"
        self._profile_count = 0

    # -- producer side (any thread) -----------------------------------
    def submit(self, rows: np.ndarray,
               t: Optional[float] = None) -> int:
        # thread-affinity: any
        """Offer a chunk of header rows; returns how many were
        admitted.  Never blocks on the datapath: overflow sheds by
        the configured policy and is surfaced as counted monitor DROP
        events.  Raises after :meth:`stop` — a post-drain chunk would
        queue forever, neither dispatched nor shed-counted.

        Under supervision a dead drain loop does NOT bounce submits:
        the queue is intact, the watchdog is restarting the consumer,
        and producers should not see a blip the supervisor will heal.
        Only a TERMINAL fault (unsupervised death, or restart budget
        exhausted) raises."""
        from . import ServingError, ServingNotStartedError

        rows = np.asarray(rows)
        if rows.ndim != 2 or not np.issubdtype(rows.dtype,
                                               np.integer):
            raise ValueError(
                "submit() wants [n, N_COLS] integer header rows, got "
                f"shape {rows.shape} dtype {rows.dtype}")
        if (self._expected_cols is not None
                and rows.shape[1] != self._expected_cols):
            raise ValueError(
                f"submit() wants {self._expected_cols}-column header "
                f"rows, got {rows.shape[1]}")
        with self._submit_lock:
            if self._error is not None and self._terminal():
                raise ServingError(
                    f"serving drain loop died: {self._error}")
            if self._stop.is_set():
                raise ServingNotStartedError(
                    "serving runtime is stopped")
            offered = len(rows)
            accepted = self.queue.offer(rows, t)
            self.stats.record_submit(offered, accepted)
            return accepted

    def _terminal(self) -> bool:
        return (self._killed or not self._supervised
                or self.restarts >= self._budget)

    def _gen_is(self, gen: int) -> bool:
        """Locked read of the drain-thread generation — the loop's
        am-I-still-the-owner check.  A bare ``self._gen == gen`` read
        was benign on CPython but violated the guarded-by contract;
        the authoritative checks in ``_dispatch_one`` stay where they
        were."""
        with self._rec_lock:
            return self._gen == gen

    def reset_warm_shapes(self) -> None:
        # thread-affinity: drain, api
        """Forget which shapes have compiled — call after a dispatch
        MODE change (ladder demotion/promotion): the same bucket then
        maps to a different executable, and its first dispatch pays a
        fresh compile the deadline must not misread as a hang.  The
        CURRENTLY in-flight dispatch (the demotion-triggering batch
        being retried on the new rung) goes cold too — its retry pays
        the new rung's compile under the old registration, and its
        completion must NOT warm the shape for the NEW mode (the
        warm-generation bump makes _dispatch_one skip the add)."""
        with self._rec_lock:
            self._warm_shapes.clear()
            self._warm_gen += 1
            if self._inflight is not None:
                gen, t0, batch, _exempt, wg = self._inflight
                self._inflight = (gen, t0, batch, True, wg)

    # -- lifecycle -----------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        # thread-affinity: api
        if self._thread is not None:
            raise ServingAlreadyActiveError(
                "serving runtime already started")
        self._stop.clear()
        with self._rec_lock:
            gen0 = self._gen
        self._thread = threading.Thread(target=self._loop,
                                        args=(gen0,),
                                        daemon=True,
                                        name="serving-drain")
        self._thread.start()
        if self._supervised:
            # watchdog tick: fine enough that a deadline is detected
            # within ~deadline * 1.25, and a dead thread within ~10ms
            tick = (min(max(self._deadline_s / 4.0, 0.002), 0.05)
                    if self._deadline_s > 0 else 0.01)
            self._watch_tick = tick
            self._watchdog = threading.Thread(target=self._watch,
                                              daemon=True,
                                              name="serving-watchdog")
            self._watchdog.start()

    def kill(self, cause: str, timeout: float = 60.0) -> dict:
        # thread-affinity: api
        """Simulated crash stop (chaos / cluster node death): no
        drain — queued rows are swept as COUNTED recovery drops, the
        runtime goes terminal (submit raises, the cause rides every
        snapshot), and the returned snapshot closes the ledger over
        the corpse.  The in-flight dispatch, if any, completes or is
        accounted exactly as a stop() would."""
        with self._submit_lock:
            self._stop.set()  # producers bounce from here on; also
            # parks the watchdog before it can clear the error below
            self._killed = True
        if self._error is None:
            self._error = f"killed: {cause}"
        return self.stop(drain=False, timeout=timeout)

    def stop(self, drain: bool = True, timeout: float = 60.0) -> dict:
        # thread-affinity: api
        """Stop the loop; with ``drain`` (default) every queued row is
        batched and dispatched before returning.  Idempotent.
        ``drain=False`` never loses silently either: pending rows are
        swept as counted recovery drops (the kill()/crash path).

        Raises :class:`ServingError` if the loop thread does not exit
        within ``timeout`` (e.g. stuck in a first-dispatch XLA
        compile): draining concurrently with a live loop would race
        on the batcher's unsynchronized buffers — the caller retries
        once the dispatch returns.

        After a drain-loop DEATH the queued-but-never-dispatched rows
        are not skipped: they are swept and counted as recovery drops
        (the same fault would fire again if we dispatched them), the
        pending sheds still flush as DROP events, and the last
        completed batch's latency is stamped — the ledger
        ``submitted == verdicts + shed + recovery_dropped`` balances
        exactly even for a stop over a corpse."""
        from . import ServingError

        with self._submit_lock:  # in-flight submit finishes or fails
            self._stop.set()
        w = self._watchdog
        if w is not None:
            w.join(timeout=5.0)
            self._watchdog = None
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise ServingError(
                    f"serving drain loop still running after "
                    f"{timeout}s (dispatch in flight?); retry stop()")
            self._thread = None
        # a batch registered in flight but never accounted means the
        # thread died (or was abandoned) between dequeue and stats —
        # account it now, before the ledger below is read
        with self._rec_lock:
            inflight, self._inflight = self._inflight, None
            self._gen += 1
            gen = self._gen
        if inflight is not None:
            self._account_lost(inflight[2], timeout_flavor=False)
        if drain and self._error is None and not self._killed:
            # the loop thread has exited; dispatch stays serialized.
            while True:
                batch = self.batcher.assemble(self.queue, force=True)
                if batch is None:
                    break
                self._dispatch_one(batch, gen)
        else:
            # dead loop / crash stop: the same fault would fire again
            # (or the operator asked for no drain) — sweep the queue
            # into counted recovery drops instead (no silent loss;
            # the error rides the snapshot)
            self._sweep_queue_as_recovery_drops()
        if self._prev_arrivals:
            t_done = time.monotonic()
            self.stats.record_completion(self._prev_arrivals, t_done)
            self._prev_arrivals = []
            self._complete_spans(t_done)
        self._flush_sheds()
        if self._profile_state == "active":
            self._profile_stop()
        return self.snapshot()

    def snapshot(self) -> dict:
        # thread-affinity: any
        out = self.stats.snapshot(queue_pending=self.queue.pending,
                                  queue_depth=self.queue.capacity)
        if self._error is not None:
            out["error"] = self._error
        ft = out.get("fault-tolerance")
        if ft is not None:
            ft["supervised"] = self._supervised
            ft["restart-budget"] = self._budget
            ft["dispatch-deadline-ms"] = round(self._deadline_s * 1e3,
                                               3)
        if self._tracer is not None:
            out["trace"] = self._tracer.stats()
        prof = self.profile_status()
        if prof is not None:
            out["profile"] = prof
        return out

    # -- the drain loop ------------------------------------------------
    def _loop(self, gen: int) -> None:
        # thread-affinity: drain
        try:
            self._loop_body(gen)
        except Exception as e:  # noqa: BLE001 — a dying drain thread
            # must leave a visible corpse: the watchdog (when armed)
            # accounts + restarts from here; otherwise submit() raises
            # from here on, serving_stats() carries the fault, and
            # stop() sweeps instead of draining
            with self._rec_lock:
                if self._gen != gen:
                    return  # abandoned generation: already accounted
            self._error = f"{type(e).__name__}: {e}"

    def _loop_body(self, gen: int) -> None:
        # thread-affinity: drain
        from .batcher import SuperBatch

        while not self._stop.is_set() and self._gen_is(gen):
            k_max = self.superbatch_k
            if k_max > 1 and self._dispatch_super is not None:
                batch = self.batcher.assemble_super(self.queue,
                                                    k_max)
            else:
                batch = self.batcher.assemble(self.queue)
            if batch is not None:
                if isinstance(batch, SuperBatch):
                    self._dispatch_one_super(batch, gen)
                else:
                    self._dispatch_one(batch, gen)
                continue
            # idle: stamp the last batch's completion now rather than
            # at the next dispatch (which may never come — an idle
            # hour must not be recorded as that batch's latency at
            # stop).  Approximate on async backends: its dispatch has
            # returned, residual device work is bounded by the drain
            # cadence.
            if self._prev_arrivals:
                t_done = time.monotonic()
                self.stats.record_completion(self._prev_arrivals,
                                             t_done)
                self._prev_arrivals = []
                self._complete_spans(t_done)
            self._flush_sheds()
            if self.queue.pending:
                # rows are waiting but neither full-bucket nor
                # deadline fired: sleep toward the deadline.  An
                # ALREADY-EXPIRED deadline (0.0 — it can expire
                # between the assemble above and here) loops straight
                # back to flush; the old `min(ttd, tick) or tick`
                # turned that 0 into a full tick of tail latency on
                # every deadline flush.
                ttd = self.batcher.time_to_deadline(self.queue)
                if ttd > 0.0:
                    # hot-path-ok: the bounded idle tick — rows are
                    # waiting but neither full-bucket nor deadline
                    # fired; sleeping toward the deadline IS the
                    # batching policy, capped at _TICK_S
                    time.sleep(min(ttd, _TICK_S))
            else:
                # the idle tick: the registry-backed gauges (queue
                # depth, arena occupancy, in-flight window) sample
                # here — off the dispatch path, at the idle cadence
                self._sample_gauges()
                if self._idle_fn is not None:
                    try:
                        self._idle_fn()
                    except Exception:  # noqa: BLE001 — an idle hook
                        pass  # must never kill the drain loop
                self.queue.wait_nonempty(self._idle_wait_s)

    def _dispatch_one(self, batch: AssembledBatch, gen: int) -> None:
        # thread-affinity: drain, api -- stop()'s final drain runs here
        from . import DispatchFailedError

        if self._profile_state == "armed":
            self._profile_start()
        t0 = time.monotonic()
        if batch.spans:
            from ..obs.trace import STAGE_DISPATCH

            for sp in batch.spans:
                sp.ts[STAGE_DISPATCH] = t0
        shape = (batch.hdr.shape, batch.packed)
        # register BEFORE the device leg: a death or hang from here on
        # can always be accounted by the watchdog / stop()
        with self._rec_lock:
            self._inflight = (gen, t0, batch,
                              shape not in self._warm_shapes,
                              self._warm_gen)
        # injection sites: a raise kills this thread (dead-thread
        # recovery); a hang (~S) wedges it past the dispatch deadline
        faults.check(faults.SITE_SERVING_DISPATCH,
                     abort=lambda: (not self._gen_is(gen)
                                    or self._stop.is_set()))
        with self._rec_lock:
            if self._gen != gen:
                # deadlined while wedged: the watchdog already
                # accounted this batch and a successor owns the loop —
                # do NOT dispatch (the device never saw these rows)
                return
        try:
            if batch.packed:
                info = self._dispatch(batch.hdr, batch.valid,
                                      batch.n_valid,
                                      packed_meta=(batch.ep,
                                                   batch.dirn))
            else:
                info = self._dispatch(batch.hdr, batch.valid,
                                      batch.n_valid)
        except DispatchFailedError:
            # contained device-leg failure (degraded-mode ladder):
            # the batch is lost but counted; the loop lives on
            self.stats.record_dispatch_failure()
            with self._rec_lock:
                mine = (self._inflight is not None
                        and self._inflight[0] == gen)
                if mine:
                    self._inflight = None
            if mine:
                self._account_lost(batch, timeout_flavor=False)
            self._flush_sheds()
            return
        t1 = time.monotonic()
        with self._rec_lock:
            if self._gen != gen:
                # a real hang that eventually completed after the
                # watchdog recovered: device effects landed, but the
                # rows were already accounted as timeout drops —
                # recording them again would double-count
                return
            inflight, self._inflight = self._inflight, None
            # skip the warm-add when a ladder transition happened
            # while this dispatch ran: the shape key now names a
            # DIFFERENT executable, and warming it would let the new
            # mode's first compile be misread as a hang
            if (inflight is not None
                    and inflight[4] == self._warm_gen):
                self._warm_shapes.add(shape)
        # the dispatcher knows best what crossed the link: the
        # sharded leg re-packs AFTER flow routing, so the assembled
        # batch's format/size can differ from the shipped one
        h2d, packed = None, batch.packed
        mode = "packed" if batch.packed else "wide"
        demoted, bid = False, -1
        if isinstance(info, dict):
            h2d = info.get("h2d_bytes")
            if "mode" in info:
                mode = info["mode"]
                packed = "packed" in mode
            demoted = bool(info.get("demoted"))
            bid = int(info.get("batch_id", -1))
        spans = batch.spans
        if spans:
            from ..obs.trace import STAGE_DISPATCH_RET

            shard_of = (info.get("shard_of")
                        if isinstance(info, dict) else None)
            overflowed = []
            kept = []
            for sp in spans:
                sp.ts[STAGE_DISPATCH_RET] = t1
                sp.mode = mode
                sp.demoted = demoted
                sp.batch_id = bid
                if (shard_of is not None
                        and 0 <= sp.batch_pos < len(shard_of)):
                    sp.shard = int(shard_of[sp.batch_pos])
                    if sp.shard < 0:
                        # the router dropped this packet (full shard
                        # block): its DROP event is already counted,
                        # and its span is a counted loss — a
                        # completed trace would report a fake e2e
                        # latency for a packet the device never saw
                        overflowed.append(sp)
                        continue
                kept.append(sp)
            if overflowed and self._tracer is not None:
                self._tracer.evict(overflowed)
            spans = tuple(kept)
            if spans and self._span_sink is not None and bid >= 0:
                # the async event plane owns these spans now: the
                # join worker stamps device/join at true window-join
                # time and commits (or evicts, counted, if the
                # window is lost)
                if self._span_sink(bid, spans):
                    spans = ()
        self.stats.record_batch(batch.n_valid, len(batch.hdr),
                                batch.arrivals, t0, packed=packed,
                                h2d_bytes=(h2d if h2d is not None
                                           else batch.hdr.nbytes))
        self.stats.record_dispatch(1)
        if self._prev_arrivals:
            self.stats.record_completion(self._prev_arrivals, t1)
        self._complete_spans(t1)
        self._prev_arrivals = batch.arrivals
        self._prev_spans = spans
        self._flush_sheds()
        if self._profile_state == "active":
            self._profile_count += 1
            if self._profile_count >= self._profile_batches:
                self._profile_stop()

    def _dispatch_one_super(self, sb, gen: int) -> None:
        # thread-affinity: drain
        """The K-batch flavor of :meth:`_dispatch_one`: same
        registration / generation / warm-shape / accounting
        discipline, one device dispatch for ``sb.k`` batches.  The
        in-flight registration carries the whole SuperBatch, so a
        death or hang accounts all K batches' rows exactly like a
        single lost batch would."""
        from . import DispatchFailedError

        if self._profile_state == "armed":
            self._profile_start()
        t0 = time.monotonic()
        flat_spans = [sp for step in sb.spans for sp in step]
        if flat_spans:
            from ..obs.trace import STAGE_DISPATCH

            for sp in flat_spans:
                sp.ts[STAGE_DISPATCH] = t0
        shape = (sb.hdr.shape, sb.packed)
        with self._rec_lock:
            self._inflight = (gen, t0, sb,
                              shape not in self._warm_shapes,
                              self._warm_gen)
        faults.check(faults.SITE_SERVING_DISPATCH,
                     abort=lambda: (not self._gen_is(gen)
                                    or self._stop.is_set()))
        with self._rec_lock:
            if self._gen != gen:
                return  # deadlined while wedged (see _dispatch_one)
        try:
            info = self._dispatch_super(sb)
        except DispatchFailedError:
            self.stats.record_dispatch_failure()
            with self._rec_lock:
                mine = (self._inflight is not None
                        and self._inflight[0] == gen)
                if mine:
                    self._inflight = None
            if mine:
                self._account_lost(sb, timeout_flavor=False)
            self._flush_sheds()
            return
        t1 = time.monotonic()
        with self._rec_lock:
            if self._gen != gen:
                return  # late wake after watchdog recovery
            inflight, self._inflight = self._inflight, None
            if (inflight is not None
                    and inflight[4] == self._warm_gen):
                self._warm_shapes.add(shape)
        h2d, mode = None, ("packed" if sb.packed else "wide")
        packed = sb.packed
        demoted, bids, n_disp = False, (), 1
        if isinstance(info, dict):
            h2d = info.get("h2d_bytes")
            if "mode" in info:
                # recompute the wire format from what actually
                # shipped: a mode-demoted per-step retry of a packed
                # superbatch ships WIDE rows (same recompute the
                # single-batch path does)
                mode = info["mode"]
                packed = "packed" in mode
            demoted = bool(info.get("demoted"))
            bids = tuple(info.get("bids", ()))
            # a demoted retry ran K single dispatches, not one fused
            # one — the dispatch scoreboard must count what happened
            n_disp = int(info.get("dispatches", 1))
        if flat_spans:
            from ..obs.trace import STAGE_DISPATCH_RET

            leftover = []
            for k, step_spans in enumerate(sb.spans):
                if not step_spans:
                    continue
                bid = bids[k] if k < len(bids) else -1
                for sp in step_spans:
                    sp.ts[STAGE_DISPATCH_RET] = t1
                    sp.mode = mode
                    sp.demoted = demoted
                    sp.batch_id = bid
                if (self._span_sink is not None and bid >= 0
                        and self._span_sink(bid, tuple(step_spans))):
                    continue  # the async event plane owns them now
                leftover.extend(step_spans)
            flat_spans = leftover
        # per-step batch accounting keeps every existing counter's
        # meaning (batches counts INNER batches); the dispatch
        # amortization shows up in dispatches/batches-per-dispatch.
        # h2d bytes for the whole superbatch land on step 0.
        total_h2d = h2d if h2d is not None else sb.hdr.nbytes
        for k in range(sb.k):
            self.stats.record_batch(
                sb.bucket, sb.bucket,
                sb.arrivals if k == 0 else [], t0, packed=packed,
                h2d_bytes=total_h2d if k == 0 else 0)
        self.stats.record_dispatch(sb.k, rows_real=sb.n_valid,
                                   rows_shipped=sb.k * sb.bucket,
                                   dispatches=n_disp)
        if self._prev_arrivals:
            self.stats.record_completion(self._prev_arrivals, t1)
        self._complete_spans(t1)
        self._prev_arrivals = sb.arrivals
        self._prev_spans = tuple(flat_spans)
        self._flush_sheds()
        if self._profile_state == "active":
            self._profile_count += 1
            if self._profile_count >= self._profile_batches:
                self._profile_stop()

    # -- the obs plane (spans, gauges, profile window) -----------------
    def _complete_spans(self, t_done: float) -> None:
        # thread-affinity: drain, api
        """Fallback (no async event plane took the spans): the batch
        whose arrivals just completed reached the join boundary —
        stamp device/join there and commit (same clock as the
        end-to-end latency histogram)."""
        spans, self._prev_spans = self._prev_spans, ()
        if not spans or self._tracer is None:
            return
        from ..obs.trace import STAGE_DEVICE, STAGE_JOIN

        for sp in spans:
            sp.ts[STAGE_DEVICE] = t_done
            sp.ts[STAGE_JOIN] = t_done
            self._tracer.commit(sp)

    def _sample_gauges(self) -> None:
        # thread-affinity: drain
        # queue backlog/depth deliberately NOT copied here: the idle
        # tick only fires when the queue is empty, so a sampled copy
        # would read ~0 during exactly the overload episodes a
        # backlog gauge exists for — the registry reads them live.
        # Arena occupancy iterates the slot dict, which only this
        # (drain) thread may do safely, hence the sampled copy
        occ = self.batcher.arena.occupancy()
        g = {"arena-shapes": occ["shapes"],
             "arena-bytes": occ["bytes"]}
        if self._gauge_fn is not None:
            try:
                g.update(self._gauge_fn())
            except Exception:  # noqa: BLE001 — a gauge hook must
                pass  # never kill the drain loop
        g["sampled-at"] = time.monotonic()
        self.stats.gauges = g  # whole-dict swap: no torn reads

    def _profile_start(self) -> None:
        # thread-affinity: drain, api
        try:
            import jax

            jax.profiler.start_trace(self._profile_dir)
            self._profile_state = "active"
        except Exception as e:  # noqa: BLE001 — profiling is
            # best-effort; a capture failure must not kill serving
            import logging

            # hot-path-ok: fires only when a profile capture FAILS to
            # start — an operator-requested debug window, never
            # steady state
            logging.getLogger(__name__).warning(
                "serving profile capture failed to start: %s", e)
            self._profile_state = "failed"

    def _profile_stop(self) -> None:
        # thread-affinity: drain, api
        try:
            import jax

            jax.profiler.stop_trace()
            self._profile_state = "done"
        except Exception:  # noqa: BLE001
            self._profile_state = "failed"

    def profile_status(self) -> Optional[dict]:
        if self._profile_state == "off":
            return None
        return {"dir": self._profile_dir,
                "state": self._profile_state,
                "batches": self._profile_count,
                "window": self._profile_batches}

    def _flush_sheds(self) -> None:
        # thread-affinity: drain, api
        rows, count = self.queue.take_sheds()
        if count == 0:
            return
        if self._on_shed is not None:
            self._on_shed(rows, count)
        self.stats.record_sheds(count,
                                len(rows) if rows is not None else 0)

    # -- the recovery plane (watchdog thread + stop path) --------------
    def _watch(self) -> None:
        # thread-affinity: watchdog
        """Supervise the drain thread: restart a dead one, deadline a
        hung dispatch, account every lost row.  Exits when the stop
        flag rises or the restart budget is exhausted."""
        backoff = self._backoff_s
        while not self._stop.wait(self._watch_tick):
            if self._stop.is_set():
                return  # stop raced the tick: not a death
            t = self._thread
            dead = (self._error is not None
                    or (t is not None and not t.is_alive()
                        and not self._stop.is_set()))
            hung = False
            if not dead and self._deadline_s > 0:
                with self._rec_lock:
                    inflight = self._inflight
                    hung = (inflight is not None
                            and inflight[0] == self._gen
                            and not inflight[3]  # cold-shape compile
                            and (time.monotonic() - inflight[1]
                                 > self._deadline_s))
            if not dead and not hung:
                backoff = self._backoff_s  # healthy: backoff re-arms
                continue
            cause = (self._error
                     or ("dispatch exceeded deadline "
                         f"{self._deadline_s * 1e3:.0f}ms" if hung
                         else "drain thread died"))
            if self.restarts >= self._budget:
                # budget exhausted: go terminal with a visible corpse
                self._error = (f"restart budget ({self._budget}) "
                               f"exhausted; last fault: {cause}")
                self._notify_restart(self._error, terminal=True)
                return
            # abandon the current generation (a wedged thread that
            # ever wakes will exit without dispatching or recording)
            # and account its in-flight batch
            with self._rec_lock:
                self._gen += 1
                gen = self._gen
                inflight, self._inflight = self._inflight, None
            # record the restart AT detection (the observable tests
            # and operators time against), then account: the first
            # accounting pays a one-time metricsmap-op compile that
            # must not read as detection latency
            self._error = None
            self.stats.record_restart(cause, timeout=hung)
            self.restarts += 1
            self._notify_restart(cause, terminal=False)
            if inflight is not None:
                self._account_lost(inflight[2], timeout_flavor=hung)
            if self._stop.wait(backoff):  # exponential, stop-aware
                return
            backoff = min(backoff * 2 if backoff else self._backoff_s,
                          _BACKOFF_CAP_S)
            t = threading.Thread(target=self._loop, args=(gen,),
                                 daemon=True,
                                 name=f"serving-drain-r{self.restarts}")
            self._thread = t
            t.start()

    def _notify_restart(self, cause: str, terminal: bool) -> None:
        # thread-affinity: watchdog
        """Fire the incident hook (watchdog thread); contained."""
        if self._on_restart is None:
            return
        try:
            self._on_restart(cause, terminal)
        except Exception:  # noqa: BLE001 — an incident hook must
            pass  # never cost the recovery it describes

    def _account_lost(self, batch,
                      timeout_flavor: bool) -> None:
        # thread-affinity: drain, watchdog, api
        """One lost batch (or SuperBatch — all K inner batches) ->
        counted recovery drops + decoded DROP events.
        ``timeout_flavor`` picks REASON_DISPATCH_TIMEOUT (watchdog
        deadline) over REASON_RECOVERY_DROP."""
        from ..datapath.verdict import (REASON_DISPATCH_TIMEOUT,
                                        REASON_RECOVERY_DROP)
        from .batcher import SuperBatch

        sup = isinstance(batch, SuperBatch)
        spans = ([sp for step in batch.spans for sp in step]
                 if sup else batch.spans)
        if spans and self._tracer is not None:
            # the batch died before the join boundary: its spans are
            # counted losses, never completed traces
            self._tracer.evict(spans)
        n = batch.n_valid
        if n == 0:
            return
        rows: Optional[np.ndarray] = None
        try:
            # the batcher emits prefix-valid buckets; reconstruct wide
            # rows for event synthesis (COPY — the hdr is an arena
            # slot that recycles under the next generation)
            if sup and batch.packed:
                from ..core.packets import unpack_rows_np

                rows = np.concatenate([
                    unpack_rows_np(np.asarray(batch.hdr[k]),
                                   int(batch.eps[k]),
                                   int(batch.dirns[k]))
                    for k in range(batch.k)])
            elif sup:
                rows = np.array(batch.hdr, copy=True).reshape(
                    n, batch.hdr.shape[2])
            elif batch.packed:
                from ..core.packets import unpack_rows_np

                rows = unpack_rows_np(np.asarray(batch.hdr[:n]),
                                      batch.ep, batch.dirn)
            else:
                rows = np.array(batch.hdr[:n], copy=True)
        except Exception:  # noqa: BLE001 — accounting must not die on
            rows = None  # a corrupt lost batch; the COUNT stays exact
        reason = (REASON_DISPATCH_TIMEOUT if timeout_flavor
                  else REASON_RECOVERY_DROP)
        self.stats.record_recovery_drops(
            n, timeout=timeout_flavor,
            events=len(rows) if rows is not None else 0)
        if self._on_recovery_drop is not None:
            self._on_recovery_drop(rows, n, reason)

    def _sweep_queue_as_recovery_drops(self) -> None:
        # thread-affinity: api
        """stop() over a dead loop: queued-but-never-dispatched rows
        become counted recovery drops (REASON_RECOVERY_DROP) instead
        of silently vanishing with the queue object."""
        from ..datapath.verdict import REASON_RECOVERY_DROP

        pending = self.queue.pending
        if pending == 0:
            return
        rows, _arrivals = self.queue.take(pending)
        n = len(rows)
        self.stats.record_recovery_drops(n, timeout=False, events=n)
        if self._on_recovery_drop is not None and n:
            self._on_recovery_drop(np.array(rows, copy=True), n,
                                   REASON_RECOVERY_DROP)
