"""The serving drain loop: ingress stream -> device batches.

Reference: upstream cilium's NAPI-ish consumption of the XDP/RSS
front end — a poll loop takes what arrived (up to the ring budget),
runs it through the datapath, and surfaces sheds as counted drops.
Production inference stacks call the same shape "continuous
batching".

Double buffering: ``dispatch`` (``Daemon.serve_batch`` under the
hood) ENQUEUES the device work and returns — jax dispatch is async —
so while batch N executes on device, this loop is already draining
the queue and padding batch N+1 on the host.  hdr/valid buffers come
from the batcher's preallocated arena (ownership transfers to the
dispatcher under the recycling horizon documented in batcher.py), so
assembly is allocation-free AND never touches pages an in-flight h2d
copy or the drain-time event join may still be reading.

The loop owns all dispatch: ``submit()`` (any thread) only offers
rows to the bounded ingress queue, which is the backpressure point —
overflow sheds by policy, sheds surface through ``on_shed`` as
monitor DROP events, and nothing ever blocks the producer.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from . import ServingAlreadyActiveError, validate_serving_config
from .batcher import AdaptiveBatcher, AssembledBatch
from .ingress import IngressQueue
from .stats import ServingStats

# dispatch(hdr [bucket, N_COLS], valid [bucket] bool, n_valid) -> any;
# packed batches (pack=True and the rows were eligible) add a
# packed_meta=(ep, dirn) kwarg and ship hdr as [bucket, 4] wire rows.
# A dispatcher may return a dict with "h2d_bytes" to override the
# link accounting (the sharded path re-routes and re-packs, so the
# bytes that actually crossed differ from the assembled hdr's size).
DispatchFn = Callable[[np.ndarray, np.ndarray, int], Optional[dict]]
# on_shed(retained header rows or None, exact shed count) -> None
ShedFn = Callable[[Optional[np.ndarray], int], None]

# idle wait granularity: how long the loop sleeps when rows are
# pending but neither bucket-full nor deadline has fired yet.  Small
# enough that a max-wait deadline is honored within ~1ms.
_TICK_S = 0.001


class ServingRuntime:
    """start() -> submit() from any thread -> stop(drain=True).

    ``dispatch`` is the device leg (``Daemon.serve_batch``); the
    runtime never imports the agent so the serving plane stays a
    leaf package."""

    def __init__(self, dispatch: DispatchFn, queue_depth: int,
                 bucket_ladder, max_wait_us: float,
                 overflow_policy: str = "drop-tail",
                 on_shed: Optional[ShedFn] = None,
                 expected_cols: Optional[int] = None,
                 pack: bool = False,
                 arena_depth: Optional[int] = None):
        from .batcher import DEFAULT_ARENA_DEPTH

        depth, ladder, wait, policy = validate_serving_config(
            queue_depth, bucket_ladder, max_wait_us, overflow_policy)
        self.queue = IngressQueue(depth, policy)
        # pack: assemble eligible IPv4 single-stream batches as the
        # 16 B/packet wire format; arena_depth: the staging-slot
        # recycling horizon — MUST exceed however many in-flight
        # batches the dispatcher retains (batcher.py module doc)
        self.batcher = AdaptiveBatcher(
            ladder, wait, pack=pack,
            arena_depth=arena_depth or DEFAULT_ARENA_DEPTH)
        self.stats = ServingStats()
        self._dispatch = dispatch
        self._on_shed = on_shed
        # row width the datapath expects (N_COLS): a malformed chunk
        # must bounce off submit() with a ValueError, not detonate
        # inside the drain thread batches later
        self._expected_cols = expected_cols
        self._error: Optional[str] = None  # terminal drain-loop fault
        self._stop = threading.Event()
        # serializes submit() against stop()'s final drain: a chunk
        # offered after the drain swept the queue would sit there
        # forever — neither dispatched nor shed-counted
        self._submit_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        # arrivals of the batch currently executing on device: its
        # end-to-end completion is stamped when the NEXT dispatch
        # returns (the device runs batches in order, so by then batch
        # N's events have been appended)
        self._prev_arrivals: List[Tuple[int, float]] = []

    # -- producer side (any thread) -----------------------------------
    def submit(self, rows: np.ndarray,
               t: Optional[float] = None) -> int:
        """Offer a chunk of header rows; returns how many were
        admitted.  Never blocks on the datapath: overflow sheds by
        the configured policy and is surfaced as counted monitor DROP
        events.  Raises after :meth:`stop` — a post-drain chunk would
        queue forever, neither dispatched nor shed-counted."""
        from . import ServingError, ServingNotStartedError

        rows = np.asarray(rows)
        if rows.ndim != 2 or not np.issubdtype(rows.dtype,
                                               np.integer):
            raise ValueError(
                "submit() wants [n, N_COLS] integer header rows, got "
                f"shape {rows.shape} dtype {rows.dtype}")
        if (self._expected_cols is not None
                and rows.shape[1] != self._expected_cols):
            raise ValueError(
                f"submit() wants {self._expected_cols}-column header "
                f"rows, got {rows.shape[1]}")
        with self._submit_lock:
            if self._error is not None:
                raise ServingError(
                    f"serving drain loop died: {self._error}")
            if self._stop.is_set():
                raise ServingNotStartedError(
                    "serving runtime is stopped")
            offered = len(rows)
            accepted = self.queue.offer(rows, t)
            self.stats.record_submit(offered, accepted)
            return accepted

    # -- lifecycle -----------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self._thread is not None:
            raise ServingAlreadyActiveError(
                "serving runtime already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        daemon=True,
                                        name="serving-drain")
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 60.0) -> dict:
        """Stop the loop; with ``drain`` (default) every queued row is
        batched and dispatched before returning.  Idempotent.

        Raises :class:`ServingError` if the loop thread does not exit
        within ``timeout`` (e.g. stuck in a first-dispatch XLA
        compile): draining concurrently with a live loop would race
        on the batcher's unsynchronized buffers — the caller retries
        once the dispatch returns."""
        from . import ServingError

        with self._submit_lock:  # in-flight submit finishes or fails
            self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise ServingError(
                    f"serving drain loop still running after "
                    f"{timeout}s (dispatch in flight?); retry stop()")
            self._thread = None
        if drain and self._error is None:
            # the loop thread has exited; dispatch stays serialized.
            # (a dead loop skips the drain — the same fault would
            # fire again; the error rides the snapshot instead)
            while True:
                batch = self.batcher.assemble(self.queue, force=True)
                if batch is None:
                    break
                self._dispatch_one(batch)
        if self._prev_arrivals:
            self.stats.record_completion(self._prev_arrivals,
                                         time.monotonic())
            self._prev_arrivals = []
        self._flush_sheds()
        return self.snapshot()

    def snapshot(self) -> dict:
        out = self.stats.snapshot(queue_pending=self.queue.pending,
                                  queue_depth=self.queue.capacity)
        if self._error is not None:
            out["error"] = self._error
        return out

    # -- the drain loop ------------------------------------------------
    def _loop(self) -> None:
        try:
            self._loop_body()
        except Exception as e:  # noqa: BLE001 — a dying drain thread
            # must leave a visible corpse: submit() raises from here
            # on, serving_stats() carries the fault, and stop() skips
            # the doomed drain
            self._error = f"{type(e).__name__}: {e}"

    def _loop_body(self) -> None:
        while not self._stop.is_set():
            batch = self.batcher.assemble(self.queue)
            if batch is not None:
                self._dispatch_one(batch)
                continue
            # idle: stamp the last batch's completion now rather than
            # at the next dispatch (which may never come — an idle
            # hour must not be recorded as that batch's latency at
            # stop).  Approximate on async backends: its dispatch has
            # returned, residual device work is bounded by the drain
            # cadence.
            if self._prev_arrivals:
                self.stats.record_completion(self._prev_arrivals,
                                             time.monotonic())
                self._prev_arrivals = []
            self._flush_sheds()
            if self.queue.pending:
                # rows are waiting but neither full-bucket nor
                # deadline fired: sleep toward the deadline.  An
                # ALREADY-EXPIRED deadline (0.0 — it can expire
                # between the assemble above and here) loops straight
                # back to flush; the old `min(ttd, tick) or tick`
                # turned that 0 into a full tick of tail latency on
                # every deadline flush.
                ttd = self.batcher.time_to_deadline(self.queue)
                if ttd > 0.0:
                    time.sleep(min(ttd, _TICK_S))
            else:
                self.queue.wait_nonempty(0.05)

    def _dispatch_one(self, batch: AssembledBatch) -> None:
        t0 = time.monotonic()
        if batch.packed:
            info = self._dispatch(batch.hdr, batch.valid,
                                  batch.n_valid,
                                  packed_meta=(batch.ep, batch.dirn))
        else:
            info = self._dispatch(batch.hdr, batch.valid,
                                  batch.n_valid)
        t1 = time.monotonic()
        # the dispatcher knows best what crossed the link: the
        # sharded leg re-packs AFTER flow routing, so the assembled
        # batch's format/size can differ from the shipped one
        h2d, packed = None, batch.packed
        if isinstance(info, dict):
            h2d = info.get("h2d_bytes")
            if "mode" in info:
                packed = "packed" in info["mode"]
        self.stats.record_batch(batch.n_valid, len(batch.hdr),
                                batch.arrivals, t0, packed=packed,
                                h2d_bytes=(h2d if h2d is not None
                                           else batch.hdr.nbytes))
        if self._prev_arrivals:
            self.stats.record_completion(self._prev_arrivals, t1)
        self._prev_arrivals = batch.arrivals
        self._flush_sheds()

    def _flush_sheds(self) -> None:
        rows, count = self.queue.take_sheds()
        if count == 0:
            return
        if self._on_shed is not None:
            self._on_shed(rows, count)
        self.stats.record_sheds(count,
                                len(rows) if rows is not None else 0)
