"""Serving front-end: the stream -> batch admission layer.

Reference: upstream cilium absorbs variable-rate traffic with the
XDP/RSS front end and per-CPU rings before any per-packet program
runs; production inference stacks solve the same problem with
continuous batching.  This package is that layer for the TPU
datapath: a packet *stream* enters, fixed-shape batches leave.

Pieces (PARITY.md row 54):

- :mod:`.ingress` — bounded admission queue (the XDP ring analogue)
  with a configurable overflow policy; sheds are counted and surface
  as monitor DROP events (``REASON_INGRESS_OVERFLOW``), never lost
  silently.
- :mod:`.batcher` — adaptive batcher padding to a small ladder of
  power-of-two bucket sizes (bounds JIT recompiles to the ladder
  length) and flushing on bucket-full OR a max-wait deadline.
  Assembles into a preallocated per-bucket arena (allocation-free hot
  path) and, with ``pack=True``, emits eligible IPv4 single-stream
  batches as the packed 16 B/packet h2d wire format.
- :mod:`.runtime` — the drain loop: assemble batch N+1 on the host
  while batch N executes on device (``Daemon.serve_batch``), with
  clean start/stop/drain semantics.
- :mod:`.stats` — per-batch telemetry: queue wait, pad efficiency,
  batches/sec, verdicts/sec, p50/p95/p99 end-to-end latency.
"""

from __future__ import annotations


class ServingError(RuntimeError):
    """Base of the serving plane's typed errors.  Subclasses
    RuntimeError so pre-existing ``except RuntimeError`` callers (and
    tests matching it) keep working."""


class ServingNotStartedError(ServingError):
    """serve_batch()/submit() before start_serving()."""


class ServingAlreadyActiveError(ServingError):
    """start_serving() while a serving session is live — silently
    replacing the drainer would drop its in-flight window without any
    loss accounting."""


class ServingBackendError(ServingError):
    """The serving path needs the tpu backend (the interpreter loader
    has no device event ring)."""


class DispatchFailedError(ServingError):
    """A dispatch callable's device leg failed in a CONTAINED way (the
    degraded-mode ladder saw the failure, counted it toward its
    demotion threshold, and did not — or could not yet — demote).  The
    drain runtime accounts the batch's rows as recovery drops
    (``REASON_RECOVERY_DROP``, counted + surfaced as DROP events) and
    KEEPS THE LOOP ALIVE: no thread death, no restart burned.  Wrap
    the original exception as ``__cause__``."""


def validate_serving_config(queue_depth: int, bucket_ladder,
                            max_wait_us, overflow_policy: str) -> tuple:
    """Validate the DaemonConfig serving knobs; returns the normalized
    ``(queue_depth, ladder, max_wait_us, overflow_policy)`` tuple.
    Raises ValueError with an actionable message — a typo'd policy or
    a non-power-of-two bucket must fail at construction, not as a
    recompile storm (or an assert) under load."""
    ladder = tuple(int(b) for b in bucket_ladder)
    if not ladder:
        raise ValueError("serving_bucket_ladder must name at least "
                         "one bucket size")
    for b in ladder:
        if b <= 0 or b & (b - 1):
            raise ValueError(
                f"serving bucket size {b} is not a power of two "
                "(each distinct batch shape is one JIT compile; the "
                "ladder exists to bound them)")
    if list(ladder) != sorted(set(ladder)):
        raise ValueError(
            f"serving_bucket_ladder {ladder} must be strictly "
            "ascending with no duplicates")
    depth = int(queue_depth)
    if depth < ladder[-1]:
        raise ValueError(
            f"serving_queue_depth {depth} is smaller than the largest "
            f"bucket {ladder[-1]}; a full bucket could never assemble")
    wait = float(max_wait_us)
    if wait < 0:
        raise ValueError("serving_max_wait_us must be >= 0")
    if overflow_policy not in ("drop-tail", "drop-oldest"):
        raise ValueError(
            f"serving_overflow_policy must be drop-tail|drop-oldest, "
            f"got {overflow_policy!r}")
    return depth, ladder, wait, overflow_policy


def validate_superbatch_config(superbatch_k) -> tuple:
    """Validate ``serving_superbatch_k``; returns ``(k_max,
    k_ladder)`` where ``k_ladder`` is the power-of-two K rung set
    {1, 2, ..., k_max} the fallback ladder walks.  Same contract as
    the validators above: a bad K fails at daemon construction, not
    as a compiled-shape explosion under load (each K is one
    executable per bucket rung)."""
    k = int(superbatch_k)
    if k < 1 or k & (k - 1):
        raise ValueError(
            f"serving_superbatch_k {k} must be a power of two >= 1 "
            "(each K is one compiled executable per bucket rung; the "
            "K ladder exists to bound them; 1 disables superbatching)")
    ladder, v = [], 1
    while v <= k:
        ladder.append(v)
        v <<= 1
    return k, tuple(ladder)


def validate_recovery_config(dispatch_deadline_ms, restart_budget,
                             restart_backoff_ms, demote_threshold,
                             promote_after,
                             promote_cooldown_s) -> tuple:
    """Validate the fault-tolerance knobs; returns the normalized
    tuple.  Same contract as :func:`validate_serving_config`: a bad
    knob fails at daemon construction with an actionable message, not
    as a watchdog that silently never fires under load."""
    deadline = float(dispatch_deadline_ms)
    if deadline < 0:
        raise ValueError("serving_dispatch_deadline_ms must be >= 0 "
                         "(0 disables hang detection)")
    budget = int(restart_budget)
    if budget < 0:
        raise ValueError("serving_restart_budget must be >= 0 "
                         "(0 disables the recovery supervisor)")
    backoff = float(restart_backoff_ms)
    if backoff < 0:
        raise ValueError("serving_restart_backoff_ms must be >= 0")
    demote = int(demote_threshold)
    if demote < 1:
        raise ValueError("serving_demote_threshold must be >= 1 "
                         "(consecutive dispatch failures per rung)")
    promote = int(promote_after)
    if promote < 1:
        raise ValueError("serving_promote_after must be >= 1 "
                         "(consecutive healthy batches)")
    cooldown = float(promote_cooldown_s)
    if cooldown < 0:
        raise ValueError("serving_promote_cooldown_s must be >= 0")
    return deadline, budget, backoff, demote, promote, cooldown


from .batcher import AdaptiveBatcher, BucketArena  # noqa: E402
from .ingress import IngressQueue  # noqa: E402
from .ladder import FallbackLadder  # noqa: E402
from .runtime import ServingRuntime  # noqa: E402
from .stats import LatencyHistogram, ServingStats  # noqa: E402

__all__ = [
    "AdaptiveBatcher",
    "BucketArena",
    "DispatchFailedError",
    "FallbackLadder",
    "IngressQueue",
    "LatencyHistogram",
    "ServingError",
    "ServingAlreadyActiveError",
    "ServingBackendError",
    "ServingNotStartedError",
    "ServingRuntime",
    "ServingStats",
    "validate_recovery_config",
    "validate_serving_config",
    "validate_superbatch_config",
]
