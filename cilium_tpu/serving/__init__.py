"""Serving front-end: the stream -> batch admission layer.

Reference: upstream cilium absorbs variable-rate traffic with the
XDP/RSS front end and per-CPU rings before any per-packet program
runs; production inference stacks solve the same problem with
continuous batching.  This package is that layer for the TPU
datapath: a packet *stream* enters, fixed-shape batches leave.

Pieces (PARITY.md row 54):

- :mod:`.ingress` — bounded admission queue (the XDP ring analogue)
  with a configurable overflow policy; sheds are counted and surface
  as monitor DROP events (``REASON_INGRESS_OVERFLOW``), never lost
  silently.
- :mod:`.batcher` — adaptive batcher padding to a small ladder of
  power-of-two bucket sizes (bounds JIT recompiles to the ladder
  length) and flushing on bucket-full OR a max-wait deadline.
  Assembles into a preallocated per-bucket arena (allocation-free hot
  path) and, with ``pack=True``, emits eligible IPv4 single-stream
  batches as the packed 16 B/packet h2d wire format.
- :mod:`.runtime` — the drain loop: assemble batch N+1 on the host
  while batch N executes on device (``Daemon.serve_batch``), with
  clean start/stop/drain semantics.
- :mod:`.stats` — per-batch telemetry: queue wait, pad efficiency,
  batches/sec, verdicts/sec, p50/p95/p99 end-to-end latency.
"""

from __future__ import annotations


class ServingError(RuntimeError):
    """Base of the serving plane's typed errors.  Subclasses
    RuntimeError so pre-existing ``except RuntimeError`` callers (and
    tests matching it) keep working."""


class ServingNotStartedError(ServingError):
    """serve_batch()/submit() before start_serving()."""


class ServingAlreadyActiveError(ServingError):
    """start_serving() while a serving session is live — silently
    replacing the drainer would drop its in-flight window without any
    loss accounting."""


class ServingBackendError(ServingError):
    """The serving path needs the tpu backend (the interpreter loader
    has no device event ring)."""


def validate_serving_config(queue_depth: int, bucket_ladder,
                            max_wait_us, overflow_policy: str) -> tuple:
    """Validate the DaemonConfig serving knobs; returns the normalized
    ``(queue_depth, ladder, max_wait_us, overflow_policy)`` tuple.
    Raises ValueError with an actionable message — a typo'd policy or
    a non-power-of-two bucket must fail at construction, not as a
    recompile storm (or an assert) under load."""
    ladder = tuple(int(b) for b in bucket_ladder)
    if not ladder:
        raise ValueError("serving_bucket_ladder must name at least "
                         "one bucket size")
    for b in ladder:
        if b <= 0 or b & (b - 1):
            raise ValueError(
                f"serving bucket size {b} is not a power of two "
                "(each distinct batch shape is one JIT compile; the "
                "ladder exists to bound them)")
    if list(ladder) != sorted(set(ladder)):
        raise ValueError(
            f"serving_bucket_ladder {ladder} must be strictly "
            "ascending with no duplicates")
    depth = int(queue_depth)
    if depth < ladder[-1]:
        raise ValueError(
            f"serving_queue_depth {depth} is smaller than the largest "
            f"bucket {ladder[-1]}; a full bucket could never assemble")
    wait = float(max_wait_us)
    if wait < 0:
        raise ValueError("serving_max_wait_us must be >= 0")
    if overflow_policy not in ("drop-tail", "drop-oldest"):
        raise ValueError(
            f"serving_overflow_policy must be drop-tail|drop-oldest, "
            f"got {overflow_policy!r}")
    return depth, ladder, wait, overflow_policy


from .batcher import AdaptiveBatcher, BucketArena  # noqa: E402
from .ingress import IngressQueue  # noqa: E402
from .runtime import ServingRuntime  # noqa: E402
from .stats import LatencyHistogram, ServingStats  # noqa: E402

__all__ = [
    "AdaptiveBatcher",
    "BucketArena",
    "IngressQueue",
    "LatencyHistogram",
    "ServingError",
    "ServingAlreadyActiveError",
    "ServingBackendError",
    "ServingNotStartedError",
    "ServingRuntime",
    "ServingStats",
    "validate_serving_config",
]
