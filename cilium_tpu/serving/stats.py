"""Serving telemetry: per-batch counters + latency histograms.

The monitor plane streams EVENTS; this module answers the operator
questions events cannot: how long do packets wait for admission, how
much device work is padding, what end-to-end latency do the p95/p99
packets see, and is the runtime keeping up with offered load.
Exposed through ``GET /serving`` and ``cilium-tpu serving stats``.

Histograms are fixed log2 buckets in microseconds (1µs .. ~17min) —
constant memory, lock-cheap to record.  Percentile reads LINEARLY
INTERPOLATE within the winning bucket (the upper bound overstated
p99 by up to 2x at coarse buckets); ``percentile(p, upper=True)``
keeps the conservative bucket-upper-bound read for callers that
want "never better than reality".
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

N_BUCKETS = 30  # 2^30 us ~ 17.9 min: past any sane serving latency


class LatencyHistogram:
    """Log2-bucketed microsecond histogram."""

    def __init__(self):
        self.buckets = [0] * N_BUCKETS
        self.count = 0
        self.max_us = 0.0
        self.total_us = 0.0  # the prometheus histogram _sum

    def record(self, us: float) -> None:
        if us < 0:
            us = 0.0
        idx = min(max(int(us), 0).bit_length(), N_BUCKETS - 1)
        self.buckets[idx] += 1
        self.count += 1
        self.total_us += us
        if us > self.max_us:
            self.max_us = us

    def percentile(self, p: float,
                   upper: bool = False) -> Optional[float]:
        """The p-quantile, linearly interpolated within the winning
        log2 bucket (None when empty).  ``upper=True`` returns the
        bucket's upper bound instead — the conservative read (a
        reported p99 is never better than reality), which the
        default overstated by up to 2x at coarse buckets."""
        if self.count == 0:
            return None
        target = p * self.count
        acc = 0
        for i, c in enumerate(self.buckets):
            if not c:
                continue
            if acc + c >= target:
                # bucket i holds [2^(i-1), 2^i); bucket 0 is [0, 1)
                hi = float(min(1 << i, max(self.max_us, 1.0)))
                if upper:
                    return hi
                lo = float(1 << (i - 1)) if i else 0.0
                hi = min(float(1 << i), max(self.max_us, lo))
                frac = (target - acc) / c
                return lo + frac * (hi - lo)
            acc += c
        return self.max_us

    def snapshot(self) -> Dict[str, Optional[float]]:
        return {
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
            "max": self.max_us if self.count else None,
            "count": self.count,
        }


class ServingStats:
    """Cumulative serving-session telemetry.  Written by the runtime
    thread, snapshot by API/CLI threads — one lock, coarse."""

    def __init__(self):
        self._lock = threading.Lock()
        # guarded-by: _lock: submitted, admitted, shed, shed_events,
        # guarded-by: _lock: batches, verdicts, padded_rows, shapes,
        # guarded-by: _lock: packed_batches, wide_batches, h2d_bytes,
        # guarded-by: _lock: queue_wait, latency, recovery_dropped,
        # guarded-by: _lock: timeout_dropped, recovery_events,
        # guarded-by: _lock: dispatch_failures, dispatch_timeouts,
        # guarded-by: _lock: restarts, last_restart_cause,
        # guarded-by: _lock: last_restart_at
        self.started_at = time.monotonic()
        self.submitted = 0  # packets offered to the queue
        self.admitted = 0  # packets the queue accepted
        self.shed = 0  # packets shed at admission (exact)
        self.shed_events = 0  # shed rows surfaced as DROP events
        self.batches = 0
        self.verdicts = 0  # real (valid) rows dispatched
        self.padded_rows = 0  # padding rows dispatched
        self.shapes: Dict[int, int] = {}  # bucket size -> batches
        # h2d link accounting (the 16 B/packet tentpole's scoreboard):
        # batches and bytes per wire format.  Bytes are the hdr tensor
        # actually shipped (packed 16 B/row vs wide 64 B/row,
        # including padding rows — they cross the link too).
        self.packed_batches = 0
        self.wide_batches = 0
        self.h2d_bytes = 0
        # superbatch dispatch scoreboard (ISSUE 11): device DISPATCHES
        # vs batches — the amortization the K-batch scan buys is
        # batches/dispatches > 1.  Fill tracks real rows vs rows
        # shipped across superbatch dispatches (the round-down
        # assembly keeps every step a full bucket, so fill defends
        # the no-empty-steps design at 1.0).
        # guarded-by: _lock: dispatches, superbatches,
        # guarded-by: _lock: super_rows_real, super_rows_shipped,
        # guarded-by: _lock: super_shapes
        self.dispatches = 0  # device dispatches (single + super)
        self.superbatches = 0  # ...of which carried K > 1 batches
        self.super_rows_real = 0
        self.super_rows_shipped = 0
        self.super_shapes: Dict[int, int] = {}  # K -> dispatches
        self.queue_wait = LatencyHistogram()  # arrival -> dispatch
        self.latency = LatencyHistogram()  # arrival -> events emitted
        # fault-tolerance plane (serving/runtime.py watchdog): the
        # conservation law the chaos suite asserts is
        #   submitted == verdicts + shed + recovery_dropped
        # after a drained stop — every offered row is exactly one of
        # dispatched, shed (either overflow policy), or accounted by
        # recovery (dead/hung/failed dispatch, or queued rows swept at
        # a dead-loop stop).
        self.recovery_dropped = 0  # rows accounted by recovery (all)
        self.timeout_dropped = 0  # ...of which via dispatch deadline
        self.recovery_events = 0  # recovery rows surfaced as DROPs
        self.dispatch_failures = 0  # contained dispatch failures
        self.dispatch_timeouts = 0  # watchdog deadline hits
        self.restarts = 0  # drain-thread restarts
        self.last_restart_cause = ""
        self.last_restart_at: Optional[float] = None  # monotonic
        # point-in-time gauges sampled by the drain loop's idle tick
        # (queue depth, arena occupancy, in-flight window) — written
        # whole-dict by the runtime, read by the metrics registry, so
        # no lock is needed beyond the GIL's dict-swap atomicity
        self.gauges: Dict[str, float] = {}

    # -- recording (runtime thread) -----------------------------------
    def record_submit(self, offered: int, accepted: int) -> None:
        """``accepted`` is what the queue took from THIS chunk.  The
        shed counter is NOT derived from the difference — under
        drop-oldest the queue admits the whole arrival and evicts
        previously-admitted rows instead, so sheds are recorded from
        the queue's own exact accounting (:meth:`record_sheds`)."""
        with self._lock:
            self.submitted += offered
            self.admitted += accepted

    def record_sheds(self, count: int, retained: int) -> None:
        """``count`` exact sheds since the last flush (either policy);
        ``retained`` of them surfaced as DROP events (retention is
        bounded, the counter is not)."""
        with self._lock:
            self.shed += count
            self.shed_events += retained

    def record_batch(self, n_valid: int, bucket: int,
                     arrivals: List[Tuple[int, float]],
                     t_dispatch: float, packed: bool = False,
                     h2d_bytes: int = 0) -> None:
        with self._lock:
            self.batches += 1
            self.verdicts += n_valid
            self.padded_rows += bucket - n_valid
            self.shapes[bucket] = self.shapes.get(bucket, 0) + 1
            if packed:
                self.packed_batches += 1
            else:
                self.wide_batches += 1
            self.h2d_bytes += h2d_bytes
            # chunk-granular: one sample per chunk keeps the record
            # cost O(chunks), not O(packets)
            for count, t in arrivals:
                if count:
                    self.queue_wait.record((t_dispatch - t) * 1e6)

    def record_dispatch(self, batches: int, rows_real: int = 0,
                        rows_shipped: int = 0,
                        dispatches: int = 1) -> None:
        """``dispatches`` DEVICE dispatches carried ``batches`` inner
        batches (1/1 on the single-batch path; K/1 for a fused
        superbatch; K/K for a demoted superbatch retried one step at
        a time — which therefore does NOT count as a superbatch).
        ``rows_real``/``rows_shipped`` feed the fill-efficiency
        read."""
        with self._lock:
            self.dispatches += dispatches
            if batches > 1 and dispatches == 1:
                self.superbatches += 1
                self.super_rows_real += rows_real
                self.super_rows_shipped += rows_shipped
                self.super_shapes[batches] = (
                    self.super_shapes.get(batches, 0) + 1)

    def record_recovery_drops(self, count: int, timeout: bool,
                              events: int = 0) -> None:
        """``count`` rows lost to a dead/hung/failed dispatch (or the
        dead-loop stop sweep), ``events`` of them surfaced as decoded
        DROP events; ``timeout`` marks the watchdog-deadline flavor
        (REASON_DISPATCH_TIMEOUT vs REASON_RECOVERY_DROP)."""
        with self._lock:
            self.recovery_dropped += count
            self.recovery_events += events
            if timeout:
                self.timeout_dropped += count

    def record_dispatch_failure(self) -> None:
        with self._lock:
            self.dispatch_failures += 1

    def record_restart(self, cause: str, timeout: bool) -> None:
        with self._lock:
            self.restarts += 1
            self.last_restart_cause = cause[:200]
            self.last_restart_at = time.monotonic()
            if timeout:
                self.dispatch_timeouts += 1

    def record_completion(self, arrivals: List[Tuple[int, float]],
                          t_done: float) -> None:
        """End-to-end: arrival -> the batch's events emitted to the
        monitor plane (the drain boundary)."""
        with self._lock:
            for _count, t in arrivals:
                self.latency.record((t_done - t) * 1e6)

    # -- reading (API/CLI threads) ------------------------------------
    def snapshot(self, queue_pending: int = 0,
                 queue_depth: int = 0) -> dict:
        with self._lock:
            dt = max(time.monotonic() - self.started_at, 1e-9)
            pad = self.padded_rows
            real = self.verdicts
            return {
                # no "active" key: liveness is the daemon's to report
                # (a snapshot outlives the session that produced it)
                "uptime-seconds": round(dt, 3),
                "submitted": self.submitted,
                "admitted": self.admitted,
                "shed": self.shed,
                "shed-events": self.shed_events,
                # the scenario drivers' shed criterion + the
                # operator's first overload read (exact, from the
                # queue's own accounting)
                "shed-fraction": round(self.shed / self.submitted, 4)
                if self.submitted else None,
                "batches": self.batches,
                "verdicts": real,
                "padded-rows": pad,
                "pad-efficiency": round(real / (real + pad), 4)
                if (real + pad) else None,
                "batches-per-sec": round(self.batches / dt, 2),
                "verdicts-per-sec": round(real / dt),
                "batch-shapes": {str(k): v for k, v in
                                 sorted(self.shapes.items())},
                "h2d": {
                    "packed-batches": self.packed_batches,
                    "wide-batches": self.wide_batches,
                    "bytes": self.h2d_bytes,
                    # per REAL packet: padding crosses the link too,
                    # so a mostly-padded session reads honestly worse
                    "bytes-per-packet": round(self.h2d_bytes / real, 2)
                    if real else None,
                },
                # the superbatch scoreboard: batches-per-dispatch is
                # THE amortization number the K-batch scan exists for
                "dispatch": {
                    "dispatches": self.dispatches,
                    "batches-per-dispatch": round(
                        self.batches / self.dispatches, 3)
                    if self.dispatches else None,
                    "superbatches": self.superbatches,
                    "superbatch-shapes": {
                        str(k): v for k, v in
                        sorted(self.super_shapes.items())},
                    "superbatch-fill": round(
                        self.super_rows_real
                        / self.super_rows_shipped, 4)
                    if self.super_rows_shipped else None,
                },
                "queue-pending": queue_pending,
                "queue-depth": queue_depth,
                "gauges": dict(self.gauges),
                "queue-wait-us": self.queue_wait.snapshot(),
                "latency-us": self.latency.snapshot(),
                "fault-tolerance": {
                    "restarts": self.restarts,
                    "dispatch-timeouts": self.dispatch_timeouts,
                    "dispatch-failures": self.dispatch_failures,
                    "recovery-dropped": self.recovery_dropped,
                    "timeout-dropped": self.timeout_dropped,
                    "recovery-events": self.recovery_events,
                    "last-restart-cause": self.last_restart_cause,
                    "seconds-since-restart": (
                        round(time.monotonic()
                              - self.last_restart_at, 3)
                        if self.last_restart_at is not None else None),
                    # the no-silent-loss ledger: exact once the queue
                    # is drained (post-stop) — while running, rows in
                    # the queue / in flight are outside every counter
                    "accounted": (self.verdicts + self.shed
                                  + self.recovery_dropped
                                  + queue_pending),
                },
            }
