"""Degraded-mode fallback ladder: sharded -> single-chip -> wide.

Reference: upstream cilium never stops forwarding because a fancier
path broke — endpoints REGENERATE after datapath faults, kvstore
clients fail over to the next endpoint, and health state gates when
traffic returns.  The serving plane's analogue is a ladder of
dispatch modes ordered by capability:

- ``sharded``  — multi-chip flow-routed dispatch (PR 2);
- ``single``   — single-chip, packed 16 B/packet when eligible;
- ``wide``     — single-chip, wide 64 B/packet rows only (the same
  per-batch fallback shape PR 2 uses for pack-ineligible traffic,
  now pinned as a MODE).

This module is the pure STATE MACHINE (hysteresis + bookkeeping);
``Daemon`` owns the transition mechanics (ring swap, CT snapshot +
restore, loader re-placement).

INCIDENT HOOK POINT (obs/flightrec.py): every demotion is a named
``ladder-demotion`` incident — ``Daemon._serving_demote`` calls
``record_incident`` right after :meth:`FallbackLadder.demote`, so a
rung drop leaves a sysdump bundle (ladder state, recent flows, live
aggregation windows) behind.  The capture runs on a dedicated
capture thread, never the drain thread driving this state machine;
promotions are routine recovery and deliberately NOT incidents.
The other serving-plane hooks live in runtime.py (``on_restart``,
the watchdog) and eventplane.py (``on_terminal``, the join worker).

Rules:

- DEMOTE after ``demote_threshold`` CONSECUTIVE dispatch failures on
  the current rung (one success resets the streak — flapping shards
  must not walk the ladder down);
- PROMOTE one rung after ``promote_after`` consecutive healthy
  batches AND ``cooldown_s`` since the last transition (hysteresis:
  a half-healed mesh that fails again right after re-promotion burns
  a full cooldown before the next attempt);
- the FLOOR rung never demotes away — at the floor, failures are no
  longer containable and escalate to the runtime's restart budget.
"""

from __future__ import annotations

import time
from typing import List, Optional

RUNG_SHARDED = "sharded"
RUNG_SINGLE = "single"
RUNG_WIDE = "wide"
# capability order, best first
RUNG_ORDER = (RUNG_SHARDED, RUNG_SINGLE, RUNG_WIDE)


class FallbackLadder:
    """Hysteresis state machine over the rungs a serving session can
    actually run (built from its start_serving config: no mesh ->
    no ``sharded`` rung; packing disabled -> no ``single`` rung).

    Driven from the drain thread only (record_* / demote / promote);
    reads from API threads are snapshot-style (``to_dict``)."""

    def __init__(self, rungs: List[str], demote_threshold: int = 3,
                 promote_after: int = 64, cooldown_s: float = 5.0,
                 k_ladder=(1,)):
        if not rungs:
            raise ValueError("ladder needs at least one rung")
        order = [r for r in RUNG_ORDER if r in rungs]
        if len(order) != len(rungs):
            raise ValueError(f"unknown rung in {rungs!r}; rungs: "
                             f"{RUNG_ORDER}")
        self.rungs = tuple(order)
        self.rung = self.rungs[0]  # start at the best the config has
        # the superbatch K dimension (ISSUE 11): K is a RUNG PROPERTY
        # — demotion shrinks K one step before it would ever change
        # mode (a K-related fault costs amortization, not capability),
        # and the floor is the last mode at K=1.  The sharded rung
        # pins K=1 (superbatching is a single-chip dispatch shape;
        # the router re-routes per batch), so sharded sessions walk
        # the K ladder only after demoting off the mesh.  Default
        # (1,) keeps the pre-superbatch ladder byte-identical.
        kl = tuple(sorted(set(int(k) for k in k_ladder)))
        if not kl or kl[0] < 1:
            raise ValueError(f"k_ladder must be >= 1, got {k_ladder!r}")
        self.k_ladder = kl
        self.demote_threshold = int(demote_threshold)
        self.promote_after = int(promote_after)
        self.cooldown_s = float(cooldown_s)
        self._k_idx = len(self._k_options()) - 1  # best K of the rung
        self.fail_streak = 0
        self.ok_streak = 0
        self.demotions = 0
        self.promotions = 0
        self.last_change: Optional[float] = None  # monotonic
        self.last_cause = ""

    def _k_options(self):
        """The K rungs the CURRENT mode can run (sharded pins 1)."""
        return self.k_ladder if self.rung != RUNG_SHARDED else (1,)

    @property
    def k(self) -> int:
        """The superbatch K of the current (mode, K) rung."""
        return self._k_options()[self._k_idx]

    @property
    def at_floor(self) -> bool:
        return self.rung == self.rungs[-1] and self._k_idx == 0

    @property
    def degraded(self) -> bool:
        return (self.rung != self.rungs[0]
                or self._k_idx != len(self._k_options()) - 1)

    def record_failure(self, cause: str = "") -> bool:
        # thread-affinity: drain, api
        """One dispatch failure on the current rung.  Returns True
        when the threshold fired and the caller should demote NOW
        (via :meth:`demote` after performing the mode switch); at the
        floor it always returns False — escalate instead."""
        self.fail_streak += 1
        self.ok_streak = 0
        self.last_cause = cause[:200]
        return (not self.at_floor
                and self.fail_streak >= self.demote_threshold)

    def record_success(self,
                       now: Optional[float] = None) -> bool:
        # thread-affinity: drain, api
        """One healthy dispatch.  Returns True when sustained health
        plus an elapsed cooldown warrant promoting one rung."""
        self.fail_streak = 0
        self.ok_streak += 1
        if not self.degraded:
            return False
        if self.ok_streak < self.promote_after:
            return False
        if self.last_change is not None:
            if now is None:
                now = time.monotonic()
            if now - self.last_change < self.cooldown_s:
                return False
        return True

    def demote(self) -> str:
        # thread-affinity: drain, api
        """Step one (mode, K) rung down; returns the (possibly
        unchanged) mode rung.  K shrinks FIRST: only at K=1 does the
        mode itself demote — entering the next mode at ITS best K
        (the new mode's executables are fresh capability; the K tax
        re-proves itself there)."""
        assert not self.at_floor, "cannot demote past the floor"
        if self._k_idx > 0:
            self._k_idx -= 1
        else:
            i = self.rungs.index(self.rung)
            self.rung = self.rungs[i + 1]
            self._k_idx = len(self._k_options()) - 1
        self.demotions += 1
        self.fail_streak = 0
        self.ok_streak = 0
        self.last_change = time.monotonic()
        return self.rung

    def promote(self) -> str:
        # thread-affinity: drain, api
        """Step one (mode, K) rung up (the exact inverse of
        :meth:`demote`'s walk); returns the mode rung.  K grows back
        to the mode's best before the mode itself promotes, and a
        mode promotion enters the better mode at its SMALLEST K."""
        opts = self._k_options()
        if self._k_idx < len(opts) - 1:
            self._k_idx += 1
        else:
            i = self.rungs.index(self.rung)
            assert i > 0, "already at the top rung"
            self.rung = self.rungs[i - 1]
            self._k_idx = 0
        self.promotions += 1
        self.fail_streak = 0
        self.ok_streak = 0
        self.last_change = time.monotonic()
        return self.rung

    def to_dict(self) -> dict:
        return {
            "rung": self.rung,
            "rungs": list(self.rungs),
            "k": self.k,
            "k-ladder": list(self.k_ladder),
            "degraded": self.degraded,
            "demotions": self.demotions,
            "promotions": self.promotions,
            "fail-streak": self.fail_streak,
            "ok-streak": self.ok_streak,
            "demote-threshold": self.demote_threshold,
            "promote-after": self.promote_after,
            "cooldown-s": self.cooldown_s,
            "last-cause": self.last_cause,
            "seconds-since-change": (
                round(time.monotonic() - self.last_change, 3)
                if self.last_change is not None else None),
        }
