"""Bounded admission queue — the XDP ring / per-CPU ring analogue.

Reference: upstream cilium's front end admits packets into per-CPU
rings sized by ``--...-ring-size``; when producers outrun the
consumer the ring sheds and the drop is COUNTED (the metricsmap's
queue-overflow reason), never silently lost.  Same contract here:
:class:`IngressQueue` bounds admission by packet count, sheds by a
configurable policy, and retains the shed rows (bounded) so the
serving runtime can surface them as monitor DROP events with
``REASON_INGRESS_OVERFLOW``.

Packets arrive as CHUNKS of header rows (``[n, N_COLS] uint32``) —
the arrival unit of a NIC ring doorbell, not a Python object per
packet — so admission is O(chunks), and batch assembly slices numpy
views.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

# retained shed HEADERS are bounded (the counter is always exact):
# an unbounded retention buffer would turn a sustained overload into
# a host OOM — exactly the failure the bounded queue exists to stop
MAX_RETAINED_SHED_ROWS = 1 << 14


class IngressQueue:
    """Bounded FIFO of header-row chunks.

    ``policy``:
      - ``drop-tail`` (default): an arriving chunk that does not fit
        is truncated; the overflow sheds (new traffic pays).
      - ``drop-oldest``: the oldest queued rows shed to make room for
        the arrival (stale traffic pays — the wrap-overwrite ring
        semantics of the monitor plane, applied to admission).
    """

    def __init__(self, capacity: int, policy: str = "drop-tail"):
        if capacity <= 0:
            raise ValueError("ingress queue capacity must be > 0")
        if policy not in ("drop-tail", "drop-oldest"):
            raise ValueError(f"unknown overflow policy {policy!r}")
        self.capacity = int(capacity)
        self.policy = policy
        self._chunks: deque = deque()  # (rows, t_arrival, spans)
        self._pending = 0
        self.admitted = 0  # packets ever admitted
        self.shed = 0  # packets ever shed (exact)
        self._shed_rows: List[np.ndarray] = []  # bounded retention
        self._shed_retained = 0
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        # guarded-by: _lock: _chunks, _pending, admitted, shed,
        # guarded-by: _lock: _shed_rows, _shed_retained, _dequeued_spans
        # obs plane (obs/trace.py SpanTracer or None): when armed,
        # admission allocates spans for 1-in-N admitted packets; the
        # spans ride their chunk ((offset, span) tuples, offsets
        # re-based as chunks split/evict) and leave via take_into for
        # the batcher to stamp.  None = the zero-overhead default.
        self.tracer = None
        self._dequeued_spans: List[tuple] = []  # (pos_in_out, span)

    # -- producer side -------------------------------------------------
    def offer(self, rows: np.ndarray,
              t: Optional[float] = None) -> int:
        # thread-affinity: any
        """Admit a chunk; returns how many of its rows were accepted.
        Sheds (from either end, per policy) are counted and retained
        for drop-event synthesis.

        The queue COPIES what it admits (one vectorized memcpy per
        chunk — exactly a NIC ring copying the frame into ring
        memory): producers refill their chunk buffer the moment
        offer() returns, and a queued view of caller memory would
        silently dispatch the refilled bytes as the earlier
        packets."""
        rows = np.asarray(rows)
        if rows.ndim != 2:
            raise ValueError("offer() wants [n, N_COLS] header rows")
        n = len(rows)
        if n == 0:
            return 0
        if t is None:
            t = time.monotonic()
        with self._nonempty:
            room = self.capacity - self._pending
            if n <= room:
                accepted = n
            elif self.policy == "drop-tail":
                accepted = max(room, 0)
                if accepted < n:
                    self._shed(rows[accepted:])
                rows = rows[:accepted]
            else:  # drop-oldest: evict from the head until it fits
                accepted = min(n, self.capacity)
                if accepted < n:  # chunk larger than the whole queue
                    self._shed(rows[:n - accepted])
                    rows = rows[n - accepted:]
                need = accepted - room
                while need > 0 and self._chunks:
                    old, old_t, old_sp = self._chunks.popleft()
                    if len(old) <= need:
                        self._shed(old)
                        if old_sp:
                            self.tracer.evict(s for _, s in old_sp)
                        self._pending -= len(old)
                        need -= len(old)
                    else:
                        self._shed(old[:need])
                        if old_sp:
                            self.tracer.evict(
                                s for o, s in old_sp if o < need)
                            old_sp = tuple((o - need, s)
                                           for o, s in old_sp
                                           if o >= need)
                        self._chunks.appendleft((old[need:], old_t,
                                                 old_sp))
                        self._pending -= need
                        need = 0
            if accepted:
                # spans sample over the ADMITTED rows only (the shed
                # tail never enters the pipeline); the tracer's
                # admitted-seq counter advances under this lock, so
                # the sampled set is deterministic per stream
                spans = (tuple(self.tracer.sample_chunk(accepted, t))
                         if self.tracer is not None else ())
                self._chunks.append((np.array(rows, copy=True), t,
                                     spans))
                self._pending += accepted
                self.admitted += accepted
                self._nonempty.notify()
            return accepted

    def _shed(self, rows: np.ndarray) -> None:
        # holds: _lock -- only called from offer()'s locked region
        n = len(rows)
        self.shed += n
        keep = min(n, MAX_RETAINED_SHED_ROWS - self._shed_retained)
        if keep > 0:
            self._shed_rows.append(np.array(rows[:keep]))
            self._shed_retained += keep

    # -- consumer side -------------------------------------------------
    @property
    def pending(self) -> int:
        # thread-affinity: any
        with self._lock:
            return self._pending

    def row_width(self) -> Optional[int]:
        """Column count of the queued rows (None when empty) — the
        batcher sizes its staging scratch from the head chunk."""
        with self._lock:
            if not self._chunks:
                return None
            return self._chunks[0][0].shape[1]

    def oldest_age(self, now: Optional[float] = None) -> float:
        """Seconds the head-of-line chunk has waited (0 when empty)."""
        with self._lock:
            if not self._chunks:
                return 0.0
            head_t = self._chunks[0][1]
        return (now if now is not None else time.monotonic()) - head_t

    def take(self, n: int) -> Tuple[np.ndarray, List[Tuple[int, float]]]:
        # thread-affinity: drain, api
        """Dequeue up to ``n`` rows in FIFO order.

        Returns ``(rows, arrivals)`` where ``arrivals`` is a list of
        ``(count, t_arrival)`` at chunk granularity — the batcher's
        queue-wait / latency accounting input."""
        parts: List[np.ndarray] = []
        arrivals: List[Tuple[int, float]] = []
        got = 0
        with self._lock:
            while got < n and self._chunks:
                rows, t, spans = self._chunks[0]
                want = n - got
                if len(rows) <= want:
                    self._chunks.popleft()
                    parts.append(rows)
                    arrivals.append((len(rows), t))
                    got += len(rows)
                    if spans:  # take() rows bypass the span pipeline
                        self.tracer.evict(s for _, s in spans)
                else:
                    parts.append(rows[:want])
                    if spans:
                        self.tracer.evict(
                            s for o, s in spans if o < want)
                        spans = tuple((o - want, s) for o, s in spans
                                      if o >= want)
                    self._chunks[0] = (rows[want:], t, spans)
                    arrivals.append((want, t))
                    got += want
            self._pending -= got
        if not parts:
            return np.zeros((0, 0), dtype=np.uint32), arrivals
        if len(parts) == 1:
            return parts[0], arrivals
        return np.concatenate(parts), arrivals

    def take_into(self, out: np.ndarray
                  ) -> Tuple[int, List[Tuple[int, float]]]:
        # thread-affinity: drain, api
        """Dequeue up to ``len(out)`` rows in FIFO order DIRECTLY into
        ``out`` (the batcher's staging arena): one vectorized memcpy
        per chunk, no intermediate concatenate — the zero-copy half of
        batch assembly.  Returns ``(n, arrivals)``; ``out[:n]`` holds
        the rows, everything past ``n`` is untouched.

        EXCEPTION-ATOMIC: all copies land before ANY chunk is popped
        (copy first, commit after), so a memcpy fault mid-dequeue —
        the ``serving.queue.take`` injection site, or a real staging
        failure — leaves every row still queued.  A dead drain thread
        then loses nothing: its restart (or the stop-path recovery
        sweep) finds the rows where they were."""
        from ..infra import faults

        n = len(out)
        arrivals: List[Tuple[int, float]] = []
        got = 0
        with self._lock:
            # copy phase: nothing is mutated; a raise here (injected
            # or organic) aborts with the queue intact
            plan: List[int] = []
            pos = 0
            for rows, t, _spans in self._chunks:
                if pos >= n:
                    break
                faults.check(faults.SITE_QUEUE_TAKE)
                take = min(len(rows), n - pos)
                out[pos:pos + take] = rows[:take]
                arrivals.append((take, t))
                plan.append(take)
                pos += take
            # commit phase: pure pointer moves, cannot fail.  Spans
            # whose rows left stamp STAGE_DEQUEUE here (commit time:
            # an aborted copy must leave them queued) and move to the
            # dequeued list the batcher drains right after.
            t_deq = time.monotonic() if self.tracer is not None else 0.0
            for take in plan:
                rows, t, spans = self._chunks[0]
                if spans:
                    from ..obs.trace import STAGE_DEQUEUE

                    keep = []
                    for off, sp in spans:
                        if off < take:
                            sp.ts[STAGE_DEQUEUE] = t_deq
                            self._dequeued_spans.append((got + off,
                                                         sp))
                        else:
                            keep.append((off - take, sp))
                    spans = tuple(keep)
                got += take
                if take == len(rows):
                    self._chunks.popleft()
                else:
                    self._chunks[0] = (rows[take:], t, spans)
            self._pending -= got
        return got, arrivals

    def pop_dequeued_spans(self) -> List[tuple]:
        # thread-affinity: drain, api
        """Drain the ``(batch_pos, span)`` pairs the last
        :meth:`take_into` committed — the batcher attaches them to
        its :class:`~.batcher.AssembledBatch`.  Single-consumer like
        take_into itself (the drain thread)."""
        with self._lock:
            if not self._dequeued_spans:
                return []
            out, self._dequeued_spans = self._dequeued_spans, []
        return out

    def take_sheds(self) -> Tuple[Optional[np.ndarray], int]:
        # thread-affinity: drain, api
        """Drain the shed accounting accumulated since the last call:
        ``(retained header rows or None, exact shed count)``.  The
        count can exceed the row count when retention was capped."""
        with self._lock:
            rows_list, self._shed_rows = self._shed_rows, []
            count = self.shed - getattr(self, "_shed_reported", 0)
            self._shed_reported = self.shed
            self._shed_retained = 0
        if not rows_list:
            return None, count
        rows = (rows_list[0] if len(rows_list) == 1
                else np.concatenate(rows_list))
        return rows, count

    def wait_nonempty(self, timeout: float) -> bool:
        # thread-affinity: drain
        """Block until a chunk is queued (or timeout); the runtime's
        idle wait between deadline checks."""
        with self._nonempty:
            if self._pending:
                return True
            return self._nonempty.wait(timeout) or self._pending > 0
