"""The L7 proxy plane: REDIRECT as a first-class serving outcome.

Reference: upstream cilium's redirect lifecycle — the datapath verdict
says ``REDIRECT`` with a proxy port, the packet detours through the
userspace proxy (Envoy / proxylib parsers), the proxy's L7 verdict
decides the flow's fate, and DNS answers observed by the dnsproxy
mint new identities that change SUBSEQUENT datapath verdicts
(``pkg/proxy``, ``pkg/fqdn``).  This module is the serving-tier
equivalent: it sits between the event plane and the
:class:`~..proxy.worker.L7WorkerPool`.

Lifecycle of one redirected row::

    device verdict REDIRECT (datapath/verdict.py, proxy port packed
      into the ring's 4-bit listener index)
        -> event plane join (decode_ring_rows restores the REAL port)
        -> L7Plane.ingest  [event-worker thread: select + group +
                            bounded submit, never the drain thread]
        -> L7WorkerPool    [l7 threads: synthesize/parse requests via
                            the plugin registry, fused-tensor L7
                            verdict from l7policy, per-plugin parse
                            latency into the registry histograms]
        -> allowed DNS queries resolve (dns_resolver hook) and feed
           proxy.observe_answer -> fqdn.NameManager.observe -> a LIVE
           identity mint rides the TableVersioner patch path -> the
           NEXT device batch's verdict flips, mid-serving.

Rows are the ledger unit; the pool's no-silent-loss contract
(``redirected == l7_allowed + l7_denied + l7_shed + l7_failed``)
covers everything this plane ingests.

The device carries no payload bytes (headers only — the paper's
datapath is L3/L4), so the parse leg runs on the REQUEST SOURCE seam:
``request_source(port, kind, task)`` returns the payload-shaped
requests for a redirected row group.  The default source synthesizes
one deterministic request per row (exercising the full parse +
verdict machinery); tests and embedders install real sources (e.g.
the DNS proxy's captured queries) through
``Daemon.l7_request_source``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ..policy.mapstate import VERDICT_REDIRECT
from ..proxy import registry as l7registry
from ..proxy.worker import (
    DEFAULT_L7_QUEUE,
    DEFAULT_L7_WORKERS,
    L7Task,
    L7WorkerPool,
)

# listener-kind dispatch preference when a port carries several rule
# families (upstream: one Envoy listener per parser type; here one
# port can in principle compile mixed rows)
_KIND_ORDER = ("http", "dns", "kafka")


def _default_request_source(port: int, kind: str, task: L7Task):
    """One deterministic synthetic request per redirected row — the
    parse + verdict machinery runs for real; the verdicts reflect the
    port's actual rules against the synthetic shape."""
    n = task.rows
    if kind == "dns":
        return [f"row{i}.synthesized.internal" for i in range(n)]
    if kind == "kafka":
        return [{"api_key": "fetch", "topic": "synthesized"}
                for _ in range(n)]
    return [{"method": "GET", "path": "/", "host": ""}
            for _ in range(n)]


class L7Plane:
    """Owns the worker pool and the redirect fan-out/handling logic.

    ``ingest(batch)`` runs on the event-join worker; everything
    downstream runs on the pool's ``l7`` threads."""

    def __init__(self, proxy,
                 workers: int = DEFAULT_L7_WORKERS,
                 queue_depth: int = DEFAULT_L7_QUEUE,
                 restart_budget: int = 3,
                 on_terminal: Optional[Callable[[str], None]] = None,
                 request_source: Optional[Callable] = None,
                 dns_resolver: Optional[Callable[[str], Tuple]] = None):
        self.proxy = proxy
        self.request_source = request_source or _default_request_source
        # dns_resolver(qname) -> (ips, ttl) | None: the answer leg for
        # ALLOWED dns queries; answers feed proxy.observe_answer ->
        # fqdn identity mints (live TableVersioner patches)
        self.dns_resolver = dns_resolver
        self.pool = L7WorkerPool(
            self._handle, workers=workers, queue_depth=queue_depth,
            restart_budget=restart_budget, on_terminal=on_terminal)
        self._lock = threading.Lock()
        # guarded-by: _lock: batches_ingested, dns_answers,
        # guarded-by: _lock: dns_resolve_errors
        self.batches_ingested = 0
        self.dns_answers = 0
        self.dns_resolve_errors = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        # thread-affinity: api
        self.pool.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> dict:
        # thread-affinity: api
        self.pool.stop(drain=drain, timeout=timeout)
        return self.stats()

    # -- producer side (the event-join worker) -------------------------
    def ingest(self, batch) -> int:
        # thread-affinity: event-worker
        """Fan one decoded :class:`~..monitor.api.EventBatch`'s
        redirect rows into the pool, grouped by (proxy_port, source
        identity) so every task reaches the L7 verdict with one
        homogeneous ``src_row``.  Returns rows ingested.  Never
        blocks: the pool's submit is bounded + counted."""
        if len(batch) == 0:
            return 0
        sel = (np.asarray(batch.verdict) == VERDICT_REDIRECT) \
            & (np.asarray(batch.proxy_port) > 0)
        n = int(np.count_nonzero(sel))
        if n == 0:
            return 0
        ports = np.asarray(batch.proxy_port)[sel].astype(np.uint64)
        idents = np.asarray(batch.identity)[sel].astype(np.uint64)
        keys = (ports << np.uint64(32)) | idents
        uniq, inverse = np.unique(keys, return_inverse=True)
        for g, key in enumerate(uniq):
            rows = int(np.count_nonzero(inverse == g))
            self.pool.submit(L7Task(
                port=int(key >> np.uint64(32)),
                rows=rows,
                identities=int(key & np.uint64(0xFFFFFFFF))))
        with self._lock:
            self.batches_ingested += 1
        return n

    # -- the handling leg (l7 workers) ---------------------------------
    def _kind_of(self, port: int) -> str:
        # thread-affinity: l7
        """The port's dominant rule family — upstream's parser-type
        selection at listener creation, done per task here because
        policy can re-compile the listener set mid-serving."""
        for li in self.proxy.listeners():
            if li.get("proxy-port") != port:
                continue
            best, best_n = "http", 0
            plugin_kinds = tuple(k for k in l7registry.names()
                                 if k not in _KIND_ORDER)
            for kind in _KIND_ORDER + plugin_kinds:
                c = int(li.get(f"{kind}-rules", 0) or 0)
                if c > best_n:
                    best, best_n = kind, c
            return best
        return "http"

    def _handle(self, task: L7Task) -> Tuple[int, int]:
        # thread-affinity: l7
        """Parse + verdict one redirected row group; returns
        (allowed, denied) row counts for the pool's ledger."""
        kind = self._kind_of(task.port)
        requests = self.request_source(task.port, kind, task)
        src_row = int(task.identities or 0)
        t0 = time.perf_counter()
        if kind == "dns":
            verdicts = self.proxy.handle_dns(task.port, requests,
                                             src_row=src_row)
        elif kind == "kafka":
            verdicts = self.proxy.handle_kafka(task.port, requests,
                                               src_row=src_row)
        elif kind == "http":
            verdicts = self.proxy.handle_http(task.port, requests,
                                              src_row=src_row)
        else:
            verdicts = self.proxy.handle(kind, task.port, requests,
                                         src_row=src_row)
        l7registry.observe_parse(
            kind, (time.perf_counter() - t0) * 1e6)
        v = np.asarray(verdicts)
        allowed = int(np.count_nonzero(v))
        denied = int(v.size) - allowed
        if kind == "dns" and allowed and self.dns_resolver is not None:
            self._resolve_allowed(task.port, requests, v)
        return allowed, denied

    def _resolve_allowed(self, port: int, qnames, verdicts) -> None:
        # thread-affinity: l7
        """The DNS answer leg: resolve each allowed query and feed the
        answer into the live FQDN pipeline.  Resolver failures are
        counted, never fatal — the verdict already landed."""
        for q, v in zip(qnames, verdicts):
            if not v:
                continue
            try:
                ans = self.dns_resolver(str(q))
                if not ans:
                    continue
                ips, ttl = ans
                if ips:
                    self.proxy.observe_answer(str(q), list(ips),
                                              ttl=int(ttl))
                    with self._lock:
                        self.dns_answers += 1
            except Exception:  # noqa: BLE001 — contained: an answer
                # that fails to mint must not fail the verdict ledger
                with self._lock:
                    self.dns_resolve_errors += 1

    # -- reading (API/CLI threads) -------------------------------------
    def stats(self) -> Dict[str, object]:
        # thread-affinity: any
        out = self.pool.stats()
        with self._lock:
            out["batches-ingested"] = self.batches_ingested
            out["dns-answers"] = self.dns_answers
            out["dns-resolve-errors"] = self.dns_resolve_errors
        out["parse-latency-by-plugin"] = l7registry.latency_snapshot()
        return out
