"""The off-hot-path event plane: async window join, bounded.

Reference: upstream cilium never pays for its monitor plane in the
packet path — the kernel appends to the perf ring and
``pkg/monitor/agent`` drains it from userspace at its own cadence.
Before this module our serving loop violated that separation: every
``drain_every``-th dispatch, the DRAIN THREAD blocked on a
full-capacity d2h copy plus host-side decode / wide-column
reconstruction / monitor fan-out before the next batch could
dispatch.  Now the drain thread's only event work is ``swap_window``
(block on the 8-byte cursor, start the async — occupancy-bounded —
copy) and one bounded-queue push; THIS worker completes the
transfer, decodes, joins packed rows back to wide columns, and emits
to monitor/hubble consumers.

Loss discipline (the no-silent-loss contract, applied to the event
plane's own machinery):

- bounded-queue OVERFLOW drops the OLDEST queued window, counted
  (``windows-dropped`` / ``events-dropped``), never silently — the
  freshest telemetry survives a stall, and the stalest arena-slot
  references (the ones closest to recycling) release first;
- a window whose join starts only after the producer's arena may
  have recycled its record slots is refused and counted (the
  ``seq``/join-horizon check in ``Daemon._event_join``) — stale
  windows become counted loss, never silently-corrupt events;
- a window whose join RAISES is dropped and counted — the worker
  lives on (the contained-failure shape the dispatch ladder uses);
- worker DEATH (an exception outside the per-window containment,
  e.g. the ``eventplane.join`` fault site) restarts the thread under
  a restart budget — the drain-loop watchdog pattern; terminal once
  exhausted, with every queued window swept as a counted drop;
- ``stop(drain=True)`` processes everything queued before returning,
  so ``submitted == joined + dropped`` holds exactly afterwards.

The packet ledger (``submitted == verdicts + shed +
recovery_dropped``) is untouched by any of this: verdicts are
recorded at dispatch, and event-plane loss is monitor-plane loss —
counted in its own ledger, surfaced through serving stats /
``GET /serving`` / the metrics registry.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..infra import faults
from .stats import LatencyHistogram

# how long the worker sleeps between queue polls while idle; also
# bounds how fast stop()/death detection propagate
_IDLE_WAIT_S = 0.05
DEFAULT_WINDOW_QUEUE = 4


class DrainWindow:
    """One drain window in flight between the serving drain thread
    and the event-join worker: the :class:`~..monitor.ring.RingWindow`
    transfer handle plus the host-side join context captured at swap
    time — the batch records (header arena slots, numerics snapshots)
    and the sampled trace spans of every batch whose events this
    window holds.

    Capturing the records AT SWAP (a dict handoff, zero copy) is what
    extends the arena recycling horizon cleanly: the drain thread
    forgets the window, the snapshot keeps the references, and
    ``Daemon.start_serving`` sizes the arena depth to cover every
    window the bounded queue can hold."""

    __slots__ = ("ring", "records", "spans", "n_shards", "tracer",
                 "t_swap", "seq")

    def __init__(self, ring, records: dict, spans: dict,
                 n_shards: int, tracer=None, seq=None):
        self.ring = ring
        self.records = records  # bid -> (kind, hdr, meta, numerics, ts)
        self.spans = spans  # bid -> tuple[TraceSpan]
        self.n_shards = n_shards
        self.tracer = tracer
        self.t_swap = ring.t_swap
        # producer's batch seq at swap: the join leg compares it
        # against the live seq to refuse joins whose arena-slot
        # references may have been recycled (see Daemon._event_join)
        self.seq = seq

    @property
    def appended(self) -> int:
        return self.ring.appended

    @property
    def lost(self) -> int:
        return self.ring.lost

    @property
    def d2h_bytes(self) -> int:
        return self.ring.d2h_bytes


class EventJoinWorker:
    """The dedicated join thread: pops :class:`DrainWindow` handles
    off a bounded queue and runs ``join_fn(window)`` (the daemon's
    fetch + decode + wide-column join + monitor emit leg) off the
    dispatch path.  ``drop_fn(window)``, when given, runs for every
    window the plane LOSES (overflow, contained join failure, death,
    stop sweep) so the owner can evict the window's trace spans."""

    def __init__(self, join_fn: Callable, drop_fn: Optional[Callable]
                 = None, queue_depth: int = DEFAULT_WINDOW_QUEUE,
                 restart_budget: int = 3,
                 on_terminal: Optional[Callable[[str], None]] = None):
        self._join_fn = join_fn
        self._drop_fn = drop_fn
        # INCIDENT HOOK POINT (obs/flightrec.py): on_terminal(error)
        # fires once, from the dying worker thread, when the restart
        # budget exhausts — the daemon wires it to the flight
        # recorder (a terminal event worker means the monitor plane
        # went dark, which is exactly when an operator wants a
        # state bundle).  Contained: a failing hook must not mask
        # the terminal error it reports
        self._on_terminal = on_terminal
        self.queue_depth = max(1, int(queue_depth))
        self._budget = max(0, int(restart_budget))
        self._cv = threading.Condition()
        # guarded-by: _cv: _q, _current, _stop, error, restarts,
        # guarded-by: _cv: windows_submitted, windows_joined,
        # guarded-by: _cv: windows_dropped, overflows, events_joined,
        # guarded-by: _cv: events_dropped, ring_lost, d2h_bytes,
        # guarded-by: _cv: join_lag, last_drop_cause
        self._q: list = []
        self._current: Optional[DrainWindow] = None
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[str] = None  # terminal fault
        # the event-plane ledger: submitted == joined + dropped once
        # pending reaches 0 (post-stop it always does)
        self.windows_submitted = 0
        self.windows_joined = 0
        self.windows_dropped = 0
        self.overflows = 0  # ...of the dropped, at the bounded queue
        self.events_joined = 0
        self.events_dropped = 0
        self.ring_lost = 0  # lap loss summed over windows (either way)
        self.d2h_bytes = 0
        self.restarts = 0
        self.join_lag = LatencyHistogram()  # swap -> emitted, µs
        self.last_drop_cause = ""

    # -- producer side (the serving drain thread) ----------------------
    def submit(self, window: DrainWindow) -> bool:
        # thread-affinity: any
        """Offer one window; never blocks.  A full queue drops the
        OLDEST queued window (counted) to admit the new one — the
        drop-oldest discipline the monitor queues use, so a stalled
        plane keeps the freshest telemetry AND releases the stalest
        arena references first.  A terminal/stopped worker drops the
        offered window instead.  Returns False when the offered
        window itself was dropped."""
        victim = drop_cause = None
        with self._cv:
            self.windows_submitted += 1
            # the bytes crossed the link at swap regardless of what
            # happens to the window now
            self.d2h_bytes += window.d2h_bytes
            if self.error is not None:
                drop_cause = "worker terminal"
            elif self._stop:
                drop_cause = "worker stopped"
            else:
                if len(self._q) >= self.queue_depth:
                    self.overflows += 1
                    victim = self._q.pop(0)
                self._q.append(window)
                self._cv.notify()
        if victim is not None:
            self._drop(victim, "window queue full")
            return True
        if drop_cause is not None:
            self._drop(window, drop_cause)
            return False
        return True

    @property
    def pending(self) -> int:
        # thread-affinity: any
        with self._cv:
            return len(self._q) + (1 if self._current is not None
                                   else 0)

    def _stopping(self) -> bool:
        """Locked read of the stop-and-drained predicate (the fault
        site's abort hook — the bare lambda read violated the
        guarded-by contract)."""
        with self._cv:
            return self._stop and not self._q

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        # thread-affinity: api
        assert self._thread is None, "worker already started"
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-eventjoin")
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> dict:
        # thread-affinity: api
        """Stop the worker.  With ``drain`` (default) every queued
        window is joined first — the ``stop_serving`` contract; the
        sweep below only fires for a dead/terminal worker or a
        timeout, and it COUNTS what it sweeps."""
        with self._cv:
            self._stop = True
            if not drain:
                swept, self._q = self._q, []
            self._cv.notify_all()
        if not drain:
            for w in swept:
                self._drop(w, "stopped without drain")
        deadline = time.monotonic() + timeout
        t = self._thread
        while (t is not None and t.is_alive()
               and time.monotonic() < deadline):
            t.join(timeout=0.1)
            t = self._thread  # follow restart-spawned successors
        with self._cv:
            swept, self._q = self._q, []
            # claim the in-flight window too: a join hung past the
            # timeout must still land in the ledger (submitted ==
            # joined + dropped is the post-stop contract).  Claiming
            # it here transfers ownership — if the wedged join_fn
            # eventually returns, _run_body sees it lost the claim
            # and does NOT also count the window joined.
            cur, self._current = self._current, None
            # the terminal error is read under the SAME lock that
            # writes it (the bare `self.error or ...` read below the
            # block raced a dying worker's write)
            sweep_cause = self.error or "worker did not drain in time"
        for w in swept:
            self._drop(w, sweep_cause)
        if cur is not None:
            self._drop(cur, "join hung past stop timeout")
        return self.stats()

    # -- the worker thread ---------------------------------------------
    def _run(self) -> None:
        # thread-affinity: event-worker
        try:
            self._run_body()
        except BaseException as e:  # noqa: BLE001 — death path: the
            # window being joined is a counted loss, and the thread
            # restarts under the budget (the drain-loop watchdog
            # discipline applied to the join plane).  Claim under the
            # lock — stop()'s timeout sweep may have taken it already.
            with self._cv:
                cur, self._current = self._current, None
            if cur is not None:
                self._drop(cur, f"worker died: {e}")
            went_terminal = fire = False
            err = None
            with self._cv:
                if self._stop or self.restarts >= self._budget:
                    went_terminal = True
                    # a worker dying DURING stop() is the sweep's
                    # business, not an incident
                    fire = not self._stop
                    err = self.error = (
                        f"event-join worker died ({type(e).__name__}: "
                        f"{e}); restart budget "
                        f"{self.restarts}/{self._budget} exhausted")
                    self._cv.notify_all()
                else:
                    self.restarts += 1
                    n = self.restarts
            if went_terminal:
                if fire and self._on_terminal is not None:
                    try:  # outside the lock: the hook may read
                        # stats(), so hand it the captured error
                        self._on_terminal(err)
                    except Exception:  # noqa: BLE001
                        pass
                return
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"serving-eventjoin-r{n}")
            self._thread = t
            t.start()

    def _run_body(self) -> None:
        # thread-affinity: event-worker
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(_IDLE_WAIT_S)
                if self._q:
                    window = self._q.pop(0)
                    self._current = window
                else:  # stopped AND drained
                    return
            # the injection site: a raise here kills the worker
            # (restart-on-death); a ~S hang stalls the plane so the
            # bounded queue's overflow accounting can be proven
            faults.check(faults.SITE_EVENT_JOIN, abort=self._stopping)
            try:
                self._join_fn(window)
            except Exception as e:  # noqa: BLE001 — contained: one
                # window lost (counted), the plane lives on
                with self._cv:
                    owned = self._current is window
                    self._current = None
                if owned:
                    self._drop(window, f"join failed: "
                                       f"{type(e).__name__}: {e}")
                continue
            with self._cv:
                if self._current is not window:
                    # stop()'s timeout sweep claimed this window and
                    # already counted it dropped while the join hung
                    # — never double-count it
                    continue
                self._current = None
                self.windows_joined += 1
                self.events_joined += window.appended - window.lost
                self.ring_lost += window.lost
                self.join_lag.record(
                    (time.monotonic() - window.t_swap) * 1e6)
                self._cv.notify_all()

    def _drop(self, window: DrainWindow, cause: str) -> None:
        # thread-affinity: any
        with self._cv:
            self.windows_dropped += 1
            self.events_dropped += window.appended - window.lost
            self.ring_lost += window.lost
            self.last_drop_cause = (cause or "")[:200]
            self._cv.notify_all()
        if self._drop_fn is not None:
            try:
                self._drop_fn(window)
            except Exception:  # noqa: BLE001 — loss accounting must
                pass  # never cascade

    # -- reading (API/CLI threads) -------------------------------------
    def stats(self) -> Dict[str, object]:
        # thread-affinity: any
        with self._cv:
            out = {
                "queue-depth": self.queue_depth,
                "windows-pending": (len(self._q)
                                    + (1 if self._current is not None
                                       else 0)),
                "windows-submitted": self.windows_submitted,
                "windows-joined": self.windows_joined,
                "windows-dropped": self.windows_dropped,
                "queue-overflows": self.overflows,
                "events-joined": self.events_joined,
                "events-dropped": self.events_dropped,
                "ring-lost": self.ring_lost,
                "d2h-bytes": self.d2h_bytes,
                "d2h-bytes-per-event": (
                    round(self.d2h_bytes
                          / (self.events_joined + self.events_dropped),
                          2)
                    if (self.events_joined + self.events_dropped)
                    else None),
                "worker-restarts": self.restarts,
                "join-lag-us": self.join_lag.snapshot(),
            }
            if self.last_drop_cause:
                out["last-drop-cause"] = self.last_drop_cause
            if self.error is not None:
                out["error"] = self.error
            return out
