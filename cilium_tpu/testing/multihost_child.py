"""Multi-process (jax.distributed) sharded-datapath child.

Run as ``python -m cilium_tpu.testing.multihost_child <coordinator>
<num_processes> <process_id> <devices_per_process>``: joins the
distributed runtime on the CPU backend, builds the GLOBAL 1-D mesh
over every process's virtual devices, and runs one step of the full
sharded datapath (batch sharded, CT sharded, tables replicated,
counters psum-replicated over ICI/DCN — here the TCP transport
jax.distributed provides).

This is the ClusterMesh/multi-host axis of SURVEY.md §2c validated
without multi-host hardware: 2 processes x 4 virtual devices = the
same program a 2-host x 4-chip pod slice runs.
"""

import json
import os
import sys


def main() -> None:
    coordinator, n_proc, pid, dev_per_proc = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]))
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=n_proc, process_id=pid)
    import jax.numpy as jnp
    import numpy as np

    from cilium_tpu.parallel import (
        make_mesh,
        make_sharded_step,
        route_by_flow,
        shard_state,
    )
    from cilium_tpu.testing.fixtures import build_world, bench_traffic

    n_devices = n_proc * dev_per_proc
    assert len(jax.devices()) == n_devices, (
        len(jax.devices()), n_devices)
    # identical world on every process (deterministic build): the
    # replicated tables agree byte-for-byte, like kvstore-synced agents
    world = build_world(n_identities=64, n_rules=8,
                        ct_capacity=(1 << 10) * n_devices,
                        ct_shards=n_devices)
    mesh = make_mesh(n_devices)
    state = shard_state(world.state, mesh)
    step = make_sharded_step(mesh)

    rng = np.random.default_rng(7)  # same seed everywhere
    batch = bench_traffic(world, 32 * n_devices, rng)
    routed, valid, _, ovf = route_by_flow(batch, n_devices)
    out, state = step(state, jnp.asarray(routed), jnp.uint32(10),
                      jnp.asarray(valid))
    out.block_until_ready()
    # metrics are psum-replicated: every process sees the GLOBAL count
    metrics = np.asarray(state.metrics)
    print(json.dumps({
        "process": pid,
        "n_devices": n_devices,
        "forwarded": int(metrics[0].sum()),
        "dropped": int(metrics[1:].sum()),
        "overflow": ovf,
    }))


if __name__ == "__main__":
    main()
