"""Fault injection for the distributed control plane.

Reference: upstream cilium's kvstore layer is exercised against etcd
failures (connection loss, partitions, ambiguous commits); agents are
expected to retry with backoff and converge.  :class:`ChaosKVStore`
wraps any kvstore-like object and injects those failure classes
deterministically (seeded):

- **transient errors**: an op raises ``ConnectionError`` with
  probability ``fail_rate``;
- **ambiguous commits**: half of injected MUTATION failures apply the
  op BEFORE raising — the caller cannot tell (exactly etcd's
  commit-then-timeout case), so protocols must be re-entrant;
- **partitions**: while ``partition()`` is active every op fails;
- **watch lag**: events deliver after ``watch_delay`` seconds.

The invariants the fault suite asserts (tests/test_fault_injection.py)
are the reference's: no duplicate identity numerics for one label set,
no lost allocations after heal, replicas converge.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

_MUTATORS = ("update", "create_only", "delete", "delete_if",
             "keepalive")
_READERS = ("get", "list_prefix")


class ChaosKVStore:
    """A kvstore proxy that injects seeded faults (see module doc)."""

    def __init__(self, inner, fail_rate: float = 0.0, seed: int = 0,
                 watch_delay: float = 0.0):
        self._inner = inner
        self.fail_rate = fail_rate
        self.watch_delay = watch_delay
        self._rng = np.random.default_rng(seed)
        self._partitioned = threading.Event()
        self._lock = threading.Lock()
        self.injected = 0  # faults raised
        self.ambiguous = 0  # …of which applied before raising

    # -- fault controls ------------------------------------------------
    def partition(self, active: bool = True) -> None:
        if active:
            self._partitioned.set()
        else:
            self._partitioned.clear()

    def _maybe_fail(self) -> bool:
        """-> True when this op should raise; thread-safe draw."""
        if self._partitioned.is_set():
            return True
        if self.fail_rate <= 0:
            return False
        with self._lock:
            return bool(self._rng.random() < self.fail_rate)

    def _flip(self) -> bool:
        with self._lock:
            return bool(self._rng.random() < 0.5)

    # -- op wrappers ---------------------------------------------------
    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name in _MUTATORS:
            def wrapped(*a, **kw):
                if self._maybe_fail():
                    self.injected += 1
                    if not self._partitioned.is_set() and self._flip():
                        # ambiguous commit: applied, then "timed out"
                        self.ambiguous += 1
                        attr(*a, **kw)
                    raise ConnectionError(
                        f"injected kvstore fault on {name}")
                return attr(*a, **kw)

            return wrapped
        if name in _READERS:
            def wrapped(*a, **kw):
                if self._maybe_fail():
                    self.injected += 1
                    raise ConnectionError(
                        f"injected kvstore fault on {name}")
                return attr(*a, **kw)

            return wrapped
        if name == "watch_prefix" and self.watch_delay > 0:
            delay = self.watch_delay

            def wrapped(prefix, fn, *a, **kw):
                def lagged(ev):
                    time.sleep(delay)
                    fn(ev)

                return attr(prefix, lagged, *a, **kw)

            return wrapped
        return attr


def retry(fn: Callable, attempts: int = 12,
          backoff: float = 0.0, swallow=ConnectionError):
    """The agent-controller retry shape: re-run ``fn`` through
    transient faults; raises the last error when attempts exhaust."""
    last: Optional[BaseException] = None
    for i in range(attempts):
        try:
            return fn()
        except swallow as e:  # noqa: PERF203
            last = e
            if backoff:
                time.sleep(backoff * (i + 1))
    raise last
