"""Oracle datapath: sequential pure-Python reference semantics.

Reference: the eBPF behavior of ``bpf/bpf_lxc.c`` + ``bpf/lib`` as
described in SURVEY.md §3.2, implemented with plain dicts so the TPU
datapath can be checked packet-for-packet (the divergence gate is 0%
in-tree; BASELINE.md allows <=1%).

Batch semantics match the device: lookups see the state as of batch
start (snapshot), then updates apply — the device is data-parallel
within a batch, so the oracle must not let packet i's CT insert be
visible to packet i+1 of the same batch.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP0,
    COL_EP,
    COL_FAMILY,
    COL_FLAGS,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP0,
    FLAG_RELATED,
    TCP_FIN,
    TCP_RST,
    HeaderBatch,
    words_to_ip,
)
from ..datapath.conntrack import (
    CT_ESTABLISHED,
    CT_NEW,
    CT_RELATED,
    CT_REPLY,
    LIFETIME_CLOSE,
    LIFETIME_NONTCP,
    LIFETIME_SYN,
    LIFETIME_TCP,
)
from ..datapath.verdict import (
    EV_DROP,
    EV_TRACE,
    EV_VERDICT,
    REASON_FORWARDED,
    REASON_NO_ENDPOINT,
    REASON_POLICY_DEFAULT_DENY,
    REASON_POLICY_DENY,
)
from ..policy.mapstate import (
    VERDICT_ALLOW,
    VERDICT_DENY,
    VERDICT_REDIRECT,
)
from ..policy.compiler import make_proto_table
from ..policy.resolve import EndpointPolicy


@dataclass
class _CTEntry:
    state: int  # ST_* from conntrack
    expires: int
    proxy: int


@dataclass
class OracleResult:
    verdict: int
    proxy: int
    ct: int
    identity: int  # remote numeric identity
    reason: int
    event: int


class OracleDatapath:
    """Sequential reference of the full verdict pipeline."""

    def __init__(self, ep_policies: Dict[int, EndpointPolicy],
                 ipcache: Dict[str, int]):
        self.ep_policies = ep_policies
        # mutual-auth grants: (subject labels key, remote numeric
        # identity) -> expires (the authmap; see Loader.auth_upsert)
        self.auth: Dict[Tuple[str, int], int] = {}
        self.ipcache: List[Tuple[int, int, int, int]] = []  # ver, net, plen, id
        # host-route fast path: /32 (v4) and /128 (v6) are the longest
        # possible prefixes, so an exact hit always wins LPM — keeps the
        # oracle usable at the 10k-identity scale without changing
        # longest-prefix-match semantics
        self._exact: Dict[Tuple[int, int], int] = {}
        for cidr, ident in ipcache.items():
            net = ipaddress.ip_network(cidr, strict=False)
            host_bits = 32 if net.version == 4 else 128
            if net.prefixlen == host_bits:
                self._exact[(net.version,
                             int(net.network_address))] = ident
            else:
                self.ipcache.append((net.version,
                                     int(net.network_address),
                                     net.prefixlen, ident))
        self._lpm_memo: Dict[str, int] = {}
        self.ct: Dict[tuple, _CTEntry] = {}
        self.proto_table = make_proto_table()

    def lookup_identity(self, ip: str) -> int:
        cached = self._lpm_memo.get(ip)
        if cached is not None:
            return cached
        addr = ipaddress.ip_address(ip)
        n = int(addr)
        exact = self._exact.get((addr.version, n))
        if exact is not None:
            self._lpm_memo[ip] = exact
            return exact
        bits = 32 if addr.version == 4 else 128
        best_len, best_id = -1, 0
        for ver, net, plen, ident in self.ipcache:
            if ver != addr.version:
                continue
            shift = bits - plen
            if plen == 0 or (n >> shift) == (net >> shift):
                if plen > best_len:
                    best_len, best_id = plen, ident
        self._lpm_memo[ip] = best_id
        return best_id

    @staticmethod
    def _tuple(row: np.ndarray) -> tuple:
        proto = int(row[COL_PROTO])
        icmp = proto in (1, 58)
        sport = 0 if icmp else int(row[COL_SPORT])
        dport = 0 if icmp else int(row[COL_DPORT])
        src = tuple(int(x) for x in row[COL_SRC_IP0:COL_SRC_IP0 + 4])
        dst = tuple(int(x) for x in row[COL_DST_IP0:COL_DST_IP0 + 4])
        return (src, dst, sport, dport, proto, int(row[COL_DIR]))

    @staticmethod
    def _rev(t: tuple) -> tuple:
        # reply: swap tuple AND hook direction (ipv4_ct_tuple_reverse)
        return (t[1], t[0], t[3], t[2], t[4], 1 - t[5])

    def step(self, batch: HeaderBatch, now: int,
             pre_drop=None,
             pre_drop_reason=None,
             lb_drop=None, audit=False) -> List[OracleResult]:
        """``pre_drop`` ([N] bool) marks rows the SNAT stage condemned
        (pool exhaustion).  Policy/lxcmap drops keep precedence
        (upstream order: bpf_lxc judges before host SNAT); rows that
        would otherwise forward drop with REASON_NAT_EXHAUSTED and
        neither create nor refresh CT.  ``pre_drop_reason`` ([N]
        uint32, 0 = none) is the generalized per-row form (bandwidth
        manager), same precedence and CT semantics.  ``lb_drop``
        ([N] bool) is the PRE-policy LB no-backend drop
        (REASON_NO_SERVICE): upstream's LB lookup runs before the
        endpoint program, so it wins over policy AND the lxcmap
        gate, and touches no CT state."""
        from ..datapath.verdict import (REASON_AUTH_REQUIRED,
                                        REASON_NAT_EXHAUSTED,
                                        REASON_NO_SERVICE)

        results: List[OracleResult] = []
        updates: List[Tuple[tuple, np.ndarray, bool, int, int]] = []
        # phase 1: lookups against the batch-start snapshot
        for i in range(len(batch)):
            row = batch.data[i]
            dirn = int(row[COL_DIR])
            fam = int(row[COL_FAMILY])
            remote_words = (row[COL_SRC_IP0:COL_SRC_IP0 + 4] if dirn == 0
                            else row[COL_DST_IP0:COL_DST_IP0 + 4])
            ident = self.lookup_identity(words_to_ip(remote_words, fam))

            fwd = self._tuple(row)
            entry = self.ct.get(fwd)
            is_reply = False
            related = bool(int(row[COL_FLAGS]) & FLAG_RELATED)
            if related:
                # ICMP error carrying the embedded original tuple:
                # probe that tuple under BOTH hook directions (the
                # datapath's related rev-key flips only the dir bit)
                if entry is None or entry.expires < now:
                    entry = self.ct.get(fwd[:5] + (1 - fwd[5],))
                if entry is not None and entry.expires >= now:
                    ct_res = CT_RELATED
                else:
                    ct_res, entry = CT_NEW, None
            elif entry is not None and entry.expires >= now:
                ct_res = CT_ESTABLISHED
            else:
                rentry = self.ct.get(self._rev(fwd))
                if rentry is not None and rentry.expires >= now:
                    ct_res, is_reply, entry = CT_REPLY, True, rentry
                else:
                    ct_res, entry = CT_NEW, None

            if lb_drop is not None and bool(lb_drop[i]):
                # LB ran before policy (bpf/lib/lb.h): a frontend hit
                # with no backend drops NO_SERVICE regardless of the
                # policy/lxcmap verdict, creating/refreshing nothing
                results.append(OracleResult(
                    VERDICT_DENY, 0, ct_res, ident,
                    REASON_NO_SERVICE, EV_DROP))
                updates.append((fwd, row, is_reply, CT_NEW, 0, False,
                                related))
                continue
            pol = self.ep_policies.get(int(row[COL_EP]))
            if pol is None:
                # lxcmap miss: unregistered endpoint -> drop, CT
                # untouched (reference: bpf_lxc endpoint lookup
                # failure), even for packets matching a live CT entry
                results.append(OracleResult(
                    VERDICT_DENY, 0, ct_res, ident,
                    REASON_NO_ENDPOINT, EV_DROP))
                updates.append((fwd, row, is_reply, CT_NEW, 0, False,
                                related))
                continue
            proto_idx = int(self.proto_table[int(row[COL_PROTO])])
            p_verdict, p_proxy, p_auth = pol.lookup_full(
                dirn, ident, proto_idx, int(row[COL_DPORT]))
            if ct_res != CT_NEW:
                # a related ICMP error is forwarded, never redirected
                proxy = 0 if ct_res == CT_RELATED else entry.proxy
                verdict = VERDICT_REDIRECT if proxy > 0 else VERDICT_ALLOW
                reason = REASON_FORWARDED
                event = EV_TRACE
            elif p_verdict in (VERDICT_ALLOW, VERDICT_REDIRECT) and (
                    p_auth and self.auth.get(
                        (pol.subject_labels.sorted_key(), ident),
                        0) <= now):
                # policy allows but mutual auth is missing/expired:
                # drop AUTH_REQUIRED, touch nothing (pkg/auth)
                proxy = 0
                verdict = VERDICT_DENY
                reason = REASON_AUTH_REQUIRED
                event = EV_DROP
            elif p_verdict in (VERDICT_ALLOW, VERDICT_REDIRECT):
                proxy = p_proxy if p_verdict == VERDICT_REDIRECT else 0
                verdict = p_verdict
                reason = REASON_FORWARDED
                event = EV_VERDICT
            else:
                proxy = 0
                verdict = p_verdict
                reason = (REASON_POLICY_DENY if p_verdict == VERDICT_DENY
                          else REASON_POLICY_DEFAULT_DENY)
                event = EV_DROP
            # audit first: a row the policy stage would deny is
            # forwarded UNLESS a later stage (NAT exhaustion,
            # bandwidth) really drops it — those stages act on the
            # post-audit allowed set, mirroring the device
            audit_fwd = (audit and ct_res == CT_NEW
                         and reason in (REASON_POLICY_DENY,
                                        REASON_POLICY_DEFAULT_DENY,
                                        REASON_AUTH_REQUIRED))
            if (pre_drop is not None and bool(pre_drop[i])
                    and (reason == REASON_FORWARDED or audit_fwd)):
                verdict, proxy = VERDICT_DENY, 0
                reason, event = REASON_NAT_EXHAUSTED, EV_DROP
                audit_fwd = False
            if (pre_drop_reason is not None
                    and int(pre_drop_reason[i]) != 0
                    and (reason == REASON_FORWARDED or audit_fwd)):
                verdict, proxy = VERDICT_DENY, 0
                reason, event = int(pre_drop_reason[i]), EV_DROP
                audit_fwd = False
            if audit_fwd:
                # policy-audit-mode: forward, CT-create, keep the
                # would-be reason on the verdict event
                verdict, proxy, event = VERDICT_ALLOW, 0, EV_VERDICT
            results.append(OracleResult(verdict, proxy, ct_res, ident,
                                        reason, event))
            allowed = reason == REASON_FORWARDED or audit_fwd
            # a NAT-dropped row must not refresh an existing entry
            # either: CT_NEW + allowed=False touches nothing
            if reason == REASON_NAT_EXHAUSTED or (
                    pre_drop_reason is not None
                    and int(pre_drop_reason[i]) != 0
                    and reason == int(pre_drop_reason[i])):
                ct_res = CT_NEW
            updates.append((fwd, row, is_reply, ct_res, proxy if allowed
                            else 0, allowed, related))
        # phase 2: apply CT updates
        from ..datapath.conntrack import (ST_CLOSING, ST_ESTABLISHED,
                                          ST_SYN_SENT)
        for fwd, row, is_reply, ct_res, proxy, allowed, related in (
                updates):
            if related or ct_res == CT_RELATED:
                continue  # ICMP errors neither create nor refresh
            proto = int(row[COL_PROTO])
            flags = int(row[COL_FLAGS])
            is_tcp = proto == 6
            closing = is_tcp and (flags & (TCP_FIN | TCP_RST)) != 0
            if ct_res == CT_NEW:
                if allowed:
                    st = ST_SYN_SENT if is_tcp else ST_ESTABLISHED
                    life = LIFETIME_SYN if is_tcp else LIFETIME_NONTCP
                    self.ct[fwd] = _CTEntry(st, now + life, proxy)
                continue
            key = self._rev(fwd) if is_reply else fwd
            e = self.ct[key]
            if is_reply and e.state == ST_SYN_SENT:
                e.state = ST_ESTABLISHED
            if closing:
                e.state = ST_CLOSING
            if e.state == ST_CLOSING:
                life = LIFETIME_CLOSE
            elif is_tcp:
                life = (LIFETIME_TCP if e.state >= ST_ESTABLISHED
                        else LIFETIME_SYN)
            else:
                life = LIFETIME_NONTCP
            e.expires = now + life
        return results

    def gc(self, now: int) -> int:
        """Expire entries (ctmap.GC)."""
        dead = [k for k, e in self.ct.items() if e.expires < now]
        for k in dead:
            del self.ct[k]
        return len(dead)
