"""Multi-process cluster agent child.

Run as ``python -m cilium_tpu.testing.cluster_child <socket> <node>
<labels>``: connects a :class:`RemoteKVStore` to the kvstore server
process, runs a full agent daemon against it (interpreter datapath —
this test is about the CONTROL plane), allocates the identity for
``labels``, enforces one packet, prints a JSON status line, then holds
its leased identity refs alive until killed.

This is the reference's deployment shape in miniature: N agent
processes + 1 operator sharing one etcd (VERDICT r03 item 1) — same
allocator/daemon code as the in-process tests, only the store handle
differs.  Killing this process stops its keepalive controller, so its
leased refs expire and identity GC can sweep — the crash-recovery
path the reference gets from etcd lease expiry.
"""

import json
import sys
import time


def main() -> None:
    socket_path, node, labels_arg = sys.argv[1], sys.argv[2], sys.argv[3]
    lease_ttl = float(sys.argv[4]) if len(sys.argv) > 4 else 1.0

    from cilium_tpu.agent import Daemon, DaemonConfig
    from cilium_tpu.core import TCP_SYN, make_batch
    from cilium_tpu.kvstore import RemoteKVStore
    from cilium_tpu.labels import LabelSet

    kv = RemoteKVStore(("unix", socket_path))
    d = Daemon(DaemonConfig(node_name=node, backend="interpreter",
                            identity_lease_ttl=lease_ttl), kvstore=kv)
    d.add_endpoint(f"db-{node}", ("10.0.2.1",), ["k8s:app=db"])
    d.policy_import([{
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [
            {"fromEndpoints": [{"matchLabels": {"role": "web"}}],
             "toPorts": [{"ports": [{"port": "5432",
                                     "protocol": "TCP"}]}]},
        ],
    }])
    d.start()

    web = d.allocator.allocate(LabelSet.parse(*labels_arg.split(",")))
    d.upsert_ipcache("10.1.0.9/32", web.numeric_id)
    ep = d.endpoints.list()[0]
    pkt = make_batch([dict(src="10.1.0.9", dst="10.0.2.1", sport=40000,
                           dport=5432, proto=6, flags=TCP_SYN,
                           ep=ep.id, dir=0)]).data
    out = d.process_batch(pkt, now=10)
    print(json.dumps({
        "node": node,
        "identity": web.numeric_id,
        "verdict": [int(v) for v in out.verdict],
    }), flush=True)
    # hold refs (keepalive controller is running) until killed
    while True:
        time.sleep(0.1)


if __name__ == "__main__":
    main()
