"""Adversarial workload scenario library (ROADMAP item 4; ISSUE 12).

bench.py's overload/paced legs drive uniform synthetic flows; real
clusters serve hostile traffic shapes — SYN floods that fill the CT
map, port scans, NAT port exhaustion, heavy-tailed flow popularity —
while the control plane churns under them.  This module factors that
gap into NAMED, SEEDED scenarios: each one is a deterministic
generator of driver events (traffic batches and/or control-plane ops)
that the chaos tests, the everything-on soak gate, and ``bench.py
--scenarios`` replay — same seed, same schedule, byte for byte — with
per-scenario PASS CRITERIA declared on the scenario class and
evaluated by one shared :func:`run_scenario` driver.

The contract every registry entry satisfies (statically enforced by
the CTA010 checker, ``analysis/scenario_lint.py``):

- a docstring saying what hostile shape it reproduces;
- a ``name`` literal (the registry key / bench artifact key);
- a ``criteria`` dict literal — the declared pass criteria
  (``ledger_exact``, ``max_shed_frac``, ``p99_ms``,
  ``min_ct_insert_drops``, ``min_nat_failures``, ``min_drop_frac``,
  ``min_rotations``;
  unknown keys FAIL evaluation, so a typo'd criterion is loud);
- a ``seed`` constructor parameter (same name+seed => byte-identical
  op/packet streams, proven per-entry by the determinism contract
  test via :meth:`Scenario.signature`).

Scenarios:

- ``identity_churn`` (ISSUE 10) — mint/withdraw label-selected peer
  identities, Zipf-weighted (the original entry, API unchanged);
- ``syn_flood`` — a new-flow storm of unique-tuple SYNs sized past
  the CT map, driving insert-drop pressure (``CTTable.dropped``) and
  the full-window-probe rerun path;
- ``port_scan`` — one source sweeping the port space with tiny SYNs,
  feeding the drop-spike detector, the flow aggregates, and the
  anomaly models;
- ``nat_exhaustion`` — an egress ramp of unique flows that drains
  the SNAT port pool into ``REASON_NAT_EXHAUSTED`` drops (runs on
  the offline ``process_batch`` path — masquerade rides there);
- ``elephant_mice`` — Zipf flow popularity over a fixed flow pool,
  stressing the space-saving top-K sketches;
- ``endpoint_churn`` — endpoints connecting/disconnecting (full
  add_endpoint/remove regeneration churn) under live traffic;
- ``rotation_storm`` (ISSUE 18) — repeated cluster-wide key-epoch
  rotations at a fixed cadence under mixed traffic, sweeping the
  grace-window rotation-race interleavings on the encrypted data
  channel (``cluster_ops = True``: ops target the cluster facade).
"""

from __future__ import annotations

import hashlib
import itertools
import time
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP3,
    COL_EP,
    COL_FAMILY,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP3,
    N_COLS,
    TCP_ACK,
    TCP_SYN,
)


def _ip(s: str) -> int:
    import ipaddress

    return int(ipaddress.IPv4Address(s))


def _rows(n: int) -> np.ndarray:
    out = np.zeros((n, N_COLS), dtype=np.uint32)
    out[:, COL_FAMILY] = 4
    out[:, COL_PROTO] = 6
    return out


def _zipf_weights(n: int, a: float) -> np.ndarray:
    """Rank -> probability ~ 1/rank^a (normalized); rank 0 is the
    elephant.  ONE definition for every Zipf-weighted scenario."""
    w = 1.0 / np.power(np.arange(1, n + 1), a)
    return w / w.sum()


class Scenario:
    """The scenario contract (see the module docstring; CTA010
    enforces the declaration half statically).

    A scenario owns two deterministic streams — ``iter_batches(ep)``
    (wide ``[N, N_COLS]`` uint32 header tensors) and ``ops(n)``
    (control-plane events applied via :meth:`apply`) — plus
    ``setup(target)``, which registers whatever endpoints/policy the
    streams assume (``target`` is duck-typed: a ``Daemon`` or a
    ``ClusterServing`` — both expose ``add_endpoint`` /
    ``policy_import``).  ``path`` picks the driver leg: ``serving``
    (admission queue -> drain loop) or ``offline``
    (``process_batch`` — the masquerade/NAT pipeline only rides
    there).  ``daemon_overrides`` are the DaemonConfig knobs the
    scenario's pressure shape needs (a tiny CT map for ``syn_flood``,
    masquerade + a small SNAT pool for ``nat_exhaustion``); tests and
    ``bench.py --scenarios`` both build daemons from them.
    """

    name: str = ""
    criteria: Dict[str, object] = {}
    path: str = "serving"
    daemon_overrides: Dict[str, object] = {}
    interval_s: float = 0.0  # op spacing; 0 = no op stream

    def setup(self, target) -> dict:
        """Register the scenario's world; returns the driver context
        (at least ``{"ep": <endpoint id>}`` for traffic scenarios)."""
        return {"ep": 0}

    def iter_batches(self, ep: int) -> Iterator[np.ndarray]:
        return iter(())

    def ops(self, n: Optional[int] = None) -> List:
        return []

    def apply(self, daemon, op, live: Dict) -> None:
        raise NotImplementedError

    def drain(self, daemon, live: Dict) -> None:
        """Unwind every surviving op (teardown; default no-op)."""

    # -- the determinism contract --------------------------------------
    def signature(self, ep: int = 7, n_batches: int = 3,
                  n_ops: int = 64) -> str:
        """Digest of the scenario's first ``n_batches`` batches and
        ``n_ops`` ops — two fresh instances with the same constructor
        args must agree byte for byte (the contract test's surface)."""
        h = hashlib.sha256()
        for b in itertools.islice(self.iter_batches(ep), n_batches):
            h.update(np.ascontiguousarray(b).tobytes())
        for op in self.ops(n_ops):
            h.update(repr(op).encode())
        return h.hexdigest()


@dataclass(frozen=True)
class ChurnOp:
    """One scenario event: mint or withdraw slot ``slot``'s identity.

    ``cidr`` is the slot's /32.  Minting allocates an identity for
    the slot's labels (see :meth:`IdentityChurnScenario.slot_labels`
    — rules select them via the ``k8s:churn=yes`` convention) and
    upserts the /32; withdrawing deletes the ipcache entry and
    releases the identity.  ``t_s`` is the op's offset from the
    scenario start at the configured rate."""

    kind: str  # "mint" | "withdraw"
    slot: int
    cidr: str
    t_s: float


class IdentityChurnScenario(Scenario):
    """Mint/withdraw CIDR identities at ``rate_hz``, Zipf-weighted
    over ``n_slots`` peer slots.

    Each slot alternates mint -> withdraw -> mint ... (an op on a
    live slot withdraws it, on a dead slot mints it), so the op
    stream is valid by construction and the live set follows the
    Zipf weights.  Deterministic per (seed, n_slots, zipf_a,
    rate_hz): the chaos gate and ``bench.py --churn`` replay the
    same schedule.
    """

    name = "identity_churn"
    criteria = {"ledger_exact": True, "max_shed_frac": 0.95}
    path = "serving"
    daemon_overrides = {"serving_bucket_ladder": (64,),
                        "serving_max_wait_us": 500.0}

    def __init__(self, seed: int = 0, n_slots: int = 16,
                 zipf_a: float = 1.3, rate_hz: float = 200.0,
                 subnet: Tuple[int, int] = (10, 9),
                 n_batches: int = 48):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        if zipf_a <= 1.0:
            raise ValueError("zipf_a must be > 1 (Zipf exponent)")
        if rate_hz <= 0:
            raise ValueError("rate_hz must be > 0")
        self.seed = int(seed)
        self.n_batches = int(n_batches)
        self.n_slots = int(n_slots)
        self.zipf_a = float(zipf_a)
        self.rate_hz = float(rate_hz)
        self.interval_s = 1.0 / self.rate_hz
        if self.n_slots > 65534:
            raise ValueError("n_slots must fit the /16 slot space")
        a, b = subnet
        # host s+1 within the /16 (skips .0.0; (s+1) & 0xFF may be 0
        # — x.y.z.0/32 is a valid host route)
        self._cidrs = [f"{a}.{b}.{(s + 1) >> 8}.{(s + 1) & 0xFF}/32"
                       for s in range(self.n_slots)]
        # slot 0 is the elephant peer
        self._weights = _zipf_weights(self.n_slots, self.zipf_a)

    def slot_cidr(self, slot: int) -> str:
        return self._cidrs[slot]

    def slot_ip(self, slot: int) -> str:
        return self._cidrs[slot].rsplit("/", 1)[0]

    def slot_labels(self, slot: int) -> List[str]:
        """The slot identity's labels.  ``k8s:churn=yes`` is the
        selection convention: a rule with ``fromEndpoints``
        ``matchLabels {"churn": "yes"}`` admits exactly the LIVE
        slots (a dead slot's /32 resolves to identity 0 and
        default-denies) — deliberately NOT a ``fromCIDR`` rule,
        whose covering-prefix identity would admit the whole subnet
        regardless of slot liveness."""
        return [f"k8s:app=churn{slot}", "k8s:churn=yes",
                "k8s:ns=default"]

    def setup(self, target) -> dict:
        target.add_endpoint("churn-web", ("10.9.255.1",),
                            ["k8s:app=churn-web"])
        ep = target.add_endpoint("churn-db", ("10.9.255.2",),
                                 ["k8s:app=churn-db"])
        target.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "churn-db"}},
            "ingress": [
                {"fromEndpoints": [
                    {"matchLabels": {"app": "churn-web"}}],
                 "toPorts": [{"ports": [{"port": "5432",
                                         "protocol": "TCP"}]}]},
                {"fromEndpoints": [{"matchLabels": {"churn": "yes"}}],
                 "toPorts": [{"ports": [{"port": "5432",
                                         "protocol": "TCP"}]}]},
            ],
        }])
        return {"ep": ep.id}

    def iter_batches(self, ep: int) -> Iterator[np.ndarray]:
        """A light stable-allowed stream (churn-web -> :5432) so the
        serving plane has traffic while the op stream churns —
        ``n_batches`` of 64 rows (bounded: run_scenario drains the
        whole stream)."""
        rng = np.random.default_rng(self.seed + 1)
        for _ in range(self.n_batches):
            out = _rows(64)
            out[:, COL_SRC_IP3] = _ip("10.9.255.1")
            out[:, COL_DST_IP3] = _ip("10.9.255.2")
            out[:, COL_SPORT] = rng.integers(1024, 60000, 64)
            out[:, COL_DPORT] = 5432
            out[:, COL_FLAGS] = TCP_ACK
            out[:, COL_LEN] = 512
            out[:, COL_EP] = ep
            yield out

    def ops(self, n: Optional[int] = None) -> List[ChurnOp]:
        """The first ``n`` ops of the schedule (deterministic)."""
        return list(self.iter_ops(n if n is not None else 256))

    def iter_ops(self, n: Optional[int] = None) -> Iterator[ChurnOp]:
        rng = np.random.default_rng(self.seed)
        live = [False] * self.n_slots
        i = 0
        while n is None or i < n:
            slot = int(rng.choice(self.n_slots, p=self._weights))
            kind = "withdraw" if live[slot] else "mint"
            live[slot] = not live[slot]
            yield ChurnOp(kind=kind, slot=slot,
                          cidr=self._cidrs[slot],
                          t_s=i * self.interval_s)
            i += 1

    # -- the daemon driver (chaos tests + bench share it) --------------
    def apply(self, daemon, op: ChurnOp, live: Dict[int, object]
              ) -> None:
        """Apply one op against a live daemon.  ``live`` is the
        caller's slot -> Identity map (the scenario owns the
        schedule, the caller owns the handles).

        Mint allocates the slot's labeled identity — the allocator
        observer chain applies it to the selecting contributions and
        patches its verdict row in place (``patch_identity``) — then
        upserts the slot's /32 (``patch_ipcache``).  Withdraw
        deletes the ipcache entry FIRST (no LPM entry may reference
        the row when it recycles), then releases the identity."""
        from ..labels import LabelSet

        if op.kind == "mint":
            ident = daemon.allocator.allocate(
                LabelSet.parse(*self.slot_labels(op.slot)))
            daemon.upsert_ipcache(op.cidr, ident.numeric_id,
                                  source="generated")
            live[op.slot] = ident
        else:
            ident = live.pop(op.slot, None)
            if ident is not None:
                daemon.delete_ipcache(op.cidr)
                daemon.allocator.release(ident)

    def drain(self, daemon, live: Dict[int, object]) -> None:
        """Withdraw every surviving slot — the teardown both bench
        legs and test cleanup use, so op semantics (field order,
        withdraw steps) live only here."""
        for slot in list(live):
            self.apply(daemon, ChurnOp("withdraw", slot,
                                       self.slot_cidr(slot), 0.0),
                       live)


class SynFloodScenario(Scenario):
    """A new-flow SYN storm: ``n_flows`` unique (src, sport) tuples,
    each one SYN at the victim's allowed port — every packet is a CT
    insert, so a storm sized past the CT map fills it and drives
    insert-drop pressure (``CTTable.dropped``, the ctmap map-pressure
    analogue) plus the fingerprint-overflow full-window-probe rerun
    at high occupancy.  The flood is ALLOWED traffic by design
    (``fromEntities: [world]`` to the flood port): only the allow
    path creates CT entries, and surviving a flood of wanted-looking
    connections is exactly the ctmap GC story."""

    name = "syn_flood"
    criteria = {"ledger_exact": True, "max_shed_frac": 0.95,
                "min_ct_insert_drops": 1, "p99_ms": 120000.0}
    path = "serving"
    # the storm must outsize the CT map: 4096 unique flows against a
    # 1k-entry table (bench + tests build the daemon from these)
    daemon_overrides = {"ct_capacity": 1 << 10,
                        "serving_bucket_ladder": (512,),
                        "serving_queue_depth": 1 << 14}

    def __init__(self, seed: int = 0, n_flows: int = 4096,
                 batch: int = 512, dport: int = 80):
        if n_flows < 1 or batch < 1:
            raise ValueError("n_flows and batch must be >= 1")
        self.seed = int(seed)
        self.n_flows = int(n_flows)
        self.batch = int(batch)
        self.dport = int(dport)

    def setup(self, target) -> dict:
        ep = target.add_endpoint("sf-victim", ("10.0.40.1",),
                                 ["k8s:app=sf-victim"])
        target.policy_import([{
            "endpointSelector": {"matchLabels":
                                 {"app": "sf-victim"}},
            "ingress": [{"fromEntities": ["world"],
                         "toPorts": [{"ports": [
                             {"port": str(self.dport),
                              "protocol": "TCP"}]}]}],
        }])
        return {"ep": ep.id}

    def iter_batches(self, ep: int) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed)
        base = _ip("172.16.0.1")
        dst = _ip("10.0.40.1")
        flow = 0
        while flow < self.n_flows:
            n = min(self.batch, self.n_flows - flow)
            i = np.arange(flow, flow + n, dtype=np.uint32)
            out = _rows(n)
            # unique tuple per flow: 1024 sources x rotating sports
            out[:, COL_SRC_IP3] = base + (i % 1024)
            out[:, COL_SPORT] = 1024 + (i // 1024) * 1024 \
                + rng.integers(0, 1024, n).astype(np.uint32)
            out[:, COL_DST_IP3] = dst
            out[:, COL_DPORT] = self.dport
            out[:, COL_FLAGS] = TCP_SYN
            out[:, COL_LEN] = rng.integers(40, 60, n)
            out[:, COL_EP] = ep
            yield out
            flow += n


class PortScanScenario(Scenario):
    """One source sweeping the destination port space with tiny SYNs
    (the classic recon shape): all but the victim's one allowed port
    default-deny, so the stream feeds the drop-spike detector, the
    per-identity-pair aggregates, and the anomaly models a clean
    synthetic attack (the r05 evaluation's ``portscan`` kind,
    replayed through the REAL serving/offline pipeline)."""

    name = "port_scan"
    criteria = {"ledger_exact": True, "max_shed_frac": 0.95,
                "min_drop_frac": 0.5}
    path = "serving"
    daemon_overrides = {"serving_bucket_ladder": (512,),
                        "serving_queue_depth": 1 << 14,
                        "spike_min_drops": 64}

    def __init__(self, seed: int = 0, n_packets: int = 4096,
                 batch: int = 512, open_port: int = 5432):
        if n_packets < 1 or batch < 1:
            raise ValueError("n_packets and batch must be >= 1")
        self.seed = int(seed)
        self.n_packets = int(n_packets)
        self.batch = int(batch)
        self.open_port = int(open_port)

    def setup(self, target) -> dict:
        ep = target.add_endpoint("ps-victim", ("10.0.41.1",),
                                 ["k8s:app=ps-victim"])
        target.policy_import([{
            "endpointSelector": {"matchLabels":
                                 {"app": "ps-victim"}},
            "ingress": [{"fromEntities": ["world"],
                         "toPorts": [{"ports": [
                             {"port": str(self.open_port),
                              "protocol": "TCP"}]}]}],
        }])
        return {"ep": ep.id}

    def iter_batches(self, ep: int) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed)
        src = _ip("172.20.0.7")
        dst = _ip("10.0.41.1")
        sent = 0
        while sent < self.n_packets:
            n = min(self.batch, self.n_packets - sent)
            out = _rows(n)
            out[:, COL_SRC_IP3] = src
            out[:, COL_SPORT] = rng.integers(1024, 65535, n)
            out[:, COL_DST_IP3] = dst
            out[:, COL_DPORT] = rng.integers(1, 65535, n)
            out[:, COL_FLAGS] = TCP_SYN
            out[:, COL_LEN] = rng.integers(40, 60, n)
            out[:, COL_EP] = ep
            yield out
            sent += n


class L7AbuseScenario(Scenario):
    """Port-scan-shaped probes against a victim whose one open port
    carries an L7 HTTP redirect rule (ISSUE 16): a slice of the sweep
    lands on the redirect port and verdicts REDIRECT — feeding the
    serving L7 plane a sustained redirect stream under drop pressure
    — while the rest of the sweep default-denies.  Proves the proxy
    plane's no-silent-loss ledger (``redirected == l7_allowed +
    l7_denied + l7_shed + l7_failed``) closes under recon-shaped
    abuse, not just clean traffic."""

    name = "l7_abuse"
    criteria = {"ledger_exact": True, "l7_ledger_exact": True,
                "min_l7_redirected": 1, "max_shed_frac": 0.95,
                "min_drop_frac": 0.25}
    path = "serving"
    daemon_overrides = {"serving_bucket_ladder": (512,),
                        "serving_queue_depth": 1 << 14,
                        "spike_min_drops": 64}

    def __init__(self, seed: int = 0, n_packets: int = 4096,
                 batch: int = 512, redirect_port: int = 80,
                 redirect_every: int = 4):
        if n_packets < 1 or batch < 1:
            raise ValueError("n_packets and batch must be >= 1")
        if redirect_every < 1:
            raise ValueError("redirect_every must be >= 1")
        self.seed = int(seed)
        self.n_packets = int(n_packets)
        self.batch = int(batch)
        self.redirect_port = int(redirect_port)
        self.redirect_every = int(redirect_every)

    def setup(self, target) -> dict:
        ep = target.add_endpoint("l7-victim", ("10.0.47.1",),
                                 ["k8s:app=l7-victim"])
        target.policy_import([{
            "endpointSelector": {"matchLabels":
                                 {"app": "l7-victim"}},
            "ingress": [{"fromEntities": ["world"],
                         "toPorts": [{
                             "ports": [
                                 {"port": str(self.redirect_port),
                                  "protocol": "TCP"}],
                             "rules": {"http": [
                                 {"method": "GET",
                                  "path": "/public"}]},
                         }]}],
        }])
        return {"ep": ep.id}

    def iter_batches(self, ep: int) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed)
        src = _ip("172.20.0.9")
        dst = _ip("10.0.47.1")
        sent = 0
        while sent < self.n_packets:
            n = min(self.batch, self.n_packets - sent)
            out = _rows(n)
            out[:, COL_SRC_IP3] = src
            out[:, COL_SPORT] = rng.integers(1024, 65535, n)
            out[:, COL_DST_IP3] = dst
            dports = rng.integers(1, 65535, n).astype(np.uint32)
            # every redirect_every-th probe hits the L7 port: the
            # sweep's recon shape stays, the redirect stream is
            # deterministic and non-empty
            idx = np.arange(sent, sent + n)
            dports[idx % self.redirect_every == 0] = \
                self.redirect_port
            out[:, COL_DPORT] = dports
            out[:, COL_FLAGS] = TCP_SYN
            out[:, COL_LEN] = rng.integers(40, 60, n)
            out[:, COL_EP] = ep
            yield out
            sent += n


class NatExhaustionScenario(Scenario):
    """An egress ramp of unique pod -> world flows sized past the
    SNAT port pool: once every probe-window slot is live, allocation
    fails and the row drops as ``REASON_NAT_EXHAUSTED``
    (DROP_NAT_NO_MAPPING) — counted in ``NATTable.failed`` (the NAT
    pool-pressure signal) and decoded metricsmap -> monitor -> flow
    -> CLI.  Runs on the OFFLINE path: masquerade rides
    ``process_batch`` (LB -> SNAT -> datapath), not the serving drain
    loop."""

    name = "nat_exhaustion"
    criteria = {"ledger_exact": True, "min_nat_failures": 1}
    path = "offline"
    # a 256-port pool against a 1k-flow ramp: exhaustion by design
    daemon_overrides = {"masquerade": True, "node_ip": "192.168.0.1",
                        "nat_pool_capacity": 256,
                        "ct_capacity": 1 << 12}

    def __init__(self, seed: int = 0, n_flows: int = 1024,
                 batch: int = 256):
        if n_flows < 1 or batch < 1:
            raise ValueError("n_flows and batch must be >= 1")
        self.seed = int(seed)
        self.n_flows = int(n_flows)
        self.batch = int(batch)

    def setup(self, target) -> dict:
        ep = target.add_endpoint("nat-client", ("10.0.45.1",),
                                 ["k8s:app=nat-client"])
        target.policy_import([{
            "endpointSelector": {"matchLabels":
                                 {"app": "nat-client"}},
            "egress": [{"toEntities": ["world"]}],
        }])
        return {"ep": ep.id}

    def iter_batches(self, ep: int) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed)
        src = _ip("10.0.45.1")
        dst_base = _ip("93.184.0.1")
        flow = 0
        while flow < self.n_flows:
            n = min(self.batch, self.n_flows - flow)
            i = np.arange(flow, flow + n, dtype=np.uint32)
            out = _rows(n)
            out[:, COL_SRC_IP3] = src
            out[:, COL_SPORT] = 1024 + (i % 60000)
            out[:, COL_DST_IP3] = dst_base + (i % 512)
            out[:, COL_DPORT] = 443
            out[:, COL_FLAGS] = TCP_SYN
            out[:, COL_LEN] = rng.integers(60, 120, n)
            out[:, COL_EP] = ep
            out[:, COL_DIR] = 1  # egress: the masquerade hook
            yield out
            flow += n


class ElephantMiceScenario(Scenario):
    """Zipf flow popularity over a fixed flow pool: a few elephant
    flows carry most packets while a long tail of mice appears once
    or twice — the heavy-tail shape the space-saving top-K sketches
    must survive (elephants always retained, per-key overcount
    bounded; the mergeable-summaries contract under realistic
    skew)."""

    name = "elephant_mice"
    criteria = {"ledger_exact": True, "max_shed_frac": 0.95,
                "p99_ms": 120000.0}
    path = "serving"
    daemon_overrides = {"serving_bucket_ladder": (512,),
                        "serving_queue_depth": 1 << 14}

    def __init__(self, seed: int = 0, n_flows: int = 512,
                 n_packets: int = 8192, batch: int = 512,
                 zipf_a: float = 1.2):
        if n_flows < 1 or n_packets < 1 or batch < 1:
            raise ValueError("n_flows/n_packets/batch must be >= 1")
        if zipf_a <= 1.0:
            raise ValueError("zipf_a must be > 1 (Zipf exponent)")
        self.seed = int(seed)
        self.n_flows = int(n_flows)
        self.n_packets = int(n_packets)
        self.batch = int(batch)
        self.zipf_a = float(zipf_a)
        self._weights = _zipf_weights(self.n_flows, self.zipf_a)

    def setup(self, target) -> dict:
        ep = target.add_endpoint("em-srv", ("10.0.42.1",),
                                 ["k8s:app=em-srv"])
        target.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "em-srv"}},
            "ingress": [{"fromEntities": ["world"]}],
        }])
        return {"ep": ep.id}

    def flow_tuple(self, rank: int) -> Tuple[int, int]:
        """Rank -> (src ip, sport); rank 0 is the top elephant."""
        return (_ip("172.24.0.1") + rank % 256,
                1024 + rank)

    def iter_batches(self, ep: int) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed)
        dst = _ip("10.0.42.1")
        sent = 0
        while sent < self.n_packets:
            n = min(self.batch, self.n_packets - sent)
            ranks = rng.choice(self.n_flows, n, p=self._weights)
            srcs = (_ip("172.24.0.1")
                    + (ranks % 256)).astype(np.uint32)
            sports = (1024 + ranks).astype(np.uint32)
            out = _rows(n)
            out[:, COL_SRC_IP3] = srcs
            out[:, COL_SPORT] = sports
            out[:, COL_DST_IP3] = dst
            out[:, COL_DPORT] = 443
            out[:, COL_FLAGS] = TCP_ACK
            out[:, COL_LEN] = rng.integers(60, 1500, n)
            out[:, COL_EP] = ep
            yield out
            sent += n


@dataclass(frozen=True)
class EndpointOp:
    """One endpoint-churn event: connect or disconnect slot
    ``slot``'s endpoint (full add_endpoint/remove regeneration)."""

    kind: str  # "connect" | "disconnect"
    slot: int
    ip: str
    t_s: float


class EndpointChurnScenario(Scenario):
    """Endpoints connecting and disconnecting under live traffic:
    each op is a FULL ``add_endpoint``/``remove`` (policy
    re-resolve + regeneration + table publish), Zipf-weighted over
    slots — the pod-churn shape that stresses the attach path while
    the serving plane keeps dispatching."""

    name = "endpoint_churn"
    criteria = {"ledger_exact": True, "max_shed_frac": 0.95}
    path = "serving"
    daemon_overrides = {"serving_bucket_ladder": (64,),
                        "serving_max_wait_us": 500.0}

    def __init__(self, seed: int = 0, n_slots: int = 8,
                 zipf_a: float = 1.3, rate_hz: float = 50.0,
                 n_batches: int = 32):
        if n_slots < 1 or n_slots > 250:
            raise ValueError("n_slots must be in [1, 250]")
        if zipf_a <= 1.0:
            raise ValueError("zipf_a must be > 1 (Zipf exponent)")
        if rate_hz <= 0:
            raise ValueError("rate_hz must be > 0")
        self.seed = int(seed)
        self.n_slots = int(n_slots)
        self.n_batches = int(n_batches)
        self.zipf_a = float(zipf_a)
        self.rate_hz = float(rate_hz)
        self.interval_s = 1.0 / self.rate_hz
        self._weights = _zipf_weights(self.n_slots, self.zipf_a)

    def slot_ip(self, slot: int) -> str:
        return f"10.0.44.{slot + 1}"

    def setup(self, target) -> dict:
        ep = target.add_endpoint("ec-svc", ("10.0.43.1",),
                                 ["k8s:app=ec-svc"])
        target.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "ec-svc"}},
            "ingress": [{"fromEntities": ["world"],
                         "toPorts": [{"ports": [
                             {"port": "8080",
                              "protocol": "TCP"}]}]}],
        }])
        return {"ep": ep.id}

    def iter_batches(self, ep: int) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed + 1)
        dst = _ip("10.0.43.1")
        for _ in range(self.n_batches):
            out = _rows(64)
            out[:, COL_SRC_IP3] = _ip("172.28.0.1") \
                + rng.integers(0, 64, 64).astype(np.uint32)
            out[:, COL_SPORT] = rng.integers(1024, 60000, 64)
            out[:, COL_DST_IP3] = dst
            out[:, COL_DPORT] = 8080
            out[:, COL_FLAGS] = TCP_ACK
            out[:, COL_LEN] = 256
            out[:, COL_EP] = ep
            yield out

    def ops(self, n: Optional[int] = None) -> List[EndpointOp]:
        return list(self.iter_ops(n if n is not None else 256))

    def iter_ops(self, n: Optional[int] = None
                 ) -> Iterator[EndpointOp]:
        rng = np.random.default_rng(self.seed)
        live = [False] * self.n_slots
        i = 0
        while n is None or i < n:
            slot = int(rng.choice(self.n_slots, p=self._weights))
            kind = "disconnect" if live[slot] else "connect"
            live[slot] = not live[slot]
            yield EndpointOp(kind=kind, slot=slot,
                             ip=self.slot_ip(slot),
                             t_s=i * self.interval_s)
            i += 1

    def apply(self, daemon, op: EndpointOp,
              live: Dict[int, object]) -> None:
        if op.kind == "connect":
            live[op.slot] = daemon.add_endpoint(
                f"ec{op.slot}", (op.ip,),
                [f"k8s:app=ec{op.slot}", "k8s:ec-churn=yes"])
        else:
            ep = live.pop(op.slot, None)
            if ep is not None:
                daemon.endpoints.remove(ep.id)

    def drain(self, daemon, live: Dict[int, object]) -> None:
        for slot in list(live):
            self.apply(daemon, EndpointOp("disconnect", slot,
                                          self.slot_ip(slot), 0.0),
                       live)


@dataclass(frozen=True)
class RotateOp:
    """One rotation-storm event: the ``n``-th cluster-wide key-epoch
    bump, ``t_s`` seconds into the storm (ISSUE 18)."""

    n: int
    t_s: float


class RotationStormScenario(Scenario):
    """Repeated cluster-wide key-epoch rotations at a fixed cadence
    under mixed SYN/ACK traffic (ISSUE 18): every op re-keys every
    live encrypted channel WORKER-FIRST while sealed frames are in
    flight, sweeping exactly the rotation-race interleavings the
    previous-epoch grace window exists for.  The pass criteria are
    the robustness core: the cluster ledger stays exact across every
    seam (no frame lost or double-counted to an epoch boundary) and
    at least ``min_rotations`` bumps actually landed — on a
    plaintext or thread-mode target :meth:`apply` degrades to a
    no-op, the rotation count stays 0, and the criterion fails
    loudly instead of vacuously passing.  Declares
    ``cluster_ops = True``: the op stream targets the CLUSTER facade
    (``rotate_epoch``), not a node-local daemon, so the plain-daemon
    driver ignores it and only the cluster leg rotates."""

    name = "rotation_storm"
    criteria = {"ledger_exact": True, "max_shed_frac": 0.95,
                "min_rotations": 3}
    path = "serving"
    # ops apply against the ClusterServing facade, not a daemon
    cluster_ops = True
    daemon_overrides = {"serving_bucket_ladder": (256,),
                        "serving_queue_depth": 1 << 14,
                        "cluster_encrypt": True,
                        "cluster_epoch_grace_s": 2.0}

    def __init__(self, seed: int = 0, n_flows: int = 256,
                 n_packets: int = 8192, batch: int = 256,
                 rotations: int = 6, rate_hz: float = 8.0):
        if n_flows < 1 or n_packets < 1 or batch < 1:
            raise ValueError("n_flows/n_packets/batch must be >= 1")
        if rotations < 1:
            raise ValueError("rotations must be >= 1")
        if rate_hz <= 0:
            raise ValueError("rate_hz must be > 0")
        self.seed = int(seed)
        self.n_flows = int(n_flows)
        self.n_packets = int(n_packets)
        self.batch = int(batch)
        self.rotations = int(rotations)
        self.rate_hz = float(rate_hz)
        self.interval_s = 1.0 / self.rate_hz
        # paced submission: spread the batch stream across the whole
        # storm so every rotation lands under LIVE mixed traffic —
        # an unpaced stream drains in milliseconds and the seams
        # would all fall on an idle pipeline
        n_batches = (self.n_packets + self.batch - 1) // self.batch
        self.pace_s = ((self.rotations + 1) * self.interval_s
                       / max(n_batches, 1))

    def setup(self, target) -> dict:
        ep = target.add_endpoint("rs-srv", ("10.0.45.1",),
                                 ["k8s:app=rs-srv"])
        target.policy_import([{
            "endpointSelector": {"matchLabels": {"app": "rs-srv"}},
            "ingress": [{"fromEntities": ["world"]}],
        }])
        return {"ep": ep.id}

    def iter_batches(self, ep: int) -> Iterator[np.ndarray]:
        # mixed traffic: new-flow SYNs and established ACKs over a
        # fixed pool, so rotation seams land between both shapes
        rng = np.random.default_rng(self.seed)
        dst = _ip("10.0.45.1")
        sent = 0
        while sent < self.n_packets:
            n = min(self.batch, self.n_packets - sent)
            flows = rng.integers(0, self.n_flows, n)
            out = _rows(n)
            out[:, COL_SRC_IP3] = (_ip("172.30.0.1")
                                   + flows % 256).astype(np.uint32)
            out[:, COL_SPORT] = (1024 + flows).astype(np.uint32)
            out[:, COL_DST_IP3] = dst
            out[:, COL_DPORT] = 443
            out[:, COL_FLAGS] = np.where(
                rng.random(n) < 0.25, TCP_SYN, TCP_ACK
            ).astype(np.uint32)
            out[:, COL_LEN] = rng.integers(60, 1500, n)
            out[:, COL_EP] = ep
            yield out
            sent += n

    def ops(self, n: Optional[int] = None) -> List[RotateOp]:
        k = self.rotations if n is None else min(n, self.rotations)
        return [RotateOp(n=i + 1, t_s=(i + 1) * self.interval_s)
                for i in range(k)]

    def apply(self, target, op: RotateOp, live: Dict) -> None:
        rotate = getattr(target, "rotate_epoch", None)
        if rotate is None:
            return  # plain daemon: no cluster-wide epoch to bump
        from ..serving import ServingError
        try:
            live.setdefault("epochs", []).append(rotate()["epoch"])
        except ServingError:
            # plaintext / thread-mode cluster: no keypair to rotate.
            # Deliberately NOT counted — min_rotations then fails.
            live["rotate_rejected"] = \
                live.get("rotate_rejected", 0) + 1


# -- the registry ------------------------------------------------------
# name -> scenario class: every entry is runnable by name from tests,
# the everything-on soak gate, and `bench.py --scenarios`, and must
# satisfy the CTA010 declaration contract (docstring, name literal,
# criteria dict, seed parameter)
SCENARIOS = {
    IdentityChurnScenario.name: IdentityChurnScenario,
    SynFloodScenario.name: SynFloodScenario,
    PortScanScenario.name: PortScanScenario,
    L7AbuseScenario.name: L7AbuseScenario,
    NatExhaustionScenario.name: NatExhaustionScenario,
    ElephantMiceScenario.name: ElephantMiceScenario,
    EndpointChurnScenario.name: EndpointChurnScenario,
    RotationStormScenario.name: RotationStormScenario,
}


def make_scenario(name: str, seed: int = 0, **kw):
    """Instantiate a named scenario; unknown names list the registry
    (the bench flag's error message)."""
    cls = SCENARIOS.get(name)
    if cls is None:
        raise ValueError(
            f"unknown scenario {name!r}; registered: "
            f"{', '.join(sorted(SCENARIOS))}")
    return cls(seed=seed, **kw)


def scenario_cluster(scenario, nodes: int = 2, mode: str = "thread",
                     serving_kwargs: Optional[dict] = None,
                     **overrides):
    """Build a ``ClusterServing`` shaped for ``scenario`` (its
    ``daemon_overrides`` under the caller's ``overrides``), run the
    scenario's ``setup`` against it (endpoints + policy fan out and
    CONVERGE over the kvstore), then start serving — the cluster
    analogue of :func:`scenario_daemon`, and the construction the
    soak gate's cluster leg and tests share.  Returns ``(cluster,
    ctx)`` for ``run_scenario(cluster, scenario, ctx=ctx)``; the
    caller owns ``shutdown()`` (``run_scenario`` stops it)."""
    from ..agent.daemon import DaemonConfig
    from ..cluster import ClusterServing

    cfg = dict(backend="tpu", flow_ring_capacity=1 << 13,
               cluster_mode=mode)
    cfg.update(scenario.daemon_overrides)
    cfg.update(overrides)
    c = ClusterServing(nodes=nodes, config=DaemonConfig(**cfg))
    try:
        ctx = scenario.setup(c)
        assert c.wait_policy(timeout=15), \
            f"{scenario.name} policy never converged cluster-wide"
        kw = dict(ring_capacity=1 << 13, trace_sample=0, packed=True)
        kw.update(serving_kwargs or {})
        c.start(**kw)
    except BaseException:
        c.shutdown()
        raise
    return c, ctx


def scenario_daemon(scenario, **overrides):
    """Build a Daemon shaped for ``scenario`` (its
    ``daemon_overrides`` under the caller's ``overrides``) — the one
    construction tests and ``bench.py --scenarios`` share, so the
    pressure shape a scenario declares is the shape it is always
    run against."""
    from ..agent.daemon import Daemon, DaemonConfig

    cfg = dict(backend="tpu", flow_ring_capacity=1 << 13)
    cfg.update(scenario.daemon_overrides)
    cfg.update(overrides)
    return Daemon(DaemonConfig(**cfg))


# -- criteria evaluation ----------------------------------------------
def evaluate_criteria(criteria: Dict[str, object],
                      metrics: Dict[str, object]) -> Dict[str, bool]:
    """Declared criteria -> {criterion: passed}.  Unknown criterion
    keys evaluate False (a typo'd criterion must fail loudly, not
    vacuously pass)."""
    checks: Dict[str, bool] = {}
    for key, want in criteria.items():
        if key == "ledger_exact":
            checks[key] = bool(metrics.get("ledger_exact")) == bool(
                want)
        elif key == "max_shed_frac":
            shed = metrics.get("shed_frac")
            checks[key] = shed is not None and shed <= float(want)
        elif key == "p99_ms":
            p99 = metrics.get("p99_us")
            checks[key] = (p99 is not None
                           and p99 <= float(want) * 1e3)
        elif key == "min_ct_insert_drops":
            checks[key] = (metrics.get("ct_insert_drops", 0)
                           >= int(want))
        elif key == "min_nat_failures":
            checks[key] = (metrics.get("nat_failures", 0)
                           >= int(want))
        elif key == "min_drop_frac":
            frac = metrics.get("drop_frac")
            checks[key] = frac is not None and frac >= float(want)
        elif key == "l7_ledger_exact":
            checks[key] = bool(metrics.get("l7_ledger_exact")) \
                == bool(want)
        elif key == "min_l7_redirected":
            checks[key] = (metrics.get("l7_redirected", 0)
                           >= int(want))
        elif key == "min_rotations":
            checks[key] = (metrics.get("rotations", 0)
                           >= int(want))
        else:
            checks[key] = False
    return checks


def run_scenario(daemon, scenario, *, ctx: Optional[dict] = None,
                 max_ops: int = 256,
                 serving_kwargs: Optional[dict] = None) -> dict:
    """The one scenario driver tests and ``bench.py --scenarios``
    share: replay the scenario's batch stream (serving or offline
    path) while applying its op stream on schedule, then evaluate
    the declared pass criteria.

    Returns ``{"name", "seed", "criteria", "metrics", "checks",
    "passed"}`` where ``metrics`` carries ``submitted`` /
    ``verdicts`` / ``shed`` / ``shed_frac`` / ``sustained_pps`` /
    ``p99_us`` / ``ledger_exact`` / ``ct_insert_drops`` /
    ``nat_failures`` / ``drop_frac`` and ``checks`` maps each
    declared criterion to its verdict.

    ``daemon`` may also be a STARTED ``ClusterServing`` (thread or
    process mode — ISSUE 13 satellite): serving-path scenarios then
    drive the cluster front end (``submit`` -> flow-affine router ->
    node replicas), the ledger criterion becomes the CLUSTER-WIDE
    ledger, and pressure counters sum over the replicas.  The driver
    STOPS the cluster at the end (the ledger is exact only closed);
    the caller keeps shutdown."""
    if _is_cluster(daemon):
        return _run_scenario_cluster(daemon, scenario, ctx=ctx)
    if ctx is None:
        ctx = scenario.setup(daemon)
    ep = ctx.get("ep", 0)
    pressure0 = daemon.loader.map_pressure(daemon._now())
    metrics0 = np.array(daemon.loader.metrics(), dtype=np.int64)
    ops = iter(scenario.ops(max_ops))
    live: Dict = {}
    applied = 0
    next_op = None

    def tick_ops(elapsed: float) -> None:
        nonlocal next_op, applied
        if scenario.interval_s <= 0:
            return
        if next_op is None:
            next_op = elapsed
        # catch-up is CAPPED: an op that runs slower than its
        # schedule (endpoint churn's full regeneration on CPU) must
        # not replay its whole backlog in one burst — the driver
        # degrades to best-effort rate instead of stalling traffic
        burst = 0
        while next_op is not None and elapsed >= next_op \
                and burst < 4:
            try:
                scenario.apply(daemon, next(ops), live)
                applied += 1
                burst += 1
                next_op += scenario.interval_s
            except StopIteration:
                next_op = None
        if next_op is not None and elapsed - next_op \
                > 64 * scenario.interval_s:
            next_op = elapsed  # drop an unservable backlog

    submitted = 0
    events = 0
    if scenario.path == "serving":
        kw = dict(ring_capacity=1 << 13, trace_sample=0,
                  packed=True, ingress=True)
        kw.update(serving_kwargs or {})
        daemon.start_serving(**kw)
        q = daemon._serving["runtime"].queue
        t0 = time.perf_counter()
        for b in scenario.iter_batches(ep):
            # submit() returns the ADMITTED count; the exact
            # submitted/shed split comes from the front-end snapshot
            daemon.submit(b)
            tick_ops(time.perf_counter() - t0)
            # backpressure: let the drain loop keep up instead of
            # shedding the whole storm at admission
            while q.pending > q.capacity // 2:
                time.sleep(0.001)
                tick_ops(time.perf_counter() - t0)
        st = daemon.stop_serving()
        fe = st["front-end"]
        l7 = st.get("l7") or {}
        dt = max(time.perf_counter() - t0, 1e-9)
        ft = fe["fault-tolerance"]
        ledger_exact = fe["submitted"] == (
            fe["verdicts"] + fe["shed"] + ft["recovery-dropped"])
        shed_frac = (fe["shed"] / fe["submitted"]
                     if fe["submitted"] else 0.0)
        p99 = (fe.get("latency-us") or {}).get("p99")
        verdicts = fe["verdicts"]
        submitted = fe["submitted"]
        pps = verdicts / dt
    else:  # offline: the process_batch pipeline (LB -> SNAT -> step)
        l7 = {}
        t0 = time.perf_counter()
        for b in scenario.iter_batches(ep):
            evb = daemon.process_batch(b)
            submitted += len(b)
            events += len(evb)
            tick_ops(time.perf_counter() - t0)
        dt = max(time.perf_counter() - t0, 1e-9)
        ledger_exact = events == submitted
        shed_frac = 0.0
        p99 = None
        verdicts = events
        pps = submitted / dt
    scenario.drain(daemon, live)
    pressure1 = daemon.loader.map_pressure(daemon._now())
    metrics1 = np.array(daemon.loader.metrics(), dtype=np.int64)
    reason_delta = (metrics1 - metrics0).sum(axis=1)
    dropped = int(reason_delta[1:].sum())  # reason 0 = forwarded
    metrics = {
        "submitted": int(submitted),
        "verdicts": int(verdicts),
        "shed_frac": round(float(shed_frac), 4),
        "sustained_pps": round(float(pps), 1),
        "p99_us": p99,
        "ledger_exact": bool(ledger_exact),
        "ops_applied": applied,
        "ct_insert_drops": (pressure1["ct"]["insert-drops"]
                            - pressure0["ct"]["insert-drops"]),
        "ct_occupancy": pressure1["ct"]["occupancy"],
        "nat_failures": (pressure1["nat"]["failures"]
                         - pressure0["nat"]["failures"]),
        "drop_frac": (round(dropped / submitted, 4)
                      if submitted else None),
        "drops_by_reason": {
            int(r): int(n) for r, n in enumerate(reason_delta)
            if r and n},
        "elapsed_s": round(dt, 3),
        # L7 proxy-plane ledger (ISSUE 16): rows that verdicted
        # REDIRECT and their fate through the worker pool
        "l7_redirected": int(l7.get("redirected", 0)),
        "l7_allowed": int(l7.get("l7-allowed", 0)),
        "l7_denied": int(l7.get("l7-denied", 0)),
        "l7_shed": int(l7.get("l7-shed", 0)),
        "l7_failed": int(l7.get("l7-failed", 0)),
        "l7_ledger_exact": bool(l7.get("ledger-exact", False)),
    }
    checks = evaluate_criteria(scenario.criteria, metrics)
    return {
        "name": scenario.name,
        "seed": scenario.seed,
        "criteria": dict(scenario.criteria),
        "metrics": metrics,
        "checks": checks,
        "passed": all(checks.values()),
    }


def _is_cluster(target) -> bool:
    """Duck-typed ClusterServing detection (no cluster import on the
    workloads module path): the tier facade is the only target with
    a router + cluster-wide ledger."""
    return hasattr(target, "router") and hasattr(target, "ledgers")


def _run_scenario_cluster(cluster, scenario, *,
                          ctx: Optional[dict] = None,
                          pending_cap: int = 1 << 13) -> dict:
    """The cluster leg of :func:`run_scenario`: serving-path
    scenarios against a STARTED ``ClusterServing`` (thread or
    process mode).  Offline-path scenarios (nat_exhaustion rides
    process_batch) have no cluster analogue and are rejected
    loudly."""
    if scenario.path != "serving":
        raise ValueError(
            f"scenario {scenario.name!r} runs the offline path; the "
            f"cluster leg only drives serving-path scenarios")
    if cluster.router is None:
        raise ValueError(
            "run_scenario(cluster, ...) wants a STARTED cluster "
            "(start_cluster_serving)")
    if ctx is None:
        ctx = scenario.setup(cluster)
        assert cluster.wait_policy(), \
            f"{scenario.name} policy never converged cluster-wide"
    ep = ctx.get("ep", 0)

    def pressures():
        out = []
        for n in cluster.nodes:
            if not n.alive:
                continue
            p = n.map_pressure()
            if p is not None:
                out.append(p)
        return out

    def metric_sums():
        tot = None
        for n in cluster.nodes:
            if not n.alive:
                continue
            m = n.metrics()
            if m is None:
                continue
            m = np.asarray(m, dtype=np.int64).sum(axis=1)
            tot = m if tot is None else tot + m
        return tot if tot is not None else np.zeros(1, np.int64)

    # cluster-level op stream (ISSUE 18): scenarios that declare
    # ``cluster_ops = True`` apply ops against the TIER facade
    # (epoch rotations) on the daemon driver's capped-catch-up
    # schedule.  Everything else keeps the historical contract:
    # cluster legs drive traffic only, ops stay node-local.
    cluster_ops = bool(getattr(scenario, "cluster_ops", False)) \
        and scenario.interval_s > 0
    ops = iter(scenario.ops(256) if cluster_ops else ())
    live: Dict = {}
    applied = 0
    next_op = None

    def tick_ops(elapsed: float) -> None:
        nonlocal next_op, applied
        if not cluster_ops:
            return
        if next_op is None:
            next_op = elapsed
        burst = 0
        while next_op is not None and elapsed >= next_op \
                and burst < 4:
            try:
                scenario.apply(cluster, next(ops), live)
                applied += 1
                burst += 1
                next_op += scenario.interval_s
            except StopIteration:
                next_op = None
        if next_op is not None and elapsed - next_op \
                > 64 * scenario.interval_s:
            next_op = elapsed  # drop an unservable backlog

    pace_s = float(getattr(scenario, "pace_s", 0.0))
    p0 = pressures()
    m0 = metric_sums()
    t0 = time.perf_counter()
    for i, b in enumerate(scenario.iter_batches(ep)):
        cluster.submit(b)
        tick_ops(time.perf_counter() - t0)
        # backpressure at the ROUTER: bounded forward queues are the
        # cluster-level admission point
        while cluster.forward_pending() > pending_cap:
            time.sleep(0.001)
            tick_ops(time.perf_counter() - t0)
        # paced submission (cluster_ops scenarios): hold the next
        # batch until its slot so the op schedule interleaves with
        # traffic instead of firing on a drained pipeline
        while pace_s > 0 \
                and time.perf_counter() - t0 < (i + 1) * pace_s:
            time.sleep(0.002)
            tick_ops(time.perf_counter() - t0)
    # drain the remaining op schedule (bounded) before closing the
    # ledger — a storm's declared op count is part of its contract
    deadline = t0 + 30.0
    while cluster_ops and next_op is not None \
            and time.perf_counter() < deadline:
        time.sleep(0.002)
        tick_ops(time.perf_counter() - t0)
    st = cluster.stop()
    scenario.drain(cluster, live)
    dt = max(time.perf_counter() - t0, 1e-9)
    led = st["ledger"]
    submitted = led["submitted"]
    verdicts = shed = 0
    p99 = None
    l7_sums = {"redirected": 0, "l7-allowed": 0, "l7-denied": 0,
               "l7-shed": 0, "l7-failed": 0}
    l7_exact = True
    l7_seen = False
    for node_st in st["per-node"].values():
        fe = node_st.get("front-end") or {}
        verdicts += fe.get("verdicts", 0)
        shed += fe.get("shed", 0)
        node_p99 = (fe.get("latency-us") or {}).get("p99")
        if node_p99 is not None:
            # percentiles don't merge exactly across nodes; the MAX
            # is the conservative cluster-wide read (the true p99 is
            # never worse than the worst node's)
            p99 = node_p99 if p99 is None else max(p99, node_p99)
        nl7 = node_st.get("l7")
        if nl7:
            l7_seen = True
            for k in l7_sums:
                l7_sums[k] += int(nl7.get(k, 0))
            # cluster-wide exactness = every node's pool closed its
            # own ledger (sums of exact ledgers are exact)
            l7_exact = l7_exact and bool(nl7.get("ledger-exact"))
    shed_all = (shed + led["router-overflow"]
                + led["failover-dropped"] + led["crash-dropped"]
                + led.get("crypto-dropped", 0))
    p1 = pressures()
    m1 = metric_sums()
    reason_delta = (m1 - m0) if len(m1) == len(m0) else m1

    def psum(ps, *keys):
        tot = 0
        for p in ps:
            v = p
            for k in keys:
                v = (v or {}).get(k, 0)
            tot += int(v or 0)
        return tot

    dropped = int(reason_delta[1:].sum()) if len(reason_delta) > 1 \
        else 0
    metrics = {
        "submitted": int(submitted),
        "verdicts": int(verdicts),
        "shed_frac": round(shed_all / submitted, 4) if submitted
        else 0.0,
        "sustained_pps": round(verdicts / dt, 1),
        "p99_us": p99,
        "ledger_exact": bool(led["exact"]),
        "ops_applied": applied,  # non-zero only for cluster_ops
        # scenarios; node-local op streams stay traffic-only here
        "ct_insert_drops": (psum(p1, "ct", "insert-drops")
                            - psum(p0, "ct", "insert-drops")),
        "ct_occupancy": max(
            (float((p.get("ct") or {}).get("occupancy") or 0.0)
             for p in p1), default=0.0),
        "nat_failures": (psum(p1, "nat", "failures")
                         - psum(p0, "nat", "failures")),
        "drop_frac": (round(dropped / submitted, 4)
                      if submitted else None),
        "drops_by_reason": {
            int(r): int(n) for r, n in enumerate(reason_delta)
            if r and n},
        "elapsed_s": round(dt, 3),
        "l7_redirected": l7_sums["redirected"],
        "l7_allowed": l7_sums["l7-allowed"],
        "l7_denied": l7_sums["l7-denied"],
        "l7_shed": l7_sums["l7-shed"],
        "l7_failed": l7_sums["l7-failed"],
        "l7_ledger_exact": bool(l7_seen and l7_exact),
        # epoch rotations that LANDED (len(cluster._rotations) via
        # the facade counter) — the min_rotations criterion's input
        "rotations": int(getattr(cluster, "crypto_rotations_total",
                                 lambda: 0)()),
        "cluster": {
            "mode": cluster.mode,
            "nodes": len(cluster.nodes),
            "router_overflow": led["router-overflow"],
            "failover_dropped": led["failover-dropped"],
            "crash_dropped": led["crash-dropped"],
            "crypto_dropped": led.get("crypto-dropped", 0),
        },
    }
    checks = evaluate_criteria(scenario.criteria, metrics)
    return {
        "name": scenario.name,
        "seed": scenario.seed,
        "criteria": dict(scenario.criteria),
        "metrics": metrics,
        "checks": checks,
        "passed": all(checks.values()),
    }
