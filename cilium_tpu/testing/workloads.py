"""Reusable workload scenario library (ROADMAP item 5 down payment).

bench.py's overload/paced legs drive uniform synthetic flows; real
clusters serve heavy-tailed traffic while the control plane churns
under them.  This module factors that gap into NAMED, SEEDED
scenarios: each one is a deterministic generator of driver events
that both the chaos tests and ``bench.py`` replay — same seed, same
schedule, byte for byte — with per-scenario pass criteria living in
the caller (ledger exact, oracle match, p99 bounds).

The registry is the extension point: later scenarios (SYN flood,
port scan, NAT-exhaustion ramp, endpoint connect/disconnect churn,
pcap replay — ROADMAP item 5's full list) slot in as new entries
without touching any driver.

First entry: ``identity_churn`` (ISSUE 10) — peer identities minted
and withdrawn at a fixed rate over a pool of slots, slot choice
Zipf-weighted (elephant peers churn often, mice rarely — the
heavy-tail shape SelectorCache updates see in production).  Each
mint drives BOTH incremental paths: the identity's labels join the
selecting contributions (``patch_identity``) and its /32 lands in
the ipcache (``patch_ipcache``); a withdraw unwinds both, so a
slot's traffic verdict flips with its liveness — the pre/post
oracle pair the churn chaos gate checks against.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class ChurnOp:
    """One scenario event: mint or withdraw slot ``slot``'s identity.

    ``cidr`` is the slot's /32.  Minting allocates an identity for
    the slot's labels (see :meth:`IdentityChurnScenario.slot_labels`
    — rules select them via the ``k8s:churn=yes`` convention) and
    upserts the /32; withdrawing deletes the ipcache entry and
    releases the identity.  ``t_s`` is the op's offset from the
    scenario start at the configured rate."""

    kind: str  # "mint" | "withdraw"
    slot: int
    cidr: str
    t_s: float


class IdentityChurnScenario:
    """Mint/withdraw CIDR identities at ``rate_hz``, Zipf-weighted
    over ``n_slots`` peer slots.

    Each slot alternates mint -> withdraw -> mint ... (an op on a
    live slot withdraws it, on a dead slot mints it), so the op
    stream is valid by construction and the live set follows the
    Zipf weights.  Deterministic per (seed, n_slots, zipf_a,
    rate_hz): the chaos gate and ``bench.py --churn`` replay the
    same schedule.
    """

    name = "identity_churn"

    def __init__(self, seed: int = 0, n_slots: int = 16,
                 zipf_a: float = 1.3, rate_hz: float = 200.0,
                 subnet: Tuple[int, int] = (10, 9)):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        if zipf_a <= 1.0:
            raise ValueError("zipf_a must be > 1 (Zipf exponent)")
        if rate_hz <= 0:
            raise ValueError("rate_hz must be > 0")
        self.seed = int(seed)
        self.n_slots = int(n_slots)
        self.zipf_a = float(zipf_a)
        self.rate_hz = float(rate_hz)
        self.interval_s = 1.0 / self.rate_hz
        if self.n_slots > 65534:
            raise ValueError("n_slots must fit the /16 slot space")
        a, b = subnet
        # host s+1 within the /16 (skips .0.0; (s+1) & 0xFF may be 0
        # — x.y.z.0/32 is a valid host route)
        self._cidrs = [f"{a}.{b}.{(s + 1) >> 8}.{(s + 1) & 0xFF}/32"
                       for s in range(self.n_slots)]
        # rank -> probability ~ 1/rank^a (normalized), slot i = rank
        # i+1: slot 0 is the elephant peer
        w = 1.0 / np.power(np.arange(1, self.n_slots + 1),
                           self.zipf_a)
        self._weights = w / w.sum()

    def slot_cidr(self, slot: int) -> str:
        return self._cidrs[slot]

    def slot_ip(self, slot: int) -> str:
        return self._cidrs[slot].rsplit("/", 1)[0]

    def slot_labels(self, slot: int) -> List[str]:
        """The slot identity's labels.  ``k8s:churn=yes`` is the
        selection convention: a rule with ``fromEndpoints``
        ``matchLabels {"churn": "yes"}`` admits exactly the LIVE
        slots (a dead slot's /32 resolves to identity 0 and
        default-denies) — deliberately NOT a ``fromCIDR`` rule,
        whose covering-prefix identity would admit the whole subnet
        regardless of slot liveness."""
        return [f"k8s:app=churn{slot}", "k8s:churn=yes",
                "k8s:ns=default"]

    def ops(self, n: int) -> List[ChurnOp]:
        """The first ``n`` ops of the schedule (deterministic)."""
        return list(self.iter_ops(n))

    def iter_ops(self, n: Optional[int] = None) -> Iterator[ChurnOp]:
        rng = np.random.default_rng(self.seed)
        live = [False] * self.n_slots
        i = 0
        while n is None or i < n:
            slot = int(rng.choice(self.n_slots, p=self._weights))
            kind = "withdraw" if live[slot] else "mint"
            live[slot] = not live[slot]
            yield ChurnOp(kind=kind, slot=slot,
                          cidr=self._cidrs[slot],
                          t_s=i * self.interval_s)
            i += 1

    # -- the daemon driver (chaos tests + bench share it) --------------
    def apply(self, daemon, op: ChurnOp, live: Dict[int, object]
              ) -> None:
        """Apply one op against a live daemon.  ``live`` is the
        caller's slot -> Identity map (the scenario owns the
        schedule, the caller owns the handles).

        Mint allocates the slot's labeled identity — the allocator
        observer chain applies it to the selecting contributions and
        patches its verdict row in place (``patch_identity``) — then
        upserts the slot's /32 (``patch_ipcache``).  Withdraw
        deletes the ipcache entry FIRST (no LPM entry may reference
        the row when it recycles), then releases the identity."""
        from ..labels import LabelSet

        if op.kind == "mint":
            ident = daemon.allocator.allocate(
                LabelSet.parse(*self.slot_labels(op.slot)))
            daemon.upsert_ipcache(op.cidr, ident.numeric_id,
                                  source="generated")
            live[op.slot] = ident
        else:
            ident = live.pop(op.slot, None)
            if ident is not None:
                daemon.delete_ipcache(op.cidr)
                daemon.allocator.release(ident)

    def drain(self, daemon, live: Dict[int, object]) -> None:
        """Withdraw every surviving slot — the teardown both bench
        legs and test cleanup use, so op semantics (field order,
        withdraw steps) live only here."""
        for slot in list(live):
            self.apply(daemon, ChurnOp("withdraw", slot,
                                       self.slot_cidr(slot), 0.0),
                       live)


# -- the registry ------------------------------------------------------
# name -> scenario class; later entries (ROADMAP item 5: syn_flood,
# port_scan, nat_exhaustion, endpoint_churn, pcap_replay) register
# here and become runnable by name from tests and bench
SCENARIOS = {
    IdentityChurnScenario.name: IdentityChurnScenario,
}


def make_scenario(name: str, seed: int = 0, **kw):
    """Instantiate a named scenario; unknown names list the registry
    (the bench flag's error message)."""
    cls = SCENARIOS.get(name)
    if cls is None:
        raise ValueError(
            f"unknown scenario {name!r}; registered: "
            f"{', '.join(sorted(SCENARIOS))}")
    return cls(seed=seed, **kw)
