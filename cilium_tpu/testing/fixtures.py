"""Canned worlds: policy sets + ipcache + device state for bench/demo.

The big one mirrors BASELINE.md's "10k-identity L3/L4 CIDR policy set"
config: 10k distinct identities with /32 ipcache entries, a rule set
mixing selector allows, CIDR ranges, port ranges, denies and an L7
redirect, compiled to device tensors.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..identity.allocator import CachingIdentityAllocator
from ..labels import LabelSet
from ..policy import IdentityRowMap, PolicyRepository, compile_policy
from ..policy.compiler import PolicyTensors
from ..policy.resolve import EndpointPolicy
from ..datapath.lpm import LPMTensors, compile_lpm
from ..datapath.verdict import DatapathState, build_state


@dataclass
class World:
    state: DatapathState
    policies: List[EndpointPolicy]
    ep_policy: np.ndarray
    row_map: IdentityRowMap
    ipcache: Dict[str, int]  # cidr -> numeric identity
    alloc: CachingIdentityAllocator
    repo: PolicyRepository
    tensors: PolicyTensors
    lpm: LPMTensors
    pod_ips: List[str]
    pod_ips6: List[str] = None  # v6 pods (build_world(n_v6=...))


def _pod_ip(i: int) -> str:
    return f"10.{(i >> 16) & 0xFF}.{(i >> 8) & 0xFF}.{i & 0xFF}"


def build_world(n_identities: int = 10_000, n_rules: int = 64,
                ct_capacity: int = 1 << 20, ct_shards: int = 1,
                row_capacity: Optional[int] = None,
                n_v6: int = 0) -> World:
    """The 10k-identity benchmark world (BASELINE.md config #3).

    Identities svc0..svcN-1 get /32 pod IPs; the subject endpoint (a
    "db" workload, ep 0) has ``n_rules`` ingress rules allowing slices
    of the identity space on assorted port ranges, CIDR allows, one
    deny, and one L7 redirect — so the compiled tensors exercise every
    verdict class.
    """
    alloc = CachingIdentityAllocator()
    repo = PolicyRepository(alloc)
    db = LabelSet.parse("k8s:app=db")
    alloc.allocate(db)
    world_id = alloc.allocate(LabelSet.parse("reserved:world")).numeric_id

    pod_ips: List[str] = []
    ipcache: Dict[str, int] = {}
    for i in range(n_identities):
        ident = alloc.allocate(LabelSet.parse(f"k8s:app=svc{i}",
                                              "k8s:ns=default"))
        ip = _pod_ip(i + 256)  # skip 10.0.0.x
        pod_ips.append(ip)
        ipcache[ip + "/32"] = ident.numeric_id
    ipcache["0.0.0.0/0"] = world_id

    # dual-stack pods (the wide-path benchmark's v6 sources): same
    # ns=default label space so the broad 5432 allow admits them
    pod_ips6: List[str] = []
    for i in range(n_v6):
        ident = alloc.allocate(LabelSet.parse(f"k8s:app=v6svc{i}",
                                              "k8s:ns=default"))
        ip6 = f"2001:db8::{i + 1:x}"
        pod_ips6.append(ip6)
        ipcache[ip6 + "/128"] = ident.numeric_id
    if n_v6:
        ipcache["::/0"] = world_id

    # rule set: each rule allows one "service group" label slice on a
    # port range; every identity matches ns=default so selector slices
    # use app labels
    rules: List[dict] = []
    group = max(n_identities // n_rules, 1)
    for r in range(n_rules):
        ports = [{"port": str(1000 + r * 7), "protocol": "TCP",
                  "endPort": 1000 + r * 7 + 5}]
        sel = {"matchLabels": {"app": f"svc{r * group}"}}
        rules.append({
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [
                {"fromEndpoints": [sel], "toPorts": [{"ports": ports}]},
            ],
        })
    rules.append({
        "endpointSelector": {"matchLabels": {"app": "db"}},
        "ingress": [
            # broad: everyone in the namespace may reach 5432/TCP
            {"fromEndpoints": [{"matchLabels": {"ns": "default"}}],
             "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
            {"fromCIDR": ["192.168.0.0/16"],
             "toPorts": [{"ports": [{"port": "8000", "endPort": 8999}]}]},
            {"fromEndpoints": [{"matchLabels": {"ns": "default"}}],
             "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}],
                          "rules": {"http": [{"method": "GET"}]}}]},
        ],
        "ingressDeny": [
            {"fromEndpoints": [{"matchLabels": {"app": "svc0"}}],
             "toPorts": [{"ports": [{"port": "22", "protocol": "TCP"}]}]},
        ],
        "egress": [
            {"toEntities": ["world"],
             "toPorts": [{"ports": [{"port": "53", "protocol": "UDP"}]}]},
        ],
    })
    repo.add_obj(rules)
    pol_db = repo.resolve(db)

    if row_capacity is None:
        row_capacity = 1
        while row_capacity < n_identities + n_v6 + 64:
            row_capacity *= 2
    row_map = IdentityRowMap(capacity=row_capacity)
    for ident in alloc.all_identities():
        row_map.add(ident.numeric_id)
    policies = [pol_db]
    tensors = compile_policy(policies, row_map)
    lpm = compile_lpm({c: row_map.row(i) for c, i in ipcache.items()})
    ep_policy = np.zeros(4096, dtype=np.int32)  # every ep -> db policy
    state = build_state(tensors, lpm, ep_policy, ct_capacity=ct_capacity,
                        ct_shards=ct_shards)
    return World(state=state, policies=policies, ep_policy=ep_policy,
                 row_map=row_map, ipcache=ipcache, alloc=alloc, repo=repo,
                 tensors=tensors, lpm=lpm, pod_ips=pod_ips,
                 pod_ips6=pod_ips6)


def steady_flow_pool(world: World, n_flows: int,
                     rng: np.random.Generator,
                     denied_frac: float = 0.02) -> np.ndarray:
    """A bounded pool of flows for steady-state benchmarking.

    Returns [n_flows, N_COLS] header rows (SYN) — replaying the pool
    once establishes every allowed flow in CT; subsequent draws from
    the pool are the established 95%+ of real traffic.  ``denied_frac``
    of flows target a denied port (they re-drop every time, the way
    real scans do)."""
    from ..core.packets import (COL_DPORT, COL_DST_IP3, COL_FAMILY,
                                COL_FLAGS, COL_LEN, COL_PROTO, COL_SPORT,
                                COL_SRC_IP3, N_COLS, TCP_SYN)
    import ipaddress

    out = np.zeros((n_flows, N_COLS), dtype=np.uint32)
    ips = np.array([int(ipaddress.IPv4Address(ip))
                    for ip in world.pod_ips], dtype=np.uint32)
    out[:, COL_SRC_IP3] = rng.choice(ips, n_flows)
    out[:, COL_DST_IP3] = int(ipaddress.IPv4Address(world.pod_ips[0]))
    # sports in a dedicated low range so fresh flows (high range) never
    # collide with pool flows
    out[:, COL_SPORT] = 1024 + rng.integers(0, 30000, n_flows,
                                            dtype=np.uint32)
    # 5432 (allowed for every ns=default pod) + 80 (the L7 redirect);
    # NOT 1007 — its rule admits a single service identity, so random
    # sources would mass-drop and flood the event ring
    allowed = np.array([5432, 5432, 5432, 80, 80], dtype=np.uint32)
    out[:, COL_DPORT] = rng.choice(allowed, n_flows)
    denied = rng.random(n_flows) < denied_frac
    out[:, COL_DPORT] = np.where(denied, 443, out[:, COL_DPORT])
    out[:, COL_PROTO] = 6
    out[:, COL_FLAGS] = TCP_SYN
    out[:, COL_LEN] = rng.integers(60, 1500, n_flows, dtype=np.uint32)
    out[:, COL_FAMILY] = 4
    return out


def steady_traffic(pool: np.ndarray, n: int, rng: np.random.Generator,
                   new_frac: float = 0.05) -> np.ndarray:
    """One steady-state batch: draws from the established flow pool
    (ACK data packets) with ``new_frac`` fresh connections (SYN, sport
    in the high range so they are genuinely new flows)."""
    from ..core.packets import (COL_FLAGS, COL_LEN, COL_SPORT, TCP_ACK,
                                TCP_SYN)

    rows = pool[rng.integers(0, len(pool), n)].copy()
    rows[:, COL_FLAGS] = np.where(rows[:, COL_FLAGS] == TCP_SYN, TCP_ACK,
                                  rows[:, COL_FLAGS])
    rows[:, COL_LEN] = rng.integers(60, 1500, n, dtype=np.uint32)
    fresh = rng.random(n) < new_frac
    rows[:, COL_SPORT] = np.where(
        fresh, 40000 + rng.integers(0, 20000, n, dtype=np.uint32),
        rows[:, COL_SPORT])
    rows[:, COL_FLAGS] = np.where(fresh, TCP_SYN, rows[:, COL_FLAGS])
    return rows


def wide_flow_pool(world: World, n_flows: int, rng: np.random.Generator,
                   v6_frac: float = 0.15) -> np.ndarray:
    """A dual-stack steady pool: ``v6_frac`` of the flows ride IPv6
    sources (``build_world(n_v6=...)`` pods, 128-bit addresses through
    the TCAM LPM) — the wide-path benchmark's flow universe."""
    from ..core.packets import (COL_DST_IP0, COL_FAMILY, COL_SRC_IP0,
                                ip_to_words)

    pool = steady_flow_pool(world, n_flows, rng)
    n6 = int(n_flows * v6_frac)
    if n6 and world.pod_ips6:
        idx = rng.choice(n_flows, n6, replace=False)
        v6w = np.array([ip_to_words(ip) for ip in world.pod_ips6],
                       dtype=np.uint32)
        pick = rng.integers(0, len(v6w), n6)
        cols = np.arange(4)
        pool[idx[:, None], COL_SRC_IP0 + cols] = v6w[pick]
        dst6 = np.asarray(ip_to_words("2001:db8::d:b"), dtype=np.uint32)
        pool[idx[:, None], COL_DST_IP0 + cols] = dst6[None, :]
        pool[idx, COL_FAMILY] = 6
    return pool


def wide_traffic(pool: np.ndarray, n: int, rng: np.random.Generator,
                 related_frac: float = 0.03,
                 new_frac: float = 0.05) -> np.ndarray:
    """One wide-path batch: the steady dual-stack mix plus
    ``related_frac`` ICMP destination-unreachable rows about
    established v4 pool flows (FLAG_RELATED, embedded-tuple semantics —
    the path the packed 16 B format cannot carry)."""
    from ..core.packets import COL_FAMILY, COL_FLAGS, FLAG_RELATED

    rows = steady_traffic(pool, n, rng, new_frac=new_frac)
    nrel = int(n * related_frac)
    if nrel and len(pool):
        # errors about v4 AND v6 flows (the renderer emits ICMPv4 or
        # ICMPv6 per the embedded family)
        pick = rng.integers(0, len(pool), nrel)
        idx = rng.choice(n, nrel, replace=False)
        rows[idx] = pool[pick]
        rows[idx, COL_FLAGS] = FLAG_RELATED
    return rows


def bench_traffic(world: World, n: int, rng: np.random.Generator,
                  new_flow_frac: float = 0.05) -> np.ndarray:
    """Benchmark traffic over the world's pod IPs: steady-state mix of
    established flows + a trickle of new connections (iperf-ish)."""
    from ..core.packets import (COL_DIR, COL_DPORT, COL_DST_IP3, COL_EP,
                                COL_FAMILY, COL_FLAGS, COL_LEN, COL_PROTO,
                                COL_SPORT, COL_SRC_IP3, N_COLS, TCP_ACK,
                                TCP_SYN)
    import ipaddress

    out = np.zeros((n, N_COLS), dtype=np.uint32)
    ips = np.array([int(ipaddress.IPv4Address(ip))
                    for ip in world.pod_ips], dtype=np.uint32)
    src = rng.choice(ips, n)
    dst_db = int(ipaddress.IPv4Address(world.pod_ips[0]))
    out[:, COL_SRC_IP3] = src
    out[:, COL_DST_IP3] = dst_db
    out[:, COL_SPORT] = rng.integers(1024, 61000, n, dtype=np.uint32)
    out[:, COL_DPORT] = rng.choice(
        np.array([5432, 5432, 80, 1007, 443, 8080], dtype=np.uint32), n)
    out[:, COL_PROTO] = 6
    is_new = rng.random(n) < new_flow_frac
    out[:, COL_FLAGS] = np.where(is_new, TCP_SYN, TCP_ACK)
    out[:, COL_LEN] = rng.integers(60, 1500, n, dtype=np.uint32)
    out[:, COL_FAMILY] = 4
    out[:, COL_EP] = 0
    out[:, COL_DIR] = 0
    return out
