"""The `cilium connectivity test` analogue (BASELINE config 1).

Reference: cilium-cli's ``cilium connectivity test`` deploys client/
server pods into a kind cluster, applies policy scenarios, probes the
matrix (curl/ping per scenario), and prints per-scenario pass/fail.
Here the cluster is a self-contained daemon: client/server endpoints
arrive through the k8s watcher path, each scenario imports its policy
as a CiliumNetworkPolicy, synthesizes the probe flows, runs them
through the REAL datapath (``process_batch``), and asserts the
expected verdict per probe — the same L3/L4/L7/deny/entity coverage,
minus the kubelet.

Run via ``cilium-tpu connectivity test`` or
:func:`run_connectivity_tests`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

CLIENT_IP = "10.200.1.10"
CLIENT2_IP = "10.200.1.11"
SERVER_IP = "10.200.2.10"
WORLD_IP = "198.51.100.99"
NS = "io.kubernetes.pod.namespace"


@dataclass
class Probe:
    name: str
    src: str
    dst: str
    dport: int
    expect: str  # "allow" | "deny" | "auth-then-allow"
    proto: int = 6
    direction: int = 0  # judged at the SERVER (ingress) by default
    l7_path: Optional[str] = None
    l7_expect: Optional[str] = None  # "allow" | "deny"


@dataclass
class Scenario:
    name: str
    policies: List[dict]
    probes: List[Probe]


@dataclass
class ProbeResult:
    scenario: str
    probe: str
    expected: str
    got: str
    ok: bool


def _scenarios() -> List[Scenario]:
    allow = "allow"
    deny = "deny"
    return [
        Scenario("no-policies", [], [
            Probe("client->server:8080", CLIENT_IP, SERVER_IP, 8080,
                  allow),
            Probe("client2->server:8080", CLIENT2_IP, SERVER_IP, 8080,
                  allow),
        ]),
        Scenario("client-ingress-l3", [{
            "endpointSelector": {"matchLabels": {"name": "server"}},
            "ingress": [{"fromEndpoints": [
                {"matchLabels": {"name": "client"}}]}],
        }], [
            Probe("client->server:8080", CLIENT_IP, SERVER_IP, 8080,
                  allow),
            Probe("client2-denied", CLIENT2_IP, SERVER_IP, 8080,
                  deny),
        ]),
        Scenario("client-ingress-l4", [{
            "endpointSelector": {"matchLabels": {"name": "server"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"name": "client"}}],
                "toPorts": [{"ports": [{"port": "8080",
                                        "protocol": "TCP"}]}],
            }],
        }], [
            Probe("client->server:8080", CLIENT_IP, SERVER_IP, 8080,
                  allow),
            Probe("client->server:9090-denied", CLIENT_IP, SERVER_IP,
                  9090, deny),
        ]),
        Scenario("all-ingress-deny", [{
            "endpointSelector": {"matchLabels": {"name": "server"}},
            "ingressDeny": [{}],
            "ingress": [{}],
        }], [
            Probe("client-denied", CLIENT_IP, SERVER_IP, 8080, deny),
            Probe("client2-denied", CLIENT2_IP, SERVER_IP, 8080,
                  deny),
        ]),
        Scenario("client-egress-l4", [{
            "endpointSelector": {"matchLabels": {"name": "client"}},
            "egress": [{
                "toEndpoints": [{"matchLabels": {"name": "server"}}],
                "toPorts": [{"ports": [{"port": "8080",
                                        "protocol": "TCP"}]}],
            }],
        }], [
            Probe("egress:8080", CLIENT_IP, SERVER_IP, 8080, allow,
                  direction=1),
            Probe("egress:9090-denied", CLIENT_IP, SERVER_IP, 9090,
                  deny, direction=1),
        ]),
        Scenario("to-entities-world", [{
            "endpointSelector": {"matchLabels": {"name": "client"}},
            "egress": [{"toEntities": ["world"],
                        "toPorts": [{"ports": [
                            {"port": "443",
                             "protocol": "TCP"}]}]}],
        }], [
            Probe("egress-world:443", CLIENT_IP, WORLD_IP, 443,
                  allow, direction=1),
            Probe("egress-server-denied", CLIENT_IP, SERVER_IP, 8080,
                  deny, direction=1),
        ]),
        Scenario("echo-ingress-l7", [{
            "endpointSelector": {"matchLabels": {"name": "server"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"name": "client"}}],
                "toPorts": [{
                    "ports": [{"port": "8080", "protocol": "TCP"}],
                    "rules": {"http": [{"method": "GET",
                                        "path": "/public"}]},
                }],
            }],
        }], [
            Probe("GET /public", CLIENT_IP, SERVER_IP, 8080,
                  "redirect", l7_path="/public", l7_expect="allow"),
            Probe("GET /admin-denied", CLIENT_IP, SERVER_IP, 8080,
                  "redirect", l7_path="/admin", l7_expect="deny"),
        ]),
        Scenario("echo-ingress-mutual-auth", [{
            "endpointSelector": {"matchLabels": {"name": "server"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"name": "client"}}],
                "authentication": {"mode": "required"},
            }],
        }], [
            Probe("first-connect-authenticates", CLIENT_IP, SERVER_IP,
                  8080, "auth-then-allow"),
        ]),
    ]


def _wrap_cnp(spec: dict, i: int) -> dict:
    return {"kind": "CiliumNetworkPolicy",
            "metadata": {"name": f"conn-test-{i}",
                         "namespace": "test"},
            "spec": spec}


def run_connectivity_tests(backend: str = "interpreter",
                           daemon=None) -> List[ProbeResult]:
    """Build the two-pod world, run every scenario, return results."""
    from ..agent import Daemon, DaemonConfig
    from ..core import TCP_SYN, make_batch
    from ..datapath.verdict import (REASON_AUTH_REQUIRED,
                                    REASON_FORWARDED)
    from ..policy.mapstate import (VERDICT_ALLOW, VERDICT_REDIRECT)

    d = daemon or Daemon(DaemonConfig(backend=backend,
                                      ct_capacity=1 << 12))
    hub = d.k8s_watchers()

    def pod(name: str, ip: str):
        hub.dispatch("add", {
            "kind": "Pod",
            "metadata": {"name": name, "namespace": "test",
                         "labels": {"name": name}},
            "spec": {"nodeName": d.config.node_name},
            "status": {"podIP": ip}})
        return d.endpoints.lookup_by_ip(ip)

    from ..identity import ID_WORLD

    client = pod("client", CLIENT_IP)
    client2 = pod("client2", CLIENT2_IP)
    server = pod("server", SERVER_IP)
    assert client and client2 and server, "pod watcher must attach"
    d.upsert_ipcache(f"{WORLD_IP}/32", ID_WORLD)

    results: List[ProbeResult] = []
    sport = [40000]

    def run_probe(sc: Scenario, p: Probe, now: int) -> ProbeResult:
        sport[0] += 1
        ep = server if p.direction == 0 else client
        ev = d.process_batch(make_batch([
            dict(src=p.src, dst=p.dst, sport=sport[0], dport=p.dport,
                 proto=p.proto, flags=TCP_SYN, ep=ep.id,
                 dir=p.direction)
        ]).data, now=now)
        verdict, reason = int(ev.verdict[0]), int(ev.reason[0])
        if p.expect == "auth-then-allow":
            # mutual auth: drop AUTH_REQUIRED, then the retry forwards
            first_auth = reason == REASON_AUTH_REQUIRED
            ev2 = d.process_batch(make_batch([
                dict(src=p.src, dst=p.dst, sport=sport[0],
                     dport=p.dport, proto=p.proto, flags=TCP_SYN,
                     ep=ep.id, dir=p.direction)
            ]).data, now=now + 1)
            got = ("auth-then-allow"
                   if first_auth
                   and int(ev2.reason[0]) == REASON_FORWARDED
                   else f"reason={reason},{int(ev2.reason[0])}")
            return ProbeResult(sc.name, p.name, p.expect, got,
                               got == p.expect)
        if p.expect == "redirect":
            ok = verdict == VERDICT_REDIRECT
            got = "redirect" if ok else f"verdict={verdict}"
            if ok and p.l7_path:
                verdicts = d.handle_l7_http(
                    int(ev.proxy_port[0]),
                    [{"method": "GET", "path": p.l7_path,
                      "host": "server"}],
                    src_identity=client.identity.numeric_id)
                l7got = ("allow" if int(verdicts[0]) == 1
                         else "deny")
                ok = l7got == p.l7_expect
                got = f"redirect+l7-{l7got}"
            return ProbeResult(sc.name, p.name,
                               f"redirect+l7-{p.l7_expect}", got, ok)
        allowed = (verdict in (VERDICT_ALLOW, VERDICT_REDIRECT)
                   and reason == REASON_FORWARDED)
        got = "allow" if allowed else "deny"
        return ProbeResult(sc.name, p.name, p.expect, got,
                           got == p.expect)

    now = 100
    for i, sc in enumerate(_scenarios()):
        # replace the previous scenario's policies (the cilium-cli
        # flow: apply, probe, delete)
        for j, spec in enumerate(sc.policies):
            hub.dispatch("add", _wrap_cnp(spec, j))
        for p in sc.probes:
            results.append(run_probe(sc, p, now))
            now += 2
        for j, spec in enumerate(sc.policies):
            hub.dispatch("delete", _wrap_cnp(spec, j))
        now += 100  # age out scenario CT state between scenarios
    return results


def format_results(results: List[ProbeResult]) -> str:
    lines = []
    by_sc: dict = {}
    for r in results:
        by_sc.setdefault(r.scenario, []).append(r)
    npass = sum(r.ok for r in results)
    for sc, rs in by_sc.items():
        ok = all(r.ok for r in rs)
        lines.append(f"  [{'OK' if ok else 'FAIL'}] {sc}")
        for r in rs:
            mark = "+" if r.ok else "!"
            extra = "" if r.ok else f" (expected {r.expected}, " \
                                    f"got {r.got})"
            lines.append(f"      {mark} {r.probe}{extra}")
    lines.append(f"Test Summary: {npass}/{len(results)} probes "
                 f"passed, {len(by_sc)} scenarios")
    return "\n".join(lines)
