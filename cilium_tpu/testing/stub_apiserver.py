"""Stub k8s apiserver: LIST + chunked WATCH over real HTTP.

The fake-clientset pattern (SURVEY §4) upgraded to the wire: tests
mutate the object store (:meth:`add`/:meth:`update`/:meth:`delete`)
and the stub speaks enough of the k8s API for
:class:`~cilium_tpu.k8s.informer.K8sClient` to drive a live agent —
LIST with a collection resourceVersion, ``watch=true`` streams of
ADDED/MODIFIED/DELETED JSON lines resuming from ``resourceVersion``,
and 410 Gone once history is compacted (:meth:`compact`), which
forces the client's re-LIST path.

Runs standalone too: ``python -m cilium_tpu.testing.stub_apiserver``
prints its address and serves until killed.
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

# collection path by kind (must mirror informer.DEFAULT_RESOURCES)
PATH_BY_KIND = {
    "Namespace": "/api/v1/namespaces",
    "Pod": "/api/v1/pods",
    "Service": "/api/v1/services",
    "Endpoints": "/api/v1/endpoints",
    "CiliumNetworkPolicy": "/apis/cilium.io/v2/ciliumnetworkpolicies",
    "CiliumClusterwideNetworkPolicy":
        "/apis/cilium.io/v2/ciliumclusterwidenetworkpolicies",
    "CiliumIdentity": "/apis/cilium.io/v2/ciliumidentities",
    "CiliumEndpoint": "/apis/cilium.io/v2/ciliumendpoints",
    "CiliumEndpointSlice":
        "/apis/cilium.io/v2alpha1/ciliumendpointslices",
    "CiliumEgressGatewayPolicy":
        "/apis/cilium.io/v2/ciliumegressgatewaypolicies",
    "CiliumLocalRedirectPolicy":
        "/apis/cilium.io/v2/ciliumlocalredirectpolicies",
    "CiliumNode": "/apis/cilium.io/v2/ciliumnodes",
}


def _key(obj: dict) -> str:
    meta = obj.get("metadata") or {}
    return f"{meta.get('namespace', '')}/{meta.get('name', '')}"


class StubAPIServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._lock = threading.Lock()
        self._rv = 0
        # path -> {key -> obj}
        self._objects: Dict[str, Dict[str, dict]] = {
            p: {} for p in PATH_BY_KIND.values()}
        # event log: (rv, path, type, obj); watch replays entries
        # with rv > the client's resourceVersion
        self._log: List[Tuple[int, str, str, dict]] = []
        self._log_floor = 0  # rv below which history is compacted
        self._watchers: List[queue.Queue] = []

        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                stub._handle(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.url = f"http://{host}:{self._httpd.server_address[1]}"
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    # -- test-side mutations -------------------------------------------
    def _bump(self, path: str, typ: str, obj: dict) -> dict:
        self._rv += 1
        obj = dict(obj)
        meta = dict(obj.get("metadata") or {})
        meta["resourceVersion"] = str(self._rv)
        obj["metadata"] = meta
        self._log.append((self._rv, path, typ, obj))
        for q in list(self._watchers):
            q.put((self._rv, path, typ, obj))
        return obj

    def add(self, obj: dict) -> None:
        path = PATH_BY_KIND[obj["kind"]]
        with self._lock:
            obj = self._bump(path, "ADDED", obj)
            self._objects[path][_key(obj)] = obj

    def update(self, obj: dict) -> None:
        path = PATH_BY_KIND[obj["kind"]]
        with self._lock:
            obj = self._bump(path, "MODIFIED", obj)
            self._objects[path][_key(obj)] = obj

    def delete(self, obj: dict) -> None:
        path = PATH_BY_KIND[obj["kind"]]
        with self._lock:
            obj = self._bump(path, "DELETED", obj)
            self._objects[path].pop(_key(obj), None)

    def compact(self) -> None:
        """Drop watch history (forces 410 -> client re-LIST).  Open
        watch streams get the 410 too — an apiserver that compacted
        under a live watch terminates it the same way."""
        with self._lock:
            # strictly everything-so-far: a watch resuming from any
            # rv <= the current one gets 410 (etcd compaction at now)
            self._log_floor = self._rv + 1
            self._log.clear()
            for q in list(self._watchers):
                q.put((0, None, "ERROR",
                       {"kind": "Status", "code": 410}))

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- HTTP ----------------------------------------------------------
    def _handle(self, h: BaseHTTPRequestHandler) -> None:
        u = urlparse(h.path)
        path = u.path.rstrip("/")
        q = parse_qs(u.query)
        objs = self._objects.get(path)
        if objs is None:
            h.send_response(404)
            h.send_header("Content-Length", "0")
            h.end_headers()
            return
        if q.get("watch", ["false"])[0] == "true":
            self._serve_watch(h, path,
                              int(q.get("resourceVersion", ["0"])[0]))
        else:
            self._serve_list(h, path)

    def _serve_list(self, h, path: str) -> None:
        with self._lock:
            body = json.dumps({
                "kind": "List",
                "metadata": {"resourceVersion": str(self._rv)},
                "items": list(self._objects[path].values()),
            }).encode()
        h.send_response(200)
        h.send_header("Content-Type", "application/json")
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    def _serve_watch(self, h, path: str, rv: int) -> None:
        q: queue.Queue = queue.Queue()
        with self._lock:
            if rv < self._log_floor:
                # history compacted: 410 the way etcd/apiserver does
                replay: List = [(0, path, "ERROR",
                                 {"kind": "Status", "code": 410})]
            else:
                replay = [e for e in self._log
                          if e[0] > rv and e[1] == path]
            self._watchers.append(q)
        h.send_response(200)
        h.send_header("Content-Type", "application/json")
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()

        def send(typ: str, obj: dict) -> bool:
            line = json.dumps({"type": typ, "object": obj}) + "\n"
            data = line.encode()
            try:
                h.wfile.write(f"{len(data):x}\r\n".encode() + data
                              + b"\r\n")
                h.wfile.flush()
                return True
            except OSError:
                return False

        try:
            for _rv, _path, typ, obj in replay:
                if not send(typ, obj):
                    return
                if typ == "ERROR":
                    return
            while True:
                try:
                    ev_rv, ev_path, typ, obj = q.get(timeout=1.0)
                except queue.Empty:
                    continue
                if ev_path is not None and ev_path != path:
                    continue
                if not send(typ, obj):
                    return
                if typ == "ERROR":
                    return  # 410 terminates the stream
        finally:
            with self._lock:
                if q in self._watchers:
                    self._watchers.remove(q)


def main() -> None:
    import time

    srv = StubAPIServer()
    print(json.dumps({"url": srv.url}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.close()


if __name__ == "__main__":
    main()
