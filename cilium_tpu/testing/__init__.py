"""Test support: the oracle datapath + golden packet corpora.

Reference: upstream cilium's ``pkg/datapath/fake`` (a no-kernel
Datapath/Loader) and ``bpf/tests`` golden packets — the model for the
verdict-divergence suite (BASELINE.md gate)."""

from .oracle import OracleDatapath  # noqa: F401
