"""Socket-LB analogue: connect-time service translation, cached per
flow.

Reference: upstream cilium's ``bpf_sock.c`` cgroup hooks translate a
service VIP to a backend ONCE, at ``connect(2)`` — east-west traffic
then never pays per-packet DNAT, and an established connection keeps
its backend across backend-set changes (the socket was already
rewritten).  SURVEY §2a's "Host/overlay/XDP/sock" row; this was the
one genuinely absent datapath component through r04.

TPU-first redesign: the "socket" is a FLOW here, so the connect-time
map is a CT-style open-addressing table keyed by the wire 5-tuple,
valued with the resolved (backend_ip, backend_port):

- **Established path** (the ~95%): one fingerprintless window probe +
  one row gather per packet — O(window), independent of the number of
  services.  This replaces the per-packet ``[N, S]`` frontend compare
  + Maglev of ``lb_stage``.
- **Connect path** (cache misses): miss rows COMPACT into a
  fixed-size connect buffer (cumsum + scatter — static shapes), and
  only that small buffer pays the ``[M, S]`` frontend compare +
  Maglev selection; resolutions scatter back and claim table slots
  with the same write-then-verify discipline as CT/NAT.  Non-service
  flows cache a negative entry, so they also ride the probe path.
- **Affinity**: cached flows keep their backend when the service's
  backend set changes — exactly the upstream socket semantics (and
  deliberately NOT per-packet Maglev re-selection, which would
  re-shuffle live flows on every backend change).

A batch with more than ``connect_cap`` genuinely-new flows falls back
to resolving every row (lax.cond — the full branch only EXECUTES on
such bursts, it only costs compile time otherwise).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.packets import (
    COL_DPORT,
    COL_DST_IP3,
    COL_FAMILY,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP3,
)
from . import LBTensors, lb_stage

SOCK_PROBE = 8  # claim/probe window
SOCK_DEFAULT_CAPACITY = 1 << 16
CONNECT_CAP = 1 << 13  # compacted connect-path buffer (per batch)

# lifetimes track conntrack's (a cached translation outliving its CT
# entry is harmless; one expiring under a live flow would re-resolve
# — same backend unless the set changed)
LIFETIME_TCP = 21600
LIFETIME_NONTCP = 180

ROW_WORDS = 8
SK_SRC = 0
SK_SPORT = 1
SK_VIP = 2
SK_DP = 3  # dport << 8 | proto
SK_BE_IP = 4
SK_BE_PORT = 5  # NO_BACKEND for cached "not a service" entries
SK_EXPIRES = 6
SK_PAD = 7

NO_BACKEND = 0xFFFFFFFF

# -- sessionAffinity: ClientIP sub-table (reference: the lb4/lb6
# affinity BPF maps keyed {svc, client-ip} consulted at socket-LB
# connect time).  Key here = (client src ip, frontend vip,
# dport<<8|proto); value = the pinned backend + expiry.
AFF_WORDS = 8
AF_SRC = 0
AF_VIP = 1
AF_DP = 2
AF_BE_IP = 3
AF_BE_PORT = 4
AF_EXPIRES = 5
AFF_PROBE = 8
AFF_SALT = 0x5EED_AFF1  # keyed apart from the flow-cache hash


@jax.tree_util.register_pytree_node_class
@dataclass
class SockLBTable:
    """``fp`` is the per-slot 1-byte key fingerprint (0 = free), the
    same probe diet conntrack runs (r04): the established path gathers
    the [N, K] fingerprint window (8 words/pkt) and full rows for only
    the fingerprint CANDIDATES — on TPU the full [N, K, 8-word] row
    gather measured SLOWER than a brute [N, n_services] broadcast
    compare at 512 services (random-gather bytes vs streaming
    compares); the fingerprint probe wins at any service count."""

    table: jnp.ndarray  # [P, ROW_WORDS] uint32
    fp: jnp.ndarray  # [P] uint32 — key fingerprint, 0 = free
    aff: jnp.ndarray  # [A, AFF_WORDS] uint32 ClientIP affinity rows

    @staticmethod
    def create(capacity: int = SOCK_DEFAULT_CAPACITY,
               aff_capacity: int = None) -> "SockLBTable":
        if capacity & (capacity - 1):
            raise ValueError("socklb capacity must be a power of two")
        a = aff_capacity if aff_capacity is not None else capacity
        if a & (a - 1):
            raise ValueError("affinity capacity must be a power of two")
        return SockLBTable(table=jnp.zeros((capacity, ROW_WORDS),
                                           dtype=jnp.uint32),
                           fp=jnp.zeros((capacity,), dtype=jnp.uint32),
                           aff=jnp.zeros((a, AFF_WORDS),
                                         dtype=jnp.uint32))

    @property
    def capacity(self) -> int:
        return self.table.shape[0]

    def tree_flatten(self):
        return ((self.table, self.fp, self.aff), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def prune_affinity(self, valid_backends: set) -> "SockLBTable":
        """Host-side sweep: expire affinity rows whose pinned backend
        no longer exists in ANY service (reference: upstream validates
        the affinity backend against the backend map on lookup and
        falls back to reselection).  Run on service-set changes — the
        device path deliberately skips the per-row [M, B] membership
        compare."""
        a = np.asarray(self.aff).copy()
        live = a[:, AF_EXPIRES] > 0
        if not live.any():
            return self
        packed = ((a[:, AF_BE_IP].astype(np.uint64) << 32)
                  | a[:, AF_BE_PORT].astype(np.uint64))
        valid = np.asarray(
            [(int(ip) << 32) | int(port)
             for ip, port in valid_backends], dtype=np.uint64)
        keep = np.isin(packed, valid)
        a[live & ~keep, AF_EXPIRES] = 0
        return SockLBTable(table=self.table, fp=self.fp,
                           aff=jnp.asarray(a))


def _hash(words: jnp.ndarray) -> jnp.ndarray:
    """FNV-1a over [N, 4] uint32 key words -> [N] uint32."""
    h = jnp.full(words.shape[0], 0x811C9DC5, dtype=jnp.uint32)
    for w in range(4):
        h = (h ^ words[:, w]) * jnp.uint32(0x01000193)
    return h


# the fingerprint construction is conntrack's, shared so the two
# tables can never silently diverge (key hash -> byte in 1..255,
# 0 = free marker)
from ..datapath.conntrack import _fp_mix  # noqa: E402

# full-row gathers per packet on the established path; overflow past
# this budget falls back to the full-window probe under lax.cond
SOCK_CAND = 2


def _resolve(t: LBTensors, hdr: jnp.ndarray
             ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                        jnp.ndarray, jnp.ndarray]:
    """The connect-path resolution: frontend compare + Maglev.
    -> (is_service [M], no_backend [M], be_ip [M], be_port [M],
    aff_ttl [M]) for each row.  ``no_backend`` rows matched a
    frontend that selects nothing (empty or fully-drained backend
    set) — they DROP upstream (DROP_NO_SERVICE) and are deliberately
    NOT cached, so backends appearing take effect on the very next
    batch.  ``aff_ttl`` is the matched service's sessionAffinity
    ClientIP timeout (0 = affinity off)."""
    dst = hdr[:, COL_DST_IP3]
    dport = hdr[:, COL_DPORT]
    proto = hdr[:, COL_PROTO]
    v4 = hdr[:, COL_FAMILY] == 4
    hit_s = ((dst[:, None] == t.svc_ip[None, :])
             & (dport[:, None] == t.svc_port[None, :])
             & (proto[:, None] == t.svc_proto[None, :])
             & v4[:, None])
    svc = jnp.argmax(hit_s, axis=1).astype(jnp.int32)
    hit = jnp.any(hit_s, axis=1)
    h = (hdr[:, COL_SRC_IP3] * jnp.uint32(0x9E3779B1)
         ^ hdr[:, COL_SPORT] * jnp.uint32(0x85EBCA6B)
         ^ dst * jnp.uint32(0xC2B2AE35) ^ dport ^ proto)
    slot = (h % jnp.uint32(t.m)).astype(jnp.int32)
    be = t.maglev[svc, slot]
    is_svc = hit & (be >= 0)
    no_be = hit & (be < 0)
    be_safe = jnp.maximum(be, 0)
    aff_ttl = jnp.where(hit, t.svc_aff[svc], 0).astype(jnp.uint32)
    return (is_svc, no_be, t.backend_ip[be_safe],
            t.backend_port[be_safe], aff_ttl)


def _aff_probe(aff_tbl: jnp.ndarray, src: jnp.ndarray,
               vip: jnp.ndarray, dp: jnp.ndarray, now: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Window-probe the ClientIP affinity table for (client, frontend)
    rows.  -> (found [M], row [M, AFF_WORDS], hash [M])."""
    amask = aff_tbl.shape[0] - 1
    akey = jnp.stack([src, vip, dp,
                      jnp.full_like(src, AFF_SALT)], axis=1)
    ah = _hash(akey)
    awin = ((ah[:, None] + jnp.arange(AFF_PROBE, dtype=jnp.uint32))
            & amask).astype(jnp.int32)
    arows = aff_tbl[awin]  # [M, K, W]
    amatch = ((arows[..., AF_SRC] == src[:, None])
              & (arows[..., AF_VIP] == vip[:, None])
              & (arows[..., AF_DP] == dp[:, None])
              & (arows[..., AF_EXPIRES] >= now))
    found = jnp.any(amatch, axis=1)
    col = jnp.argmax(amatch, axis=1)
    slot = jnp.take_along_axis(awin, col[:, None], axis=1)[:, 0]
    return found, aff_tbl[slot], ah


def socklb_stage(tbl: SockLBTable, t: LBTensors, hdr: jnp.ndarray,
                 now: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                            SockLBTable]:
    """Flow-cached LB: probe -> compacted connect path for misses.

    Returns (hdr', is_service_hit [N] bool, no_backend [N] bool,
    tbl') — drop-in for :func:`lb_stage` plus the threaded table.
    ``no_backend`` rows (frontend hit, nothing to select — upstream
    DROP_NO_SERVICE) ride the connect path every batch rather than
    cache: upstream's connect(2) fails without creating a socket, so
    a backend appearing must take effect immediately, not after a
    negative entry expires."""
    hdr = hdr.astype(jnp.uint32)
    n = hdr.shape[0]
    P = tbl.capacity
    mask = P - 1
    src = hdr[:, COL_SRC_IP3]
    sport = hdr[:, COL_SPORT]
    dst = hdr[:, COL_DST_IP3]
    dp = (hdr[:, COL_DPORT] << 8) | hdr[:, COL_PROTO]
    v4 = hdr[:, COL_FAMILY] == 4
    key = jnp.stack([src, sport, dst, dp], axis=1)
    h = _hash(key)
    lifetime = jnp.where(hdr[:, COL_PROTO] == 6,
                         jnp.uint32(LIFETIME_TCP),
                         jnp.uint32(LIFETIME_NONTCP))

    # -- established path: fingerprint-filtered window probe -----------
    win = ((h[:, None] + jnp.arange(SOCK_PROBE, dtype=jnp.uint32))
           & mask).astype(jnp.int32)  # [N, K]
    key_fp = _fp_mix(h)
    win_fp = tbl.fp[win]  # [N, K] — 8 words/pkt, not 64
    fmatch = win_fp == key_fp[:, None]

    def _row_match(rows):
        return ((rows[..., SK_SRC] == src[:, None])
                & (rows[..., SK_SPORT] == sport[:, None])
                & (rows[..., SK_VIP] == dst[:, None])
                & (rows[..., SK_DP] == dp[:, None])
                & (rows[..., SK_EXPIRES] >= now))

    # full rows for only the first SOCK_CAND fingerprint candidates.
    # Two argmax sweeps, NOT a [N, K] sort: XLA sorts cost ~20 ms at
    # this batch on TPU, two masked argmax reductions are ~free
    # (SOCK_CAND == 2 is baked into this construction)
    steps_i = jnp.arange(SOCK_PROBE, dtype=jnp.int32)
    i1 = jnp.argmax(fmatch, axis=1).astype(jnp.int32)
    has1 = jnp.any(fmatch, axis=1)
    f2 = fmatch & (steps_i[None, :] != i1[:, None])
    i2 = jnp.argmax(f2, axis=1).astype(jnp.int32)
    has2 = jnp.any(f2, axis=1)
    pos = jnp.stack([i1, i2], axis=1)  # [N, 2]
    cand_valid = jnp.stack([has1, has2], axis=1)
    cand_slots = jnp.take_along_axis(win, pos, axis=1)  # [N, C]
    crows = tbl.table[cand_slots]  # [N, C, W]
    cmatch = cand_valid & _row_match(crows)
    found = jnp.any(cmatch, axis=1)
    first = jnp.argmax(cmatch, axis=1)
    slot_fp = jnp.take_along_axis(cand_slots, first[:, None],
                                  axis=1)[:, 0]
    # a miss with MORE fingerprint matches than the candidate budget
    # could hide the true entry past it — rerun the full-window probe
    # (rare: ~(1/255)^2-rate events decide this branch's execution)
    overflow = ~found & (jnp.sum(fmatch, axis=1) > SOCK_CAND)

    def full_probe(_):
        wrows = tbl.table[win]  # [N, K, W]
        match = _row_match(wrows)
        f = jnp.any(match, axis=1)
        mcol = jnp.argmax(match, axis=1)
        return f, jnp.take_along_axis(win, mcol[:, None],
                                      axis=1)[:, 0]

    found, mslot = jax.lax.cond(
        jnp.any(overflow), full_probe,
        lambda _: (found, slot_fp), None)
    cached = found & v4
    mrow = tbl.table[mslot]
    c_be_ip = mrow[:, SK_BE_IP]
    c_be_port = mrow[:, SK_BE_PORT]
    # refresh on use (same row content; scatter order immaterial)
    table = tbl.table.at[jnp.where(cached, mslot, P), SK_EXPIRES].set(
        now + lifetime, mode="drop")
    fp_arr = tbl.fp

    miss = v4 & ~cached
    n_miss = jnp.sum(miss)

    def connect_compact(carry):
        table, fp_arr, aff_arr = carry
        # compact miss rows into the fixed connect buffer
        pos = jnp.where(miss, jnp.cumsum(miss) - 1, CONNECT_CAP)
        comp = jnp.zeros(CONNECT_CAP, dtype=jnp.int32).at[pos].set(
            jnp.arange(n, dtype=jnp.int32), mode="drop")
        sub = hdr[comp]
        is_svc, no_be, be_ip, be_port, aff_ttl = _resolve(t, sub)
        # sessionAffinity: a live (client, frontend) pin overrides the
        # Maglev selection (reference: lb4_affinity consulted before
        # backend selection in the sock path)
        a_src = sub[:, COL_SRC_IP3]
        a_vip = sub[:, COL_DST_IP3]  # pre-rewrite dst IS the vip
        a_dp = (sub[:, COL_DPORT] << 8) | sub[:, COL_PROTO]
        m_rows = a_src.shape[0]
        # gated: with no affinity service in the batch the probe's
        # gathers never execute (same pattern as the established
        # path's overflow cond)
        afound, arow, ah = jax.lax.cond(
            jnp.any(aff_ttl > 0),
            lambda _: _aff_probe(aff_arr, a_src, a_vip, a_dp, now),
            lambda _: (jnp.zeros(m_rows, dtype=bool),
                       jnp.zeros((m_rows, AFF_WORDS),
                                 dtype=jnp.uint32),
                       jnp.zeros(m_rows, dtype=jnp.uint32)),
            None)
        use_aff = is_svc & (aff_ttl > 0) & afound
        be_ip = jnp.where(use_aff, arow[:, AF_BE_IP], be_ip)
        be_port = jnp.where(use_aff, arow[:, AF_BE_PORT], be_port)
        # rows beyond the real miss count are duplicates of row 0 in
        # `comp` (scatter default) — mask them out of the claim
        live = jnp.arange(CONNECT_CAP, dtype=jnp.uint32) < n_miss
        be_port = jnp.where(is_svc, be_port,
                            jnp.uint32(NO_BACKEND))
        be_ip = jnp.where(is_svc, be_ip, 0)
        # claim slots (write-then-verify; lowest connect row wins a
        # contended slot, losers of the SAME tuple adopt via readback)
        ck = key[comp]
        ch = _hash(ck)
        new_row = jnp.stack([
            ck[:, 0], ck[:, 1], ck[:, 2], ck[:, 3],
            be_ip, be_port,
            (now + jnp.where((ck[:, 3] & 0xFF) == 6,
                             jnp.uint32(LIFETIME_TCP),
                             jnp.uint32(LIFETIME_NONTCP))),
            jnp.zeros(CONNECT_CAP, dtype=jnp.uint32),
        ], axis=1).astype(jnp.uint32)
        ridx = jnp.arange(CONNECT_CAP, dtype=jnp.int32)
        # no_be rows never claim a slot (no caching — see module doc)
        pending = live & ~no_be
        claim_fp = _fp_mix(ch)
        for step in range(SOCK_PROBE):
            s = ((ch + step) & mask).astype(jnp.int32)
            stored = table[s]
            same = ((stored[:, SK_SRC] == ck[:, 0])
                    & (stored[:, SK_SPORT] == ck[:, 1])
                    & (stored[:, SK_VIP] == ck[:, 2])
                    & (stored[:, SK_DP] == ck[:, 3]))
            claimable = (stored[:, SK_EXPIRES] < now) | same
            trying = pending & claimable
            rows = jnp.where(trying, s, P)
            owner = jnp.full((P + 1,), CONNECT_CAP, dtype=jnp.int32
                             ).at[rows].min(ridx, mode="drop")
            writer = trying & (owner[s] == ridx)
            wtarget = jnp.where(writer, s, P)
            table = table.at[wtarget].set(new_row, mode="drop")
            fp_arr = fp_arr.at[wtarget].set(claim_fp, mode="drop")
            back = table[s]
            won = trying & ((back[:, SK_SRC] == ck[:, 0])
                            & (back[:, SK_SPORT] == ck[:, 1])
                            & (back[:, SK_VIP] == ck[:, 2])
                            & (back[:, SK_DP] == ck[:, 3]))
            pending = pending & ~won
        # claim/refresh affinity pins for affinity-enabled service
        # rows (write-then-verify like the flow claim; a row whose
        # key already lives in the window overwrites it in place —
        # that IS the expiry refresh).  Two same-client first
        # connects in one batch: the lowest connect row's backend
        # wins the pin; see DIVERGENCES #22
        amask_c = aff_arr.shape[0] - 1
        A = aff_arr.shape[0]
        a_pending0 = live & is_svc & (aff_ttl > 0)

        def do_aff_claims(aff_arr):
            a_new = jnp.stack([
                a_src, a_vip, a_dp, be_ip, be_port, now + aff_ttl,
                jnp.zeros(CONNECT_CAP, dtype=jnp.uint32),
                jnp.zeros(CONNECT_CAP, dtype=jnp.uint32),
            ], axis=1).astype(jnp.uint32)
            a_pending = a_pending0
            for step in range(AFF_PROBE):
                s = ((ah + step) & amask_c).astype(jnp.int32)
                stored = aff_arr[s]
                same = ((stored[:, AF_SRC] == a_src)
                        & (stored[:, AF_VIP] == a_vip)
                        & (stored[:, AF_DP] == a_dp))
                claimable = (stored[:, AF_EXPIRES] < now) | same
                trying = a_pending & claimable
                rows_t = jnp.where(trying, s, A)
                owner = jnp.full((A + 1,), CONNECT_CAP,
                                 dtype=jnp.int32
                                 ).at[rows_t].min(ridx, mode="drop")
                writer = trying & (owner[s] == ridx)
                wt = jnp.where(writer, s, A)
                aff_arr = aff_arr.at[wt].set(a_new, mode="drop")
                back = aff_arr[s]
                won = trying & ((back[:, AF_SRC] == a_src)
                                & (back[:, AF_VIP] == a_vip)
                                & (back[:, AF_DP] == a_dp))
                a_pending = a_pending & ~won
            return aff_arr

        # the 8-round claim only executes when some row pins
        aff_arr = jax.lax.cond(jnp.any(a_pending0), do_aff_claims,
                               lambda x: x, aff_arr)
        # scatter resolutions back to batch rows; DEAD slots (comp
        # defaulted to row 0) must scatter out of bounds, not onto
        # row 0 — duplicate scatter indices have unspecified order
        comp_t = jnp.where(live, comp, n)
        r_ip = jnp.zeros(n, dtype=jnp.uint32).at[comp_t].set(
            be_ip, mode="drop")
        r_port = jnp.zeros(n, dtype=jnp.uint32).at[comp_t].set(
            be_port, mode="drop")
        r_svc = jnp.zeros(n, dtype=bool).at[comp_t].set(
            is_svc, mode="drop")
        r_nobe = jnp.zeros(n, dtype=bool).at[comp_t].set(
            no_be, mode="drop")
        return (table, fp_arr, aff_arr), r_ip, r_port, \
            r_svc & miss, r_nobe & miss

    def connect_full(carry):
        # burst of new flows beyond the connect buffer: resolve every
        # row (no caching for this batch — correctness over cache;
        # affinity pins are READ but not claimed)
        is_svc, no_be, be_ip, be_port, aff_ttl = _resolve(t, hdr)
        afound, arow, _ah = jax.lax.cond(
            jnp.any(aff_ttl > 0),
            lambda _: _aff_probe(carry[2], src, dst, dp, now),
            lambda _: (jnp.zeros(n, dtype=bool),
                       jnp.zeros((n, AFF_WORDS), dtype=jnp.uint32),
                       jnp.zeros(n, dtype=jnp.uint32)),
            None)
        use_aff = is_svc & (aff_ttl > 0) & afound
        be_ip = jnp.where(use_aff, arow[:, AF_BE_IP], be_ip)
        be_port = jnp.where(use_aff, arow[:, AF_BE_PORT], be_port)
        return (carry, be_ip, be_port, is_svc & miss, no_be & miss)

    (table, fp_arr, aff_arr), r_ip, r_port, r_svc, r_nobe = \
        jax.lax.cond(
            n_miss <= CONNECT_CAP, connect_compact, connect_full,
            (table, fp_arr, tbl.aff))

    svc_hit = (cached & (c_be_port != jnp.uint32(NO_BACKEND))) | r_svc
    new_dst = jnp.where(cached & (c_be_port != jnp.uint32(NO_BACKEND)), c_be_ip,
                        jnp.where(r_svc, r_ip, dst))
    new_dport = jnp.where(cached & (c_be_port != jnp.uint32(NO_BACKEND)), c_be_port,
                          jnp.where(r_svc, r_port, hdr[:, COL_DPORT]))
    hdr = hdr.at[:, COL_DST_IP3].set(new_dst)
    hdr = hdr.at[:, COL_DPORT].set(new_dport)
    return hdr, svc_hit, r_nobe, SockLBTable(table=table, fp=fp_arr,
                                             aff=aff_arr)


socklb_stage_jit = jax.jit(socklb_stage, donate_argnums=0)


def socklb_entries_from_snapshot(table: np.ndarray, now: int,
                                 limit: int = 1000) -> list:
    """Decode live flow-cache slots for `cilium-tpu bpf lb list`
    (reference: `cilium bpf lb list` over the sock rev-NAT maps).
    Negative entries (cached "not a service") report backend=None."""
    import ipaddress

    table = np.asarray(table)
    live = np.nonzero(table[:, SK_EXPIRES] >= now)[0][:limit]
    out = []
    for s in live:
        row = table[s]
        neg = int(row[SK_BE_PORT]) == NO_BACKEND
        out.append({
            "src": str(ipaddress.IPv4Address(int(row[SK_SRC]))),
            "sport": int(row[SK_SPORT]),
            "vip": str(ipaddress.IPv4Address(int(row[SK_VIP]))),
            "dport": int(row[SK_DP]) >> 8,
            "proto": int(row[SK_DP]) & 0xFF,
            "backend": (None if neg else
                        str(ipaddress.IPv4Address(int(row[SK_BE_IP])))
                        + f":{int(row[SK_BE_PORT])}"),
            "expires": int(row[SK_EXPIRES]),
        })
    return out

