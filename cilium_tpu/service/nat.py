"""NAT: egress masquerade (SNAT) with per-node port allocation.

Reference: upstream ``bpf/lib/nat.h`` + ``pkg/maps/nat`` — egress
traffic leaving the cluster is source-NATed to the node IP with a
port allocated from a per-node pool; the NAT map remembers the
translation both ways so replies reverse-translate on ingress.

TPU-first redesign of the NAT map: **the port pool IS the table
index**.  One ``[P, 6]`` tensor, where slot ``s`` owns node port
``NAT_PORT_MIN + s``:

- egress allocation = CT-style write-then-verify hash claim over the
  slot window (each claimed slot is a unique node port — collision-
  free by construction, closing DIVERGENCES #17);
- reverse translation on ingress = ONE gather (``dport - PORT_MIN``
  indexes the table directly; no reverse map, no second hash table —
  the reference needs a whole second BPF map for this direction).

Port allocation covers port-bearing protocols (TCP/UDP/SCTP); ICMP
keeps the port-preserving rewrite (its "port" is the type/id).  The
CT entry is created with the POST-NAT tuple, so replies hit CT as
REPLY on the wire tuple; the reverse stage then restores the original
destination for delivery.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP3,
    COL_FAMILY,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP3,
)


@dataclass
class NATConfig:
    """Masquerade configuration (node-level).

    ``egress_rules`` is the egress-gateway policy table (reference:
    CiliumEgressGatewayPolicy): (source pod IP, destination CIDR,
    egress IP) triples.  A matching row SNATs via its designated
    egress IP — even toward destinations the non-masquerade list
    would otherwise exempt (the policy is an explicit override).
    """

    node_ip: str
    # destinations inside these ranges keep the original source
    # (cluster-internal traffic; reference: --native-routing-cidr /
    # ipMasqAgent nonMasqueradeCIDRs)
    non_masquerade_cidrs: Tuple[str, ...] = ("10.0.0.0/8",)
    enabled: bool = True
    egress_rules: Tuple[Tuple[str, str, str], ...] = ()

    def compile(self) -> "NATTensors":
        nets = [ipaddress.ip_network(c)
                for c in self.non_masquerade_cidrs]
        nets = [n for n in nets if n.version == 4]
        k = max(len(nets), 1)
        # an EMPTY exclusion list must match nothing ("masquerade
        # everything"); a zero pad row (dst & 0 == 0) would match
        # every destination and silently disable SNAT — pad with an
        # unsatisfiable row instead (dst & 0 == 0xFFFFFFFF)
        net = np.full(k, 0xFFFFFFFF, dtype=np.uint32)
        mask = np.zeros(k, dtype=np.uint32)
        for i, n in enumerate(nets):
            net[i] = int(n.network_address)
            mask[i] = int(n.netmask)
        # egress-gateway table, padded with one unsatisfiable row
        # (src 0 never appears on the wire as a pod source)
        g = max(len(self.egress_rules), 1)
        g_src = np.zeros(g, dtype=np.uint32)
        g_net = np.full(g, 0xFFFFFFFF, dtype=np.uint32)
        g_mask = np.zeros(g, dtype=np.uint32)
        g_ip = np.zeros(g, dtype=np.uint32)
        for i, (src_ip, dst_cidr, eip) in enumerate(self.egress_rules):
            n4 = ipaddress.ip_network(dst_cidr)
            g_src[i] = int(ipaddress.IPv4Address(src_ip))
            g_net[i] = int(n4.network_address)
            g_mask[i] = int(n4.netmask)
            g_ip[i] = int(ipaddress.IPv4Address(eip))
        return NATTensors(
            node_ip=jnp.uint32(int(ipaddress.IPv4Address(self.node_ip))),
            net=jnp.asarray(net),
            mask=jnp.asarray(mask),
            egw_src=jnp.asarray(g_src),
            egw_net=jnp.asarray(g_net),
            egw_mask=jnp.asarray(g_mask),
            egw_ip=jnp.asarray(g_ip),
            enabled=self.enabled,
        )


@jax.tree_util.register_pytree_node_class
@dataclass
class NATTensors:
    node_ip: jnp.ndarray  # [] uint32
    net: jnp.ndarray  # [K] uint32 non-masquerade networks
    mask: jnp.ndarray  # [K] uint32
    egw_src: jnp.ndarray  # [G] uint32 egress-gateway source pod IPs
    egw_net: jnp.ndarray  # [G] uint32 destination networks
    egw_mask: jnp.ndarray  # [G] uint32
    egw_ip: jnp.ndarray  # [G] uint32 designated egress IPs
    enabled: bool

    def tree_flatten(self):
        return ((self.node_ip, self.net, self.mask, self.egw_src,
                 self.egw_net, self.egw_mask, self.egw_ip),
                self.enabled)

    @classmethod
    def tree_unflatten(cls, enabled, children):
        return cls(*children, enabled=enabled)


# --- the NAT table (per-node port pool) ------------------------------

NAT_PORT_MIN = 32768  # pool = [NAT_PORT_MIN, NAT_PORT_MIN + capacity)
NAT_PROBE = 8  # claim window (linear probes from the tuple hash)
NAT_DEFAULT_CAPACITY = 1 << 14  # shared by NATTable.create + mirrors

# NAT entry lifetimes track conntrack's (reference: the NAT map is
# GC'd alongside CT): a mapping outliving its flow's CT entry is
# harmless, but one that expires UNDER a live CT entry re-ports an
# idle-but-established connection mid-stream.  Refreshed on every use
# in either direction.
NAT_LIFETIME_TCP = 21600  # == conntrack.LIFETIME_TCP
NAT_LIFETIME_NONTCP = 180  # >= conntrack.LIFETIME_NONTCP (60)


def _nat_lifetime_py(proto: int) -> int:
    return NAT_LIFETIME_TCP if proto == 6 else NAT_LIFETIME_NONTCP


def _nat_hash_py(key) -> int:
    """Host FNV-1a identical to :func:`_nat_hash` — kept adjacent so a
    hash change cannot silently break TPU/interpreter port parity."""
    h = 0x811C9DC5
    for w in key:
        h = ((h ^ (w & 0xFFFFFFFF)) * 0x01000193) & 0xFFFFFFFF
    return h

NAT_ROW_WORDS = 6
NV_SRC = 0  # original source IP
NV_SPORT = 1  # original source port
NV_DST = 2  # destination IP
NV_DP = 3  # dport << 8 | proto
NV_EXPIRES = 4
NV_SNAT_IP = 5  # the IP this mapping rewrote to (0 = pre-r05: node_ip)
NV_PAD = NV_SNAT_IP  # historical alias


@jax.tree_util.register_pytree_node_class
@dataclass
class NATTable:
    """Slot ``s`` <=> node port ``NAT_PORT_MIN + s``."""

    table: jnp.ndarray  # [P, NAT_ROW_WORDS] uint32
    failed: jnp.ndarray  # [] uint32 — pool-pressure allocation failures

    @staticmethod
    def create(capacity: int = NAT_DEFAULT_CAPACITY) -> "NATTable":
        if capacity & (capacity - 1):
            raise ValueError("NAT capacity must be a power of two")
        if NAT_PORT_MIN + capacity > 65536:
            raise ValueError("NAT pool exceeds the port space")
        return NATTable(
            table=jnp.zeros((capacity, NAT_ROW_WORDS), dtype=jnp.uint32),
            failed=jnp.uint32(0))

    @property
    def capacity(self) -> int:
        return self.table.shape[0]

    def tree_flatten(self):
        return ((self.table, self.failed), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def nat_entries_from_snapshot(table: np.ndarray,
                              limit: int = 1000) -> list:
    """Decode live NAT slots for display (``cilium bpf nat list``):
    original tuple -> allocated node port (= NAT_PORT_MIN + slot)."""
    import ipaddress

    table = np.asarray(table)
    live = np.nonzero(table[:, NV_EXPIRES] > 0)[0][:limit]
    out = []
    for s in live:
        row = table[s]
        out.append({
            "node_port": int(NAT_PORT_MIN + s),
            "src": str(ipaddress.IPv4Address(int(row[NV_SRC]))),
            "sport": int(row[NV_SPORT]),
            "dst": str(ipaddress.IPv4Address(int(row[NV_DST]))),
            "dport": int(row[NV_DP]) >> 8,
            "proto": int(row[NV_DP]) & 0xFF,
            "expires": int(row[NV_EXPIRES]),
        })
    return out


def _nat_hash(words: jnp.ndarray) -> jnp.ndarray:
    """FNV-1a over [N, 4] uint32 key words -> [N] uint32."""
    h = jnp.full(words.shape[0], 0x811C9DC5, dtype=jnp.uint32)
    for w in range(4):
        h = (h ^ words[:, w]) * jnp.uint32(0x01000193)
    return h


def snat_egress(tbl: NATTable, t: NATTensors, ct, hdr: jnp.ndarray,
                now: jnp.ndarray
                ) -> Tuple[jnp.ndarray, NATTable, jnp.ndarray]:
    """Egress masquerade with port allocation.

    Port-bearing egress-to-world rows claim a slot (= unique node
    port) via the CT-style write-then-verify loop; existing mappings
    refresh in place (``claimable`` includes the row's own tuple).
    Rows whose reverse CT entry exists reply to an INBOUND connection
    and keep their source.  Pool exhaustion DROPS: the third return
    is the per-row drop mask the datapath step consumes as
    ``pre_drop`` (reference: DROP_NAT_NO_MAPPING — a port-preserving
    fallback could emit two flows with one node-side 5-tuple, exactly
    the collision SNAT exists to prevent); ``failed`` counts the
    drops as the pool-pressure signal."""
    from ..datapath.conntrack import _probe, ct_keys_from_headers

    hdr = hdr.astype(jnp.uint32)
    if not t.enabled:
        return hdr, tbl, jnp.zeros(hdr.shape[0], dtype=bool)
    P = tbl.capacity
    mask = P - 1
    src = hdr[:, COL_SRC_IP3]
    dst = hdr[:, COL_DST_IP3]
    sport = hdr[:, COL_SPORT]
    dport = hdr[:, COL_DPORT]
    proto = hdr[:, COL_PROTO]
    internal = jnp.any(
        (dst[:, None] & t.mask[None, :]) == t.net[None, :], axis=1)
    egress = hdr[:, COL_DIR] == 1
    v4 = hdr[:, COL_FAMILY] == 4
    _fwd, rev = ct_keys_from_headers(hdr)
    r_found, _slot = _probe(ct.table, rev, now)
    # egress-gateway policy: (source pod, destination CIDR) pairs
    # SNAT via their designated egress IP, overriding the
    # non-masquerade exemption (reference: CiliumEgressGatewayPolicy)
    g_hit = ((src[:, None] == t.egw_src[None, :])
             & ((dst[:, None] & t.egw_mask[None, :])
                == t.egw_net[None, :]))
    gw = jnp.any(g_hit, axis=1)
    g_first = jnp.argmax(g_hit, axis=1)
    rewrite_ip = jnp.where(gw, t.egw_ip[g_first], t.node_ip)
    masq = egress & v4 & (~internal | gw) & ~r_found
    portful = (proto == 6) | (proto == 17) | (proto == 132)
    need = masq & portful

    dp = (dport << 8) | proto
    key = jnp.stack([src, sport, dst, dp], axis=1)
    h = _nat_hash(key)
    lifetime = jnp.where(proto == 6, jnp.uint32(NAT_LIFETIME_TCP),
                         jnp.uint32(NAT_LIFETIME_NONTCP))
    expires = (now + lifetime).astype(jnp.uint32)
    n = src.shape[0]
    ridx = jnp.arange(n, dtype=jnp.int32)

    def key_match_w(rows):  # window gather [N, K, W]
        return ((rows[..., NV_SRC] == src[:, None])
                & (rows[..., NV_SPORT] == sport[:, None])
                & (rows[..., NV_DST] == dst[:, None])
                & (rows[..., NV_DP] == dp[:, None]))

    def key_match(rows):  # one row per packet [N, W]
        return ((rows[:, NV_SRC] == src)
                & (rows[:, NV_SPORT] == sport)
                & (rows[:, NV_DST] == dst)
                & (rows[:, NV_DP] == dp))

    table = tbl.table
    # phase 1: scan the WHOLE window for a live same-tuple mapping —
    # an existing allocation must win over any expired earlier slot,
    # or a live flow's node port would change mid-stream (r04 review)
    win = ((h[:, None] + jnp.arange(NAT_PROBE, dtype=jnp.uint32))
           & mask).astype(jnp.int32)  # [N, K]
    wrows = table[win]  # [N, K, W]
    live_same = (wrows[..., NV_EXPIRES] >= now) & key_match_w(wrows)
    have_match = jnp.any(live_same, axis=1)
    mcol = jnp.argmax(live_same, axis=1)
    mslot = jnp.take_along_axis(win, mcol[:, None], axis=1)[:, 0]
    # a LIVE mapping keeps the IP it was created with: an egress
    # policy added/removed mid-flow must not flip the flow's SNAT ip
    # mid-stream (same invariant phase 1 protects for the node port);
    # stored 0 = pre-upgrade row, which could only mean node_ip
    stored_ip = table[mslot][:, NV_SNAT_IP]
    stored_ip = jnp.where(stored_ip != 0, stored_ip, t.node_ip)
    rewrite_ip = jnp.where(have_match & need, stored_ip, rewrite_ip)
    new_row = jnp.stack([
        src, sport, dst, dp, expires,
        rewrite_ip,
    ], axis=1)
    # refresh matched mappings (duplicate rows of one flow write the
    # same content, so scatter order is immaterial here)
    refresh = jnp.where(need & have_match, mslot, P)
    table = table.at[refresh].set(new_row, mode="drop")

    # phase 2: claim loop.  Per step, contended slots are awarded to
    # the LOWEST batch row (scatter-min owner) so the result is
    # deterministic and equal to the interpreter mirror's
    # step-outer/row-inner order; same-tuple losers adopt the
    # winner's slot via the readback check.
    pending = need & ~have_match
    final_slot = jnp.where(have_match, mslot,
                           jnp.zeros_like(mslot))
    for step in range(NAT_PROBE):
        s = ((h + step) & mask).astype(jnp.int32)
        stored = table[s]
        same = key_match(stored)
        claimable = (stored[:, NV_EXPIRES] < now) | same
        trying = pending & claimable
        rows = jnp.where(trying, s, P)
        owner = jnp.full((P + 1,), n, dtype=jnp.int32
                         ).at[rows].min(ridx, mode="drop")
        writer = trying & (owner[s] == ridx)
        wslots = jnp.where(writer, s, P)
        table = table.at[wslots].set(new_row, mode="drop")
        back = table[s]
        won = trying & key_match(back)
        final_slot = jnp.where(won, s, final_slot)
        pending = pending & ~won

    allocated = need & ~pending
    dropped = need & pending  # exhaustion: no slot in the window
    new_port = (jnp.uint32(NAT_PORT_MIN)
                + final_slot.astype(jnp.uint32))
    hdr = hdr.at[:, COL_SRC_IP3].set(
        jnp.where(masq, rewrite_ip, src))
    hdr = hdr.at[:, COL_SPORT].set(
        jnp.where(allocated, new_port, sport))
    failed = tbl.failed + jnp.sum(dropped).astype(jnp.uint32)
    return hdr, NATTable(table=table, failed=failed), dropped


def snat_reverse(tbl: NATTable, t: NATTensors, hdr: jnp.ndarray,
                 now: jnp.ndarray) -> Tuple[jnp.ndarray, NATTable]:
    """Ingress reverse translation: ONE gather.

    A reply to ``node_ip:(NAT_PORT_MIN + s)`` whose source matches
    slot s's recorded destination restores the original
    (pod IP, pod port); everything else passes through untouched."""
    hdr = hdr.astype(jnp.uint32)
    if not t.enabled:
        return hdr, tbl
    P = tbl.capacity
    src = hdr[:, COL_SRC_IP3]
    dst = hdr[:, COL_DST_IP3]
    sport = hdr[:, COL_SPORT]
    dport = hdr[:, COL_DPORT]
    proto = hdr[:, COL_PROTO]
    ingress = hdr[:, COL_DIR] == 0
    v4 = hdr[:, COL_FAMILY] == 4
    in_pool = (dport >= NAT_PORT_MIN) & (dport < NAT_PORT_MIN + P)
    cand = jnp.where(in_pool, dport - NAT_PORT_MIN, 0).astype(jnp.int32)
    row = tbl.table[cand]
    # the reply's (src, sport) must be the mapping's (dst, dport)
    rdp = (sport << 8) | proto
    # the reply must target the IP this mapping actually rewrote to
    # (node_ip or an egress-gateway IP; 0 = a pre-upgrade snapshot row
    # that could only have used node_ip)
    row_ip = row[:, NV_SNAT_IP]
    ip_ok = jnp.where(row_ip != 0, dst == row_ip, dst == t.node_ip)
    hit = (ingress & v4 & in_pool & ip_ok
           & (row[:, NV_EXPIRES] >= now)
           & (row[:, NV_DST] == src) & (row[:, NV_DP] == rdp))
    hdr = hdr.at[:, COL_DST_IP3].set(
        jnp.where(hit, row[:, NV_SRC], dst))
    hdr = hdr.at[:, COL_DPORT].set(
        jnp.where(hit, row[:, NV_SPORT], dport))
    # refresh on use (replies keep the mapping alive, like the
    # reference's NAT entry aging)
    lifetime = jnp.where(proto == 6, jnp.uint32(NAT_LIFETIME_TCP),
                         jnp.uint32(NAT_LIFETIME_NONTCP))
    refresh_rows = jnp.where(hit, cand, P)
    table = tbl.table.at[refresh_rows, NV_EXPIRES].set(
        now + lifetime, mode="drop")
    return hdr, NATTable(table=table, failed=tbl.failed)


snat_egress_jit = jax.jit(snat_egress, donate_argnums=0)
snat_reverse_jit = jax.jit(snat_reverse, donate_argnums=0)


def nat_live_count(tbl: NATTable, now: int) -> int:
    return int(np.asarray(
        jnp.sum(tbl.table[:, NV_EXPIRES] >= jnp.uint32(now))))


def snat_stage(t: NATTensors, hdr: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Masquerade egress IPv4 leaving the cluster: src -> node IP.

    Returns (hdr', masqueraded [N] bool).  Composes after the LB
    stage and before the datapath step (the CT entry then carries the
    post-NAT tuple, which is what replies will match)."""
    from ..core.packets import COL_DST_IP3

    hdr = hdr.astype(jnp.uint32)
    if not t.enabled:
        return hdr, jnp.zeros(hdr.shape[0], dtype=bool)
    dst = hdr[:, COL_DST_IP3]
    internal = jnp.any(
        (dst[:, None] & t.mask[None, :]) == t.net[None, :], axis=1)
    egress = hdr[:, COL_DIR] == 1
    v4 = hdr[:, COL_FAMILY] == 4
    masq = egress & v4 & ~internal
    new_src = jnp.where(masq, t.node_ip, hdr[:, COL_SRC_IP3])
    hdr = hdr.at[:, COL_SRC_IP3].set(new_src)
    return hdr, masq


snat_stage_jit = jax.jit(snat_stage)
