"""NAT: egress masquerade (SNAT) schema + device stage.

Reference: upstream ``bpf/lib/nat.h`` + ``pkg/maps/nat`` — egress
traffic leaving the cluster is source-NATed to the node IP, with a
NAT map remembering the translation for reverse application on
replies.  SURVEY.md §2b keeps NAT at schema-level scope for this
rebuild; what is implemented:

- :class:`NATConfig` — masquerade prefixes (destinations that should
  NOT be masqueraded, i.e. cluster-internal ranges) + the node IP.
- :func:`snat_stage` — batched egress rewrite: src -> node IP for
  packets leaving the cluster ranges.  PORT-PRESERVING (documented
  divergence: the reference allocates a free port per flow from the
  NAT map; here source ports pass through, which is collision-free
  per node as long as local endpoints don't share sports to one
  destination — the common CNI case).
- reverse translation rides conntrack: the CT entry is created with
  the POST-NAT tuple, so replies match it and the deployment's
  ingress adapter restores the original destination from the CT
  reverse lookup.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.packets import COL_DIR, COL_FAMILY, COL_SRC_IP3


@dataclass
class NATConfig:
    """Masquerade configuration (node-level)."""

    node_ip: str
    # destinations inside these ranges keep the original source
    # (cluster-internal traffic; reference: --native-routing-cidr /
    # ipMasqAgent nonMasqueradeCIDRs)
    non_masquerade_cidrs: Tuple[str, ...] = ("10.0.0.0/8",)
    enabled: bool = True

    def compile(self) -> "NATTensors":
        nets = [ipaddress.ip_network(c)
                for c in self.non_masquerade_cidrs]
        nets = [n for n in nets if n.version == 4]
        k = max(len(nets), 1)
        # an EMPTY exclusion list must match nothing ("masquerade
        # everything"); a zero pad row (dst & 0 == 0) would match
        # every destination and silently disable SNAT — pad with an
        # unsatisfiable row instead (dst & 0 == 0xFFFFFFFF)
        net = np.full(k, 0xFFFFFFFF, dtype=np.uint32)
        mask = np.zeros(k, dtype=np.uint32)
        for i, n in enumerate(nets):
            net[i] = int(n.network_address)
            mask[i] = int(n.netmask)
        return NATTensors(
            node_ip=jnp.uint32(int(ipaddress.IPv4Address(self.node_ip))),
            net=jnp.asarray(net),
            mask=jnp.asarray(mask),
            enabled=self.enabled,
        )


@jax.tree_util.register_pytree_node_class
@dataclass
class NATTensors:
    node_ip: jnp.ndarray  # [] uint32
    net: jnp.ndarray  # [K] uint32 non-masquerade networks
    mask: jnp.ndarray  # [K] uint32
    enabled: bool

    def tree_flatten(self):
        return ((self.node_ip, self.net, self.mask), self.enabled)

    @classmethod
    def tree_unflatten(cls, enabled, children):
        return cls(*children, enabled=enabled)


def snat_stage(t: NATTensors, hdr: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Masquerade egress IPv4 leaving the cluster: src -> node IP.

    Returns (hdr', masqueraded [N] bool).  Composes after the LB
    stage and before the datapath step (the CT entry then carries the
    post-NAT tuple, which is what replies will match)."""
    from ..core.packets import COL_DST_IP3

    hdr = hdr.astype(jnp.uint32)
    if not t.enabled:
        return hdr, jnp.zeros(hdr.shape[0], dtype=bool)
    dst = hdr[:, COL_DST_IP3]
    internal = jnp.any(
        (dst[:, None] & t.mask[None, :]) == t.net[None, :], axis=1)
    egress = hdr[:, COL_DIR] == 1
    v4 = hdr[:, COL_FAMILY] == 4
    masq = egress & v4 & ~internal
    new_src = jnp.where(masq, t.node_ip, hdr[:, COL_SRC_IP3])
    hdr = hdr.at[:, COL_SRC_IP3].set(new_src)
    return hdr, masq


snat_stage_jit = jax.jit(snat_stage)
