"""Service load balancing: Maglev backend selection on device.

Reference: upstream cilium ``pkg/service`` + ``pkg/loadbalancer`` +
``pkg/maps/lbmap`` — k8s Services become frontend (VIP:port/proto) ->
backend sets, selected in-kernel via Maglev consistent hashing
(cilium 1.8+, ``bpf/lib/lb.h``), then DNAT'd.  TPU-first redesign:

- the Maglev permutation per service compiles on host (the classic
  offset/skip fill over a prime table size, default 16381 like
  upstream's ``--bpf-lb-maglev-table-size``);
- frontends compile to compare tensors, backends to a flat table;
- selection is a batched gather: ``maglev[svc, flow_hash % M]`` —
  and the DNAT rewrite is a vectorized where() over the header
  tensor, composing BEFORE the policy pipeline exactly like the
  reference's LB-before-policy ordering.

Consistent-hashing property (the reason Maglev exists): removing one
backend reassigns only ~1/B of flows; tests pin this.
"""

from __future__ import annotations

import ipaddress
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.packets import (
    COL_DPORT,
    COL_DST_IP0,
    COL_DST_IP3,
    COL_FAMILY,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP0,
    COL_SRC_IP3,
    ip_to_words,
)

M_DEFAULT = 16381  # prime; upstream --bpf-lb-maglev-table-size default


def _fnv1a64(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & ((1 << 64) - 1)
    return h


def maglev_table(backend_keys: Sequence[str], m: int = M_DEFAULT,
                 weights: Optional[Sequence[int]] = None) -> np.ndarray:
    """The classic Maglev population: each backend walks its own
    permutation (offset + j*skip mod m) claiming free slots round-
    robin until the table is full.  [m] int32 of backend indices;
    all -1 when there are no backends.

    ``weights`` (Maglev paper §3.4 / upstream's weighted
    ``bpf-lb-maglev``): per sweep, a backend claims a slot only while
    its claim count is at or below its quota ``filled * w_i / sum(w)``
    — slot share converges to w/Σw for ANY weight magnitudes (claiming
    w_i consecutive turns instead would let one large-weight backend
    fill the whole table before the next ever claimed).  Weight 0
    backends take no slots (drained)."""
    n = len(backend_keys)
    if n == 0:
        return np.full(m, -1, dtype=np.int32)
    w = (np.ones(n, dtype=np.int64) if weights is None
         else np.asarray(list(weights), dtype=np.int64))
    if len(w) != n:
        raise ValueError("weights length != backends length")
    if (w < 0).any():
        raise ValueError("negative backend weight")
    if not w.any():
        return np.full(m, -1, dtype=np.int32)  # all drained
    offsets = np.empty(n, dtype=np.int64)
    skips = np.empty(n, dtype=np.int64)
    for i, key in enumerate(backend_keys):
        kb = key.encode()
        offsets[i] = _fnv1a64(kb) % m
        skips[i] = _fnv1a64(kb + b"skip") % (m - 1) + 1
    table = np.full(m, -1, dtype=np.int32)
    next_j = np.zeros(n, dtype=np.int64)
    claims = np.zeros(n, dtype=np.int64)
    total_w = int(w.sum())
    filled = 0
    # every sweep makes progress: if no backend were behind quota,
    # summing claims[i]*total_w > filled*w[i] over i gives the
    # contradiction filled*total_w > filled*total_w
    while filled < m:
        for i in range(n):
            if w[i] == 0 or claims[i] * total_w > filled * w[i]:
                continue  # at/above quota this sweep
            # advance backend i's permutation to its next free slot
            while True:
                slot = (offsets[i] + next_j[i] * skips[i]) % m
                next_j[i] += 1
                if table[slot] < 0:
                    table[slot] = i
                    claims[i] += 1
                    filled += 1
                    break
            if filled == m:
                break
    return table


@dataclass(frozen=True)
class Backend:
    ip: str
    port: int
    weight: int = 1  # weighted Maglev fill turns (0 = drained)

    @property
    def key(self) -> str:
        return f"{self.ip}:{self.port}"


@dataclass
class Service:
    name: str
    frontend_ip: str
    frontend_port: int
    protocol: int = 6  # TCP
    backends: List[Backend] = field(default_factory=list)
    # frontend class, for display + scope bookkeeping (reference:
    # pkg/loadbalancer SVCType): ClusterIP | NodePort | ExternalIP |
    # LoadBalancer | LocalRedirect
    kind: str = "ClusterIP"
    # sessionAffinity: ClientIP timeout in seconds (0 = disabled)
    affinity_timeout: int = 0

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "frontend": f"{self.frontend_ip}:{self.frontend_port}",
            "protocol": self.protocol,
            "kind": self.kind,
            "backends": [{"ip": b.ip, "port": b.port,
                          "weight": b.weight} for b in self.backends],
            **({"sessionAffinityTimeout": self.affinity_timeout}
               if self.affinity_timeout else {}),
        }


@jax.tree_util.register_pytree_node_class
@dataclass
class LBTensors:
    """Compiled device LB state (a pytree; threads through jit)."""

    svc_ip: jnp.ndarray  # [S] uint32 frontend v4 address
    svc_port: jnp.ndarray  # [S] uint32
    svc_proto: jnp.ndarray  # [S] uint32
    maglev: jnp.ndarray  # [S, M] int32 -> backend table row (-1 none)
    backend_ip: jnp.ndarray  # [B] uint32
    backend_port: jnp.ndarray  # [B] uint32
    svc_aff: jnp.ndarray  # [S] uint32 ClientIP affinity TTL (0 = off)
    m: int

    def tree_flatten(self):
        return ((self.svc_ip, self.svc_port, self.svc_proto,
                 self.maglev, self.backend_ip, self.backend_port,
                 self.svc_aff),
                self.m)

    @classmethod
    def tree_unflatten(cls, m, children):
        return cls(*children, m=m)


def _split_hostport(s: str) -> Tuple[str, int]:
    """"ip:port" / "[v6]:port" / "v6:port" -> (ip, port)."""
    if s.startswith("["):
        host, _, port = s[1:].partition("]:")
        return host, int(port)
    host, _, port = s.rpartition(":")
    return host, int(port)


def _is_v6(ip: str) -> bool:
    return ":" in ip


@jax.tree_util.register_pytree_node_class
@dataclass
class LBTensors6:
    """Compiled V6 frontends (dual-stack services; reference:
    lb6 maps).  Word layout matches the header tensor's 4-word
    big-endian IP columns."""

    svc_ip: jnp.ndarray  # [S, 4] uint32 frontend v6 words
    svc_port: jnp.ndarray  # [S]
    svc_proto: jnp.ndarray  # [S]
    maglev: jnp.ndarray  # [S, M]
    backend_ip: jnp.ndarray  # [B, 4]
    backend_port: jnp.ndarray  # [B]
    m: int

    def tree_flatten(self):
        return ((self.svc_ip, self.svc_port, self.svc_proto,
                 self.maglev, self.backend_ip, self.backend_port),
                self.m)

    @classmethod
    def tree_unflatten(cls, m, children):
        return cls(*children, m=m)


class ServiceManager:
    """The service registry + compiler (pkg/service analogue)."""

    def __init__(self, m: int = M_DEFAULT):
        self._lock = threading.Lock()
        self._services: Dict[str, Service] = {}
        self.m = m
        self._tensors: Optional[LBTensors] = None
        self._tensors6 = None  # LBTensors6 | False ("no v6") | None
        self._version = 0  # bumps on any upsert/delete (see .version)

    def upsert(self, name: str, frontend: str, backends: Sequence[str],
               protocol: int = 6,
               weights: Optional[Sequence[int]] = None,
               kind: str = "ClusterIP",
               affinity_timeout: int = 0) -> Service:
        """``frontend``/``backends`` are "ip:port" strings;
        ``weights`` (optional, parallel to ``backends``) drive the
        weighted Maglev fill.  A service may carry ZERO backends: its
        frontend still compiles, and matching traffic DROPS with
        ``REASON_NO_SERVICE`` (upstream DROP_NO_SERVICE — a clusterIP
        with no ready endpoint, or externalTrafficPolicy=Local with no
        node-local backend, must not fall through to routing)."""
        fip, fport = _split_hostport(frontend)
        if weights is not None and len(weights) != len(backends):
            raise ValueError("weights length != backends length")
        bes = []
        for i, b in enumerate(backends):
            bip, bport = _split_hostport(b)
            bes.append(Backend(bip, bport,
                               weight=(int(weights[i])
                                       if weights is not None else 1)))
        svc = Service(name=name, frontend_ip=fip,
                      frontend_port=int(fport), protocol=protocol,
                      kind=kind, affinity_timeout=int(affinity_timeout),
                      backends=bes)
        with self._lock:
            self._services[name] = svc
            self._tensors = None
            self._tensors6 = None
            self._version += 1
        return svc

    def delete(self, name: str) -> bool:
        with self._lock:
            gone = self._services.pop(name, None) is not None
            if gone:
                self._tensors = None
                self._tensors6 = None
                self._version += 1
        return gone

    @property
    def version(self) -> int:
        """Monotone change counter — consumers holding derived state
        (the daemon's ClientIP affinity prune) compare against it."""
        with self._lock:
            return self._version

    def backend_set(self) -> set:
        """The live (ip, port) backend universe, for affinity
        pruning (a cached affinity entry steering NEW flows to a
        backend no service references must die with the backend)."""
        with self._lock:
            return {(int(ipaddress.IPv4Address(b.ip)), b.port)
                    for s in self._services.values()
                    for b in s.backends if not _is_v6(b.ip)}

    @property
    def any_affinity(self) -> bool:
        """True when any installed service pins ClientIP affinity —
        gates the daemon's prune sweep (an all-zero affinity table
        need not ride device->host on every Endpoints churn)."""
        with self._lock:
            return any(s.affinity_timeout
                       for s in self._services.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._services)

    def list(self) -> List[Service]:
        with self._lock:
            return [self._services[k]
                    for k in sorted(self._services)]

    def tensors(self) -> LBTensors:
        with self._lock:
            if self._tensors is None:
                self._tensors = self._compile()
            return self._tensors

    def tensors6(self) -> Optional[LBTensors6]:
        """Compiled V6 frontends, or None when no service carries a
        v6 frontend (the common all-v4 cluster skips the v6 pass
        entirely)."""
        with self._lock:
            if self._tensors6 is None:
                self._tensors6 = self._compile6()
            return self._tensors6 or None

    def _compile6(self):
        svcs = [self._services[k] for k in sorted(self._services)
                if _is_v6(self._services[k].frontend_ip)]
        if not svcs:
            return False  # cached "no v6" marker (None = stale)
        s = len(svcs)
        svc_ip = np.zeros((s, 4), dtype=np.uint32)
        svc_port = np.zeros(s, dtype=np.uint32)
        svc_proto = np.zeros(s, dtype=np.uint32)
        maglev = np.full((s, self.m), -1, dtype=np.int32)
        b_ip: List[Tuple[int, int, int, int]] = []
        b_port: List[int] = []
        for i, svc in enumerate(svcs):
            svc_ip[i] = ip_to_words(svc.frontend_ip)
            svc_port[i] = svc.frontend_port
            svc_proto[i] = svc.protocol
            base = len(b_ip)
            # family consistency: a v6 frontend DNATs only to v6
            # backends (k8s dual-stack slices are per-family)
            bes = [be for be in svc.backends if _is_v6(be.ip)]
            for be in bes:
                b_ip.append(ip_to_words(be.ip))
                b_port.append(be.port)
            local = maglev_table([be.key for be in bes], self.m,
                                 weights=[be.weight for be in bes])
            maglev[i] = np.where(local >= 0, local + base, -1)
        if not b_ip:
            b_ip, b_port = [(0, 0, 0, 0)], [0]
        return LBTensors6(
            svc_ip=jnp.asarray(svc_ip),
            svc_port=jnp.asarray(svc_port),
            svc_proto=jnp.asarray(svc_proto),
            maglev=jnp.asarray(maglev),
            backend_ip=jnp.asarray(np.asarray(b_ip, dtype=np.uint32)),
            backend_port=jnp.asarray(np.asarray(b_port,
                                                dtype=np.uint32)),
            m=self.m,
        )

    def _compile(self) -> LBTensors:
        svcs = [self._services[k] for k in sorted(self._services)
                if not _is_v6(self._services[k].frontend_ip)]
        s = max(len(svcs), 1)
        svc_ip = np.zeros(s, dtype=np.uint32)
        svc_port = np.zeros(s, dtype=np.uint32)
        svc_proto = np.zeros(s, dtype=np.uint32)
        svc_aff = np.zeros(s, dtype=np.uint32)
        maglev = np.full((s, self.m), -1, dtype=np.int32)
        b_ip: List[int] = []
        b_port: List[int] = []
        for i, svc in enumerate(svcs):
            svc_ip[i] = int(ipaddress.IPv4Address(svc.frontend_ip))
            svc_port[i] = svc.frontend_port
            svc_proto[i] = svc.protocol
            svc_aff[i] = svc.affinity_timeout
            base = len(b_ip)
            bes = [be for be in svc.backends if not _is_v6(be.ip)]
            for be in bes:
                b_ip.append(int(ipaddress.IPv4Address(be.ip)))
                b_port.append(be.port)
            local = maglev_table([be.key for be in bes], self.m,
                                 weights=[be.weight for be in bes])
            maglev[i] = np.where(local >= 0, local + base, -1)
        if not b_ip:
            b_ip, b_port = [0], [0]
        return LBTensors(
            svc_ip=jnp.asarray(svc_ip),
            svc_port=jnp.asarray(svc_port),
            svc_proto=jnp.asarray(svc_proto),
            maglev=jnp.asarray(maglev),
            backend_ip=jnp.asarray(np.asarray(b_ip, dtype=np.uint32)),
            backend_port=jnp.asarray(np.asarray(b_port,
                                                dtype=np.uint32)),
            svc_aff=jnp.asarray(svc_aff),
            m=self.m,
        )


def lb_stage(t: LBTensors, hdr: jnp.ndarray
             ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Batched frontend match + Maglev select + DNAT rewrite.

    Returns (hdr', is_service_hit [N] bool, no_backend [N] bool);
    hdr' has dst ip/port rewritten to the selected backend for hits.
    ``no_backend`` marks rows whose dst matched a frontend that has no
    backend — upstream drops these with DROP_NO_SERVICE (a lookup
    succeeding but selecting nothing must not fall through to
    routing).  Composes BEFORE datapath_step (reference: bpf/lib/lb.h
    runs before policy, so policy applies to the backend, not the
    VIP)."""
    hdr = hdr.astype(jnp.uint32)
    dst = hdr[:, COL_DST_IP3]
    dport = hdr[:, COL_DPORT]
    proto = hdr[:, COL_PROTO]
    v4 = hdr[:, COL_FAMILY] == 4
    # [N, S] frontend compare (S is small: services on this node)
    hit_s = ((dst[:, None] == t.svc_ip[None, :])
             & (dport[:, None] == t.svc_port[None, :])
             & (proto[:, None] == t.svc_proto[None, :])
             & v4[:, None])
    svc = jnp.argmax(hit_s, axis=1).astype(jnp.int32)
    hit = jnp.any(hit_s, axis=1)
    # per-flow hash -> Maglev slot (5-tuple, dst side is the VIP so
    # src ip/port dominate; same flow -> same backend)
    h = (hdr[:, COL_SRC_IP3] * jnp.uint32(0x9E3779B1)
         ^ hdr[:, COL_SPORT] * jnp.uint32(0x85EBCA6B)
         ^ dst * jnp.uint32(0xC2B2AE35) ^ dport ^ proto)
    slot = (h % jnp.uint32(t.m)).astype(jnp.int32)
    be = t.maglev[svc, slot]
    have_backend = hit & (be >= 0)
    no_backend = hit & (be < 0)
    be_safe = jnp.maximum(be, 0)
    new_dst = jnp.where(have_backend, t.backend_ip[be_safe], dst)
    new_dport = jnp.where(have_backend, t.backend_port[be_safe], dport)
    hdr = hdr.at[:, COL_DST_IP3].set(new_dst)
    hdr = hdr.at[:, COL_DPORT].set(new_dport)
    return hdr, have_backend, no_backend


lb_stage_jit = jax.jit(lb_stage)


def lb6_stage(t: LBTensors6, hdr: jnp.ndarray
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The V6 frontend pass: 4-word dst compare + Maglev + DNAT.

    Drop-in alongside :func:`lb_stage`/``socklb_stage`` (which judge
    v4 rows only); composes AFTER them in the daemon — each pass
    ignores the other family's rows.  V6 services ride this
    per-packet path rather than the socket-LB flow cache (the cache
    rows are v4-word-keyed; see DIVERGENCES #25)."""
    hdr = hdr.astype(jnp.uint32)
    dstw = hdr[:, COL_DST_IP0:COL_DST_IP0 + 4]
    dport = hdr[:, COL_DPORT]
    proto = hdr[:, COL_PROTO]
    hit_s = ((dstw[:, None, :] == t.svc_ip[None, :, :]).all(-1)
             & (dport[:, None] == t.svc_port[None, :])
             & (proto[:, None] == t.svc_proto[None, :])
             & (hdr[:, COL_FAMILY] == 6)[:, None])
    svc = jnp.argmax(hit_s, axis=1).astype(jnp.int32)
    hit = jnp.any(hit_s, axis=1)
    srcw = hdr[:, COL_SRC_IP0:COL_SRC_IP0 + 4]
    h = (srcw[:, 0] * jnp.uint32(0x9E3779B1)
         ^ srcw[:, 1] * jnp.uint32(0x85EBCA6B)
         ^ srcw[:, 2] * jnp.uint32(0xC2B2AE35)
         ^ srcw[:, 3] * jnp.uint32(0x27D4EB2F)
         ^ hdr[:, COL_SPORT] * jnp.uint32(0x165667B1)
         ^ dstw[:, 3] ^ dport ^ proto)
    slot = (h % jnp.uint32(t.m)).astype(jnp.int32)
    be = t.maglev[svc, slot]
    have = hit & (be >= 0)
    no_backend = hit & (be < 0)
    be_safe = jnp.maximum(be, 0)
    new_dst = jnp.where(have[:, None], t.backend_ip[be_safe], dstw)
    hdr = hdr.at[:, COL_DST_IP0:COL_DST_IP0 + 4].set(new_dst)
    hdr = hdr.at[:, COL_DPORT].set(
        jnp.where(have, t.backend_port[be_safe], dport))
    return hdr, have, no_backend


lb6_stage_jit = jax.jit(lb6_stage)
