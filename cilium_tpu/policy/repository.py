"""Policy repository: the rule list + revision counter + resolve cache.

Reference: upstream cilium ``pkg/policy/repository.go`` (``Repository``,
``AddList``/``DeleteByLabels``, revision bump on every mutation) and
``pkg/policy/distillery.go`` (``PolicyCache`` sharing one resolved
``SelectorPolicy`` across all endpoints with the same identity).

Mutations notify listeners (the endpoint manager) so affected endpoints
regenerate — the 3.3 call stack in SURVEY.md.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

from ..labels import LabelSet
from ..identity.allocator import CachingIdentityAllocator
from .api import Rule, rules_from_obj
from .resolve import EndpointPolicy, resolve_policy
from .selectorcache import SelectorCache


class PolicyRepository:
    def __init__(self, allocator: CachingIdentityAllocator,
                 selector_cache: Optional[SelectorCache] = None):
        self._lock = threading.RLock()
        self.allocator = allocator
        self.selector_cache = selector_cache or SelectorCache(allocator)
        self._rules: List[Rule] = []
        self._revision = 1
        # distillery: subject labels key -> resolved policy @ revision
        self._cache: Dict[str, EndpointPolicy] = {}
        self._listeners: List[Callable[[int], None]] = []
        # node-wide named-port MULTIMAP (name -> set of numbers) for
        # EGRESS rules, where the named port is the destination pod's
        # (reference: NamedPortMultiMap).  Endpoint churn that changes
        # bindings calls invalidate(), so cached resolutions never
        # outlive the map they resolved against.
        self.peer_named_ports_getter: Optional[
            Callable[[], Dict[str, frozenset]]] = None

    # -- mutation --------------------------------------------------------
    def add_list(self, rules: Sequence[Rule]) -> int:
        with self._lock:
            self._rules.extend(rules)
            return self._bump()

    def add_obj(self, obj) -> int:
        """Accept cilium policy-import JSON (list or single rule dict)."""
        return self.add_list(rules_from_obj(obj))

    def delete_by_labels(self, labels: Sequence[str]) -> int:
        """Delete all rules carrying every given label string."""
        want = set(labels)
        with self._lock:
            self._rules = [r for r in self._rules
                           if not want.issubset(set(r.labels))]
            return self._bump()

    def replace_all(self, rules: Sequence[Rule]) -> int:
        with self._lock:
            self._rules = list(rules)
            return self._bump()

    def _bump(self) -> int:
        self._revision += 1
        self._cache.clear()
        rev = self._revision
        for fn in list(self._listeners):
            fn(rev)
        return rev

    def invalidate(self) -> int:
        """Bump the revision without a rule change — identity churn
        makes cached resolutions stale because peer sets are frozen at
        resolve time (reference: SelectorCache identity notifications
        trigger incremental policy-map updates; here the daemon calls
        this and regenerates)."""
        with self._lock:
            return self._bump()

    def invalidate_cache(self) -> None:
        """Drop cached resolutions WITHOUT bumping the revision or
        firing listeners.  For identity churn before the daemon
        starts: the caller's own regeneration (add_endpoint triggers
        one) re-resolves with fresh peer sets, and firing listeners
        here would run one full regeneration per replayed identity at
        startup."""
        with self._lock:
            self._cache.clear()

    # -- queries ---------------------------------------------------------
    @property
    def revision(self) -> int:
        with self._lock:
            return self._revision

    def rules(self) -> List[Rule]:
        with self._lock:
            return list(self._rules)

    def on_change(self, fn: Callable[[int], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def resolve(self, subject_labels: LabelSet,
                named_ports: Optional[Dict[str, int]] = None
                ) -> EndpointPolicy:
        """Resolve (cached per subject label-set + named-port bindings
        + revision).

        ``named_ports`` is the ENDPOINT's own name->number map
        (reference: named ports resolve against the pod's container
        ports, strictly per endpoint — two endpoints naming the same
        port differently each get their own resolution); the distillery
        cache keys on it so label-identical endpoints with identical
        bindings still share one resolve."""
        key = subject_labels.sorted_key()
        if named_ports:
            key += "|np:" + ",".join(
                f"{n}={p}" for n, p in sorted(named_ports.items()))
        with self._lock:
            pol = self._cache.get(key)
            if pol is not None and pol.revision == self._revision:
                return pol
            peer_np = (self.peer_named_ports_getter()
                       if self.peer_named_ports_getter else None)
            pol = resolve_policy(self._rules, subject_labels,
                                 self.selector_cache, self.allocator,
                                 revision=self._revision,
                                 named_ports=named_ports,
                                 peer_named_ports=peer_np)
            self._cache[key] = pol
            return pol
