"""MapState: the desired per-endpoint policy-map contents + the verdict
oracle implementing eBPF lookup semantics.

Reference: upstream cilium ``pkg/policy/mapstate.go`` (``MapState``,
keys ``{identity, dport, proto, direction}`` -> entries with
deny/redirect flags) and ``bpf/lib/policy.h``'s
``__policy_can_access`` lookup order (exact -> L3-only -> L4-wildcard ->
all-wildcard, deny precedence).

Verdict semantics implemented here (and compiled into the dense tensors
by :mod:`cilium_tpu.policy.compiler`):

1. If any matching **deny** contribution covers ``(identity, proto,
   port)`` -> DENY.  (Deny always wins — reference: deny rules 1.9+.)
2. Else if any matching **allow** contribution covers it -> ALLOW, or
   REDIRECT when the allow carries L7 rules (proxy redirect).
3. Else: default-deny if any rule selects this endpoint for that
   direction, default-allow otherwise (policy enforcement "default"
   mode — reference: option.DefaultEnforcement).

``MapState.lookup`` is the **oracle** for the divergence suite: the
TPU datapath must agree with it on every packet (target <=1%,
BASELINE.md; we gate at 0%).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple

# Verdict codes surfaced by the datapath (u8 on device).
VERDICT_DEFAULT_DENY = 0
VERDICT_ALLOW = 1
VERDICT_DENY = 2
VERDICT_REDIRECT = 3

# Dense proto indices used on-device (IP proto -> dense via table).
# OTHER buckets every IP proto without port semantics (GRE, ESP, ...):
# only portless (L3) contributions can match it.
PROTO_TCP = 0
PROTO_UDP = 1
PROTO_ICMP = 2
PROTO_SCTP = 3
PROTO_OTHER = 4
PROTO_ANY = -1  # host-side wildcard marker
N_PROTO = 5

IP_PROTO_NUMBERS = {PROTO_TCP: 6, PROTO_UDP: 17, PROTO_ICMP: 1,
                    PROTO_SCTP: 132}
PROTO_BY_NAME = {"TCP": PROTO_TCP, "UDP": PROTO_UDP, "ICMP": PROTO_ICMP,
                 "SCTP": PROTO_SCTP, "ANY": PROTO_ANY}
PROTO_NAMES = {v: k for k, v in PROTO_BY_NAME.items()}
PROTO_NAMES[PROTO_OTHER] = "OTHER"

DIR_INGRESS = 0
DIR_EGRESS = 1


@dataclass(frozen=True)
class Contribution:
    """One (peer-set x port-spec) grant/deny derived from a rule.

    ``identities`` is None for an L3-wildcard peer (rule had no peer
    constraint, or explicitly selected all).  ``proto`` is a dense proto
    index or PROTO_ANY.  ``lo``/``hi`` is an inclusive dport range
    ([0, 65535] = all ports; for ICMP the range is over icmp type).

    ``selectors``/``fqdn_patterns`` record WHERE the identity set came
    from (the label selectors + fqdn matchPattern globs whose
    selections were unioned in), so identity churn can be applied
    incrementally: a new identity joins the frozen set iff it matches
    one of them (reference: L4Filter holds CachedSelectors and receives
    SelectorCache delta notifications).  CIDR-derived members are
    static (resolved by ipcache/LPM, not by labels).
    """

    is_deny: bool
    identities: Optional[FrozenSet[int]]  # None == wildcard peer
    proto: int
    lo: int
    hi: int
    redirect: bool = False
    proxy_port: int = 0
    # mutual authentication required before this grant forwards
    # (reference: api.Rule Authentication -> MapStateEntry auth type)
    auth: bool = False
    rule_label: str = ""
    selectors: Tuple = ()  # Tuple[EndpointSelector, ...]
    fqdn_patterns: Tuple[str, ...] = ()

    def covers(self, identity: int, proto: int, port: int) -> bool:
        if self.identities is not None and identity not in self.identities:
            return False
        if self.proto != PROTO_ANY and self.proto != proto:
            return False
        return self.lo <= port <= self.hi

    def selects_labels(self, labels) -> bool:
        """Would an identity with these labels belong to the peer set?
        (The incremental-membership test; wildcard peers select all.)"""
        from ..fqdn.matchpattern import matches as _pat_matches

        if self.identities is None:
            return True
        if any(sel.matches(labels) for sel in self.selectors):
            return True
        for pat in self.fqdn_patterns:
            for lab in labels:
                if lab.source == "fqdn" and _pat_matches(pat, lab.key):
                    return True
        return False


@dataclass(frozen=True)
class PolicyKey:
    """A cilium policymap-style key, for display/diff (bpf policy get)."""

    direction: int
    identity: int  # 0 == any
    proto: int  # PROTO_ANY == any
    dport_lo: int
    dport_hi: int


@dataclass(frozen=True)
class PolicyEntry:
    verdict: int
    proxy_port: int = 0
    derived_from: Tuple[str, ...] = ()


@dataclass
class MapState:
    """Desired policy state for one direction of one endpoint."""

    direction: int
    enforcing: bool  # False => default-allow (no rule selects endpoint)
    contributions: List[Contribution] = field(default_factory=list)

    def lookup(self, identity: int, proto: int, port: int
               ) -> Tuple[int, int]:
        """Oracle verdict: returns (verdict, proxy_port)."""
        v, p, _a = self.lookup_full(identity, proto, port)
        return v, p

    def lookup_full(self, identity: int, proto: int, port: int
                    ) -> Tuple[int, int, bool]:
        """(verdict, proxy_port, auth_required) — auth is the WINNING
        allow contribution's flag (denies and default verdicts never
        require auth; there is nothing to gate)."""
        allow: Optional[Contribution] = None
        for c in self.contributions:
            if not c.covers(identity, proto, port):
                continue
            if c.is_deny:
                return VERDICT_DENY, 0, False
            if allow is None or (c.redirect and not allow.redirect):
                allow = c
        if allow is not None:
            if allow.redirect:
                return VERDICT_REDIRECT, allow.proxy_port, allow.auth
            return VERDICT_ALLOW, 0, allow.auth
        if self.enforcing:
            return VERDICT_DEFAULT_DENY, 0, False
        return VERDICT_ALLOW, 0, False

    def to_entries(self) -> Dict[PolicyKey, PolicyEntry]:
        """Materialize cilium-style map entries (for CLI/diff display)."""
        out: Dict[PolicyKey, PolicyEntry] = {}
        for c in self.contributions:
            ids = sorted(c.identities) if c.identities is not None else [0]
            for ident in ids:
                key = PolicyKey(self.direction, ident, c.proto, c.lo, c.hi)
                verdict = (VERDICT_DENY if c.is_deny
                           else VERDICT_REDIRECT if c.redirect
                           else VERDICT_ALLOW)
                prev = out.get(key)
                if prev is not None and prev.verdict == VERDICT_DENY:
                    continue  # deny sticks
                out[key] = PolicyEntry(
                    verdict=verdict,
                    proxy_port=c.proxy_port,
                    derived_from=(c.rule_label,) if c.rule_label else (),
                )
        return out
