"""Policy rule schema — accepts cilium's rule JSON/YAML ~verbatim.

Reference: upstream cilium ``pkg/policy/api`` (``Rule``,
``EndpointSelector``, ``IngressRule``/``EgressRule``, ``PortRule``,
``CIDRRule``, entities, deny rules, L7 ``PortRuleHTTP``/``PortRuleDNS``).

The dict format handled by :func:`rule_from_dict` matches what
``cilium policy import`` accepts (and what a CiliumNetworkPolicy spec
carries), so reference policy sets replay unchanged — a requirement for
the verdict-divergence gate in BASELINE.md.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..labels import Label, LabelSet, SOURCE_ANY, SOURCE_RESERVED

# ---------------------------------------------------------------------------
# Selectors


@dataclass(frozen=True)
class Requirement:
    """One matchExpressions entry (k8s LabelSelectorRequirement)."""

    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: Tuple[str, ...] = ()


@dataclass(frozen=True)
class EndpointSelector:
    """Label selector over endpoint identities.

    Reference: pkg/policy/api ``EndpointSelector`` wrapping a k8s
    LabelSelector.  ``match_labels`` keys may carry a source prefix
    (``k8s:app`` / ``reserved:host``/ ``any:app``); bare keys default to
    ``any``.
    """

    match_labels: Tuple[Tuple[str, str], ...] = ()
    match_expressions: Tuple[Requirement, ...] = ()

    @staticmethod
    def from_dict(d: Optional[dict]) -> "EndpointSelector":
        if not d:
            return EndpointSelector()  # empty selector == wildcard
        ml = tuple(sorted((str(k), str(v))
                          for k, v in (d.get("matchLabels") or {}).items()))
        me = []
        for e in d.get("matchExpressions") or ():
            if e["operator"] not in ("In", "NotIn", "Exists", "DoesNotExist"):
                raise ValueError(
                    f"unknown matchExpressions operator {e['operator']!r}")
            me.append(Requirement(
                key=e["key"],
                operator=e["operator"],
                values=tuple(e.get("values") or ()),
            ))
        me = tuple(me)
        return EndpointSelector(match_labels=ml, match_expressions=me)

    @staticmethod
    def from_labels(*labels: str) -> "EndpointSelector":
        return EndpointSelector(
            match_labels=tuple(sorted(_split_kv(l) for l in labels))
        )

    @property
    def is_wildcard(self) -> bool:
        return not self.match_labels and not self.match_expressions

    def matches(self, labels: LabelSet) -> bool:
        for raw_key, value in self.match_labels:
            sel = _selector_label(raw_key, value)
            if not labels.has(sel):
                return False
        for req in self.match_expressions:
            source, key = _split_source(req.key)
            found = labels.get(source, key)
            if req.operator == "Exists":
                if found is None:
                    return False
            elif req.operator == "DoesNotExist":
                if found is not None:
                    return False
            elif req.operator == "In":
                if found is None or found.value not in req.values:
                    return False
            elif req.operator == "NotIn":
                if found is not None and found.value in req.values:
                    return False
            else:
                raise ValueError(f"unknown operator {req.operator!r}")
        return True


def _split_source(raw_key: str) -> Tuple[str, str]:
    if ":" in raw_key:
        source, key = raw_key.split(":", 1)
        return source, key
    return SOURCE_ANY, raw_key


def _split_kv(s: str) -> Tuple[str, str]:
    if "=" in s:
        k, v = s.split("=", 1)
        return k, v
    return s, ""


def _selector_label(raw_key: str, value: str) -> Label:
    source, key = _split_source(raw_key)
    return Label(source=source, key=key, value=value)


# ---------------------------------------------------------------------------
# Entities (reference: pkg/policy/api entities — named peers)

Entity = str
ENTITY_ALL = "all"
ENTITY_WORLD = "world"
ENTITY_HOST = "host"
ENTITY_CLUSTER = "cluster"
ENTITY_REMOTE_NODE = "remote-node"
ENTITY_HEALTH = "health"
ENTITY_INIT = "init"
ENTITY_KUBE_APISERVER = "kube-apiserver"
ENTITY_INGRESS = "ingress"

ENTITY_SELECTORS: Dict[str, EndpointSelector] = {
    ENTITY_ALL: EndpointSelector(),
    ENTITY_WORLD: EndpointSelector.from_labels(f"{SOURCE_RESERVED}:world"),
    ENTITY_HOST: EndpointSelector.from_labels(f"{SOURCE_RESERVED}:host"),
    ENTITY_REMOTE_NODE: EndpointSelector.from_labels(
        f"{SOURCE_RESERVED}:remote-node"),
    ENTITY_HEALTH: EndpointSelector.from_labels(f"{SOURCE_RESERVED}:health"),
    ENTITY_INIT: EndpointSelector.from_labels(f"{SOURCE_RESERVED}:init"),
    ENTITY_KUBE_APISERVER: EndpointSelector.from_labels(
        f"{SOURCE_RESERVED}:kube-apiserver"),
    ENTITY_INGRESS: EndpointSelector.from_labels(f"{SOURCE_RESERVED}:ingress"),
}


# ---------------------------------------------------------------------------
# L4 / L7


import re as _re

# k8s IANA_SVC_NAME: lowercase alnum + '-', <=15 chars, at least one
# letter, no leading/trailing/double '-'
_NAMED_PORT_RE = _re.compile(
    r"(?=.*[a-z])(?!-)(?!.*--)[a-z0-9-]{1,15}(?<!-)")


@dataclass(frozen=True)
class PortProtocol:
    """One port+protocol spec.

    ICMP semantics (deliberate, documented): for ``protocol: ICMP`` the
    ``port`` value is the **ICMP type** — the datapath carries the ICMP
    type in the dport column (core/packets.py COL_DPORT) and ICMP owns
    its own dense proto class row, so a TCP port-80 rule and an ICMP
    type-8 rule never share table entries.  The upstream ``icmps`` rule
    field (reference: api.ICMPRule, cilium 1.12+) parses into exactly
    this form.  ``protocol: ANY`` never covers ICMP (matches upstream:
    port rules expand to TCP/UDP/SCTP only)."""

    port: str  # numeric string or named port; "0" or "" == all ports
    protocol: str = "ANY"  # TCP | UDP | SCTP | ICMP | ANY
    end_port: int = 0  # inclusive range end (0 = single port)
    # exact ICMP type from an `icmps` rule; distinguishes type 0 (echo
    # reply) from the "port 0 == all" wildcard convention above
    icmp_type: Optional[int] = None

    @staticmethod
    def from_dict(d: dict) -> "PortProtocol":
        """Parse + sanitize (reference: api.Rule.Sanitize rejects bad
        ports at import time, not resolve time).  Named ports (k8s
        IANA_SVC_NAME: lowercase alphanumeric + '-', <= 15 chars, at
        least one letter) are kept symbolic and resolved against the
        endpoint port registry at resolve time."""
        port = str(d.get("port", "0"))
        end_port = int(d.get("endPort", 0))
        try:
            port_num = int(port or 0)
        except ValueError:
            if not _NAMED_PORT_RE.fullmatch(port):
                raise ValueError(
                    f"invalid port {port!r}: not numeric and not a "
                    "valid named port") from None
            if end_port:
                raise ValueError("endPort cannot combine with a named "
                                 f"port {port!r}")
            port_num = None
        if port_num is not None and not 0 <= port_num <= 65535:
            raise ValueError(f"port {port_num} out of range")
        if end_port and port_num is not None and end_port < port_num:
            raise ValueError(
                f"endPort {end_port} must be >= port {port_num}")
        protocol = str(d.get("protocol", "ANY")).upper()
        if protocol not in ("TCP", "UDP", "SCTP", "ICMP", "ANY"):
            raise ValueError(f"unknown protocol {protocol!r}")
        icmp_type = d.get("icmpType")
        if icmp_type is not None and protocol != "ICMP":
            raise ValueError(
                f"icmpType is only valid with protocol ICMP, got "
                f"{protocol!r}")
        return PortProtocol(port=port, protocol=protocol,
                            end_port=end_port,
                            icmp_type=(int(icmp_type)
                                       if icmp_type is not None else None))

    @property
    def is_named(self) -> bool:
        try:
            int(self.port or 0)
            return False
        except ValueError:
            return True

    def port_range(self, named_ports=None) -> Optional[Tuple[int, int]]:
        """Resolve to one inclusive [lo, hi] numeric port range (first
        of :meth:`port_ranges`, or None when the spec matches
        nothing)."""
        ranges = self.port_ranges(named_ports)
        return ranges[0] if ranges else None

    def port_ranges(self, named_ports=None) -> List[Tuple[int, int]]:
        """Resolve to inclusive [lo, hi] numeric port ranges.

        A named port resolves through ``named_ports`` — name -> number
        for an endpoint's own ports (ingress), or name -> iterable of
        numbers for the node-wide multimap (egress: the destination
        could be any pod, so every binding of the name gets an entry;
        reference: NamedPortMultiMap).  Unresolvable names return []
        and the spec matches nothing (policy with unknown named ports
        selects no traffic until a pod defines the name)."""
        if self.icmp_type is not None:
            return [(self.icmp_type, self.icmp_type)]
        try:
            p = int(self.port or 0)
        except ValueError:
            num = (named_ports or {}).get(self.port)
            if num is None:
                return []
            if isinstance(num, (int, str)):
                return [(int(num), int(num))]
            return [(int(n), int(n)) for n in sorted(num)]
        if p == 0:
            return [(0, 65535)]
        return [(p, self.end_port if self.end_port else p)]


@dataclass(frozen=True)
class PortRuleHTTP:
    method: str = ""
    path: str = ""
    host: str = ""
    headers: Tuple[str, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "PortRuleHTTP":
        return PortRuleHTTP(
            method=d.get("method", ""),
            path=d.get("path", ""),
            host=d.get("host", ""),
            headers=tuple(d.get("headers") or ()),
        )


@dataclass(frozen=True)
class PortRuleDNS:
    match_name: str = ""
    match_pattern: str = ""

    @staticmethod
    def from_dict(d: dict) -> "PortRuleDNS":
        return PortRuleDNS(
            match_name=d.get("matchName", ""),
            match_pattern=d.get("matchPattern", ""),
        )


@dataclass(frozen=True)
class L7Rules:
    http: Tuple[PortRuleHTTP, ...] = ()
    dns: Tuple[PortRuleDNS, ...] = ()
    kafka: Tuple[dict, ...] = ()  # schema passthrough
    # plugin protocols (proxy/registry.py): ((kind_name, (rule, ...)),
    # ...) — schema keys beyond the three built-ins pass through to
    # whatever parser plugin registered that name (reference:
    # api.PortRuleL7 "l7proto" + proxylib plugin rules)
    extra: Tuple[Tuple[str, Tuple[dict, ...]], ...] = ()

    @property
    def is_empty(self) -> bool:
        return not (self.http or self.dns or self.kafka or self.extra)

    @property
    def extra_by_name(self) -> Dict[str, Tuple[dict, ...]]:
        return dict(self.extra)

    @staticmethod
    def from_dict(d: Optional[dict]) -> "L7Rules":
        if not d:
            return L7Rules()
        d = dict(d)
        # upstream api.PortRuleL7 spells plugin rules as
        # {"l7proto": "<parser>", "l7": [rule, ...]}; normalize to the
        # keyed-by-parser form
        proto_name = d.pop("l7proto", None)
        l7_list = d.pop("l7", None)
        extra_items: dict = {}
        if proto_name:
            extra_items[str(proto_name)] = list(l7_list or ())
        for k, v in d.items():
            if k in ("http", "dns", "kafka") or not v:
                continue
            if not isinstance(v, (list, tuple)):
                raise ValueError(
                    f"L7 rules for {k!r} must be a list of rule "
                    f"objects, got {type(v).__name__}")
            extra_items.setdefault(str(k), []).extend(v)
        extra = tuple(
            (k, tuple(dict(x) for x in rules))
            for k, rules in sorted(extra_items.items()) if rules)
        return L7Rules(
            http=tuple(PortRuleHTTP.from_dict(x) for x in d.get("http") or ()),
            dns=tuple(PortRuleDNS.from_dict(x) for x in d.get("dns") or ()),
            kafka=tuple(dict(x) for x in d.get("kafka") or ()),
            extra=extra,
        )


@dataclass(frozen=True)
class PortRule:
    ports: Tuple[PortProtocol, ...] = ()
    rules: L7Rules = field(default_factory=L7Rules)

    @staticmethod
    def from_dict(d: dict) -> "PortRule":
        return PortRule(
            ports=tuple(PortProtocol.from_dict(p) for p in d.get("ports") or ()),
            rules=L7Rules.from_dict(d.get("rules")),
        )


def _icmp_port_rules(icmps) -> Tuple[PortRule, ...]:
    """Upstream ``icmps`` field -> PortRules with protocol ICMP.

    Reference schema (api.ICMPRule): ``[{fields: [{type: 8, family:
    "IPv4"}]}]``.  ICMPv4 and ICMPv6 share one dense proto class here
    (compiler.make_proto_table maps both 1 and 58 to PROTO_ICMP), so
    family only validates."""
    out = []
    for icmp in icmps or ():
        ports = []
        for f in icmp.get("fields") or ():
            fam = str(f.get("family", "IPv4"))
            if fam not in ("IPv4", "IPv6", "4", "6"):
                raise ValueError(f"unknown ICMP family {fam!r}")
            t = int(f.get("type", 0))
            if not 0 <= t <= 255:
                raise ValueError(f"ICMP type {t} out of range")
            ports.append(PortProtocol(port=str(t), protocol="ICMP",
                                      icmp_type=t))
        if ports:
            out.append(PortRule(ports=tuple(ports)))
    return tuple(out)


# ---------------------------------------------------------------------------
# CIDR


@dataclass(frozen=True)
class CIDRRule:
    cidr: str
    except_cidrs: Tuple[str, ...] = ()

    @staticmethod
    def from_obj(obj) -> "CIDRRule":
        if isinstance(obj, str):
            return CIDRRule(cidr=obj)
        if obj.get("cidrGroupRef"):
            # like toServices: silently dropping the ref would leave
            # the entry peer-less (an L3 wildcard).  The k8s layer
            # expands group refs against the live CiliumCIDRGroup
            # cache (upstream pkg/policy api CIDRGroupRef).
            raise ValueError(
                "cidrGroupRef must be expanded against the "
                "CiliumCIDRGroup cache: import the policy as a "
                "CiliumNetworkPolicy through the k8s watcher path")
        return CIDRRule(
            cidr=obj["cidr"],
            except_cidrs=tuple(obj.get("except") or ()),
        )


def _fqdn_from_obj(obj) -> str:
    """One toFQDNs entry -> name or glob pattern string.

    Reference: api.FQDNSelector has matchName (exact) and matchPattern
    (glob, ``*`` wildcards).  Patterns keep their ``*`` and are matched
    under the per-label grammar (fqdn/matchpattern.py) against
    observed fqdn labels at resolve time.
    """
    if isinstance(obj, str):
        return obj
    name = obj.get("matchName")
    if name:
        return name
    pattern = obj.get("matchPattern")
    if pattern:
        return pattern
    raise ValueError(f"toFQDNs entry needs matchName or matchPattern: {obj}")


# ---------------------------------------------------------------------------
# Ingress / Egress rules

AUTH_MODES = ("", "required", "disabled")


def _auth_mode(d: dict) -> str:
    """Rule-level mutual authentication (reference: api.Rule
    Authentication, cilium 1.14+ pkg/auth): ``required`` gates the
    entry's allows behind a live authmap entry; ``disabled``
    explicitly opts out.  Unknown modes are rejected — silently
    ignoring one would drop the operator's auth requirement."""
    auth = d.get("authentication")
    if not auth:
        return ""
    mode = str(auth.get("mode", ""))
    if mode not in AUTH_MODES:
        raise ValueError(f"unknown authentication mode {mode!r}")
    return mode


@dataclass(frozen=True)
class IngressRule:
    from_endpoints: Tuple[EndpointSelector, ...] = ()
    from_cidr: Tuple[CIDRRule, ...] = ()
    from_entities: Tuple[Entity, ...] = ()
    to_ports: Tuple[PortRule, ...] = ()
    auth_mode: str = ""  # "" | "required" | "disabled"

    @staticmethod
    def from_dict(d: dict) -> "IngressRule":
        return IngressRule(
            auth_mode=_auth_mode(d),
            from_endpoints=tuple(EndpointSelector.from_dict(s)
                                 for s in d.get("fromEndpoints") or ()),
            from_cidr=tuple(CIDRRule.from_obj(c)
                            for c in (d.get("fromCIDR") or ())) +
                      tuple(CIDRRule.from_obj(c)
                            for c in (d.get("fromCIDRSet") or ())),
            from_entities=tuple(d.get("fromEntities") or ()),
            to_ports=tuple(PortRule.from_dict(p)
                           for p in d.get("toPorts") or ()) +
                     _icmp_port_rules(d.get("icmps")),
        )

    @property
    def peer_is_wildcard(self) -> bool:
        """True when no L3 peer constraint at all (L4-only rule)."""
        return not (self.from_endpoints or self.from_cidr or self.from_entities)


@dataclass(frozen=True)
class EgressRule:
    to_endpoints: Tuple[EndpointSelector, ...] = ()
    to_cidr: Tuple[CIDRRule, ...] = ()
    to_entities: Tuple[Entity, ...] = ()
    to_ports: Tuple[PortRule, ...] = ()
    to_fqdns: Tuple[str, ...] = ()
    auth_mode: str = ""  # "" | "required" | "disabled"

    @staticmethod
    def from_dict(d: dict) -> "EgressRule":
        if d.get("toServices"):
            # silently ignoring this key would turn the entry into an
            # L3 WILDCARD (allow-to-everything) — the opposite of the
            # author's intent.  Upstream's k8s layer translates
            # toServices to toCIDRSet against the live service cache
            # (pkg/k8s TranslateToServicesRule); ours does too.
            raise ValueError(
                "toServices must be expanded against the k8s service "
                "cache: import the policy as a CiliumNetworkPolicy "
                "through the k8s watcher path")
        return EgressRule(
            auth_mode=_auth_mode(d),
            to_endpoints=tuple(EndpointSelector.from_dict(s)
                               for s in d.get("toEndpoints") or ()),
            to_cidr=tuple(CIDRRule.from_obj(c)
                          for c in (d.get("toCIDR") or ())) +
                    tuple(CIDRRule.from_obj(c)
                          for c in (d.get("toCIDRSet") or ())),
            to_entities=tuple(d.get("toEntities") or ()),
            to_ports=tuple(PortRule.from_dict(p)
                           for p in d.get("toPorts") or ()) +
                     _icmp_port_rules(d.get("icmps")),
            to_fqdns=tuple(_fqdn_from_obj(f) for f in (d.get("toFQDNs")
                                                       or ())),
        )

    @property
    def peer_is_wildcard(self) -> bool:
        return not (self.to_endpoints or self.to_cidr or self.to_entities
                    or self.to_fqdns)


# ---------------------------------------------------------------------------
# Rule


@dataclass(frozen=True)
class Rule:
    """One policy rule (reference: pkg/policy/api ``Rule``).

    ``endpoint_selector`` picks the *subject* endpoints; ingress/egress
    lists grant traffic; the deny variants (reference: 1.9+ deny rules)
    take precedence over any allow at the same or broader scope.
    """

    endpoint_selector: EndpointSelector
    ingress: Tuple[IngressRule, ...] = ()
    egress: Tuple[EgressRule, ...] = ()
    ingress_deny: Tuple[IngressRule, ...] = ()
    egress_deny: Tuple[EgressRule, ...] = ()
    labels: Tuple[str, ...] = ()
    description: str = ""

    @property
    def enables_ingress(self) -> bool:
        return bool(self.ingress or self.ingress_deny)

    @property
    def enables_egress(self) -> bool:
        return bool(self.egress or self.egress_deny)


def rule_from_dict(d: dict) -> Rule:
    sel = d.get("endpointSelector")
    if sel is None and "nodeSelector" in d:
        sel = d["nodeSelector"]
    return Rule(
        endpoint_selector=EndpointSelector.from_dict(sel),
        ingress=tuple(IngressRule.from_dict(r) for r in d.get("ingress") or ()),
        egress=tuple(EgressRule.from_dict(r) for r in d.get("egress") or ()),
        ingress_deny=tuple(IngressRule.from_dict(r)
                           for r in d.get("ingressDeny") or ()),
        egress_deny=tuple(EgressRule.from_dict(r)
                          for r in d.get("egressDeny") or ()),
        labels=tuple(str(l) for l in d.get("labels") or ()),
        description=d.get("description", ""),
    )


def rules_from_obj(obj) -> List[Rule]:
    """Accept a single rule dict, a list of rules, or a
    CiliumNetworkPolicy object (`cilium policy import` takes all
    three; CNPs route through the k8s translation layer)."""
    if isinstance(obj, dict):
        if obj.get("kind") in ("CiliumNetworkPolicy",
                               "CiliumClusterwideNetworkPolicy"):
            from ..k8s import rules_from_cnp

            return rules_from_cnp(obj)
        return [rule_from_dict(obj)]
    out: List[Rule] = []
    for d in obj:
        out.extend(rules_from_obj(d))
    return out


# ---------------------------------------------------------------------------
# Serialization (GET /policy renders the repository back as JSON)


def _selector_to_dict(sel: EndpointSelector) -> dict:
    d: dict = {}
    if sel.match_labels:
        d["matchLabels"] = {k: v for k, v in sel.match_labels}
    if sel.match_expressions:
        d["matchExpressions"] = [
            {"key": r.key, "operator": r.operator,
             **({"values": list(r.values)} if r.values else {})}
            for r in sel.match_expressions]
    return d


def _ports_to_dict(pr: PortRule) -> dict:
    d: dict = {"ports": [
        {"port": p.port, "protocol": p.protocol,
         **({"endPort": p.end_port} if p.end_port else {}),
         # extension key so exact ICMP types (esp. type 0) survive the
         # serialize -> import round trip (checkpoint saves rules as
         # JSON); absent for plain port rules, ignored by upstream
         **({"icmpType": p.icmp_type} if p.icmp_type is not None else {})}
        for p in pr.ports]}
    rules: dict = {}
    if pr.rules.http:
        rules["http"] = [
            {k: v for k, v in (("method", h.method), ("path", h.path),
                               ("host", h.host)) if v}
            for h in pr.rules.http]
    if pr.rules.dns:
        rules["dns"] = [
            {k: v for k, v in (("matchName", x.match_name),
                               ("matchPattern", x.match_pattern)) if v}
            for x in pr.rules.dns]
    if pr.rules.kafka:
        rules["kafka"] = [dict(x) for x in pr.rules.kafka]
    if rules:
        d["rules"] = rules
    return d


def _ingress_to_dict(r: IngressRule) -> dict:
    d: dict = {}
    if r.from_endpoints:
        d["fromEndpoints"] = [_selector_to_dict(s) for s in r.from_endpoints]
    if r.from_cidr:
        d["fromCIDRSet"] = [
            {"cidr": c.cidr,
             **({"except": list(c.except_cidrs)} if c.except_cidrs else {})}
            for c in r.from_cidr]
    if r.from_entities:
        d["fromEntities"] = list(r.from_entities)
    if r.to_ports:
        d["toPorts"] = [_ports_to_dict(p) for p in r.to_ports]
    if r.auth_mode:
        d["authentication"] = {"mode": r.auth_mode}
    return d


def _egress_to_dict(r: EgressRule) -> dict:
    d: dict = {}
    if r.to_endpoints:
        d["toEndpoints"] = [_selector_to_dict(s) for s in r.to_endpoints]
    if r.to_cidr:
        d["toCIDRSet"] = [
            {"cidr": c.cidr,
             **({"except": list(c.except_cidrs)} if c.except_cidrs else {})}
            for c in r.to_cidr]
    if r.to_entities:
        d["toEntities"] = list(r.to_entities)
    if r.to_fqdns:
        d["toFQDNs"] = [
            ({"matchPattern": f} if "*" in f else {"matchName": f})
            for f in r.to_fqdns]
    if r.to_ports:
        d["toPorts"] = [_ports_to_dict(p) for p in r.to_ports]
    if r.auth_mode:
        d["authentication"] = {"mode": r.auth_mode}
    return d


def rule_to_dict(rule: Rule) -> dict:
    d: dict = {"endpointSelector": _selector_to_dict(rule.endpoint_selector)}
    if rule.ingress:
        d["ingress"] = [_ingress_to_dict(r) for r in rule.ingress]
    if rule.ingress_deny:
        d["ingressDeny"] = [_ingress_to_dict(r) for r in rule.ingress_deny]
    if rule.egress:
        d["egress"] = [_egress_to_dict(r) for r in rule.egress]
    if rule.egress_deny:
        d["egressDeny"] = [_egress_to_dict(r) for r in rule.egress_deny]
    if rule.labels:
        d["labels"] = list(rule.labels)
    if rule.description:
        d["description"] = rule.description
    return d
