"""Incremental policy updates: identity churn -> tensor row patches.

Reference: upstream cilium's SelectorCache notifies L4Filters of
identity deltas and the endpoint applies *incremental* policy-map
updates (``pkg/policy/mapstate.go`` ``ApplyPolicyMapChanges``) — it
never recompiles the map on identity churn.  TPU-first equivalent
(SURVEY.md §7 hard part #3): an identity add/remove patches ONE row of
the device verdict tensor (``verdict.at[:, :, row, :].set(vals)``) and
one LPM slot, under the loader lock, with no retrace, no full
``compile_policy``, and no full upload.

Two pieces:

- :func:`update_contributions` — apply the delta to the resolved
  policies' frozen peer sets (via the live selectors each contribution
  carries), keeping the oracle/MapState view consistent with the
  patched tensors.
- :func:`compose_row` — compute the [n_pol, 2, n_classes] verdict
  vector for one identity row, mirroring the full compiler's
  precedence (plain allows, then redirects, then denies) exactly; a
  test asserts equality with ``compile_policy`` output.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence

import numpy as np

from .compiler import (ClassStructure, IdentityRowMap, PolicyTensors,
                       class_structure, ensure_identity_rows,
                       pack_entry, packed_scatter_order, paint_policy)
from .mapstate import (
    N_PROTO,
    PROTO_ANY,
    VERDICT_ALLOW,
    VERDICT_DEFAULT_DENY,
    VERDICT_DENY,
    VERDICT_REDIRECT,
)
from .resolve import EndpointPolicy


def update_contributions(policies: Sequence[EndpointPolicy], kind: str,
                         numeric_id: int, labels) -> bool:
    """Apply one identity add/remove to the resolved policies in place.

    Membership is re-evaluated from each contribution's live selectors
    (``Contribution.selects_labels``); the frozen ``identities`` sets
    are swapped for updated ones.  Returns True when any contribution
    changed (i.e. the identity's verdict row differs from the default
    row and a tensor patch is needed)."""
    changed = False
    for pol in policies:
        for ms in (pol.ingress, pol.egress):
            for i, c in enumerate(ms.contributions):
                if c.identities is None:
                    continue
                if kind == "add":
                    if (numeric_id not in c.identities
                            and c.selects_labels(labels)):
                        ms.contributions[i] = replace(
                            c, identities=c.identities | {numeric_id})
                        changed = True
                else:
                    if numeric_id in c.identities:
                        ms.contributions[i] = replace(
                            c, identities=c.identities - {numeric_id})
                        changed = True
    return changed


@dataclass
class DeltaPlan:
    """The outcome of :func:`delta_compile`: which policy rows must
    repaint, their freshly painted slices, and the (possibly
    unchanged) class structure.  The loader applies the plan as
    per-row ``.at[pi].set`` device patches off the dispatch path and
    paints the host mirror only AFTER the generation flip — a failed
    build must leave both the published tables and their mirrors
    untouched."""

    changed: List[int]  # policy rows whose fingerprints differ
    slices: Dict[int, np.ndarray]  # pi -> [2, n_rows, width] paint
    struct: ClassStructure
    # True when the GLOBAL partition moved (a changed policy added or
    # removed port boundaries): port_class/class_map must re-upload;
    # False reuses the active device arrays byte-for-byte
    class_structure_changed: bool
    policy_index: Dict[str, int] = field(default_factory=dict)

    def apply_structure(self, old: PolicyTensors) -> PolicyTensors:
        """The successor host mirror: SHARES ``old.verdict`` (the
        caller painted ``slices`` into it post-publish) and carries
        the plan's class structure."""
        return PolicyTensors(
            proto_table=old.proto_table,
            port_class=self.struct.port_class,
            n_classes=self.struct.n_classes,
            verdict=old.verdict,
            policy_index=self.policy_index,
            row_map=old.row_map,
            class_intervals=self.struct.class_intervals,
            class_map=self.struct.class_map,
        )


def delta_compile(old: PolicyTensors,
                  policies: Sequence[EndpointPolicy],
                  row_map: IdentityRowMap,
                  fps_old: Optional[Sequence[tuple]],
                  fps_new: Sequence[tuple],
                  class_pad: int = 128) -> Optional[DeltaPlan]:
    """Plan an attach that repaints ONLY the policies whose
    fingerprints changed (selector churn, rule edits), reusing every
    unchanged policy's verdict slice from the previous attach.

    The r05 per-policy class compaction makes this sound: a policy's
    verdict slice addresses its own LOCAL classes, which depend only
    on its own port boundaries — all inside the fingerprint — so an
    unchanged fingerprint implies a byte-identical slice (a property
    test pins this against :func:`~.compiler.compile_policy`).

    Returns None (caller falls back to a full compile) when the
    shapes cannot be reused: policy count changed, a different row
    map, row capacity grew (a new identity spilled past the headroom),
    or the widest policy outgrew the tensor's local-class padding.
    """
    if old is None or fps_old is None:
        return None
    if len(policies) != len(fps_old):
        return None
    if old.verdict.shape[0] != len(policies):
        return None
    if row_map is not old.row_map:
        return None
    if row_map.capacity != old.verdict.shape[2]:
        return None
    changed = [i for i, (a, b) in enumerate(zip(fps_old, fps_new))
               if a != b]
    # rows for any newly referenced identities; growth past the
    # tensor's row capacity forces the full path (the add itself is
    # harmless either way — full compile redoes it idempotently)
    ensure_identity_rows(policies, row_map)
    if row_map.capacity != old.verdict.shape[2]:
        return None
    struct = class_structure(policies, class_pad)
    width = old.verdict.shape[3]
    if struct.n_local_padded > width:
        return None  # widest policy outgrew the local-class padding
    class_structure_changed = (
        struct.class_map.shape != old.class_map.shape
        or not np.array_equal(struct.class_map, old.class_map)
        or not np.array_equal(struct.port_class, old.port_class))
    slices = {pi: paint_policy(policies[pi], pi, struct, row_map,
                               width=width)
              for pi in changed}
    policy_index = {p.subject_labels.sorted_key(): i
                    for i, p in enumerate(policies)}
    return DeltaPlan(changed=changed, slices=slices, struct=struct,
                     class_structure_changed=class_structure_changed,
                     policy_index=policy_index)


def compose_row(policies: Sequence[EndpointPolicy], numeric_id: int,
                tensors: PolicyTensors) -> np.ndarray:
    """Verdict vector [n_pol, 2, n_local_padded] for ONE identity.

    Must stay the per-row mirror of ``compile_policy``'s scatter order:
    default fill, plain allows, redirects (reversed: first covering
    redirect's port wins), denies last.  Classes are the PER-POLICY
    local classes (compiler class_map): global classes mapped through
    the policy's row of the map."""
    n_cls = tensors.verdict.shape[3]
    out = np.zeros((len(policies), 2, n_cls), dtype=np.int32)

    for pi, pol in enumerate(policies):
        cmap = tensors.class_map[pi]

        def classes_for(proto: int, lo: int, hi: int) -> np.ndarray:
            return np.unique(
                cmap[tensors.port_class[proto, lo:hi + 1]])

        for di, ms in ((0, pol.ingress), (1, pol.egress)):
            default = (pack_entry(VERDICT_DEFAULT_DENY) if ms.enforcing
                       else pack_entry(VERDICT_ALLOW))
            out[pi, di, :] = default
            for c, val in packed_scatter_order(ms):
                if (c.identities is not None
                        and numeric_id not in c.identities):
                    continue
                protos = (range(N_PROTO) if c.proto == PROTO_ANY
                          else [c.proto])
                cls = np.unique(np.concatenate(
                    [classes_for(p, c.lo, c.hi) for p in protos]))
                out[pi, di, cls] = val
    return out
