"""Incremental policy updates: identity churn -> tensor row patches.

Reference: upstream cilium's SelectorCache notifies L4Filters of
identity deltas and the endpoint applies *incremental* policy-map
updates (``pkg/policy/mapstate.go`` ``ApplyPolicyMapChanges``) — it
never recompiles the map on identity churn.  TPU-first equivalent
(SURVEY.md §7 hard part #3): an identity add/remove patches ONE row of
the device verdict tensor (``verdict.at[:, :, row, :].set(vals)``) and
one LPM slot, under the loader lock, with no retrace, no full
``compile_policy``, and no full upload.

Two pieces:

- :func:`update_contributions` — apply the delta to the resolved
  policies' frozen peer sets (via the live selectors each contribution
  carries), keeping the oracle/MapState view consistent with the
  patched tensors.
- :func:`compose_row` — compute the [n_pol, 2, n_classes] verdict
  vector for one identity row, mirroring the full compiler's
  precedence (plain allows, then redirects, then denies) exactly; a
  test asserts equality with ``compile_policy`` output.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Dict, List, Sequence

import numpy as np

from .compiler import (PolicyTensors, pack_entry,
                       packed_scatter_order)
from .mapstate import (
    N_PROTO,
    PROTO_ANY,
    VERDICT_ALLOW,
    VERDICT_DEFAULT_DENY,
    VERDICT_DENY,
    VERDICT_REDIRECT,
)
from .resolve import EndpointPolicy


def update_contributions(policies: Sequence[EndpointPolicy], kind: str,
                         numeric_id: int, labels) -> bool:
    """Apply one identity add/remove to the resolved policies in place.

    Membership is re-evaluated from each contribution's live selectors
    (``Contribution.selects_labels``); the frozen ``identities`` sets
    are swapped for updated ones.  Returns True when any contribution
    changed (i.e. the identity's verdict row differs from the default
    row and a tensor patch is needed)."""
    changed = False
    for pol in policies:
        for ms in (pol.ingress, pol.egress):
            for i, c in enumerate(ms.contributions):
                if c.identities is None:
                    continue
                if kind == "add":
                    if (numeric_id not in c.identities
                            and c.selects_labels(labels)):
                        ms.contributions[i] = replace(
                            c, identities=c.identities | {numeric_id})
                        changed = True
                else:
                    if numeric_id in c.identities:
                        ms.contributions[i] = replace(
                            c, identities=c.identities - {numeric_id})
                        changed = True
    return changed


def compose_row(policies: Sequence[EndpointPolicy], numeric_id: int,
                tensors: PolicyTensors) -> np.ndarray:
    """Verdict vector [n_pol, 2, n_local_padded] for ONE identity.

    Must stay the per-row mirror of ``compile_policy``'s scatter order:
    default fill, plain allows, redirects (reversed: first covering
    redirect's port wins), denies last.  Classes are the PER-POLICY
    local classes (compiler class_map): global classes mapped through
    the policy's row of the map."""
    n_cls = tensors.verdict.shape[3]
    out = np.zeros((len(policies), 2, n_cls), dtype=np.int32)

    for pi, pol in enumerate(policies):
        cmap = tensors.class_map[pi]

        def classes_for(proto: int, lo: int, hi: int) -> np.ndarray:
            return np.unique(
                cmap[tensors.port_class[proto, lo:hi + 1]])

        for di, ms in ((0, pol.ingress), (1, pol.egress)):
            default = (pack_entry(VERDICT_DEFAULT_DENY) if ms.enforcing
                       else pack_entry(VERDICT_ALLOW))
            out[pi, di, :] = default
            for c, val in packed_scatter_order(ms):
                if (c.identities is not None
                        and numeric_id not in c.identities):
                    continue
                protos = (range(N_PROTO) if c.proto == PROTO_ANY
                          else [c.proto])
                cls = np.unique(np.concatenate(
                    [classes_for(p, c.lo, c.hi) for p in protos]))
                out[pi, di, cls] = val
    return out
