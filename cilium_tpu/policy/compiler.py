"""MapState -> dense device tensors (the "policymap" of the TPU datapath).

Reference: upstream cilium ``pkg/maps/policymap`` (the kernel-side
policy map the agent syncs MapState into) and ``bpf/lib/policy.h``'s
lookup.  TPU-first redesign: instead of a sparse hash map probed with
wildcard fallbacks, ALL precedence (deny > redirect > allow > default,
L3-only vs L4 wildcards) is resolved at **compile time** on the host
into a dense verdict tensor, so the device hot path is two gathers:

    class   = port_class[proto_idx, dport]          # [N_PROTO, 65536]
    packed  = verdict[policy_row, dir, id_row, class]

``packed`` (int32) encodes ``verdict | proxy_port << 8``.

Identity axis: numeric identities are remapped to dense rows by
:class:`IdentityRowMap` (row 0 = unknown), with power-of-two capacity
headroom so identity churn patches rows instead of reshaping tensors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..identity import Identity
from .mapstate import (
    Contribution,
    MapState,
    N_PROTO,
    PROTO_ANY,
    PROTO_ICMP,
    PROTO_OTHER,
    PROTO_SCTP,
    PROTO_TCP,
    PROTO_UDP,
    VERDICT_ALLOW,
    VERDICT_DEFAULT_DENY,
    VERDICT_DENY,
    VERDICT_REDIRECT,
)
from .resolve import EndpointPolicy

VERDICT_MASK = 0xFF
PROXY_SHIFT = 8
PROXY_MASK = 0xFFFF
AUTH_SHIFT = 24  # bit 24: mutual-auth-required (pkg/auth analogue)


def pack_entry(verdict: int, proxy_port: int = 0,
               auth: bool = False) -> int:
    return ((verdict & VERDICT_MASK) | (proxy_port << PROXY_SHIFT)
            | (int(bool(auth)) << AUTH_SHIFT))


def unpack_verdict(packed: np.ndarray) -> np.ndarray:
    return packed & VERDICT_MASK


def unpack_proxy(packed: np.ndarray) -> np.ndarray:
    return (packed >> PROXY_SHIFT) & PROXY_MASK


def unpack_auth(packed: np.ndarray) -> np.ndarray:
    return (packed >> AUTH_SHIFT) & 1


def packed_scatter_order(ms):
    """(contribution, packed value) pairs in SCATTER order.

    Both the full compile and the incremental ``compose_row`` write
    with last-writer-wins scatters, while the oracle's winner is the
    FIRST covering contribution of its precedence class (with
    redirects beating plain allows) — so each class iterates
    REVERSED, and denies go last.  ONE definition so the two tensor
    paths can never desynchronize."""
    out = []
    for c in reversed(ms.contributions):
        if not c.is_deny and not c.redirect:
            out.append((c, pack_entry(VERDICT_ALLOW, auth=c.auth)))
    for c in reversed(ms.contributions):
        if c.redirect and not c.is_deny:
            out.append((c, pack_entry(VERDICT_REDIRECT, c.proxy_port,
                                      auth=c.auth)))
    for c in ms.contributions:
        if c.is_deny:
            out.append((c, pack_entry(VERDICT_DENY)))
    return out


def make_proto_table() -> np.ndarray:
    """IP protocol number -> dense proto index (device lookup table)."""
    t = np.full(256, PROTO_OTHER, dtype=np.int32)
    t[6] = PROTO_TCP
    t[17] = PROTO_UDP
    t[1] = PROTO_ICMP
    t[58] = PROTO_ICMP  # ICMPv6 shares the ICMP class space
    t[132] = PROTO_SCTP
    return t


class IdentityRowMap:
    """Numeric identity <-> dense device row, with capacity headroom.

    Row 0 is pinned to numeric identity 0 (unknown/invalid), so an
    ipcache miss naturally lands on the wildcard-only policy row.
    """

    def __init__(self, capacity: int = 1024):
        import threading

        self.capacity = capacity
        self._num_to_row: Dict[int, int] = {0: 0}
        self._row_to_num = np.zeros(capacity, dtype=np.int64)
        self._next = 1
        self._free: List[int] = []  # recycled rows (identity released)
        # bumped on every mapping mutation: the map object is REUSED
        # across regenerations, so consumers holding decode snapshots
        # (the serving path's per-batch numerics) must key refreshes
        # on (id(map), version), never on object identity alone
        self.version = 0
        # mutation lock: the map is shared between REGENERATION
        # (resolve + compile on API/trigger threads) and live CHURN
        # patch builders (loader table-builder lock) — add/remove
        # are compound (free-list pop / next bump + two stores) and
        # an interleaving could hand ONE row to two identities, the
        # silent-misverdict class ISSUE 10 exists to close.  Reads
        # (row/numeric lookups) stay lock-free: CPython dict/array
        # point reads are GIL-atomic against these locked mutations
        self._mut = threading.Lock()

    def row_occupancy(self) -> Tuple[int, int]:
        # thread-affinity: any
        """(mapped identities, current capacity) — the policy-table
        pressure sample (ISSUE 19).  Capacity grows on demand, so
        the fraction reads headroom-to-next-grow: the moment
        identity churn is about to pay a regeneration.  (Named
        distinctly from the drain-affine arena ``occupancy`` — the
        callgraph's name-match fallback must not bind them.)"""
        with self._mut:
            return len(self._num_to_row), self.capacity

    def add(self, numeric_id: int) -> int:
        with self._mut:
            row = self._num_to_row.get(numeric_id)
            if row is not None:
                return row
            if self._free:
                row = self._free.pop()
            else:
                if self._next >= self.capacity:
                    self._grow()
                row = self._next
                self._next += 1
            self._num_to_row[numeric_id] = row
            self._row_to_num[row] = numeric_id
            self.version += 1
            return row

    def remove(self, numeric_id: int) -> Optional[int]:
        """Recycle a released identity's row (fqdn/identity churn must
        not grow the verdict tensor without bound).  Callers free a
        row ONLY after its tensor contents were reset to defaults and
        no LPM entry references it."""
        with self._mut:
            row = self._num_to_row.pop(numeric_id, None)
            if row is None or row == 0:
                return None
            self._row_to_num[row] = 0
            self._free.append(row)
            self.version += 1
            return row

    def _grow(self) -> None:
        self.capacity *= 2
        grown = np.zeros(self.capacity, dtype=np.int64)
        grown[: len(self._row_to_num)] = self._row_to_num
        self._row_to_num = grown

    def row(self, numeric_id: int) -> int:
        return self._num_to_row.get(numeric_id, 0)

    def numeric(self, row: int) -> int:
        return int(self._row_to_num[row]) if 0 <= row < self.capacity else 0

    def rows_for(self, ids: Iterable[int]) -> np.ndarray:
        rows = [self._num_to_row[i] for i in ids if i in self._num_to_row]
        return np.asarray(sorted(rows), dtype=np.int32)

    @property
    def n_rows(self) -> int:
        return self._next

    def numeric_array(self) -> np.ndarray:
        """Device-side row -> numeric identity table (for event decode)."""
        return self._row_to_num.copy()


@dataclass
class PolicyTensors:
    """The compiled device policy state (all host-side numpy; the
    datapath uploads them as jax arrays)."""

    proto_table: np.ndarray  # [256] int32: ip proto -> dense proto
    port_class: np.ndarray  # [N_PROTO, 65536] int32: dport -> class
    n_classes: int
    verdict: np.ndarray  # [n_pol, 2, n_rows, n_local_padded] int32
    policy_index: Dict[str, int]  # subject labels key -> policy row
    row_map: IdentityRowMap
    class_intervals: Dict[int, List[Tuple[int, int, int]]] = field(
        default_factory=dict)  # proto -> [(lo, hi_excl, class_id)]
    # per-policy class compaction (r05, SURVEY §7 hard part 3 / HBM
    # audit): GLOBAL classes refine the union of every policy's port
    # boundaries, so their count scales with the number of DISTINCT
    # policies — 128 policies x 10k identities was a 17 GB dense
    # tensor.  Each policy only distinguishes its OWN boundaries, so
    # the verdict tensor's last axis is per-policy LOCAL classes and
    # ``class_map`` [n_pol, n_classes_padded] maps global -> local
    # (one extra tiny gather on device; 32x HBM on that config).
    class_map: Optional[np.ndarray] = None

    def policy_row(self, subject_key: str) -> int:
        return self.policy_index[subject_key]

    # NumPy reference of the device lookup — used by CPU tests and as
    # executable documentation of the gather semantics.
    def lookup_np(self, policy_row: np.ndarray, direction: np.ndarray,
                  id_row: np.ndarray, ip_proto: np.ndarray,
                  dport: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        proto = self.proto_table[ip_proto]
        cls = self.port_class[proto, dport]
        cls = self.class_map[policy_row, cls]
        packed = self.verdict[policy_row, direction, id_row, cls]
        return unpack_verdict(packed), unpack_proxy(packed)

    def hbm_bytes(self) -> int:
        """Device bytes of the compiled policy state (the audit
        number: verdict dominates; class_map/port_class are fixed)."""
        return (self.verdict.nbytes + self.class_map.nbytes
                + self.port_class.nbytes + self.proto_table.nbytes)


def policy_fingerprint(pol: EndpointPolicy) -> tuple:
    """Structural fingerprint of one resolved policy — everything
    that feeds its verdict-tensor slice: subject key, enforcement,
    and every contribution's (proto, port range, verdict class,
    proxy, auth, FROZEN peer set).  Two policies with equal
    fingerprints compile to byte-equal ``verdict[pi]`` slices (given
    the same row map), which is exactly what
    :func:`~..policy.incremental.delta_compile` needs to reuse the
    previous attach's slice instead of repainting it.

    Identity churn is IN the fingerprint (``identities``): an
    identity joining a selector's peer set marks only the policies
    whose selectors changed — the delta-compile partition the r05
    class compaction set up."""

    def ms_fp(ms) -> tuple:
        return (bool(ms.enforcing), tuple(
            (c.proto, c.lo, c.hi, bool(c.is_deny), bool(c.redirect),
             int(c.proxy_port), bool(c.auth),
             None if c.identities is None
             else tuple(sorted(c.identities)))
            for c in ms.contributions))

    return (pol.subject_labels.sorted_key(),
            ms_fp(pol.ingress), ms_fp(pol.egress))


def _collect_boundaries(policies: Sequence[EndpointPolicy]
                        ) -> Dict[int, np.ndarray]:
    """Per-proto sorted boundary sets partitioning [0, 65536)."""
    bounds: Dict[int, set] = {p: {0, 65536} for p in range(N_PROTO)}
    for pol in policies:
        for ms in (pol.ingress, pol.egress):
            for c in ms.contributions:
                protos = (range(N_PROTO) if c.proto == PROTO_ANY
                          else [c.proto])
                for p in protos:
                    bounds[p].add(c.lo)
                    bounds[p].add(c.hi + 1)
    return {p: np.asarray(sorted(x for x in b if 0 <= x <= 65536),
                          dtype=np.int64)
            for p, b in bounds.items()}


@dataclass
class ClassStructure:
    """The class-partition half of a compile — everything EXCEPT the
    verdict paint.  Shared by :func:`compile_policy` and the delta
    path (``policy.incremental.delta_compile``): ONE definition so a
    delta attach can never desynchronize from a full one."""

    port_class: np.ndarray  # [N_PROTO, 65536] global classes
    n_classes: int
    class_intervals: Dict[int, List[Tuple[int, int, int]]]
    class_map: np.ndarray  # [n_pol, n_classes_padded] global -> local
    local_bounds: List[Dict[int, np.ndarray]]
    local_base: List[Dict[int, int]]
    n_local_padded: int


def class_structure(policies: Sequence[EndpointPolicy],
                    class_pad: int = 128) -> ClassStructure:
    """Global + per-policy-local port class partitions."""
    bounds = _collect_boundaries(policies)
    port_class = np.zeros((N_PROTO, 65536), dtype=np.int32)
    class_intervals: Dict[int, List[Tuple[int, int, int]]] = {}
    next_class = 0
    for p in range(N_PROTO):
        b = bounds[p]
        intervals = []
        for lo, hi in zip(b[:-1], b[1:]):
            port_class[p, lo:hi] = next_class
            intervals.append((int(lo), int(hi), next_class))
            next_class += 1
        class_intervals[p] = intervals
    n_classes = next_class
    n_classes_padded = -(-n_classes // class_pad) * class_pad

    # per-policy LOCAL class spaces (see PolicyTensors.class_map): a
    # policy's boundaries partition each proto's port space much more
    # coarsely than the global union; the verdict tensor's last axis
    # is sized to the WIDEST policy, not the union
    local_bounds = [_collect_boundaries([pol]) for pol in policies]
    local_base: List[Dict[int, int]] = []
    n_local_max = 1
    for lb in local_bounds:
        base: Dict[int, int] = {}
        nxt = 0
        for p in range(N_PROTO):
            base[p] = nxt
            nxt += len(lb[p]) - 1
        local_base.append(base)
        n_local_max = max(n_local_max, nxt)
    n_local_padded = -(-n_local_max // class_pad) * class_pad
    class_map = np.zeros((max(len(policies), 1), n_classes_padded),
                         dtype=np.int32)
    for pi, lb in enumerate(local_bounds):
        for p in range(N_PROTO):
            for lo, _hi, g in class_intervals[p]:
                k = int(np.searchsorted(lb[p], lo, side="right")) - 1
                class_map[pi, g] = local_base[pi][p] + k
    return ClassStructure(
        port_class=port_class, n_classes=n_classes,
        class_intervals=class_intervals, class_map=class_map,
        local_bounds=local_bounds, local_base=local_base,
        n_local_padded=n_local_padded)


def paint_policy(pol: EndpointPolicy, pi: int,
                 struct: ClassStructure, row_map: IdentityRowMap,
                 width: Optional[int] = None) -> np.ndarray:
    """One policy's verdict slice [2, n_rows, width] — the per-policy
    half of the compile, shared verbatim by :func:`compile_policy`
    and the delta path.  ``width`` may exceed the structure's
    ``n_local_padded`` (delta reuse into a wider existing tensor: the
    extra padding classes keep the direction default, and the class
    map never addresses them)."""
    lb = struct.local_bounds[pi]
    base = struct.local_base[pi]
    width = struct.n_local_padded if width is None else width
    out = np.zeros((2, row_map.capacity, width), dtype=np.int32)

    def classes_for(proto: int, lo: int, hi: int) -> np.ndarray:
        # contribution bounds are local boundaries by construction
        k0 = int(np.searchsorted(lb[proto], lo, side="right")) - 1
        k1 = int(np.searchsorted(lb[proto], hi, side="right")) - 1
        return np.arange(base[proto] + k0, base[proto] + k1 + 1)

    for di, ms in ((0, pol.ingress), (1, pol.egress)):
        default = (pack_entry(VERDICT_DEFAULT_DENY) if ms.enforcing
                   else pack_entry(VERDICT_ALLOW))
        out[di, :, :] = default
        for c, val in packed_scatter_order(ms):
            protos = (range(N_PROTO) if c.proto == PROTO_ANY
                      else [c.proto])
            cls = np.unique(np.concatenate(
                [classes_for(p, c.lo, c.hi) for p in protos]))
            if c.identities is None:
                out[di][:, cls] = val
            else:
                rows = row_map.rows_for(c.identities)
                if rows.size:
                    out[di][np.ix_(rows, cls)] = val
    return out


def ensure_identity_rows(policies: Sequence[EndpointPolicy],
                         row_map: IdentityRowMap) -> None:
    """Every identity referenced by any contribution gets a row."""
    for pol in policies:
        for ms in (pol.ingress, pol.egress):
            for c in ms.contributions:
                if c.identities:
                    for i in c.identities:
                        row_map.add(i)


def compile_policy(
    policies: Sequence[EndpointPolicy],
    row_map: IdentityRowMap,
    class_pad: int = 128,
) -> PolicyTensors:
    """Compile resolved endpoint policies into dense device tensors.

    O(contributions x touched-rows) via vectorized numpy scatters; the
    10k-identity benchmark set compiles in milliseconds.
    """
    ensure_identity_rows(policies, row_map)
    struct = class_structure(policies, class_pad)

    verdict = np.zeros((len(policies), 2, row_map.capacity,
                        struct.n_local_padded), dtype=np.int32)
    policy_index: Dict[str, int] = {}
    for pi, pol in enumerate(policies):
        policy_index[pol.subject_labels.sorted_key()] = pi
        verdict[pi] = paint_policy(pol, pi, struct, row_map)

    return PolicyTensors(
        proto_table=make_proto_table(),
        port_class=struct.port_class,
        n_classes=struct.n_classes,
        verdict=verdict,
        policy_index=policy_index,
        row_map=row_map,
        class_intervals=struct.class_intervals,
        class_map=struct.class_map,
    )
