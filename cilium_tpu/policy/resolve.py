"""Policy resolution: repository rules + subject labels -> EndpointPolicy.

Reference: upstream cilium ``pkg/policy/resolve.go`` (``ResolvePolicy``
producing an ``EndpointPolicy`` whose ``MapState`` holds the desired
policy-map entries) and ``pkg/policy/l4.go`` (``L4Filter`` expansion of
peer selectors x port specs).

Expansion rules (mirroring the reference's L4Filter semantics):

- a rule with no ``toPorts`` grants all protocols/ports (one PROTO_ANY
  contribution covering every dense proto, including OTHER);
- ``toPorts`` with protocol ANY expands to TCP+UDP+SCTP (port rules
  never cover ICMP/OTHER);
- peer sets are the union of fromEndpoints/toEndpoints selections (via
  SelectorCache), entity selectors, and CIDR-derived local identities;
- an L7 section on an allow turns it into a proxy REDIRECT.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import FrozenSet, List, Optional, Sequence, Tuple

from ..labels import Label, LabelSet, SOURCE_RESERVED
from ..identity.allocator import CachingIdentityAllocator
from .api import (
    CIDRRule,
    ENTITY_ALL,
    ENTITY_CLUSTER,
    ENTITY_SELECTORS,
    EgressRule,
    EndpointSelector,
    IngressRule,
    PortRule,
    Rule,
)
from .mapstate import (
    Contribution,
    DIR_EGRESS,
    DIR_INGRESS,
    MapState,
    PROTO_ANY,
    PROTO_BY_NAME,
    PROTO_ICMP,
    PROTO_SCTP,
    PROTO_TCP,
    PROTO_UDP,
)
from .selectorcache import SelectorCache

# Base port for proxy redirect allocation (reference: pkg/proxy port
# allocator range).
PROXY_PORT_BASE = 10000


@dataclass
class EndpointPolicy:
    """Resolved policy for one subject identity (shared across endpoints
    with the same identity — reference: pkg/policy/distillery.go
    ``SelectorPolicy``/``PolicyCache``)."""

    subject_labels: LabelSet
    revision: int
    ingress: MapState
    egress: MapState
    # (proxy_port, rule_label, L7Rules) per redirect — the L7 proxy
    # compiles these into per-port request-verdict tensors
    redirects: List[Tuple[int, str, object]] = field(default_factory=list)

    def mapstate(self, direction: int) -> MapState:
        return self.ingress if direction == DIR_INGRESS else self.egress

    def lookup(self, direction: int, identity: int, proto: int,
               port: int) -> Tuple[int, int]:
        return self.mapstate(direction).lookup(identity, proto, port)

    def lookup_full(self, direction: int, identity: int, proto: int,
                    port: int) -> Tuple[int, int, bool]:
        """(verdict, proxy, auth_required) — see MapState.lookup_full."""
        return self.mapstate(direction).lookup_full(identity, proto,
                                                    port)


# Policy enforcement modes (reference: pkg/option PolicyEnforcement —
# "default" enforces iff a rule selects the endpoint, "always" is
# default-deny even with no rules, "never" disables enforcement).
ENFORCEMENT_DEFAULT = "default"
ENFORCEMENT_ALWAYS = "always"
ENFORCEMENT_NEVER = "never"
ENFORCEMENT_MODES = (ENFORCEMENT_DEFAULT, ENFORCEMENT_ALWAYS,
                     ENFORCEMENT_NEVER)


def with_enforcement(pol: EndpointPolicy, mode: str) -> EndpointPolicy:
    """Apply a policy-enforcement mode to a resolved policy.

    The mode is per ENDPOINT while the resolved policy is per identity
    (distillery sharing), so endpoints with non-default modes get
    their own derived policy — contribution lists are copied so
    incremental identity churn patches each variant independently."""
    if mode == ENFORCEMENT_DEFAULT:
        return pol
    if mode == ENFORCEMENT_ALWAYS:
        return EndpointPolicy(
            subject_labels=pol.subject_labels,
            revision=pol.revision,
            ingress=MapState(DIR_INGRESS, True,
                             list(pol.ingress.contributions)),
            egress=MapState(DIR_EGRESS, True,
                            list(pol.egress.contributions)),
            redirects=list(pol.redirects))
    if mode == ENFORCEMENT_NEVER:
        return EndpointPolicy(
            subject_labels=pol.subject_labels,
            revision=pol.revision,
            ingress=MapState(DIR_INGRESS, False, []),
            egress=MapState(DIR_EGRESS, False, []),
            redirects=[])
    raise ValueError(
        f"enforcement mode {mode!r} not in {ENFORCEMENT_MODES}")


# The "cluster" entity as a live selector: every identity NOT carrying
# reserved:world (reference: entity "cluster" covers all
# cluster-managed endpoints + host).  Expressed as a selector so
# identity churn updates cluster peer sets incrementally.
from .api import Requirement  # noqa: E402

CLUSTER_SELECTOR = EndpointSelector(
    match_expressions=(Requirement(key=f"{SOURCE_RESERVED}:world",
                                   operator="DoesNotExist"),))


@dataclass(frozen=True)
class PeerSet:
    """Resolved peer identities + the live selectors they came from
    (the selectors make the set incrementally updatable on churn)."""

    ids: Optional[FrozenSet[int]]  # None == wildcard peer
    selectors: Tuple[EndpointSelector, ...] = ()
    fqdn_patterns: Tuple[str, ...] = ()


def _peer_identities(
    selectors: Sequence[EndpointSelector],
    cidrs: Sequence[CIDRRule],
    entities: Sequence[str],
    selector_cache: SelectorCache,
    allocator: CachingIdentityAllocator,
    fqdns: Sequence[str] = (),
) -> PeerSet:
    """PeerSet(ids=None) == wildcard peer (no L3 constraint)."""
    if not selectors and not cidrs and not entities and not fqdns:
        return PeerSet(ids=None)
    ids: set = set()
    live: list = []
    patterns: list = []
    for sel in selectors:
        ids |= selector_cache.selections(sel)
        live.append(sel)
    for ent in entities:
        if ent in (ENTITY_ALL,):
            return PeerSet(ids=None)
        if ent == ENTITY_CLUSTER:
            world = Label(SOURCE_RESERVED, "world")
            ids |= {
                i.numeric_id for i in selector_cache.known_identities()
                if not i.labels.has(world)
            }
            live.append(CLUSTER_SELECTOR)
            continue
        sel = ENTITY_SELECTORS.get(ent)
        if sel is None:
            raise ValueError(f"unknown entity {ent!r}")
        ids |= selector_cache.selections(sel)
        live.append(sel)
    import ipaddress as _ip

    for c in cidrs:
        ident = allocator.allocate_cidr(c.cidr)
        ids.add(ident.numeric_id)
        # CIDR peers select by LABEL (r05, DIVERGENCES #8 closed):
        # every CIDR identity carries its parent-prefix labels, so a
        # fromCIDR range selects later-minted more-specific identities
        # (fqdn /32s, other rules' toCIDR) — with 'except' prefixes as
        # DoesNotExist requirements, exactly upstream's
        # cidrRuleToEndpointSelector translation.
        net = _ip.ip_network(c.cidr, strict=False)
        sel = EndpointSelector(
            match_labels=((f"cidr:{net}", ""),),
            match_expressions=tuple(
                Requirement(
                    key=f"cidr:{_ip.ip_network(e, strict=False)}",
                    operator="DoesNotExist")
                for e in c.except_cidrs))
        ids |= selector_cache.selections(sel)
        live.append(sel)
        # 'except' CIDRs allocate identities too so the ipcache can carve
        # them out; they are excluded from this peer set.
        for exc in c.except_cidrs:
            allocator.allocate_cidr(exc)
    # toFQDNs select identities carrying an fqdn:<name> label — created
    # by the DNS-proxy subsystem (reference: pkg/fqdn) as lookups are
    # observed.  Before any DNS activity the set is empty (deny), never
    # a wildcard.  matchPattern globs match against all observed fqdn
    # labels under the per-label ``*`` grammar (reference:
    # api.FQDNSelector.MatchPattern via pkg/fqdn/matchpattern).
    from ..fqdn.matchpattern import matches as _pat_matches

    for name in fqdns:
        if "*" in name:
            for ident in selector_cache.known_identities():
                for lab in ident.labels:
                    if lab.source == "fqdn" and _pat_matches(name,
                                                             lab.key):
                        ids.add(ident.numeric_id)
            patterns.append(name)
        else:
            sel = EndpointSelector.from_labels(f"fqdn:{name}")
            ids |= selector_cache.selections(sel)
            live.append(sel)
    return PeerSet(ids=frozenset(ids), selectors=tuple(live),
                   fqdn_patterns=tuple(patterns))


def _port_specs(to_ports: Sequence[PortRule], named_ports=None):
    """Expand toPorts into (dense_proto, lo, hi, l7_rules|None) tuples.

    ``named_ports`` (name -> number) resolves symbolic ports; a name
    with no mapping contributes nothing (matches upstream: the rule is
    inert until some endpoint defines the port name)."""
    if not to_ports:
        return [(PROTO_ANY, 0, 65535, None)]
    out = []
    for pr in to_ports:
        l7 = None if pr.rules.is_empty else pr.rules
        ports = pr.ports or ()
        if not ports:
            if l7 is not None:
                # an L7 section without ports still only applies to
                # port-bearing protocols — never ICMP/OTHER
                for p in (PROTO_TCP, PROTO_UDP, PROTO_SCTP):
                    out.append((p, 0, 65535, l7))
            else:
                out.append((PROTO_ANY, 0, 65535, None))
            continue
        for pp in ports:
            for lo, hi in pp.port_ranges(named_ports):
                proto = PROTO_BY_NAME.get(pp.protocol, PROTO_ANY)
                if proto == PROTO_ANY:
                    for p in (PROTO_TCP, PROTO_UDP, PROTO_SCTP):
                        out.append((p, lo, hi, l7))
                else:
                    out.append((proto, lo, hi, l7))
    return out


def resolve_policy(
    rules: Sequence[Rule],
    subject_labels: LabelSet,
    selector_cache: SelectorCache,
    allocator: CachingIdentityAllocator,
    revision: int = 0,
    proxy_port_for=None,
    named_ports=None,
    peer_named_ports=None,
) -> EndpointPolicy:
    """Resolve the rule set down to per-direction MapStates for a subject.

    ``proxy_port_for(key) -> port`` allocates redirect listener ports;
    the repository passes a persistent registry so ports are unique
    across ALL subjects' policies and stable across re-resolves
    (reference: pkg/proxy redirect lifecycle keeps ports across
    regenerations).  The default is a per-call counter (unit tests)."""
    ing = MapState(direction=DIR_INGRESS, enforcing=False)
    egr = MapState(direction=DIR_EGRESS, enforcing=False)
    redirects: List[Tuple[int, str, object]] = []
    if proxy_port_for is None:
        _counter = iter(range(PROXY_PORT_BASE, PROXY_PORT_BASE + 10000))

        def proxy_port_for(key: str) -> int:
            return next(_counter)

    subject_key = subject_labels.sorted_key()

    for rule in rules:
        if not rule.endpoint_selector.matches(subject_labels):
            continue
        if rule.enables_ingress:
            ing.enforcing = True
        if rule.enables_egress:
            egr.enforcing = True
        label = ",".join(rule.labels) or rule.description

        def emit(ms: MapState, peers: PeerSet,
                 to_ports, is_deny: bool, auth: bool = False) -> None:
            # named ports are direction-relative (reference): ingress
            # names the SUBJECT's own container ports; egress names the
            # DESTINATION's, which could be any pod — the node-wide
            # multimap expands every binding of the name
            np = (named_ports if ms.direction == DIR_INGRESS
                  else peer_named_ports)
            for proto, lo, hi, l7 in _port_specs(to_ports, np):
                redirect = l7 is not None and not is_deny
                proxy_port = 0
                if redirect:
                    proxy_port = proxy_port_for(
                        f"{subject_key}|{label}|{ms.direction}|"
                        f"{proto}:{lo}-{hi}")
                    redirects.append((proxy_port, label, l7))
                ms.contributions.append(Contribution(
                    is_deny=is_deny,
                    auth=auth and not is_deny,
                    identities=peers.ids,
                    proto=proto,
                    lo=lo,
                    hi=hi,
                    redirect=redirect,
                    proxy_port=proxy_port,
                    rule_label=label,
                    selectors=peers.selectors,
                    fqdn_patterns=peers.fqdn_patterns,
                ))

        for r in rule.ingress:
            peers = _peer_identities(r.from_endpoints, r.from_cidr,
                                     r.from_entities, selector_cache,
                                     allocator)
            emit(ing, peers, r.to_ports, is_deny=False,
                 auth=r.auth_mode == "required")
        for r in rule.ingress_deny:
            peers = _peer_identities(r.from_endpoints, r.from_cidr,
                                     r.from_entities, selector_cache,
                                     allocator)
            emit(ing, peers, r.to_ports, is_deny=True)
        for r in rule.egress:
            peers = _peer_identities(r.to_endpoints, r.to_cidr,
                                     r.to_entities, selector_cache,
                                     allocator, fqdns=r.to_fqdns)
            emit(egr, peers, r.to_ports, is_deny=False,
                 auth=r.auth_mode == "required")
        for r in rule.egress_deny:
            peers = _peer_identities(r.to_endpoints, r.to_cidr,
                                     r.to_entities, selector_cache,
                                     allocator, fqdns=r.to_fqdns)
            emit(egr, peers, r.to_ports, is_deny=True)

    return EndpointPolicy(
        subject_labels=subject_labels,
        revision=revision,
        ingress=ing,
        egress=egr,
        redirects=redirects,
    )
