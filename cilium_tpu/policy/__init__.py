from .api import (  # noqa: F401
    EndpointSelector,
    Rule,
    IngressRule,
    EgressRule,
    PortProtocol,
    PortRule,
    PortRuleHTTP,
    PortRuleDNS,
    CIDRRule,
    Entity,
    rule_from_dict,
    rules_from_obj,
)
from .selectorcache import SelectorCache, CachedSelector  # noqa: F401
from .repository import PolicyRepository  # noqa: F401
from .mapstate import (  # noqa: F401
    MapState,
    PolicyKey,
    PolicyEntry,
    VERDICT_DEFAULT_DENY,
    VERDICT_ALLOW,
    VERDICT_DENY,
    VERDICT_REDIRECT,
    PROTO_TCP,
    PROTO_UDP,
    PROTO_ICMP,
    PROTO_SCTP,
    PROTO_OTHER,
    PROTO_ANY,
    DIR_INGRESS,
    DIR_EGRESS,
)
from .resolve import resolve_policy, EndpointPolicy  # noqa: F401
from .compiler import PolicyTensors, IdentityRowMap, compile_policy  # noqa: F401
