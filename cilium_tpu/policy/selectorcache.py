"""SelectorCache: label selectors -> live sets of numeric identities.

Reference: upstream cilium ``pkg/policy/selectorcache.go``
(``SelectorCache``, ``CachedSelector``, identity-notification fan-out).
Policy rules reference selectors; identities churn as workloads come and
go.  The cache incrementally maintains, per selector, the set of numeric
identities whose labels match, and notifies users (resolved endpoint
policies, and the datapath compiler) of deltas so device tensors can be
patched without recompilation.

Per BASELINE.md's north star, this cache is also what seeds the learned
model's identity-embedding table (identity -> label multi-hot).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Set

from ..identity import Identity
from ..identity.allocator import CachingIdentityAllocator
from .api import EndpointSelector

# (selector, added_ids, removed_ids)
SelectorChangeFn = Callable[[EndpointSelector, Set[int], Set[int]], None]


class CachedSelector:
    """A selector plus its current identity selection."""

    def __init__(self, selector: EndpointSelector):
        self.selector = selector
        self.selections: Set[int] = set()
        self.refcount = 0

    def matches(self, numeric_id: int) -> bool:
        return numeric_id in self.selections


class SelectorCache:
    def __init__(self, allocator: CachingIdentityAllocator):
        self._lock = threading.RLock()
        # guarded-by: _lock: _selectors, _identities, _users
        self._allocator = allocator
        self._selectors: Dict[EndpointSelector, CachedSelector] = {}
        self._identities: Dict[int, Identity] = {}
        self._users: List[SelectorChangeFn] = []
        allocator.observe(self._on_identity_change)

    # -- identity events (from the allocator) ----------------------------
    # Runs on whatever thread mints/releases the identity (API, DNS
    # proxy, kvstore watch dispatcher, the churn scenario driver) —
    # and the user callbacks it fans into end in the loader's table
    # publish, so the lock ORDER here is selectorcache -> (user) ->
    # table-builder -> datapath-loader; nothing may call back into
    # this cache while holding either loader lock.
    def _on_identity_change(self, kind: str, ident: Identity) -> None:
        with self._lock:
            if kind == "add":
                self._identities[ident.numeric_id] = ident
                for cs in self._selectors.values():
                    if cs.selector.matches(ident.labels):
                        cs.selections.add(ident.numeric_id)
                        self._notify(cs.selector, {ident.numeric_id}, set())
            else:
                self._identities.pop(ident.numeric_id, None)
                for cs in self._selectors.values():
                    if ident.numeric_id in cs.selections:
                        cs.selections.discard(ident.numeric_id)
                        self._notify(cs.selector, set(), {ident.numeric_id})

    def _notify(self, sel: EndpointSelector, added: Set[int],
                removed: Set[int]) -> None:
        # holds: _lock -- only _on_identity_change calls this (RLock:
        # user callbacks may re-enter queries, not mutations)
        for fn in list(self._users):
            fn(sel, added, removed)

    # -- selector registration ------------------------------------------
    def add_selector(self, selector: EndpointSelector) -> CachedSelector:
        with self._lock:
            cs = self._selectors.get(selector)
            if cs is None:
                cs = CachedSelector(selector)
                for num, ident in self._identities.items():
                    if selector.matches(ident.labels):
                        cs.selections.add(num)
                self._selectors[selector] = cs
            cs.refcount += 1
            return cs

    def remove_selector(self, selector: EndpointSelector) -> None:
        with self._lock:
            cs = self._selectors.get(selector)
            if cs is None:
                return
            cs.refcount -= 1
            if cs.refcount <= 0:
                del self._selectors[selector]

    def subscribe(self, fn: SelectorChangeFn) -> None:
        with self._lock:
            self._users.append(fn)

    # -- queries ---------------------------------------------------------
    def selections(self, selector: EndpointSelector) -> Set[int]:
        with self._lock:
            cs = self._selectors.get(selector)
            if cs is not None:
                return set(cs.selections)
            # uncached one-shot evaluation
            return {
                num for num, ident in self._identities.items()
                if selector.matches(ident.labels)
            }

    def identity(self, numeric_id: int) -> Optional[Identity]:
        with self._lock:
            return self._identities.get(numeric_id)

    def known_identities(self) -> List[Identity]:
        with self._lock:
            return list(self._identities.values())
