"""IPAM: pod IP allocation (cluster-pool mode).

Reference: upstream cilium ``pkg/ipam`` — the agent carves pod IPs out
of the node's podCIDR; in cluster-pool mode the operator assigns each
node a podCIDR from cluster-wide pools.  The ENI/Azure cloud
allocators are out of scope (no cloud API in a TPU pod); cluster-pool
is the mode the reference's own e2e runs on.

Two pieces:

- :class:`ClusterPool` — operator side: carve per-node podCIDRs out of
  the cluster pool (kvstore-backed so every operator replica agrees).
- :class:`NodeIPAM` — agent side: allocate/release pod IPs from the
  node's podCIDR with a free-bitmap (O(1) alloc, restart-restorable).
"""

from __future__ import annotations

import ipaddress
import json
import threading
from typing import Dict, List, Optional

POOL_PREFIX = "cilium/state/podcidrs/v1"


class ClusterPool:
    """Carve node podCIDRs from a cluster pool (operator side)."""

    def __init__(self, kv, cluster_cidr: str = "10.0.0.0/8",
                 node_mask: int = 24):
        self.kv = kv
        self.cluster = ipaddress.ip_network(cluster_cidr)
        self.node_mask = node_mask
        if node_mask < self.cluster.prefixlen:
            raise ValueError("node mask shorter than the cluster pool")

    def allocate_node_cidr(self, node: str) -> str:
        """Assign (or return) the node's podCIDR — create-only on the
        kvstore makes concurrent operators collision-free."""
        key = f"{POOL_PREFIX}/{node}"
        existing = self.kv.get(key)
        if existing is not None:
            return json.loads(existing)["cidr"]
        used = {json.loads(v)["cidr"]
                for v in self.kv.list_prefix(POOL_PREFIX + "/").values()}
        for subnet in self.cluster.subnets(new_prefix=self.node_mask):
            cidr = str(subnet)
            if cidr in used:
                continue
            if self.kv.create_only(key, json.dumps(
                    {"node": node, "cidr": cidr}).encode()):
                return cidr
            # another operator claimed this node concurrently: reuse
            raced = self.kv.get(key)
            if raced is not None:
                return json.loads(raced)["cidr"]
        raise RuntimeError("cluster pool exhausted")

    def release_node_cidr(self, node: str) -> bool:
        return self.kv.delete(f"{POOL_PREFIX}/{node}")

    def assignments(self) -> Dict[str, str]:
        return {json.loads(v)["node"]: json.loads(v)["cidr"]
                for v in self.kv.list_prefix(POOL_PREFIX + "/").values()}


class NodeIPAM:
    """Per-node pod IP allocator over the podCIDR (agent side).

    The network and broadcast addresses plus the first host (gateway,
    matching the reference's router IP) are reserved."""

    def __init__(self, pod_cidr: str):
        self.cidr = ipaddress.ip_network(pod_cidr)
        n = self.cidr.num_addresses
        if n < 4:
            raise ValueError(f"podCIDR {pod_cidr} too small")
        self._lock = threading.Lock()
        self._used: set = {0, 1, n - 1}  # network, gateway, broadcast
        self._owner: Dict[int, str] = {}
        self._next = 2

    @property
    def gateway(self) -> str:
        return str(self.cidr.network_address + 1)

    def allocate(self, owner: str = "") -> str:
        with self._lock:
            n = self.cidr.num_addresses
            for _ in range(n):
                idx = self._next
                self._next = 2 + (self._next - 1) % (n - 3)
                if idx not in self._used:
                    self._used.add(idx)
                    if owner:
                        self._owner[idx] = owner
                    return str(self.cidr.network_address + idx)
            raise RuntimeError(f"podCIDR {self.cidr} exhausted")

    def allocate_specific(self, ip: str, owner: str = "") -> str:
        """Restore path: re-claim a checkpointed pod IP."""
        addr = ipaddress.ip_address(ip)
        idx = int(addr) - int(self.cidr.network_address)
        with self._lock:
            if not 0 <= idx < self.cidr.num_addresses:
                raise ValueError(f"{ip} outside podCIDR {self.cidr}")
            if idx in self._used:
                raise ValueError(f"{ip} already allocated")
            self._used.add(idx)
            if owner:
                self._owner[idx] = owner
        return ip

    def release(self, ip: str) -> bool:
        idx = int(ipaddress.ip_address(ip)) - int(
            self.cidr.network_address)
        with self._lock:
            if idx in (0, 1, self.cidr.num_addresses - 1):
                return False  # reserved
            if idx not in self._used:
                return False
            self._used.discard(idx)
            self._owner.pop(idx, None)
            return True

    def stats(self) -> dict:
        with self._lock:
            used = len(self._used) - 3
        return {"cidr": str(self.cidr), "used": used,
                "capacity": self.cidr.num_addresses - 3,
                "gateway": self.gateway}
