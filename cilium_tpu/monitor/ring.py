"""Device-resident event ring: the eventsmap/perf-buffer analogue.

Reference: upstream cilium's datapath emits events into a kernel perf
ring (``pkg/monitor/agent`` reads it); userspace drains at its own
cadence and the ring overwrites when the consumer lags.  TPU-first
redesign: the ring is a fixed HBM buffer; the fused pipeline appends
**compacted** events (drops + policy verdicts on NEW connections +
1/``trace_sample`` of established-flow traces — exactly the reference's
event economy, where TraceNotify is sampled and established traffic is
counted in the metricsmap, not streamed) entirely on device.  The host
drains asynchronously — so the hot loop never blocks on device→host
transfers, which is also what makes end-to-end benchmarking viable on
hosts where the d2h path is expensive (e.g. tunneled TPUs).

Ring semantics: wrap-overwrite (newest wins), like the Hubble observer
ring; total appended count is monotone so the host computes loss as
``appended - capacity`` when it lags a full lap.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..datapath.verdict import EV_TRACE, N_OUT, OUT_EVENT

# Decoded ring row: the N_OUT out-columns + packet index within batch
# + batch seq.  On DEVICE each row packs into RING_WORDS u32 (8 B
# instead of 32 B) — the drain is a device->host copy, and its
# bandwidth is the monitor plane's ceiling (PCIe on direct-attached
# TPUs, worse on tunneled hosts), so the wire format is packed exactly
# like the reference keeps perf events small.  r05: 12 B -> 8 B by
# (a) storing the proxy PORT as a 4-bit index into the small listener
# table (there are at most a handful of live redirect listeners —
# upstream allocates them from a ~dozen-wide range) and (b) shrinking
# the batch-seq field to 13 bits (it disambiguates/orders events
# within a drain window; windows are a few dozen batches).
# Packing (see _unpack_rows for the decode):
#   w0: verdict(0..2) | event(3..4) | reason(5..8) | ct(9..11)
#       | proxy_idx(12..15) | id_row(16..31)
#   w1: pkt_idx(0..18) | batch(19..31, wraps)
# The 4-bit reason field holds codes 0..15.  N_REASONS is 13 —
# REASON_DISPATCH_TIMEOUT (10), REASON_RECOVERY_DROP (11) and
# REASON_CLUSTER_OVERFLOW (12) are RESERVED for the serving recovery
# and cluster routing planes (host-synthesized, so they never transit
# this ring today, but the wire width must cover them:
# a drained row's reason decodes through the same DROP_REASON_NAMES
# table).  4 codes (12..15) remain before the field must widen.
# Limits (asserted where they bind): id_row < 2^16, pkt_idx < 2^19
# (batches up to 512k rows), batch seq wraps at 2^13, <= 15 live
# proxy listeners.  Empty slots carry event bits 0b11 (no EV_* code
# uses 3), which is how the drain drops never-written rows.
RING_COLS = N_OUT + 2
COL_PKT_IDX = N_OUT
COL_BATCH = N_OUT + 1
EMPTY_BATCH = 0xFFFFFFFF
RING_WORDS = 2
MAX_PROXY_PORTS = 15
_EMPTY = 0xFFFFFFFF


@jax.tree_util.register_pytree_node_class
@dataclass
class EventRing:
    """Device state of the ring (pytree: threads through jit)."""

    buf: jnp.ndarray  # [capacity, RING_WORDS] uint32 (packed rows)
    # total events ever appended, as TWO u32 words [lo, hi] — a single
    # u32 wraps after 2^32 events (hours at target rates; the reference
    # perf/Hubble rings count in u64) and a wrapped cursor makes drain
    # misread a full ring as nearly empty.  x64 is off under jit, so
    # the 64-bit count is carried as lo + carry-into-hi on device.
    cursor: jnp.ndarray  # [2] uint32

    @staticmethod
    def create(capacity: int = 1 << 15) -> "EventRing":
        assert capacity & (capacity - 1) == 0, "capacity must be 2^k"
        buf = jnp.full((capacity, RING_WORDS), _EMPTY,
                       dtype=jnp.uint32)
        return EventRing(buf=buf, cursor=jnp.zeros((2,), jnp.uint32))

    @property
    def capacity(self) -> int:
        return self.buf.shape[0]

    def tree_flatten(self):
        return ((self.buf, self.cursor), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def ring_append(ring: EventRing, out: jnp.ndarray, batch_id: jnp.ndarray,
                trace_sample: int = 1024,
                valid: jnp.ndarray = None,
                proxy_ports: jnp.ndarray = None) -> EventRing:
    """Compact one batch's out tensor into the ring (pure device op).

    Keeps every non-TRACE event (drops, NEW-connection policy
    verdicts) plus one in ``trace_sample`` established-flow traces
    (``trace_sample=0`` disables trace sampling entirely).

    ``proxy_ports`` is the live listener table ([MAX_PROXY_PORTS]
    uint32, 0-padded): redirect events store the PORT's index in it
    (4 bits on the wire); pass the same table to :func:`ring_drain`
    to restore ports.  Without it redirect events decode with proxy
    port 0.
    """
    n = out.shape[0]
    idx = jnp.arange(n, dtype=jnp.uint32)
    keep = out[:, OUT_EVENT] != EV_TRACE
    if trace_sample:
        keep = keep | (idx % trace_sample == 0)
    if valid is not None:
        keep = keep & valid
    assert n <= (1 << 19), "pkt_idx packs into 19 bits"
    pos = jnp.cumsum(keep) - 1  # position among kept rows
    count = keep.sum().astype(jnp.uint32)
    mask = ring.capacity - 1
    lo, hi = ring.cursor[0], ring.cursor[1]
    slot = ((lo + pos.astype(jnp.uint32)) & mask).astype(jnp.int32)
    # newest-wins under overflow: when one batch keeps more events than
    # the ring holds, only the newest `capacity` rows write — otherwise
    # duplicate slot indices in one scatter would make the survivor
    # order unspecified
    newest = pos.astype(jnp.uint32) + ring.capacity >= count
    target = jnp.where(keep & newest, slot, ring.capacity)  # OOB dropped
    o = out.astype(jnp.uint32)
    from ..datapath.verdict import (OUT_CT, OUT_ID_ROW, OUT_PROXY,
                                    OUT_REASON, OUT_VERDICT)

    if proxy_ports is None or proxy_ports.shape[0] == 0:
        # an EMPTY table also means "no listeners" — the sharded step
        # passes a zero-length placeholder because shard_map wants a
        # fixed arity (argmax over a 0-wide axis would be an error)
        pidx = jnp.zeros(n, dtype=jnp.uint32)
    else:
        assert proxy_ports.shape[0] <= MAX_PROXY_PORTS, \
            "listener index packs into 4 bits"
        port = o[:, OUT_PROXY]
        hit = port[:, None] == proxy_ports[None, :].astype(jnp.uint32)
        pidx = jnp.where(
            jnp.any(hit, axis=1) & (port != 0),
            jnp.argmax(hit, axis=1).astype(jnp.uint32) + 1,
            jnp.uint32(0))
    # mask each field to its wire width: a value past its width must
    # corrupt only itself, never a neighbor (the empty-slot sentinel
    # lives in the event bits)
    w0 = ((o[:, OUT_VERDICT] & 0x7) | ((o[:, OUT_EVENT] & 0x3) << 3)
          | ((o[:, OUT_REASON] & 0xF) << 5) | ((o[:, OUT_CT] & 0x7) << 9)
          | (pidx << 12) | ((o[:, OUT_ID_ROW] & 0xFFFF) << 16))
    w1 = idx | ((jnp.uint32(batch_id) & jnp.uint32(0x1FFF)) << 19)
    rows = jnp.stack([w0, w1], axis=1)
    buf = ring.buf.at[target].set(rows, mode="drop")
    new_lo = lo + count
    new_hi = hi + (new_lo < lo).astype(jnp.uint32)  # carry
    return EventRing(buf=buf, cursor=jnp.stack([new_lo, new_hi]))


ring_append_jit = jax.jit(ring_append, donate_argnums=0,
                          static_argnames=("trace_sample",))


def serve_step(state, ring: EventRing, hdr: jnp.ndarray,
               now: jnp.ndarray, batch_id: jnp.ndarray,
               trace_sample: int = 1024, valid: jnp.ndarray = None,
               proxy_ports: jnp.ndarray = None, audit: bool = False):
    """The serving-path step: fused datapath + event-ring append in ONE
    executable (one dispatch per batch; out rows that the compaction
    discards are never materialized).  Returns (state, ring)."""
    from ..datapath.verdict import datapath_step

    out, state = datapath_step(state, hdr, now, valid=valid,
                               audit=audit)
    ring = ring_append(ring, out, batch_id, trace_sample=trace_sample,
                       valid=valid, proxy_ports=proxy_ports)
    return state, ring


serve_step_jit = jax.jit(serve_step, donate_argnums=(0, 1),
                         static_argnames=("trace_sample", "audit"))


def serve_step_packed(state, ring: EventRing, packed: jnp.ndarray,
                      now: jnp.ndarray, batch_id: jnp.ndarray,
                      ep, dirn, trace_sample: int = 1024,
                      valid: jnp.ndarray = None,
                      proxy_ports: jnp.ndarray = None,
                      audit: bool = False):
    """Serving path for the packed ingest format (16 B/packet h2d):
    unpack + fused datapath + ring append, ONE dispatch per batch.
    ``valid`` masks the adaptive batcher's padding rows exactly like
    the wide :func:`serve_step` — padding touches neither CT, metrics,
    nor the ring, so each bucket size stays one compiled shape."""
    from ..datapath.verdict import datapath_step_packed

    out, state = datapath_step_packed(state, packed, now, ep, dirn,
                                      valid=valid, audit=audit)
    ring = ring_append(ring, out, batch_id, trace_sample=trace_sample,
                       valid=valid, proxy_ports=proxy_ports)
    return state, ring


serve_step_packed_jit = jax.jit(serve_step_packed, donate_argnums=(0, 1),
                                static_argnames=("trace_sample",
                                                 "audit"))


# -- K-batch superbatch dispatch (the Python-dispatch diet) ----------
# The datapath math was never the serving ceiling — the per-batch
# Python dispatch (lock acquire, arena bookkeeping, one jit call) was
# (ROADMAP item 1: BENCH_churn's no-churn leg reads 334k pps where
# BENCH_serving sustains ~200-260k).  These steps fuse K batches into
# ONE executable: a lax.scan over the K steps runs datapath + ring
# append per step entirely on device, so the host pays one staging
# copy, one lock window, and one dispatch per K batches.  Each K is
# one compiled shape ([K, bucket, cols] — K rides the shape, so the
# compile-log's one-executable guard keys on (rung, mode, K) for
# free), which is why the serving plane restricts K to a small
# power-of-two ladder (DaemonConfig.serving_superbatch_k).
#
# Per-step ``valid`` masks do double duty: within a step they mask
# the adaptive batcher's padding rows exactly like serve_step, and a
# trailing ALL-FALSE step masks an empty slot of a partially-filled
# superbatch (the batcher rounds the ready-batch count up to the K
# ladder) — an empty step touches neither CT, metrics, nor the ring,
# and appends under a batch id the host never recorded.
#
# Atomicity note (the TableVersioner interplay): the whole scan
# captures ONE ``state`` — a concurrent generation flip lands wholly
# before or wholly after the dispatch, never between inner steps, so
# superbatching cannot tear a table mid-scan; what it DOES stretch is
# update-visible latency (one dispatch pins a generation for K
# batches), which BENCH_churn measures at K>1.


def serve_superbatch(state, ring: EventRing, hdr: jnp.ndarray,
                     now: jnp.ndarray, batch_id0: jnp.ndarray,
                     trace_sample: int = 1024,
                     valid: jnp.ndarray = None,
                     proxy_ports: jnp.ndarray = None,
                     audit: bool = False):
    """K wide batches in one dispatch: ``hdr`` [K, bucket, N_COLS],
    ``valid`` [K, bucket] (REQUIRED — the empty-step masking above
    depends on it), batch ids ``batch_id0 + k`` per step (the ring's
    13-bit field wraps them exactly like the host's seq mask).
    Returns (state, ring) after all K steps."""
    from ..datapath.verdict import datapath_step

    assert valid is not None, "superbatch dispatch requires valid masks"
    K = hdr.shape[0]

    def body(carry, xs):
        st, rg = carry
        hdr_k, valid_k, k = xs
        out, st = datapath_step(st, hdr_k, now, valid=valid_k,
                                audit=audit)
        rg = ring_append(rg, out, batch_id0 + k,
                         trace_sample=trace_sample, valid=valid_k,
                         proxy_ports=proxy_ports)
        return (st, rg), None

    xs = (hdr, valid, jnp.arange(K, dtype=jnp.uint32))
    (state, ring), _ = jax.lax.scan(body, (state, ring), xs)
    return state, ring


serve_superbatch_jit = jax.jit(serve_superbatch, donate_argnums=(0, 1),
                               static_argnames=("trace_sample",
                                                "audit"))


def serve_superbatch_packed(state, ring: EventRing,
                            packed: jnp.ndarray, now: jnp.ndarray,
                            batch_id0: jnp.ndarray,
                            eps: jnp.ndarray, dirns: jnp.ndarray,
                            trace_sample: int = 1024,
                            valid: jnp.ndarray = None,
                            proxy_ports: jnp.ndarray = None,
                            audit: bool = False):
    """K packed batches in one dispatch: ``packed`` [K, bucket, 4]
    (16 B/packet on the h2d link, 4x fewer bytes AND one copy for K
    batches), ``eps``/``dirns`` [K] per-step stream-metadata scalars,
    ``valid`` [K, bucket].  On-device unpack + datapath + ring append
    per scan step; same empty-step semantics as
    :func:`serve_superbatch`."""
    from ..datapath.verdict import datapath_step_packed

    assert valid is not None, "superbatch dispatch requires valid masks"
    K = packed.shape[0]

    def body(carry, xs):
        st, rg = carry
        hdr_k, valid_k, ep_k, dirn_k, k = xs
        out, st = datapath_step_packed(st, hdr_k, now, ep_k, dirn_k,
                                       valid=valid_k, audit=audit)
        rg = ring_append(rg, out, batch_id0 + k,
                         trace_sample=trace_sample, valid=valid_k,
                         proxy_ports=proxy_ports)
        return (st, rg), None

    xs = (packed, valid, eps, dirns, jnp.arange(K, dtype=jnp.uint32))
    (state, ring), _ = jax.lax.scan(body, (state, ring), xs)
    return state, ring


serve_superbatch_packed_jit = jax.jit(serve_superbatch_packed,
                                      donate_argnums=(0, 1),
                                      static_argnames=("trace_sample",
                                                       "audit"))


# -- occupancy-bounded drain (the d2h diet) ---------------------------
# The fetched window's byte count should scale with the EVENTS the
# window appended, not the ring's capacity: `swap` already blocks on
# the 8-byte cursor, so the host knows the occupancy before a single
# buffer byte moves.  A device-side gather pulls just the occupied
# slots (wrap-aware: slot of the i-th surviving event is
# (total - kept + i) & mask, which is the identity prefix [0, total)
# until the ring laps) into a contiguous buffer bucketed to a
# power-of-two RUNG ladder — each rung is ONE compiled executable
# (registered with TPULoader.compile_log like every other serving
# shape), and the d2h copy ships rung*8 bytes instead of capacity*8.
GATHER_MIN_RUNG = 64


def _gather_rung(kept: int, cap: int) -> int:
    """Smallest ladder rung holding ``kept`` rows (power of two,
    floored at GATHER_MIN_RUNG, capped at the ring capacity)."""
    r = min(GATHER_MIN_RUNG, cap)
    while r < kept:
        r <<= 1
    return min(r, cap)


@partial(jax.jit, static_argnames=("rung", "cap"))
def ring_gather(buf: jnp.ndarray, starts: jnp.ndarray, rung: int,
                cap: int) -> jnp.ndarray:
    """Gather each shard's occupied window slots, in append order,
    into a contiguous [n_shards * rung, RING_WORDS] buffer.

    ``buf`` is [n_shards * cap, RING_WORDS] (n_shards=1 for the
    single-chip ring), ``starts`` [n_shards] uint32 — each shard's
    oldest surviving slot ((total - kept) & mask; 0 until the ring
    laps).  Slots past a shard's occupancy are EMPTY on a fresh-per-
    window ring, so the host's empty-slot filter drops them exactly
    like the full-copy path.  One executable per (rung, shard count):
    ``starts`` is traced, the rung is static."""
    n_shards = starts.shape[0]
    offs = jnp.arange(rung, dtype=jnp.uint32)[None, :]
    idx = (starts[:, None] + offs) & jnp.uint32(cap - 1)
    idx = idx + (jnp.arange(n_shards, dtype=jnp.uint32)
                 * jnp.uint32(cap))[:, None]
    return buf[idx.reshape(-1).astype(jnp.int32)]


def _cursor_totals(cursor: np.ndarray) -> np.ndarray:
    """Host cursor ([2] or [S, 2] of u32 lo/hi words) -> int64 totals
    per shard ([S])."""
    c = np.asarray(cursor, dtype=np.uint64).reshape(-1, 2)
    return (c[:, 0] | (c[:, 1] << np.uint64(32))).astype(np.int64)


@dataclass
class RingWindow:
    """One drained window's in-flight handle: the device buffer whose
    host copy is already streaming, plus everything the EVENT-JOIN
    WORKER (serving/eventplane.py) needs to finish off the dispatch
    path — the synced host cursor, the occupancy/loss math done at
    swap time, and the originating drainer for counter accounting.

    Ownership: ``swap_window`` hands the window out and the drainer
    forgets it; exactly one thread (the worker, or a legacy
    ``collect()`` caller) calls :meth:`fetch` exactly once."""

    buf: Optional[object]  # device rows (None = empty window)
    cursor: np.ndarray  # host copy, [n_shards(|1), 2] u32
    capacity: int
    n_shards: int  # 0 = single-chip ring
    appended: int  # events appended across shards this window
    lost: int  # lap loss (appended - capacity when the host lagged)
    d2h_bytes: int  # bytes this window put on the d2h link
    gathered: bool  # buf is a rung gather, already in append order
    rung: int
    proxy_ports: Optional[np.ndarray]
    drainer: object
    t_swap: float = field(default_factory=time.monotonic)

    def fetch(self):
        # thread-affinity: event-worker, api, offline -- the blocking
        # d2h wait lives here; the drain thread only ever swaps
        """Complete the transfer and decode.  Returns
        ``(rows, shard_ids, appended, lost)``; ``shard_ids`` is None
        for a single-chip window.  Updates the originating drainer's
        windows/events/lost counters (single-writer: whoever owns the
        window)."""
        d = self.drainer
        if self.buf is None:
            rows = np.zeros((0, RING_COLS), dtype=np.uint32)
            shards = (np.zeros(0, dtype=np.int64)
                      if self.n_shards else None)
            if d is not None:
                d.windows += 1
            return rows, shards, 0, 0
        buf = np.asarray(self.buf)  # blocks until the copy lands
        self.buf = None
        totals = _cursor_totals(self.cursor)
        cap = self.capacity
        if self.n_shards:
            S = self.n_shards
            blk = buf.shape[0] // S
            parts: List[np.ndarray] = []
            sids: List[np.ndarray] = []
            for s in range(S):
                r, _total, _lost = _decode_fetched(
                    buf[s * blk:(s + 1) * blk], int(totals[s]), cap,
                    self.proxy_ports, gathered=self.gathered)
                parts.append(r)
                sids.append(np.full(len(r), s, dtype=np.int64))
            rows = (np.concatenate(parts) if parts else
                    np.zeros((0, RING_COLS), dtype=np.uint32))
            shards = (np.concatenate(sids) if sids else
                      np.zeros(0, dtype=np.int64))
        else:
            rows, _total, _lost = _decode_fetched(
                buf, int(totals[0]), cap, self.proxy_ports,
                gathered=self.gathered)
            shards = None
        if d is not None:
            d.windows += 1
            d.events += self.appended - self.lost
            d.lost += self.lost
        return rows, shards, self.appended, self.lost


def _start_window(ring: EventRing, capacity: int, n_shards: int,
                  proxy_ports, drainer, gather: bool,
                  compile_log) -> RingWindow:
    # thread-affinity: drain, api, offline
    """The shared swap leg: sync the cursor (retires every queued
    dispatch — see AsyncRingDrainer.swap), do the occupancy math on
    host, start the async copy of either the rung gather or the full
    buffer, and wrap it all in a :class:`RingWindow`."""
    # hot-path-ok: the load-bearing 8-byte cursor sync — blocking on
    # the scalar drains the dispatch queue in ms where blocking on
    # the buffer pays ~9s/dispatch on tunneled runtimes (r05); it is
    # also what makes the occupancy-bounded gather possible at all
    ring.cursor.block_until_ready()
    cur = np.array(np.asarray(ring.cursor), copy=True).reshape(-1, 2)
    totals = _cursor_totals(cur)
    appended = int(totals.sum())
    lost = int(np.maximum(totals - capacity, 0).sum())
    if appended == 0:
        return RingWindow(buf=None, cursor=cur, capacity=capacity,
                          n_shards=n_shards, appended=0, lost=0,
                          d2h_bytes=0, gathered=False, rung=0,
                          proxy_ports=proxy_ports, drainer=drainer)
    if gather:
        kept = np.minimum(totals, capacity)
        rung = _gather_rung(int(kept.max()), capacity)
        # oldest surviving slot per shard: 0 until the ring laps,
        # then the wrapped cursor (total & mask)
        starts = np.where(totals > capacity,
                          totals & (capacity - 1),
                          0).astype(np.uint32)
        size = getattr(ring_gather, "_cache_size", lambda: 0)
        before = size() if compile_log is not None else 0
        t0 = time.monotonic()
        buf = ring_gather(ring.buf, starts, rung, capacity)
        if compile_log is not None:
            after = size()
            if after > before:
                compile_log.record_dispatch(
                    "gather", (max(n_shards, 1), rung), before, after,
                    time.monotonic() - t0, key_extra=(capacity,))
        buf.copy_to_host_async()
        return RingWindow(buf=buf, cursor=cur, capacity=capacity,
                          n_shards=n_shards, appended=appended,
                          lost=lost, d2h_bytes=buf.nbytes + cur.nbytes,
                          gathered=True, rung=rung,
                          proxy_ports=proxy_ports, drainer=drainer)
    ring.buf.copy_to_host_async()
    return RingWindow(buf=ring.buf, cursor=cur, capacity=capacity,
                      n_shards=n_shards, appended=appended, lost=lost,
                      d2h_bytes=ring.buf.nbytes + cur.nbytes,
                      gathered=False, rung=capacity,
                      proxy_ports=proxy_ports, drainer=drainer)


class AsyncRingDrainer:
    """Double-buffered drain: the host fetches window N-1 while the
    device steps window N.

    ``ring_drain`` blocks on a device->host copy that must first
    retire every dispatch queued since the previous fetch — on
    tunneled TPUs that sync debt dominates the drain (r04:
    drain_ms_median 10.3 s).  Double buffering hides it: at each
    window boundary ``swap(ring)`` starts an ASYNC copy of the
    just-filled ring and hands the serve loop a fresh one, and
    ``collect()`` completes the transfer that has been streaming in
    the background — by then the bytes are already on host.  This is
    also the production shape of the reference's perf-buffer consumer
    (the kernel keeps appending to live pages while userspace reads
    the pages it was handed).

    Because every window starts on a fresh ring, the fetched cursor
    IS the window's append count and per-window loss is
    ``max(0, appended - capacity)`` with no cross-window bookkeeping.
    """

    def __init__(self, capacity: int = 1 << 15,
                 proxy_ports: np.ndarray = None,
                 gather: bool = True, compile_log=None):
        self.capacity = capacity
        self.proxy_ports = proxy_ports
        # occupancy-bounded fetch (module comment at GATHER_MIN_RUNG):
        # d2h bytes scale with the window's events, not the capacity.
        # compile_log (TPULoader.compile_log) records the bucketed
        # gather's rung executables under the same one-executable-
        # per-(rung, mode) guard as the serve steps
        self.gather = bool(gather)
        self.compile_log = compile_log
        self._pending: Optional[RingWindow] = None
        self.windows = 0
        self.events = 0
        self.lost = 0

    def fresh(self) -> EventRing:
        return EventRing.create(self.capacity)

    def swap_window(self, ring: EventRing
                    ) -> Tuple[RingWindow, EventRing]:
        # thread-affinity: drain, api, offline
        """Start the async fetch of ``ring`` and hand its window out
        as a :class:`RingWindow` (ownership transfers to the caller —
        the event-join worker's shape); returns the fresh ring for
        the next window alongside it.

        The block_until_ready on the CURSOR before the copy is
        load-bearing on tunneled runtimes: a d2h transfer with queued
        dispatches pays a pathological per-dispatch flush (~9 s each,
        measured r05), while blocking on the tiny cursor drains the
        same queue in milliseconds (blocking on the large buffer
        triggers the slow path itself — sync on the scalar, then the
        copies only move bytes).  It is also what makes the
        occupancy-bounded gather possible at all: the synced cursor
        IS the window's event count, so the rung is known before a
        single buffer byte moves."""
        from ..infra import faults

        faults.check(faults.SITE_RING_SWAP)
        window = _start_window(ring, self.capacity, 0,
                               self.proxy_ports, self, self.gather,
                               self.compile_log)
        return window, self.fresh()

    def swap(self, ring: EventRing) -> EventRing:
        # thread-affinity: drain, api, offline
        """Legacy single-window double buffering: start the async
        fetch, retain the window internally for :meth:`collect`.  At
        most one fetch may be in flight."""
        assert self._pending is None, "previous window not collected"
        window, fresh = self.swap_window(ring)
        self._pending = window
        return fresh

    def collect(self) -> Tuple[np.ndarray, int, int]:
        # thread-affinity: event-worker, api, offline
        """Complete the in-flight fetch -> (rows, appended, lost) for
        that window (empty result when nothing is pending)."""
        from ..infra import faults

        faults.check(faults.SITE_RING_COLLECT)
        window = self._pending
        if window is None:
            return np.zeros((0, RING_COLS), dtype=np.uint32), 0, 0
        self._pending = None
        rows, _shards, appended, lost = window.fetch()
        return rows, appended, lost


def _unpack_rows(packed: np.ndarray,
                 proxy_ports: np.ndarray = None) -> np.ndarray:
    """Packed [m, RING_WORDS] device rows -> decoded [m, RING_COLS]
    (OUT_* columns + pkt_idx + batch), pure host numpy.
    ``proxy_ports`` (same table given to :func:`ring_append`) restores
    redirect ports from their 4-bit wire index."""
    from ..datapath.verdict import (OUT_CT, OUT_ID_ROW, OUT_PROXY,
                                    OUT_REASON, OUT_VERDICT)

    w0, w1 = packed[:, 0], packed[:, 1]
    rows = np.empty((len(packed), RING_COLS), dtype=np.uint32)
    rows[:, OUT_VERDICT] = w0 & 0x7
    rows[:, OUT_EVENT] = (w0 >> 3) & 0x3
    rows[:, OUT_REASON] = (w0 >> 5) & 0xF
    rows[:, OUT_CT] = (w0 >> 9) & 0x7
    pidx = (w0 >> 12) & 0xF
    if proxy_ports is None:
        rows[:, OUT_PROXY] = 0
    else:
        # pad to the full 4-bit index space: a drain given a SHORTER
        # table than append used (listener removed between windows)
        # must degrade stale rows to port 0, not crash the drain
        table = np.zeros(MAX_PROXY_PORTS + 1, dtype=np.uint32)
        pp = np.asarray(proxy_ports, dtype=np.uint32)
        table[1:1 + len(pp)] = pp
        rows[:, OUT_PROXY] = table[pidx]
    rows[:, OUT_ID_ROW] = w0 >> 16
    rows[:, COL_PKT_IDX] = w1 & 0x7FFFF
    rows[:, COL_BATCH] = w1 >> 19
    return rows


def _decode_fetched(buf: np.ndarray, total: int, cap: int,
                    proxy_ports: np.ndarray = None,
                    gathered: bool = False
                    ) -> Tuple[np.ndarray, int, int]:
    # thread-affinity: event-worker, api, cli, offline
    """Decode ONE ring's fetched window given its 64-bit append total:
    wrap/lost math, empty-slot filter, wire unpack.  The single
    definition of the drain rules — :func:`ring_drain` (one ring),
    :func:`sharded_ring_drain` (per-chip rings), and
    :meth:`RingWindow.fetch` (the async event plane) all land here,
    so a future wire-format change (e.g. widening the 4-bit reason
    field) lands in one place.

    ``gathered=True`` means ``buf`` is a :func:`ring_gather` output:
    already rotated into append order on device (its length is the
    rung, not the capacity), so only the prefix/empty filter
    applies."""
    lost = max(0, total - cap)
    if gathered:
        rows = buf[:min(total, cap, buf.shape[0])]
    elif total <= cap:
        rows = buf[:total]
    else:
        head = total & (cap - 1)
        rows = np.concatenate([buf[head:], buf[:head]])
    # empty slots carry event bits 0b11 (no EV_* code is 3)
    rows = rows[((rows[:, 0] >> 3) & 0x3) != 0x3]
    return _unpack_rows(rows, proxy_ports), total, lost


def _drain_window(buf: np.ndarray, cursor: np.ndarray,
                  proxy_ports: np.ndarray = None
                  ) -> Tuple[np.ndarray, int, int]:
    """Legacy full-copy decode: cursor words -> total, then
    :func:`_decode_fetched` over the whole fetched buffer."""
    total = int(_cursor_totals(cursor)[0])
    return _decode_fetched(buf, total, buf.shape[0], proxy_ports)


def sharded_ring_drain(buf: np.ndarray, cursor: np.ndarray,
                       proxy_ports: np.ndarray = None
                       ) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Host decode of a SHARDED ring window (per-chip private rings
    drained round-robin, shard 0 first).

    ``buf`` is the fetched [n_shards * cap, RING_WORDS] buffer (shard
    s owns rows [s*cap, (s+1)*cap)), ``cursor`` the [n_shards, 2]
    per-shard cursors.  Returns ``(rows, shard_ids, appended, lost)``
    — ``rows`` decoded like :func:`ring_drain` with shard-LOCAL packet
    indices, ``shard_ids`` aligned per row so the caller can map a row
    back to its per-shard header block (global row = shard * block +
    pkt_idx; the header join knows each batch's block)."""
    n_shards = cursor.shape[0]
    cap = buf.shape[0] // n_shards
    parts: List[np.ndarray] = []
    shard_ids: List[np.ndarray] = []
    appended = lost = 0
    for s in range(n_shards):
        rows, total, lost_s = _drain_window(
            buf[s * cap:(s + 1) * cap], cursor[s], proxy_ports)
        parts.append(rows)
        shard_ids.append(np.full(len(rows), s, dtype=np.int64))
        appended += total
        lost += lost_s
    return (np.concatenate(parts) if parts
            else np.zeros((0, RING_COLS), dtype=np.uint32),
            np.concatenate(shard_ids) if shard_ids
            else np.zeros(0, dtype=np.int64),
            appended, lost)


class ShardedAsyncRingDrainer:
    """The :class:`AsyncRingDrainer` shape for per-chip rings: one
    device-sharded (buf, cursor) pair holds every chip's private ring;
    ``swap`` starts the async fetch of the just-filled window and
    hands back a fresh one, ``collect`` completes it and decodes the
    shards round-robin.  Loss accounting is per shard per window
    (every window starts on fresh rings), summed."""

    def __init__(self, capacity: int, n_shards: int,
                 fresh_fn, proxy_ports: np.ndarray = None,
                 gather: bool = True, compile_log=None):
        # fresh_fn: () -> device EventRing with buf [S*cap, RING_WORDS]
        # sharded on axis 0 and cursor [S, 2] sharded (parallel.mesh
        # builds it — placement needs the mesh, which lives there)
        self.capacity = capacity
        self.n_shards = n_shards
        self.proxy_ports = proxy_ports
        self._fresh_fn = fresh_fn
        self.gather = bool(gather)
        self.compile_log = compile_log
        self._pending: Optional[RingWindow] = None
        self.windows = 0
        self.events = 0
        self.lost = 0

    def fresh(self):
        return self._fresh_fn()

    def swap_window(self, ring) -> Tuple[RingWindow, object]:
        # thread-affinity: drain, api, offline
        """Same cursor-first sync discipline as the single-chip
        drainer (see AsyncRingDrainer.swap_window): block on the
        small cursor, then the (gathered) buffer bytes stream in the
        background.  The gather rung is COMMON across shards (the max
        occupancy, bucketed) so the fetched layout stays one block
        per shard."""
        from ..infra import faults

        faults.check(faults.SITE_RING_SWAP)
        window = _start_window(ring, self.capacity, self.n_shards,
                               self.proxy_ports, self, self.gather,
                               self.compile_log)
        return window, self.fresh()

    def swap(self, ring):
        # thread-affinity: drain, api, offline
        assert self._pending is None, "previous window not collected"
        window, fresh = self.swap_window(ring)
        self._pending = window
        return fresh

    def collect(self) -> Tuple[np.ndarray, np.ndarray, int, int]:
        # thread-affinity: event-worker, api, offline
        from ..infra import faults

        faults.check(faults.SITE_RING_COLLECT)
        window = self._pending
        if window is None:
            return (np.zeros((0, RING_COLS), dtype=np.uint32),
                    np.zeros(0, dtype=np.int64), 0, 0)
        self._pending = None
        rows, shards, appended, lost = window.fetch()
        return rows, shards, appended, lost


def ring_drain(ring: EventRing,
               proxy_ports: np.ndarray = None
               ) -> Tuple[np.ndarray, int, int]:
    """Fetch + decode the ring on host.

    Returns (rows [m, RING_COLS] in append order, total_appended,
    n_overwritten).  The single host fetch happens HERE, at the
    monitor's cadence — never in the datapath hot loop."""
    return _drain_window(np.asarray(ring.buf), np.asarray(ring.cursor),
                         proxy_ports)
