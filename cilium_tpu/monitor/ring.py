"""Device-resident event ring: the eventsmap/perf-buffer analogue.

Reference: upstream cilium's datapath emits events into a kernel perf
ring (``pkg/monitor/agent`` reads it); userspace drains at its own
cadence and the ring overwrites when the consumer lags.  TPU-first
redesign: the ring is a fixed HBM buffer; the fused pipeline appends
**compacted** events (drops + policy verdicts on NEW connections +
1/``trace_sample`` of established-flow traces — exactly the reference's
event economy, where TraceNotify is sampled and established traffic is
counted in the metricsmap, not streamed) entirely on device.  The host
drains asynchronously — so the hot loop never blocks on device→host
transfers, which is also what makes end-to-end benchmarking viable on
hosts where the d2h path is expensive (e.g. tunneled TPUs).

Ring semantics: wrap-overwrite (newest wins), like the Hubble observer
ring; total appended count is monotone so the host computes loss as
``appended - capacity`` when it lags a full lap.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..datapath.verdict import EV_TRACE, N_OUT, OUT_EVENT

# ring row: the N_OUT out-columns + packet index within batch + batch seq
RING_COLS = N_OUT + 2
COL_PKT_IDX = N_OUT
COL_BATCH = N_OUT + 1
EMPTY_BATCH = 0xFFFFFFFF


@jax.tree_util.register_pytree_node_class
@dataclass
class EventRing:
    """Device state of the ring (pytree: threads through jit)."""

    buf: jnp.ndarray  # [capacity, RING_COLS] uint32
    # total events ever appended, as TWO u32 words [lo, hi] — a single
    # u32 wraps after 2^32 events (hours at target rates; the reference
    # perf/Hubble rings count in u64) and a wrapped cursor makes drain
    # misread a full ring as nearly empty.  x64 is off under jit, so
    # the 64-bit count is carried as lo + carry-into-hi on device.
    cursor: jnp.ndarray  # [2] uint32

    @staticmethod
    def create(capacity: int = 1 << 15) -> "EventRing":
        assert capacity & (capacity - 1) == 0, "capacity must be 2^k"
        buf = jnp.full((capacity, RING_COLS), EMPTY_BATCH,
                       dtype=jnp.uint32)
        return EventRing(buf=buf, cursor=jnp.zeros((2,), jnp.uint32))

    @property
    def capacity(self) -> int:
        return self.buf.shape[0]

    def tree_flatten(self):
        return ((self.buf, self.cursor), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def ring_append(ring: EventRing, out: jnp.ndarray, batch_id: jnp.ndarray,
                trace_sample: int = 1024,
                valid: jnp.ndarray = None) -> EventRing:
    """Compact one batch's out tensor into the ring (pure device op).

    Keeps every non-TRACE event (drops, NEW-connection policy
    verdicts) plus one in ``trace_sample`` established-flow traces
    (``trace_sample=0`` disables trace sampling entirely).
    """
    n = out.shape[0]
    idx = jnp.arange(n, dtype=jnp.uint32)
    keep = out[:, OUT_EVENT] != EV_TRACE
    if trace_sample:
        keep = keep | (idx % trace_sample == 0)
    if valid is not None:
        keep = keep & valid
    pos = jnp.cumsum(keep) - 1  # position among kept rows
    count = keep.sum().astype(jnp.uint32)
    mask = ring.capacity - 1
    lo, hi = ring.cursor[0], ring.cursor[1]
    slot = ((lo + pos.astype(jnp.uint32)) & mask).astype(jnp.int32)
    # newest-wins under overflow: when one batch keeps more events than
    # the ring holds, only the newest `capacity` rows write — otherwise
    # duplicate slot indices in one scatter would make the survivor
    # order unspecified
    newest = pos.astype(jnp.uint32) + ring.capacity >= count
    target = jnp.where(keep & newest, slot, ring.capacity)  # OOB dropped
    rows = jnp.concatenate([
        out.astype(jnp.uint32),
        idx[:, None],
        jnp.full((n, 1), batch_id, dtype=jnp.uint32),
    ], axis=1)
    buf = ring.buf.at[target].set(rows, mode="drop")
    new_lo = lo + count
    new_hi = hi + (new_lo < lo).astype(jnp.uint32)  # carry
    return EventRing(buf=buf, cursor=jnp.stack([new_lo, new_hi]))


ring_append_jit = jax.jit(ring_append, donate_argnums=0,
                          static_argnames=("trace_sample",))


def serve_step(state, ring: EventRing, hdr: jnp.ndarray,
               now: jnp.ndarray, batch_id: jnp.ndarray,
               trace_sample: int = 1024, valid: jnp.ndarray = None):
    """The serving-path step: fused datapath + event-ring append in ONE
    executable (one dispatch per batch; out rows that the compaction
    discards are never materialized).  Returns (state, ring)."""
    from ..datapath.verdict import datapath_step

    out, state = datapath_step(state, hdr, now, valid=valid)
    ring = ring_append(ring, out, batch_id, trace_sample=trace_sample,
                       valid=valid)
    return state, ring


serve_step_jit = jax.jit(serve_step, donate_argnums=(0, 1),
                         static_argnames=("trace_sample",))


def serve_step_packed(state, ring: EventRing, packed: jnp.ndarray,
                      now: jnp.ndarray, batch_id: jnp.ndarray,
                      ep, dirn, trace_sample: int = 1024):
    """Serving path for the packed ingest format (16 B/packet h2d):
    unpack + fused datapath + ring append, ONE dispatch per batch."""
    from ..datapath.verdict import datapath_step_packed

    out, state = datapath_step_packed(state, packed, now, ep, dirn)
    ring = ring_append(ring, out, batch_id, trace_sample=trace_sample)
    return state, ring


serve_step_packed_jit = jax.jit(serve_step_packed, donate_argnums=(0, 1),
                                static_argnames=("trace_sample",))


def ring_drain(ring: EventRing) -> Tuple[np.ndarray, int, int]:
    """Fetch + decode the ring on host.

    Returns (rows [m, RING_COLS] in append order, total_appended,
    n_overwritten).  The single host fetch happens HERE, at the
    monitor's cadence — never in the datapath hot loop."""
    buf = np.asarray(ring.buf)
    lo, hi = (int(w) for w in np.asarray(ring.cursor))
    total = (hi << 32) | lo
    cap = buf.shape[0]
    if total <= cap:
        rows = buf[:total]
        lost = 0
    else:
        head = total & (cap - 1)
        rows = np.concatenate([buf[head:], buf[:head]])
        lost = total - cap
    rows = rows[rows[:, COL_BATCH] != EMPTY_BATCH]
    return rows, total, lost
