"""Device-resident event ring: the eventsmap/perf-buffer analogue.

Reference: upstream cilium's datapath emits events into a kernel perf
ring (``pkg/monitor/agent`` reads it); userspace drains at its own
cadence and the ring overwrites when the consumer lags.  TPU-first
redesign: the ring is a fixed HBM buffer; the fused pipeline appends
**compacted** events (drops + policy verdicts on NEW connections +
1/``trace_sample`` of established-flow traces — exactly the reference's
event economy, where TraceNotify is sampled and established traffic is
counted in the metricsmap, not streamed) entirely on device.  The host
drains asynchronously — so the hot loop never blocks on device→host
transfers, which is also what makes end-to-end benchmarking viable on
hosts where the d2h path is expensive (e.g. tunneled TPUs).

Ring semantics: wrap-overwrite (newest wins), like the Hubble observer
ring; total appended count is monotone so the host computes loss as
``appended - capacity`` when it lags a full lap.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..datapath.verdict import EV_TRACE, N_OUT, OUT_EVENT

# Decoded ring row: the N_OUT out-columns + packet index within batch
# + batch seq.  On DEVICE each row packs into RING_WORDS u32 (8 B
# instead of 32 B) — the drain is a device->host copy, and its
# bandwidth is the monitor plane's ceiling (PCIe on direct-attached
# TPUs, worse on tunneled hosts), so the wire format is packed exactly
# like the reference keeps perf events small.  r05: 12 B -> 8 B by
# (a) storing the proxy PORT as a 4-bit index into the small listener
# table (there are at most a handful of live redirect listeners —
# upstream allocates them from a ~dozen-wide range) and (b) shrinking
# the batch-seq field to 13 bits (it disambiguates/orders events
# within a drain window; windows are a few dozen batches).
# Packing (see _unpack_rows for the decode):
#   w0: verdict(0..2) | event(3..4) | reason(5..8) | ct(9..11)
#       | proxy_idx(12..15) | id_row(16..31)
#   w1: pkt_idx(0..18) | batch(19..31, wraps)
# The 4-bit reason field holds codes 0..15.  N_REASONS is 12 —
# REASON_DISPATCH_TIMEOUT (10) and REASON_RECOVERY_DROP (11) are
# RESERVED for the serving recovery plane (host-synthesized, so they
# never transit this ring today, but the wire width must cover them:
# a drained row's reason decodes through the same DROP_REASON_NAMES
# table).  4 codes (12..15) remain before the field must widen.
# Limits (asserted where they bind): id_row < 2^16, pkt_idx < 2^19
# (batches up to 512k rows), batch seq wraps at 2^13, <= 15 live
# proxy listeners.  Empty slots carry event bits 0b11 (no EV_* code
# uses 3), which is how the drain drops never-written rows.
RING_COLS = N_OUT + 2
COL_PKT_IDX = N_OUT
COL_BATCH = N_OUT + 1
EMPTY_BATCH = 0xFFFFFFFF
RING_WORDS = 2
MAX_PROXY_PORTS = 15
_EMPTY = 0xFFFFFFFF


@jax.tree_util.register_pytree_node_class
@dataclass
class EventRing:
    """Device state of the ring (pytree: threads through jit)."""

    buf: jnp.ndarray  # [capacity, RING_WORDS] uint32 (packed rows)
    # total events ever appended, as TWO u32 words [lo, hi] — a single
    # u32 wraps after 2^32 events (hours at target rates; the reference
    # perf/Hubble rings count in u64) and a wrapped cursor makes drain
    # misread a full ring as nearly empty.  x64 is off under jit, so
    # the 64-bit count is carried as lo + carry-into-hi on device.
    cursor: jnp.ndarray  # [2] uint32

    @staticmethod
    def create(capacity: int = 1 << 15) -> "EventRing":
        assert capacity & (capacity - 1) == 0, "capacity must be 2^k"
        buf = jnp.full((capacity, RING_WORDS), _EMPTY,
                       dtype=jnp.uint32)
        return EventRing(buf=buf, cursor=jnp.zeros((2,), jnp.uint32))

    @property
    def capacity(self) -> int:
        return self.buf.shape[0]

    def tree_flatten(self):
        return ((self.buf, self.cursor), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def ring_append(ring: EventRing, out: jnp.ndarray, batch_id: jnp.ndarray,
                trace_sample: int = 1024,
                valid: jnp.ndarray = None,
                proxy_ports: jnp.ndarray = None) -> EventRing:
    """Compact one batch's out tensor into the ring (pure device op).

    Keeps every non-TRACE event (drops, NEW-connection policy
    verdicts) plus one in ``trace_sample`` established-flow traces
    (``trace_sample=0`` disables trace sampling entirely).

    ``proxy_ports`` is the live listener table ([MAX_PROXY_PORTS]
    uint32, 0-padded): redirect events store the PORT's index in it
    (4 bits on the wire); pass the same table to :func:`ring_drain`
    to restore ports.  Without it redirect events decode with proxy
    port 0.
    """
    n = out.shape[0]
    idx = jnp.arange(n, dtype=jnp.uint32)
    keep = out[:, OUT_EVENT] != EV_TRACE
    if trace_sample:
        keep = keep | (idx % trace_sample == 0)
    if valid is not None:
        keep = keep & valid
    assert n <= (1 << 19), "pkt_idx packs into 19 bits"
    pos = jnp.cumsum(keep) - 1  # position among kept rows
    count = keep.sum().astype(jnp.uint32)
    mask = ring.capacity - 1
    lo, hi = ring.cursor[0], ring.cursor[1]
    slot = ((lo + pos.astype(jnp.uint32)) & mask).astype(jnp.int32)
    # newest-wins under overflow: when one batch keeps more events than
    # the ring holds, only the newest `capacity` rows write — otherwise
    # duplicate slot indices in one scatter would make the survivor
    # order unspecified
    newest = pos.astype(jnp.uint32) + ring.capacity >= count
    target = jnp.where(keep & newest, slot, ring.capacity)  # OOB dropped
    o = out.astype(jnp.uint32)
    from ..datapath.verdict import (OUT_CT, OUT_ID_ROW, OUT_PROXY,
                                    OUT_REASON, OUT_VERDICT)

    if proxy_ports is None or proxy_ports.shape[0] == 0:
        # an EMPTY table also means "no listeners" — the sharded step
        # passes a zero-length placeholder because shard_map wants a
        # fixed arity (argmax over a 0-wide axis would be an error)
        pidx = jnp.zeros(n, dtype=jnp.uint32)
    else:
        assert proxy_ports.shape[0] <= MAX_PROXY_PORTS, \
            "listener index packs into 4 bits"
        port = o[:, OUT_PROXY]
        hit = port[:, None] == proxy_ports[None, :].astype(jnp.uint32)
        pidx = jnp.where(
            jnp.any(hit, axis=1) & (port != 0),
            jnp.argmax(hit, axis=1).astype(jnp.uint32) + 1,
            jnp.uint32(0))
    # mask each field to its wire width: a value past its width must
    # corrupt only itself, never a neighbor (the empty-slot sentinel
    # lives in the event bits)
    w0 = ((o[:, OUT_VERDICT] & 0x7) | ((o[:, OUT_EVENT] & 0x3) << 3)
          | ((o[:, OUT_REASON] & 0xF) << 5) | ((o[:, OUT_CT] & 0x7) << 9)
          | (pidx << 12) | ((o[:, OUT_ID_ROW] & 0xFFFF) << 16))
    w1 = idx | ((jnp.uint32(batch_id) & jnp.uint32(0x1FFF)) << 19)
    rows = jnp.stack([w0, w1], axis=1)
    buf = ring.buf.at[target].set(rows, mode="drop")
    new_lo = lo + count
    new_hi = hi + (new_lo < lo).astype(jnp.uint32)  # carry
    return EventRing(buf=buf, cursor=jnp.stack([new_lo, new_hi]))


ring_append_jit = jax.jit(ring_append, donate_argnums=0,
                          static_argnames=("trace_sample",))


def serve_step(state, ring: EventRing, hdr: jnp.ndarray,
               now: jnp.ndarray, batch_id: jnp.ndarray,
               trace_sample: int = 1024, valid: jnp.ndarray = None,
               proxy_ports: jnp.ndarray = None, audit: bool = False):
    """The serving-path step: fused datapath + event-ring append in ONE
    executable (one dispatch per batch; out rows that the compaction
    discards are never materialized).  Returns (state, ring)."""
    from ..datapath.verdict import datapath_step

    out, state = datapath_step(state, hdr, now, valid=valid,
                               audit=audit)
    ring = ring_append(ring, out, batch_id, trace_sample=trace_sample,
                       valid=valid, proxy_ports=proxy_ports)
    return state, ring


serve_step_jit = jax.jit(serve_step, donate_argnums=(0, 1),
                         static_argnames=("trace_sample", "audit"))


def serve_step_packed(state, ring: EventRing, packed: jnp.ndarray,
                      now: jnp.ndarray, batch_id: jnp.ndarray,
                      ep, dirn, trace_sample: int = 1024,
                      valid: jnp.ndarray = None,
                      proxy_ports: jnp.ndarray = None,
                      audit: bool = False):
    """Serving path for the packed ingest format (16 B/packet h2d):
    unpack + fused datapath + ring append, ONE dispatch per batch.
    ``valid`` masks the adaptive batcher's padding rows exactly like
    the wide :func:`serve_step` — padding touches neither CT, metrics,
    nor the ring, so each bucket size stays one compiled shape."""
    from ..datapath.verdict import datapath_step_packed

    out, state = datapath_step_packed(state, packed, now, ep, dirn,
                                      valid=valid, audit=audit)
    ring = ring_append(ring, out, batch_id, trace_sample=trace_sample,
                       valid=valid, proxy_ports=proxy_ports)
    return state, ring


serve_step_packed_jit = jax.jit(serve_step_packed, donate_argnums=(0, 1),
                                static_argnames=("trace_sample",
                                                 "audit"))


class AsyncRingDrainer:
    """Double-buffered drain: the host fetches window N-1 while the
    device steps window N.

    ``ring_drain`` blocks on a device->host copy that must first
    retire every dispatch queued since the previous fetch — on
    tunneled TPUs that sync debt dominates the drain (r04:
    drain_ms_median 10.3 s).  Double buffering hides it: at each
    window boundary ``swap(ring)`` starts an ASYNC copy of the
    just-filled ring and hands the serve loop a fresh one, and
    ``collect()`` completes the transfer that has been streaming in
    the background — by then the bytes are already on host.  This is
    also the production shape of the reference's perf-buffer consumer
    (the kernel keeps appending to live pages while userspace reads
    the pages it was handed).

    Because every window starts on a fresh ring, the fetched cursor
    IS the window's append count and per-window loss is
    ``max(0, appended - capacity)`` with no cross-window bookkeeping.
    """

    def __init__(self, capacity: int = 1 << 15,
                 proxy_ports: np.ndarray = None):
        self.capacity = capacity
        self.proxy_ports = proxy_ports
        self._pending: EventRing = None
        self.windows = 0
        self.events = 0
        self.lost = 0

    def fresh(self) -> EventRing:
        return EventRing.create(self.capacity)

    def swap(self, ring: EventRing) -> EventRing:
        """Start the async fetch of ``ring``; returns the fresh ring
        for the next window.  At most one fetch may be in flight:
        call :meth:`collect` first.

        The block_until_ready on the CURSOR before the copy is
        load-bearing on tunneled runtimes: a d2h transfer with queued
        dispatches pays a pathological per-dispatch flush (~9 s each,
        measured r05), while blocking on the tiny cursor drains the
        same queue in milliseconds (blocking on the large buffer
        triggers the slow path itself — sync on the scalar, then the
        copies only move bytes)."""
        from ..infra import faults

        faults.check(faults.SITE_RING_SWAP)
        assert self._pending is None, "previous window not collected"
        ring.cursor.block_until_ready()
        ring.buf.copy_to_host_async()
        ring.cursor.copy_to_host_async()
        self._pending = ring
        return self.fresh()

    def collect(self) -> Tuple[np.ndarray, int, int]:
        """Complete the in-flight fetch -> (rows, appended, lost) for
        that window (empty result when nothing is pending)."""
        from ..infra import faults

        faults.check(faults.SITE_RING_COLLECT)
        ring = self._pending
        if ring is None:
            return np.zeros((0, RING_COLS), dtype=np.uint32), 0, 0
        self._pending = None
        rows, appended, lost = ring_drain(ring, self.proxy_ports)
        self.windows += 1
        self.events += appended - lost
        self.lost += lost
        return rows, appended, lost


def _unpack_rows(packed: np.ndarray,
                 proxy_ports: np.ndarray = None) -> np.ndarray:
    """Packed [m, RING_WORDS] device rows -> decoded [m, RING_COLS]
    (OUT_* columns + pkt_idx + batch), pure host numpy.
    ``proxy_ports`` (same table given to :func:`ring_append`) restores
    redirect ports from their 4-bit wire index."""
    from ..datapath.verdict import (OUT_CT, OUT_ID_ROW, OUT_PROXY,
                                    OUT_REASON, OUT_VERDICT)

    w0, w1 = packed[:, 0], packed[:, 1]
    rows = np.empty((len(packed), RING_COLS), dtype=np.uint32)
    rows[:, OUT_VERDICT] = w0 & 0x7
    rows[:, OUT_EVENT] = (w0 >> 3) & 0x3
    rows[:, OUT_REASON] = (w0 >> 5) & 0xF
    rows[:, OUT_CT] = (w0 >> 9) & 0x7
    pidx = (w0 >> 12) & 0xF
    if proxy_ports is None:
        rows[:, OUT_PROXY] = 0
    else:
        # pad to the full 4-bit index space: a drain given a SHORTER
        # table than append used (listener removed between windows)
        # must degrade stale rows to port 0, not crash the drain
        table = np.zeros(MAX_PROXY_PORTS + 1, dtype=np.uint32)
        pp = np.asarray(proxy_ports, dtype=np.uint32)
        table[1:1 + len(pp)] = pp
        rows[:, OUT_PROXY] = table[pidx]
    rows[:, OUT_ID_ROW] = w0 >> 16
    rows[:, COL_PKT_IDX] = w1 & 0x7FFFF
    rows[:, COL_BATCH] = w1 >> 19
    return rows


def _drain_window(buf: np.ndarray, cursor: np.ndarray,
                  proxy_ports: np.ndarray = None
                  ) -> Tuple[np.ndarray, int, int]:
    """Decode ONE ring's fetched window: 64-bit cursor assembly,
    wrap/lost math, empty-slot filter, wire unpack.  The single
    definition of the drain rules — :func:`ring_drain` (one ring) and
    :func:`sharded_ring_drain` (per-chip rings) both call it, so a
    future wire-format change (e.g. widening the 4-bit reason field)
    lands in one place."""
    lo, hi = int(cursor[0]), int(cursor[1])
    total = (hi << 32) | lo
    cap = buf.shape[0]
    if total <= cap:
        rows = buf[:total]
        lost = 0
    else:
        head = total & (cap - 1)
        rows = np.concatenate([buf[head:], buf[:head]])
        lost = total - cap
    # empty slots carry event bits 0b11 (no EV_* code is 3)
    rows = rows[((rows[:, 0] >> 3) & 0x3) != 0x3]
    return _unpack_rows(rows, proxy_ports), total, lost


def sharded_ring_drain(buf: np.ndarray, cursor: np.ndarray,
                       proxy_ports: np.ndarray = None
                       ) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Host decode of a SHARDED ring window (per-chip private rings
    drained round-robin, shard 0 first).

    ``buf`` is the fetched [n_shards * cap, RING_WORDS] buffer (shard
    s owns rows [s*cap, (s+1)*cap)), ``cursor`` the [n_shards, 2]
    per-shard cursors.  Returns ``(rows, shard_ids, appended, lost)``
    — ``rows`` decoded like :func:`ring_drain` with shard-LOCAL packet
    indices, ``shard_ids`` aligned per row so the caller can map a row
    back to its per-shard header block (global row = shard * block +
    pkt_idx; the header join knows each batch's block)."""
    n_shards = cursor.shape[0]
    cap = buf.shape[0] // n_shards
    parts: List[np.ndarray] = []
    shard_ids: List[np.ndarray] = []
    appended = lost = 0
    for s in range(n_shards):
        rows, total, lost_s = _drain_window(
            buf[s * cap:(s + 1) * cap], cursor[s], proxy_ports)
        parts.append(rows)
        shard_ids.append(np.full(len(rows), s, dtype=np.int64))
        appended += total
        lost += lost_s
    return (np.concatenate(parts) if parts
            else np.zeros((0, RING_COLS), dtype=np.uint32),
            np.concatenate(shard_ids) if shard_ids
            else np.zeros(0, dtype=np.int64),
            appended, lost)


class ShardedAsyncRingDrainer:
    """The :class:`AsyncRingDrainer` shape for per-chip rings: one
    device-sharded (buf, cursor) pair holds every chip's private ring;
    ``swap`` starts the async fetch of the just-filled window and
    hands back a fresh one, ``collect`` completes it and decodes the
    shards round-robin.  Loss accounting is per shard per window
    (every window starts on fresh rings), summed."""

    def __init__(self, capacity: int, n_shards: int,
                 fresh_fn, proxy_ports: np.ndarray = None):
        # fresh_fn: () -> device EventRing with buf [S*cap, RING_WORDS]
        # sharded on axis 0 and cursor [S, 2] sharded (parallel.mesh
        # builds it — placement needs the mesh, which lives there)
        self.capacity = capacity
        self.n_shards = n_shards
        self.proxy_ports = proxy_ports
        self._fresh_fn = fresh_fn
        self._pending = None
        self.windows = 0
        self.events = 0
        self.lost = 0

    def fresh(self):
        return self._fresh_fn()

    def swap(self, ring):
        """Same cursor-first sync discipline as the single-chip
        drainer (see AsyncRingDrainer.swap): block on the small
        cursor, then the buffer bytes stream in the background."""
        from ..infra import faults

        faults.check(faults.SITE_RING_SWAP)
        assert self._pending is None, "previous window not collected"
        ring.cursor.block_until_ready()
        ring.buf.copy_to_host_async()
        ring.cursor.copy_to_host_async()
        self._pending = ring
        return self.fresh()

    def collect(self) -> Tuple[np.ndarray, np.ndarray, int, int]:
        from ..infra import faults

        faults.check(faults.SITE_RING_COLLECT)
        ring = self._pending
        if ring is None:
            return (np.zeros((0, RING_COLS), dtype=np.uint32),
                    np.zeros(0, dtype=np.int64), 0, 0)
        self._pending = None
        rows, shards, appended, lost = sharded_ring_drain(
            np.asarray(ring.buf), np.asarray(ring.cursor),
            self.proxy_ports)
        self.windows += 1
        self.events += appended - lost
        self.lost += lost
        return rows, shards, appended, lost


def ring_drain(ring: EventRing,
               proxy_ports: np.ndarray = None
               ) -> Tuple[np.ndarray, int, int]:
    """Fetch + decode the ring on host.

    Returns (rows [m, RING_COLS] in append order, total_appended,
    n_overwritten).  The single host fetch happens HERE, at the
    monitor's cadence — never in the datapath hot loop."""
    return _drain_window(np.asarray(ring.buf), np.asarray(ring.cursor),
                         proxy_ports)
