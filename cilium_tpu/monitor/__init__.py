"""Monitor plane: the datapath event bus (perf ring buffer analogue).

Reference: upstream cilium ``pkg/monitor`` — the perf-buffer reader
that fans datapath events (drop/trace/policy-verdict) out to the
``cilium monitor`` CLI and to Hubble.  TPU-first redesign: the device
returns a per-packet out tensor from the fused pipeline; the host
decodes it **vectorized** into a struct-of-arrays event batch, and the
agent fans that out to subscribers (Hubble consumer, CLI stream,
exporters) without per-event Python object churn.
"""

from .api import (  # noqa: F401
    MSG_DROP,
    MSG_POLICY_VERDICT,
    MSG_TRACE,
    DropNotify,
    EventBatch,
    MonitorEvent,
    PolicyVerdictNotify,
    TraceNotify,
    decode_out,
    decode_ring_rows,
)
from .agent import MonitorAgent  # noqa: F401
from .ring import (  # noqa: F401
    EventRing,
    ring_append,
    ring_append_jit,
    ring_drain,
)
