"""Monitor agent: fan-out of event batches to subscribers.

Reference: upstream cilium ``pkg/monitor/agent`` — the perf-buffer
reader loop that multiplexes events to unix-socket listeners (the
``cilium monitor`` CLI) and in-process consumers (Hubble).  Here the
"reader loop" is :meth:`MonitorAgent.publish` called by the datapath
loader after each device step with the decoded :class:`EventBatch`;
subscribers receive whole batches (SoA), not per-event callbacks, so
the observability plane stays vectorized end to end.

Lost-event accounting: a slow subscriber does not block the datapath —
batches are dropped for that subscriber past a queue bound and counted
(the perf ring buffer overflow analogue).
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Deque, Dict, List, Optional

from .api import EventBatch

Consumer = Callable[[EventBatch], None]


class MonitorAgent:
    def __init__(self, queue_depth: int = 64):
        self._consumers: Dict[str, Consumer] = {}
        self._queues: Dict[str, Deque[EventBatch]] = {}
        self._lost: Dict[str, int] = {}
        self._queue_depth = queue_depth
        self._lock = threading.Lock()
        # guarded-by: _lock: _consumers, _queues, _lost
        # serializes the publish fan-out across emitting threads
        # (event-join worker + drain thread) — see publish()
        self._emit_lock = threading.RLock()
        # guarded-by: _emit_lock: published
        self.published = 0

    def register(self, name: str, consumer: Consumer) -> None:
        # thread-affinity: any
        """In-process consumer (e.g. the Hubble observer)."""
        with self._lock:
            self._consumers[name] = consumer
            self._lost.setdefault(name, 0)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._consumers.pop(name, None)

    def subscribe_queue(self, name: str) -> Deque[EventBatch]:
        """Pull-style subscriber (CLI streamers poll this queue)."""
        with self._lock:
            q: Deque[EventBatch] = collections.deque(
                maxlen=self._queue_depth)
            self._queues[name] = q
            self._lost.setdefault(name, 0)
            return q

    def unsubscribe_queue(self, name: str) -> None:
        with self._lock:
            self._queues.pop(name, None)

    def publish(self, batch: EventBatch) -> None:
        # thread-affinity: any
        """Called by the loader after each datapath step.

        The fan-out is serialized under ``_emit_lock``: since the
        async event plane (PR 5) ring-event joins publish from the
        event-join WORKER while host-synthesized drops (shed /
        recovery events) still publish from the drain thread, and
        consumers (flow aggregation, metrics dicts) are not
        individually thread-safe.  Reentrant (RLock) so a consumer
        that publishes derived events from its callback cannot
        deadlock itself.

        ``_lost`` increments take ``_lock``: they used to mutate
        under ``_emit_lock`` only, racing the ``setdefault`` in
        ``register``/``subscribe_queue`` (two locks guarding one
        dict can lose an increment on a concurrent first-register —
        the static guarded-by pass surfaced it)."""
        with self._lock:
            consumers = list(self._consumers.items())
            queues = list(self._queues.items())
        with self._emit_lock:
            self.published += len(batch)
            for name, consumer in consumers:
                try:
                    consumer(batch)
                except Exception:
                    # a broken consumer must not take down the
                    # datapath
                    with self._lock:
                        self._lost[name] = (self._lost.get(name, 0)
                                            + len(batch))
            for name, q in queues:
                if q.maxlen is not None and len(q) == q.maxlen:
                    with self._lock:
                        self._lost[name] = (self._lost.get(name, 0)
                                            + len(q[0]))
                q.append(batch)

    def lost_count(self, name: str) -> int:
        # thread-affinity: any
        with self._lock:
            return self._lost.get(name, 0)
