"""Monitor event vocabulary + vectorized decode of the out tensor.

Reference: upstream cilium ``pkg/monitor/api`` message types and the
event structs emitted by ``bpf/lib/{drop,trace,policy_log}.h``:
``DropNotify``, ``TraceNotify``, ``PolicyVerdictNotify``.  Message
type numbers mirror the reference's (drop=1, trace=4, policy-verdict=9)
so exported streams read familiarly.

TPU-first: the device emits one out-tensor row per packet; the host
keeps the whole batch as a struct-of-arrays :class:`EventBatch` (no
per-event objects on the hot path) and materializes typed per-event
dataclasses only at the API/CLI edge.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterator, List, Optional, Union

import numpy as np

from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP0,
    COL_EP,
    COL_FAMILY,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP0,
    words_to_ip,
)
from ..datapath.verdict import (
    EV_DROP,
    EV_TRACE,
    EV_VERDICT,
    OUT_CT,
    OUT_EVENT,
    OUT_ID_ROW,
    OUT_PROXY,
    OUT_REASON,
    OUT_VERDICT,
)

# Reference message type numbers (pkg/monitor/api/types.go).
MSG_DROP = 1
MSG_TRACE = 4
MSG_POLICY_VERDICT = 9

_EVENT_TO_MSG = np.zeros(3, dtype=np.uint8)
_EVENT_TO_MSG[EV_TRACE] = MSG_TRACE
_EVENT_TO_MSG[EV_VERDICT] = MSG_POLICY_VERDICT
_EVENT_TO_MSG[EV_DROP] = MSG_DROP

# Drop reason rendering (reference: bpf/lib/drop.h + monitor/api
# DropReason strings).
DROP_REASON_NAMES = {
    1: "Policy denied",
    2: "Policy denied (default deny)",
    3: "Shard queue overflow",
    4: "No endpoint found",  # lxcmap miss (unregistered endpoint id)
    5: "No mapping for NAT masquerade",  # SNAT port pool exhausted
    6: "Bandwidth limit exceeded",  # egress rate limit (EDT)
    7: "No service backend",  # frontend with no backend
    8: "Authentication required",  # mutual auth missing (pkg/auth)
    9: "Ingress queue overflow",  # serving admission shed (XDP ring)
    10: "Dispatch deadline exceeded",  # watchdog deadlined a hung dispatch
    11: "Recovery drop",  # serving recovery accounted a lost batch
    12: "Cluster router overflow",  # cluster forward queue full (router shed)
}


@dataclass
class EventBatch:
    """One device batch of monitor events as struct-of-arrays.

    Columns are aligned with the header tensor rows that produced
    them.  ``identity`` is the remote NUMERIC identity (row already
    mapped via the IdentityRowMap)."""

    msg_type: np.ndarray  # [N] u8 MSG_*
    verdict: np.ndarray  # [N] final VERDICT_* code
    reason: np.ndarray  # [N] drop reason (0 = forwarded)
    ct_state: np.ndarray  # [N] CT_* result
    identity: np.ndarray  # [N] remote numeric identity
    proxy_port: np.ndarray  # [N]
    hdr: np.ndarray  # [N, N_COLS] the originating header rows
    timestamp: float  # host clock at decode

    def __len__(self) -> int:
        return len(self.msg_type)

    def __iter__(self) -> Iterator["MonitorEvent"]:
        for i in range(len(self)):
            yield materialize(self, i)


@dataclass
class MonitorEvent:
    msg_type: int
    timestamp: float
    src_ip: str
    dst_ip: str
    sport: int
    dport: int
    proto: int
    flags: int
    length: int
    endpoint: int
    direction: int  # 0 ingress / 1 egress
    identity: int  # remote numeric identity
    verdict: int
    ct_state: int
    proxy_port: int
    reason: int

    # wire format (little-endian, fixed 44 bytes):
    # type u8, pad u8, ep u16, verdict u8, ct u8, reason u8, dir u8,
    # identity u32, proxy u16, sport u16, dport u16, proto u8, flags u8,
    # len u32, family u8, pad3, src 16B? -> too big; v4-only compact +
    # full ips as 2x16B extension for v6 is overkill here: we carry
    # src/dst as 4-word each (32B) -> total 76 bytes.
    _FMT = "<BBHBBBBIHHHBBIB3s16s16s"

    def pack(self) -> bytes:
        import ipaddress

        src = int(ipaddress.ip_address(self.src_ip))
        dst = int(ipaddress.ip_address(self.dst_ip))
        return struct.pack(
            self._FMT, self.msg_type, 0, self.endpoint & 0xFFFF,
            self.verdict, self.ct_state, self.reason, self.direction,
            self.identity, self.proxy_port, self.sport, self.dport,
            self.proto, self.flags, self.length,
            4 if ":" not in self.src_ip else 6, b"\x00" * 3,
            src.to_bytes(16, "big"), dst.to_bytes(16, "big"))

    @classmethod
    def unpack(cls, data: bytes, timestamp: float = 0.0) -> "MonitorEvent":
        (mt, _, ep, verdict, ct, reason, dirn, ident, proxy, sport,
         dport, proto, flags, length, fam, _pad, src, dst) = struct.unpack(
            cls._FMT, data)
        import ipaddress

        if fam == 4:
            src_ip = str(ipaddress.IPv4Address(src[-4:]))
            dst_ip = str(ipaddress.IPv4Address(dst[-4:]))
        else:
            src_ip = str(ipaddress.IPv6Address(src))
            dst_ip = str(ipaddress.IPv6Address(dst))
        return cls(msg_type=mt, timestamp=timestamp, src_ip=src_ip,
                   dst_ip=dst_ip, sport=sport, dport=dport, proto=proto,
                   flags=flags, length=length, endpoint=ep,
                   direction=dirn, identity=ident, verdict=verdict,
                   ct_state=ct, proxy_port=proxy, reason=reason)

    WIRE_SIZE = struct.calcsize(_FMT)


def materialize(batch: EventBatch, i: int) -> MonitorEvent:
    """One row of the SoA batch -> typed event (API edge only)."""
    r = batch.hdr[i]
    fam = int(r[COL_FAMILY])
    return MonitorEvent(
        msg_type=int(batch.msg_type[i]),
        timestamp=batch.timestamp,
        src_ip=words_to_ip(r[COL_SRC_IP0:COL_SRC_IP0 + 4], fam),
        dst_ip=words_to_ip(r[COL_DST_IP0:COL_DST_IP0 + 4], fam),
        sport=int(r[COL_SPORT]),
        dport=int(r[COL_DPORT]),
        proto=int(r[COL_PROTO]),
        flags=int(r[COL_FLAGS]),
        length=int(r[COL_LEN]),
        endpoint=int(r[COL_EP]),
        direction=int(r[COL_DIR]),
        identity=int(batch.identity[i]),
        verdict=int(batch.verdict[i]),
        ct_state=int(batch.ct_state[i]),
        proxy_port=int(batch.proxy_port[i]),
        reason=int(batch.reason[i]),
    )


# Typed views mirroring the reference's struct names ------------------


@dataclass
class DropNotify:
    """Reference: monitor/api DropNotify (type=1)."""

    event: MonitorEvent

    @property
    def reason_name(self) -> str:
        return DROP_REASON_NAMES.get(self.event.reason,
                                     f"reason {self.event.reason}")


@dataclass
class TraceNotify:
    """Reference: monitor/api TraceNotify (type=4)."""

    event: MonitorEvent


@dataclass
class PolicyVerdictNotify:
    """Reference: monitor/api PolicyVerdictNotify (type=9)."""

    event: MonitorEvent

    @property
    def allowed(self) -> bool:
        return self.event.reason == 0


def decode_out(out: np.ndarray, hdr: np.ndarray,
               row_to_numeric: np.ndarray, timestamp: float,
               valid: Optional[np.ndarray] = None) -> EventBatch:
    """Vectorized out-tensor -> EventBatch (the perf-reader loop).

    ``out`` and ``hdr`` are host numpy copies of the device tensors;
    ``row_to_numeric`` maps identity rows to numeric identities;
    ``valid`` drops padding rows from routed batches."""
    out = np.asarray(out)
    hdr = np.asarray(hdr)
    if valid is not None:
        keep = np.asarray(valid)
        out = out[keep]
        hdr = hdr[keep]
    return EventBatch(
        msg_type=_EVENT_TO_MSG[out[:, OUT_EVENT]],
        verdict=out[:, OUT_VERDICT].astype(np.uint8),
        reason=out[:, OUT_REASON].astype(np.uint8),
        ct_state=out[:, OUT_CT].astype(np.uint8),
        identity=row_to_numeric[out[:, OUT_ID_ROW]].astype(np.uint32),
        proxy_port=out[:, OUT_PROXY].astype(np.uint16),
        hdr=hdr,
        timestamp=timestamp,
    )


def synth_drop_batch(hdr: np.ndarray, reason: int,
                     timestamp: float) -> EventBatch:
    """Host-synthesized DROP events for rows that never reached the
    device — today the serving plane's admission sheds
    (``REASON_INGRESS_OVERFLOW``).  Identity is 0 (unknown): the shed
    happens BEFORE ipcache resolution, exactly like an XDP-ring drop
    fires before any per-packet program runs."""
    hdr = np.asarray(hdr)
    n = len(hdr)
    return EventBatch(
        msg_type=np.full(n, MSG_DROP, dtype=np.uint8),
        verdict=np.zeros(n, dtype=np.uint8),  # 0 = dropped
        reason=np.full(n, reason, dtype=np.uint8),
        ct_state=np.zeros(n, dtype=np.uint8),
        identity=np.zeros(n, dtype=np.uint32),
        proxy_port=np.zeros(n, dtype=np.uint16),
        hdr=hdr,
        timestamp=timestamp,
    )


def decode_ring_rows(rows: np.ndarray, hdr: np.ndarray,
                     row_to_numeric: np.ndarray,
                     timestamp: float,
                     aligned: bool = False) -> EventBatch:
    # thread-affinity: event-worker, cli, offline -- NEVER the drain
    # thread: per-packet decode on the dispatch path is exactly what
    # PR 5 removed (the static half of the monkeypatch thread proof)
    """Drained ring rows of ONE batch + that batch's retained host
    header tensor -> EventBatch (the serving-path perf-reader: only
    the compacted events crossed the device->host link; the header
    columns rejoin here via the rows' packet index).

    ``rows`` is a ``ring_drain`` slice whose COL_BATCH all match the
    batch ``hdr`` came from.  ``aligned=True`` means the caller
    already gathered ``hdr`` per row (the packed/sharded serving
    windows reconstruct wide columns for just the kept rows)."""
    from .ring import COL_PKT_IDX

    rows = np.asarray(rows)
    hdr = np.asarray(hdr)
    if not aligned:
        hdr = hdr[rows[:, COL_PKT_IDX].astype(np.int64)]
    return EventBatch(
        msg_type=_EVENT_TO_MSG[rows[:, OUT_EVENT]],
        verdict=rows[:, OUT_VERDICT].astype(np.uint8),
        reason=rows[:, OUT_REASON].astype(np.uint8),
        ct_state=rows[:, OUT_CT].astype(np.uint8),
        identity=row_to_numeric[rows[:, OUT_ID_ROW]].astype(np.uint32),
        proxy_port=rows[:, OUT_PROXY].astype(np.uint16),
        hdr=hdr,
        timestamp=timestamp,
    )
