"""IPCache: the IP/CIDR -> identity metadata store (host side).

Reference: upstream cilium ``pkg/ipcache`` — the authoritative map of
prefix -> security identity (+ metadata source tracking), mirrored into
the kernel LPM map.  Here it mirrors into the datapath's DIR-16-8-8
LPM tensors on every sync (the loader swap).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class IPCacheEntry:
    cidr: str
    identity: int  # numeric
    source: str = "custom"  # k8s | kvstore | custom (metadata source)


class IPCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, IPCacheEntry] = {}
        self._listeners: List[Callable[[], None]] = []

    def upsert(self, cidr: str, identity: int,
               source: str = "custom") -> None:
        with self._lock:
            self._entries[cidr] = IPCacheEntry(cidr, identity, source)
        self._changed()

    def delete(self, cidr: str) -> bool:
        with self._lock:
            found = self._entries.pop(cidr, None) is not None
        if found:
            self._changed()
        return found

    def get(self, cidr: str) -> Optional[IPCacheEntry]:
        with self._lock:
            return self._entries.get(cidr)

    def to_identity_map(self) -> Dict[str, int]:
        """cidr -> numeric identity (the loader's attach input)."""
        with self._lock:
            return {c: e.identity for c, e in self._entries.items()}

    def entries(self) -> List[IPCacheEntry]:
        with self._lock:
            return list(self._entries.values())

    def on_change(self, fn: Callable[[], None]) -> None:
        self._listeners.append(fn)

    def _changed(self) -> None:
        for fn in list(self._listeners):
            fn()
