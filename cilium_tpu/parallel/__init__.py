"""Multi-chip scale-out for the datapath (the per-CPU / per-node axis).

Reference mapping (SURVEY.md §2c): cilium's per-packet parallelism is
per-CPU kernel execution with per-CPU maps; its scale-out axis is one
agent+datapath per node.  TPU-native equivalent: the packet batch
shards across chips over a ``jax.sharding.Mesh``; policy + ipcache
tensors are replicated (they are read-only in the hot path, updated by
the control plane via broadcast, the way the kvstore replicates
identities to every node); the conntrack table is **sharded** — each
chip owns a private CT shard, and packets are routed to the chip that
owns their flow via a symmetric flow hash (RSS-style), so both
directions of a flow land on the same shard.
"""

from .mesh import (  # noqa: F401
    add_host_drops,
    add_route_overflow,
    flow_shard_ids,
    make_mesh,
    make_sharded_ring,
    make_sharded_serve_step,
    make_sharded_step,
    route_by_flow,
    shard_state,
)
