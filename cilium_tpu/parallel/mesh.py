"""Mesh construction, flow routing, and the sharded datapath step.

Reference mapping: cilium scales per-packet work across CPUs (per-CPU
eBPF execution, RSS steering flows to CPUs) and across nodes (one
agent per node, identities replicated via kvstore).  Here:

- ``make_mesh``: 1-D device mesh over the ``data`` axis (chips).
- ``flow_shard_ids``: symmetric (direction-invariant) flow hash so
  both directions of a flow land on the same chip — the RSS analogue.
- ``route_by_flow``: host-side packet steering into equal-size
  per-shard blocks (padding masked via ``valid``).
- ``make_sharded_step``: ``shard_map``-wrapped ``datapath_step`` —
  policy/ipcache tensors replicated, conntrack sharded (each chip owns
  a private CT shard), batch sharded; drop/metric counters are
  ``psum``-ed so every replica carries the global totals, the way
  every cilium agent sees the cluster-wide identity state.

Multi-host: the same mesh spans hosts under ``jax.distributed`` — XLA
runs the psums over ICI/DCN; no application code changes (the
ClusterMesh analogue).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map  # type: ignore
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from ..core.packets import (
    COL_DPORT,
    COL_DST_IP0,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP0,
    N_COLS,
)
from ..datapath.conntrack import CTTable
from ..datapath.verdict import DatapathState, datapath_step


def make_mesh(n_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def _flow_hash_mix(src: np.ndarray, dst: np.ndarray,
                   sport: np.ndarray, dport: np.ndarray,
                   proto: np.ndarray, n_shards: int) -> np.ndarray:
    """The ONE symmetric flow-hash definition (uint64 inputs).

    Commutative combines of src/dst words and ports, so forward and
    reply orientations hash identically — shared by the header path
    (:func:`flow_shard_ids`) and the CT-snapshot path
    (:func:`ct_rows_slot_ids`): a CT row MUST land on the same slot
    as the packets that created it, or scale-out migration
    (cluster/scale.py) would ship the wrong entries."""
    h = np.zeros(len(proto), dtype=np.uint64)
    for w in range(4):
        h = h * 31 + (src[:, w] + dst[:, w])
        h ^= (src[:, w] ^ dst[:, w]) * np.uint64(0x9E3779B97F4A7C15)
    h += (sport + dport) * np.uint64(0x85EBCA6B)
    h ^= (sport ^ dport) * np.uint64(0xC2B2AE35)
    h += proto
    h ^= h >> 33
    h *= np.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> 33
    return (h % np.uint64(n_shards)).astype(np.int64)


def flow_shard_ids(data: np.ndarray, n_shards: int) -> np.ndarray:
    """Symmetric flow hash -> shard id per packet (host numpy).

    Direction-invariant: uses commutative combines of src/dst words and
    ports so a flow's forward and reply packets hash identically."""
    from ..core.packets import normalize_ports

    d = data.astype(np.uint64)
    src = d[:, COL_SRC_IP0:COL_SRC_IP0 + 4]
    dst = d[:, COL_DST_IP0:COL_DST_IP0 + 4]
    # same tuple normalization as ct_keys_from_headers, or a flow's
    # packets would land on a shard that doesn't own its CT entry
    sport, dport = normalize_ports(np, d[:, COL_PROTO], d[:, COL_SPORT],
                                   d[:, COL_DPORT])
    return _flow_hash_mix(src, dst, sport, dport, d[:, COL_PROTO],
                          n_shards)


def ct_rows_slot_ids(rows: np.ndarray, n_shards: int) -> np.ndarray:
    """Dense CT snapshot rows ([n, ROW_WORDS], conntrack layout) ->
    the same flow slot :func:`flow_shard_ids` assigns the flow's
    packets.

    The CT key already carries NORMALIZED ports (word 8 =
    sport << 16 | dport after ``normalize_ports``) and the proto in
    word 9's low byte, and the hash mix is commutative in both the
    address pair and the port pair — so hashing straight from the
    key words reproduces the header-side slot regardless of which
    direction created the entry.  This is scale-out migration's
    selector: exactly the moved slots' entries ship to the new
    owner."""
    d = np.asarray(rows).astype(np.uint64)
    if d.ndim != 2 or d.shape[1] < 10:
        raise ValueError(
            f"want dense CT rows [n, ROW_WORDS], got {d.shape}")
    src = d[:, 0:4]
    dst = d[:, 4:8]
    ports = d[:, 8]
    sport = ports >> np.uint64(16)
    dport = ports & np.uint64(0xFFFF)
    proto = d[:, 9] & np.uint64(0xFF)
    return _flow_hash_mix(src, dst, sport, dport, proto, n_shards)


def route_by_flow(data: np.ndarray, n_shards: int,
                  block: Optional[int] = None,
                  out: Optional[Tuple[np.ndarray, np.ndarray,
                                      np.ndarray]] = None
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Steer packets into equal-size per-shard blocks (host side).

    Returns (routed [n_shards*block, N_COLS], valid [...] bool,
    orig_idx [...] int64 — original row index, -1 on padding,
    n_overflow — packets dropped because their shard's block was full).
    The RSS analogue: the device-side pipeline shards this batch
    contiguously; an overflow is an RSS queue overflow and MUST be
    accounted (feed ``n_overflow`` to :func:`add_route_overflow` so it
    lands in the metricsmap like CT map-pressure drops do).

    ``block`` (per-shard rows) should be FIXED by the caller across
    batches — a data-dependent shape would retrace the jitted sharded
    step every batch.  Default: 2x the fair share, rounded to a power
    of two.

    ``out`` is an optional preallocated ``(routed, valid, orig)``
    triple (e.g. serving-arena slots) with shapes
    ``[n_shards*block, N_COLS] u32 / [n_shards*block] bool / int64``
    — the serving hot path reuses buffers instead of allocating per
    batch; contents are fully overwritten."""
    ids = flow_shard_ids(data, n_shards)
    if block is None:
        fair = max(-(-len(data) // n_shards), 1)
        block = 1
        while block < 2 * fair:
            block *= 2
    # Vectorized steering (this sits in the ingest hot path — the r02
    # per-shard Python loop cost n_shards full-array passes): one
    # stable argsort groups packets by shard; a packet's slot is
    # shard*block + its rank within the shard, ranks >= block are the
    # RSS-queue-overflow drops.
    n = len(data)
    order = np.argsort(ids, kind="stable")
    sorted_ids = ids[order]
    counts = np.bincount(ids, minlength=n_shards)
    starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
    rank = np.arange(n, dtype=np.int64) - starts[sorted_ids]
    keep = rank < block
    n_overflow = int(n - keep.sum())
    dest = sorted_ids[keep] * block + rank[keep]
    src_rows = order[keep]
    if out is None:
        routed = np.zeros((n_shards * block, N_COLS), dtype=np.uint32)
        valid = np.zeros(n_shards * block, dtype=bool)
        orig = np.full(n_shards * block, -1, dtype=np.int64)
    else:
        routed, valid, orig = out
        assert routed.shape[0] == valid.shape[0] == orig.shape[0] \
            == n_shards * block, "out buffers must match the routed shape"
        routed[:] = 0
        valid[:] = False
        orig[:] = -1
    routed[dest] = data[src_rows]
    valid[dest] = True
    orig[dest] = src_rows
    return routed, valid, orig, n_overflow


def add_host_drops(state: DatapathState, reason: int,
                   n: int) -> DatapathState:
    """Account host-side drops in the device metricsmap (ingress
    column) so the loss is visible to operators exactly like CT
    map-pressure drops.  Used for every drop class that never reaches
    the device: flow-router overflow (REASON_ROUTE_OVERFLOW), and the
    serving recovery plane's lost batches (REASON_DISPATCH_TIMEOUT /
    REASON_RECOVERY_DROP).  Sharding-preserving (.at on the
    replicated array)."""
    if n == 0:
        return state
    metrics = state.metrics.at[int(reason), 0].add(jnp.uint32(n))
    return DatapathState(policy=state.policy, ipcache=state.ipcache,
                         ct=state.ct, metrics=metrics)


def add_route_overflow(state: DatapathState, n: int) -> DatapathState:
    """RSS-queue-overflow accounting: see :func:`add_host_drops`."""
    from ..datapath.verdict import REASON_ROUTE_OVERFLOW

    return add_host_drops(state, REASON_ROUTE_OVERFLOW, n)


def shard_state(state: DatapathState, mesh: Mesh,
                axis: str = "data") -> DatapathState:
    """Place device state per the sharded-step layout: CT table sharded
    over chips, everything else replicated."""
    repl = NamedSharding(mesh, P())
    # P(axis), not P(axis, None): the spellings place identically but
    # the compile cache keys on them — see make_sharded_ring
    ct_sh = NamedSharding(mesh, P(axis))
    fp_sh = NamedSharding(mesh, P(axis))

    def put(x, sharding):
        return jax.device_put(x, sharding)

    return DatapathState(
        policy=jax.tree.map(lambda x: put(x, repl), state.policy),
        ipcache=jax.tree.map(lambda x: put(x, repl), state.ipcache),
        ct=CTTable(table=put(state.ct.table, ct_sh),
                   fp=put(state.ct.fp, fp_sh),
                   dropped=put(state.ct.dropped, repl)),
        metrics=put(state.metrics, repl),
    )


def make_sharded_ring(mesh: Mesh, capacity: int, axis: str = "data"):
    """Per-chip private event rings as ONE device-sharded EventRing:
    ``buf`` [n_shards * capacity, RING_WORDS] sharded on axis 0 (shard
    s owns its contiguous block), ``cursor`` [n_shards, 2] sharded.
    Inside the sharded serve step each chip sees exactly a single-chip
    ring and appends locally — no cross-chip traffic on the monitor
    plane, the per-CPU perf-ring layout."""
    from ..monitor.ring import RING_WORDS, EventRing, _EMPTY

    assert capacity & (capacity - 1) == 0, "capacity must be 2^k"
    n_shards = mesh.devices.size
    # P(axis), NOT P(axis, None): jit normalizes output specs by
    # trimming trailing Nones, and the two spell the SAME placement —
    # but the compilation cache keys on the spelling, so a fresh ring
    # written P(axis, None) would recompile the serve step every
    # window swap (caught by the recompile-guard test)
    row_sh = NamedSharding(mesh, P(axis))
    buf = jax.device_put(
        jnp.full((n_shards * capacity, RING_WORDS), _EMPTY,
                 dtype=jnp.uint32), row_sh)
    cursor = jax.device_put(
        jnp.zeros((n_shards, 2), dtype=jnp.uint32), row_sh)
    return EventRing(buf=buf, cursor=cursor)


def make_sharded_serve_step(mesh: Mesh, axis: str = "data",
                            packed: bool = False,
                            trace_sample: int = 1024,
                            audit: bool = False) -> Callable:
    """Build the jitted multi-chip SERVING step: per shard, fused
    datapath + event-ring append (monitor/ring.py serve_step) with the
    CT private per chip, policy/ipcache replicated, counters psum-ed,
    and each chip appending to its own private ring block (see
    :func:`make_sharded_ring`).

    ``packed=True`` builds the 16 B/packet variant: ``hdr`` is the
    flow-routed packed tensor [n_shards*block, 4] and ``ep``/``dirn``
    ride as replicated scalars (stream metadata); the wide tensor is
    only ever materialized on device, per shard.

    step(state, ring, hdr, now, batch_id, valid, proxy_ports[, ep,
    dirn]) -> (state', ring') with hdr/valid sharded on the batch
    axis.  ``proxy_ports`` must be a device array (possibly length 0 —
    "no listeners"); ``trace_sample``/``audit`` are baked into the
    built step (they are per serving session, and the loader caches
    one step per configuration)."""
    from ..datapath.verdict import datapath_step, datapath_step_packed
    from ..monitor.ring import EventRing, ring_append

    state_specs = (P(), P(), P(axis, None), P(axis), P(), P())
    ring_specs = (P(axis, None), P(axis, None))
    meta_specs = ((P(), P()) if packed else ())

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=state_specs + ring_specs
        + (P(axis, None), P(), P(), P(axis), P()) + meta_specs,
        out_specs=(P(axis, None), P(axis), P(), P(),
                   P(axis, None), P(axis, None)),
    )
    def _step(policy, ipcache, ct_table, ct_fp, ct_dropped, metrics,
              rbuf, rcur, hdr, now, batch_id, valid, proxy_ports,
              *meta):
        state = DatapathState(
            policy=policy, ipcache=ipcache,
            ct=CTTable(table=ct_table, fp=ct_fp, dropped=ct_dropped),
            metrics=metrics)
        if packed:
            ep, dirn = meta
            out, ns = datapath_step_packed(state, hdr, now, ep, dirn,
                                           valid=valid, audit=audit)
        else:
            out, ns = datapath_step(state, hdr, now, valid=valid,
                                    audit=audit)
        ring = ring_append(EventRing(buf=rbuf, cursor=rcur[0]), out,
                           batch_id, trace_sample=trace_sample,
                           valid=valid, proxy_ports=proxy_ports)
        d_dropped = jax.lax.psum(ns.ct.dropped - ct_dropped, axis)
        d_metrics = jax.lax.psum(ns.metrics - metrics, axis)
        return (ns.ct.table, ns.ct.fp, ct_dropped + d_dropped,
                metrics + d_metrics, ring.buf, ring.cursor[None])

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(state: DatapathState, ring, hdr: jnp.ndarray,
             now: jnp.ndarray, batch_id: jnp.ndarray,
             valid: jnp.ndarray, proxy_ports: jnp.ndarray,
             ep=None, dirn=None):
        meta = (ep, dirn) if packed else ()
        table, fp, dropped, metrics, rbuf, rcur = _step(
            state.policy, state.ipcache, state.ct.table, state.ct.fp,
            state.ct.dropped, state.metrics, ring.buf, ring.cursor,
            hdr, now, batch_id, valid, proxy_ports, *meta)
        return (DatapathState(
            policy=state.policy, ipcache=state.ipcache,
            ct=CTTable(table=table, fp=fp, dropped=dropped),
            metrics=metrics),
            EventRing(buf=rbuf, cursor=rcur))

    return step


def make_sharded_step(mesh: Mesh, axis: str = "data") -> Callable:
    """Build the jitted multi-chip datapath step.

    step(state, hdr, now, valid) -> (out, state') with hdr/out sharded
    on the batch axis, CT sharded, policy/ipcache replicated."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(axis, None), P(axis), P(), P(),
                  P(axis, None), P(), P(axis)),
        out_specs=(P(axis, None), P(axis, None), P(axis), P(), P()),
    )
    def _step(policy, ipcache, ct_table, ct_fp, ct_dropped, metrics,
              hdr, now, valid):
        state = DatapathState(
            policy=policy, ipcache=ipcache,
            ct=CTTable(table=ct_table, fp=ct_fp, dropped=ct_dropped),
            metrics=metrics)
        out, ns = datapath_step(state, hdr, now, valid=valid)
        # counters are replicated state: accumulate the global delta so
        # every replica agrees (the kvstore-replication analogue)
        d_dropped = jax.lax.psum(ns.ct.dropped - ct_dropped, axis)
        d_metrics = jax.lax.psum(ns.metrics - metrics, axis)
        return (out, ns.ct.table, ns.ct.fp, ct_dropped + d_dropped,
                metrics + d_metrics)

    @partial(jax.jit, donate_argnums=0)
    def step(state: DatapathState, hdr: jnp.ndarray, now: jnp.ndarray,
             valid: jnp.ndarray) -> Tuple[jnp.ndarray, DatapathState]:
        out, table, fp, dropped, metrics = _step(
            state.policy, state.ipcache, state.ct.table, state.ct.fp,
            state.ct.dropped, state.metrics, hdr, now, valid)
        return out, DatapathState(
            policy=state.policy, ipcache=state.ipcache,
            ct=CTTable(table=table, fp=fp, dropped=dropped),
            metrics=metrics)

    return step
