from .identity import (  # noqa: F401
    Identity,
    ReservedIdentity,
    ID_INVALID,
    ID_HOST,
    ID_WORLD,
    ID_UNMANAGED,
    ID_HEALTH,
    ID_INIT,
    ID_REMOTE_NODE,
    ID_KUBE_APISERVER,
    ID_INGRESS,
    LOCAL_IDENTITY_FLAG,
    RESERVED_LABELSETS,
    is_reserved,
    is_local_cidr,
    reserved_identity_labels,
)
from .allocator import CachingIdentityAllocator  # noqa: F401
