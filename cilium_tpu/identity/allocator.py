"""Identity allocation: label set -> cluster-unique numeric identity.

Reference: upstream cilium ``pkg/identity/cache``
(``CachingIdentityAllocator``) on top of ``pkg/allocator`` — ref-counted,
kvstore-backed, collision-free allocation with reserved identities
pre-registered and CIDR identities allocated from a node-local scope.

The kvstore backend here is the in-process one from
``cilium_tpu.kvstore``; in a multi-host deployment the same interface is
served by the jax.distributed-backed store (the ClusterMesh analogue).

Observers (e.g. the policy SelectorCache and the datapath's
IdentityRowMap) register callbacks fired on add/remove so incremental
identity churn propagates to device tensors without a full recompile.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Callable, Dict, List, Optional

from ..labels import Label, LabelSet, SOURCE_CIDR


def cidr_labels(cidr: str) -> list:
    """``cidr:`` labels for a prefix and every parent prefix
    (reference: pkg/labels GetCIDRLabels — 33 labels for a v4 /32,
    129 for a v6 /128), so CIDR rules select by LABEL, not by
    happening to share an exact prefix."""
    net = ipaddress.ip_network(cidr, strict=False)
    out = [Label(SOURCE_CIDR, str(net))]
    for plen in range(net.prefixlen):
        out.append(Label(SOURCE_CIDR, str(net.supernet(
            new_prefix=plen))))
    return out
from .identity import (
    Identity,
    LOCAL_IDENTITY_FLAG,
    MIN_ALLOCATED,
    MAX_ALLOCATED,
    RESERVED_BY_LABELS,
    RESERVED_LABELSETS,
)

IdentityChangeFn = Callable[[str, Identity], None]  # kind: "add"|"remove"


class CachingIdentityAllocator:
    """Ref-counted label-set -> identity allocator with observers."""

    def __init__(self, backend=None, min_id: int = MIN_ALLOCATED,
                 max_id: int = MAX_ALLOCATED):
        # backend: optional kvstore-like .allocate(key)->int shared across
        # "nodes"; None = purely local allocation.
        self._backend = backend
        self._lock = threading.RLock()
        self._by_labels: Dict[str, Identity] = {}
        self._by_id: Dict[int, Identity] = {}
        self._refcount: Dict[int, int] = {}
        self._observers: List[IdentityChangeFn] = []
        self._next_id = min_id
        self._max_id = max_id
        self._next_local = LOCAL_IDENTITY_FLAG | 1
        for num, ls in RESERVED_LABELSETS.items():
            ident = Identity(num, ls)
            self._by_labels[ls.sorted_key()] = ident
            self._by_id[num] = ident
            self._refcount[num] = 1  # pinned

    # -- observer fan-out (reference: identity Observer / events) --------
    def observe(self, fn: IdentityChangeFn) -> None:
        with self._lock:
            self._observers.append(fn)
            for ident in self._by_id.values():
                fn("add", ident)

    def _notify(self, kind: str, ident: Identity) -> None:
        for fn in list(self._observers):
            fn(kind, ident)

    # -- allocation ------------------------------------------------------
    def allocate(self, labels: LabelSet) -> Identity:
        """Allocate (or ref) the identity for a label set."""
        key = labels.sorted_key()
        with self._lock:
            if key in RESERVED_BY_LABELS:
                return self._by_labels[key]
            ident = self._by_labels.get(key)
            if ident is not None:
                prev = self._refcount.get(ident.numeric_id, 0)
                self._refcount[ident.numeric_id] = prev + 1
                if (prev == 0 and self._backend is not None
                        and hasattr(self._backend, "ref")
                        and not (ident.numeric_id & LOCAL_IDENTITY_FLAG)
                        and ident.numeric_id not in RESERVED_LABELSETS):
                    # first local use of a watch-replayed identity:
                    # take this node's kvstore reference so identity
                    # GC sees the id as live
                    self._backend.ref(key, ident.numeric_id)
                return ident
            local = any(l.source == SOURCE_CIDR for l in labels)
            if local:
                num = self._next_local
                self._next_local += 1
            elif self._backend is not None:
                num = self._backend.allocate(key)
            else:
                if self._next_id >= self._max_id:
                    raise RuntimeError("identity space exhausted")
                num = self._next_id
                self._next_id += 1
            ident = Identity(num, labels)
            self._by_labels[key] = ident
            self._by_id[num] = ident
            self._refcount[num] = 1
            self._notify("add", ident)
            return ident

    def allocate_cidr(self, cidr: str) -> Identity:
        """Allocate a node-local identity for a CIDR (toCIDR / fqdn flows).

        Reference: pkg/identity CIDR-derived local identities; labels
        are ``cidr:<prefix>`` for the prefix AND every parent prefix
        (pkg/labels GetCIDRLabels), plus ``reserved:world`` — so a
        ``fromCIDR 10.0.0.0/8`` rule label-selects a later-minted
        ``10.1.2.3/32`` identity (DIVERGENCES #8, closed r05).
        """
        labels = LabelSet(cidr_labels(cidr)
                          + [Label("reserved", "world")])
        return self.allocate(labels)

    def release(self, ident: Identity) -> bool:
        """Deref; returns True when the identity was freed."""
        with self._lock:
            num = ident.numeric_id
            if num in RESERVED_LABELSETS:
                return False
            if num not in self._refcount:
                return False  # unknown or already freed — no-op
            cnt = self._refcount[num] - 1
            if cnt > 0:
                self._refcount[num] = cnt
                return False
            self._refcount.pop(num, None)
            self._by_id.pop(num, None)
            # pop the labels index only when it still maps to THIS
            # identity — a stale release must not remove a newer
            # identity that re-bound the same label set
            cur = self._by_labels.get(ident.labels.sorted_key())
            if cur is not None and cur.numeric_id == num:
                self._by_labels.pop(ident.labels.sorted_key(), None)
            if self._backend is not None and hasattr(self._backend,
                                                     "release"):
                # drop this node's kvstore reference; the master key
                # stays until identity GC sweeps orphans (operator)
                self._backend.release(ident.labels.sorted_key())
            self._notify("remove", ident)
            return True

    # -- restore (checkpoint/resume) -------------------------------------
    def restore_identity(self, numeric_id: int,
                         labels: LabelSet) -> Identity:
        """Re-register a checkpointed identity under its old numeric id
        (reference: identities restored from the state dir / CRDs keep
        their numbers so policy maps stay valid across restarts)."""
        key = labels.sorted_key()
        with self._lock:
            if key in RESERVED_BY_LABELS:
                return self._by_labels[key]
            existing = self._by_id.get(numeric_id)
            if existing is not None:
                if existing.labels.sorted_key() != key:
                    raise ValueError(
                        f"identity {numeric_id} already bound to "
                        f"{existing.labels}")
                return existing  # idempotent, holds no ref
            ident = Identity(numeric_id, labels)
            self._by_labels[key] = ident
            self._by_id[numeric_id] = ident
            # the restore itself holds NO reference: restored endpoints
            # re-allocate (ref 1 each) as they register, so deleting
            # them later frees the identity instead of leaking it.
            # Orphans (refcount 0, e.g. CIDR identities whose rules are
            # gone) are swept by identity GC (the operator's job in the
            # reference).
            self._refcount[numeric_id] = 0
            if numeric_id & LOCAL_IDENTITY_FLAG:
                self._next_local = max(self._next_local, numeric_id + 1)
            else:
                self._next_id = max(self._next_id, numeric_id + 1)
            self._notify("add", ident)
            return ident

    # -- watch replay (ClusterIdentitySync) ------------------------------
    def watch_update(self, numeric_id: int, labels: LabelSet) -> Identity:
        """Apply a watched ``id/<num>`` create: register the identity,
        or RE-BIND a GC'd-and-reused numeric (the ABA case hole-reuse
        makes common: k1 -> N is released cluster-wide, identity GC
        sweeps id/N, another node mints k2 -> N).  A locally-referenced
        identity is never re-bound — live refs imply a kvstore ref
        that keeps GC away, so a conflicting create for a referenced
        numeric means a lease blip; keeping local state is the safe
        side."""
        key = labels.sorted_key()
        with self._lock:
            existing = self._by_id.get(numeric_id)
            if existing is not None:
                if existing.labels.sorted_key() == key:
                    return existing
                if self._refcount.get(numeric_id, 0) > 0:
                    return existing
                self._drop(existing)
            return self.restore_identity(numeric_id, labels)

    def watch_remove(self, numeric_id: int) -> bool:
        """Apply a watched ``id/<num>`` delete (identity GC swept the
        master).  Only unreferenced identities drop — local release
        stays refcount-driven."""
        with self._lock:
            if numeric_id in RESERVED_LABELSETS:
                return False
            existing = self._by_id.get(numeric_id)
            if existing is None or self._refcount.get(numeric_id, 0) > 0:
                return False
            self._drop(existing)
            return True

    def _drop(self, ident: Identity) -> None:
        num = ident.numeric_id
        self._refcount.pop(num, None)
        self._by_id.pop(num, None)
        cur = self._by_labels.get(ident.labels.sorted_key())
        if cur is not None and cur.numeric_id == num:
            self._by_labels.pop(ident.labels.sorted_key(), None)
        self._notify("remove", ident)

    def close(self) -> None:
        """Release backend resources (kvstore watch subscription)."""
        if self._backend is not None and hasattr(self._backend, "close"):
            self._backend.close()

    # -- lookup ----------------------------------------------------------
    def lookup_by_id(self, numeric_id: int) -> Optional[Identity]:
        with self._lock:
            return self._by_id.get(numeric_id)

    def lookup_by_labels(self, labels: LabelSet) -> Optional[Identity]:
        with self._lock:
            return self._by_labels.get(labels.sorted_key())

    def all_identities(self) -> List[Identity]:
        with self._lock:
            return list(self._by_id.values())
