"""Numeric security identities and the reserved-identity space.

Reference: upstream cilium ``pkg/identity`` — reserved identities
(1=host, 2=world, 3=unmanaged, 4=health, 5=init, 6=remote-node,
7=kube-apiserver, 8=ingress), the cluster-wide allocation range
[256, 65536), and locally-scoped CIDR identities carrying a scope flag
in the high bits.

TPU-first note: numeric identities are the *API-boundary* currency.  On
device, the datapath works in **dense identity rows** (0..n_rows-1)
assigned by the IdentityRowMap so the policy verdict tensor can be a
dense ``[rows, classes]`` array instead of a 16M-sparse one.  The
ipcache LPM tables store rows directly; numeric IDs only appear in
events surfaced back to the host.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from ..labels import Label, LabelSet, SOURCE_RESERVED

ID_INVALID = 0
ID_HOST = 1
ID_WORLD = 2
ID_UNMANAGED = 3
ID_HEALTH = 4
ID_INIT = 5
ID_REMOTE_NODE = 6
ID_KUBE_APISERVER = 7
ID_INGRESS = 8

# First identity the cluster-wide allocator may hand out.
MIN_ALLOCATED = 256
MAX_ALLOCATED = 65536

# Locally-scoped identities (CIDR-derived) carry this flag — they are
# node-local and never synced to the cluster store.
LOCAL_IDENTITY_FLAG = 1 << 24

_RESERVED_NAMES = {
    ID_HOST: "host",
    ID_WORLD: "world",
    ID_UNMANAGED: "unmanaged",
    ID_HEALTH: "health",
    ID_INIT: "init",
    ID_REMOTE_NODE: "remote-node",
    ID_KUBE_APISERVER: "kube-apiserver",
    ID_INGRESS: "ingress",
}

RESERVED_LABELSETS: Dict[int, LabelSet] = {
    num: LabelSet([Label(SOURCE_RESERVED, name)])
    for num, name in _RESERVED_NAMES.items()
}
RESERVED_BY_LABELS: Dict[str, int] = {
    ls.sorted_key(): num for num, ls in RESERVED_LABELSETS.items()
}


def is_reserved(numeric_id: int) -> bool:
    return 0 < numeric_id < MIN_ALLOCATED


def is_local_cidr(numeric_id: int) -> bool:
    return bool(numeric_id & LOCAL_IDENTITY_FLAG)


def reserved_identity_labels(numeric_id: int) -> Optional[LabelSet]:
    return RESERVED_LABELSETS.get(numeric_id)


def reserved_name(numeric_id: int) -> Optional[str]:
    return _RESERVED_NAMES.get(numeric_id)


@dataclass(frozen=True)
class Identity:
    """A numeric security identity bound to the label set it encodes."""

    numeric_id: int
    labels: LabelSet

    @property
    def is_reserved(self) -> bool:
        return is_reserved(self.numeric_id)

    @property
    def is_local(self) -> bool:
        return is_local_cidr(self.numeric_id)

    def __str__(self) -> str:
        name = reserved_name(self.numeric_id)
        return f"Identity({self.numeric_id}{'/' + name if name else ''})"


@dataclass(frozen=True)
class ReservedIdentity(Identity):
    pass
