"""CTA002 — thread-affinity: the static generalization of the PR 5/6
monkeypatch proofs ("decode never runs on the drain thread",
"analytics ingest never runs on the drain thread").

Every function may declare the set of threads it is allowed to run
on (``# thread-affinity: drain, api`` ...).  The checker propagates
affinities over the call graph: an annotated function's body runs
under exactly its declared set; an unannotated function inherits the
union of its callers' sets.  A call edge from code that may run
under affinity set S into a function whose declared set D satisfies
neither ``S ⊆ D`` nor ``any ∈ D`` is a violation — flagged at the
call site, naming both sides.

``any`` in the CALLER set means "may run on every thread", so it only
passes into callees that also declare ``any``.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from .callgraph import CallGraph
from .core import Finding, Repo

CODE = "CTA002"
NAME = "thread-affinity"


def propagate(graph: CallGraph
              ) -> Tuple[Dict[str, Set[str]], List[Finding]]:
    """-> (state, findings): ``state[key]`` is the set of affinities
    code in that function may execute under (declared for annotated
    functions, inherited for the rest)."""
    findings: List[Finding] = []
    declared = {k: frozenset(fi.affinity)
                for k, fi in graph.funcs.items()
                if fi.affinity is not None}
    state: Dict[str, Set[str]] = {
        k: set(v) for k, v in declared.items()}
    work = list(declared)
    reported: Set[Tuple[str, str, int]] = set()
    while work:
        f = work.pop()
        inc = (set(declared[f]) if f in declared
               else set(state.get(f, ())))
        if not inc:
            continue
        fi = graph.funcs[f]
        for g, line in graph.edges.get(f, ()):
            if g in declared:
                dg = declared[g]
                if "any" in dg:
                    continue
                bad = inc - dg
                if bad and (f, g, line) not in reported:
                    reported.add((f, g, line))
                    if fi.ctx.suppressed(CODE, line):
                        continue
                    gi = graph.funcs[g]
                    gname = (f"{gi.cls}.{gi.name}" if gi.cls
                             else gi.name)
                    findings.append(Finding(
                        CODE, fi.ctx.rel, line,
                        f"{gname} (thread-affinity: "
                        f"{', '.join(sorted(dg))}) is reachable from "
                        f"{'/'.join(sorted(bad))}-affine code via "
                        f"{fi.cls + '.' if fi.cls else ''}{fi.name}",
                        checker=NAME))
                continue
            new = inc - state.get(g, set())
            if new:
                state.setdefault(g, set()).update(new)
                work.append(g)
    return state, findings


def check(repo: Repo, graph: CallGraph) -> List[Finding]:
    _state, findings = propagate(graph)
    return findings


def affinity_map(graph: CallGraph) -> Dict[Tuple[str, str],
                                           Tuple[str, ...]]:
    """{(rel, qualname): declared affinities} — the test surface:
    deleting the ``decode_ring_rows`` or ``FlowAnalytics._ingest``
    annotation makes the tier-1 analysis test fail by this map
    losing the entry."""
    out = {}
    for fi in graph.funcs.values():
        if fi.affinity is not None:
            qual = f"{fi.cls}.{fi.name}" if fi.cls else fi.name
            out[(fi.ctx.rel, qual)] = fi.affinity
    return out
