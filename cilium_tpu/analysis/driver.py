"""The analysis driver: run every registered checker over the repo,
apply suppressions (done per-checker) and the baseline, and render
human or JSON output."""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import (affinity, cluster_lint, crypto_lint, generation,
               guarded, hotpath, nodehost_lint, proxy_lint, reasons,
               registry_lint, scenario_lint, sharding, slo_lint,
               sysdump_lint)
from .callgraph import CallGraph
from .core import BASELINE_NAME, Baseline, Finding, Repo, repo_root

# name -> (code, check(repo, graph) -> [Finding])
CHECKERS: Dict[str, Tuple[str, Callable]] = {
    "guarded-by": (guarded.CODE, guarded.check),
    "thread-affinity": (affinity.CODE, affinity.check),
    "hot-path": (hotpath.CODE, hotpath.check),
    "sharding-spec": (sharding.CODE, sharding.check),
    "reason-codes": (reasons.CODE, reasons.check),
    "metrics-registry": (registry_lint.CODE, registry_lint.check),
    "sysdump-schema": (sysdump_lint.CODE, sysdump_lint.check),
    "cluster-ledger": (cluster_lint.CODE, cluster_lint.check),
    "generation-discipline": (generation.CODE, generation.check),
    "scenario-contract": (scenario_lint.CODE, scenario_lint.check),
    "nodehost-ops": (nodehost_lint.CODE, nodehost_lint.check),
    "proxy-ledger": (proxy_lint.CODE, proxy_lint.check),
    "crypto-hygiene": (crypto_lint.CODE, crypto_lint.check),
    "slo-contract": (slo_lint.CODE, slo_lint.check),
}
# checkers that walk the call graph; selecting none of these skips
# the (comparatively expensive) CallGraph build entirely
_GRAPH_CHECKERS = {"thread-affinity", "hot-path"}


def run_analysis(root: Optional[str] = None,
                 checkers: Optional[List[str]] = None,
                 repo: Optional[Repo] = None,
                 baseline_path: Optional[str] = None) -> dict:
    """-> {"findings": [...], "baselined": [...], "config": [...],
    "elapsed-s": float, "files": int}.  ``findings`` are the new,
    unsuppressed, non-baselined ones — a clean tree has none."""
    t0 = time.monotonic()
    root = root or repo_root()
    repo = repo or Repo(root)
    names = checkers or list(CHECKERS)
    graph = (CallGraph(repo)
             if _GRAPH_CHECKERS & set(names) else None)
    all_findings: List[Finding] = list(
        graph.config_findings if graph is not None else ())
    for ctx in repo.files:
        all_findings.extend(ctx.config_findings)
        if ctx.parse_error is not None:
            all_findings.append(Finding(
                "CTA000", ctx.rel, 1,
                f"does not parse: {ctx.parse_error}",
                checker="config"))
    for name in names:
        _code, fn = CHECKERS[name]
        all_findings.extend(fn(repo, graph))
    baseline = Baseline(baseline_path
                        or os.path.join(root, BASELINE_NAME))
    new, old = baseline.split(all_findings, repo)
    new.sort(key=lambda f: (f.path, f.line, f.code))
    return {
        "findings": new,
        "baselined": old,
        "elapsed-s": round(time.monotonic() - t0, 3),
        "files": len(repo.files),
        "repo": repo,
        "graph": graph,
    }


def render_human(result: dict) -> str:
    lines: List[str] = []
    for f in result["findings"]:
        lines.append(f.render())
    if result["baselined"]:
        lines.append(f"({len(result['baselined'])} baselined "
                     f"finding(s) suppressed by {BASELINE_NAME})")
    n = len(result["findings"])
    lines.append(
        f"analysis: {n} finding(s) across {result['files']} files "
        f"in {result['elapsed-s']}s"
        + (" — clean" if n == 0 else ""))
    return "\n".join(lines)


def render_json(result: dict) -> str:
    return json.dumps({
        "findings": [f.to_dict() for f in result["findings"]],
        "baselined": [f.to_dict() for f in result["baselined"]],
        "files": result["files"],
        "elapsed-s": result["elapsed-s"],
    }, indent=1)
