"""Approximate, annotation-anchored static call graph.

Resolution policy (documented because it IS the precision contract):

1. ``self.m(...)`` resolves to method ``m`` of the lexically
   enclosing class when it exists.
2. ``name(...)`` resolves through the file's import map (module- and
   function-level ``from X import name`` / ``from . import mod``,
   relative imports included) to module-level functions, and to
   same-module functions/classes (a class call edges to its
   ``__init__``).
3. ``self.attr.m(...)`` / ``var.m(...)`` resolve through inferred
   types: ``self.attr = ClassName(...)`` anywhere in the class and
   ``var = ClassName(...)`` in the local function body bind the
   receiver to ``ClassName``.
4. Anything else (``s["drainer"].swap_window(...)``, untyped
   parameters) falls back to NAME MATCHING — but only against
   functions that carry a ``thread-affinity`` annotation, and never
   for ubiquitous names (``get``, ``append``, ``start``, ...).
   Annotating a function is what opts it into being a fallback
   target, which keeps the graph precise exactly where the checkers
   need edges.

Nested ``def``/``lambda`` bodies are deferred execution and are NOT
attributed to the enclosing function.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .annotations import extract_affinity
from .core import FileCtx, Finding, Repo

# receiver-method names too generic to name-match (containers, numpy,
# threading, re, file objects): a fallback edge on these would wire
# unrelated subsystems together
_FALLBACK_BLOCKLIST = {
    "get", "set", "add", "append", "appendleft", "pop", "popleft",
    "update", "setdefault", "items", "keys", "values", "sum", "min",
    "max", "mean", "copy", "sort", "join", "split", "strip", "read",
    "write", "close", "clear", "extend", "insert", "remove", "count",
    "index", "format", "encode", "decode", "wait", "notify",
    "notify_all", "acquire", "release", "put", "reshape", "astype",
    "tolist", "item", "any", "all", "cumsum", "start", "is_alive",
    "search", "match", "group", "flatten", "locked", "is_set",
}


@dataclass
class FuncInfo:
    key: str  # "<rel>::<Class.>name"
    name: str
    cls: Optional[str]
    node: ast.FunctionDef
    ctx: FileCtx
    affinity: Optional[Tuple[str, ...]] = None


def _module_rel(rel: str, level: int, module: str,
                repo: Repo) -> Optional[str]:
    """Resolve a (possibly relative) import to a repo-relative module
    path WITHOUT the .py suffix, or None when outside the repo."""
    if level == 0:
        parts = module.split(".") if module else []
    else:
        base = rel.rsplit("/", 1)[0].split("/")
        if level - 1 > 0:
            base = base[:-(level - 1)] if level - 1 <= len(base) else []
        parts = base + (module.split(".") if module else [])
    if not parts or parts[0] != repo.package:
        if level == 0:
            return None
    return "/".join(parts)


class CallGraph:
    def __init__(self, repo: Repo):
        self.repo = repo
        self.funcs: Dict[str, FuncInfo] = {}
        self.config_findings: List[Finding] = []
        # bare/method name -> candidate keys
        self._by_name: Dict[str, List[str]] = {}
        # (rel, Class) -> {attr: set of class "rel::Class" keys}
        self._attr_types: Dict[Tuple[str, str], Dict[str, Set[str]]] = {}
        # "rel::Class" -> {method name: key}
        self._class_methods: Dict[str, Dict[str, str]] = {}
        # rel -> {local name: target} where target is
        # ("func", key) | ("class", class key) | ("module", mod rel)
        self._scopes: Dict[str, Dict[str, tuple]] = {}
        self.edges: Dict[str, List[Tuple[str, int]]] = {}
        self._collect()
        self._resolve_imports()
        self._infer_attr_types()
        self._build_edges()

    # -- collection ----------------------------------------------------
    def _collect(self) -> None:
        for ctx in self.repo.files:
            if ctx.tree is None:
                continue
            for node in ctx.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._add_func(ctx, node, None)
                elif isinstance(node, ast.ClassDef):
                    ckey = f"{ctx.rel}::{node.name}"
                    self._class_methods.setdefault(ckey, {})
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            fi = self._add_func(ctx, sub, node.name)
                            self._class_methods[ckey][sub.name] = fi.key

    def _add_func(self, ctx: FileCtx, node, cls: Optional[str]
                  ) -> FuncInfo:
        qual = f"{cls}.{node.name}" if cls else node.name
        key = f"{ctx.rel}::{qual}"
        fi = FuncInfo(key=key, name=node.name, cls=cls, node=node,
                      ctx=ctx,
                      affinity=extract_affinity(
                          node, ctx, self.config_findings))
        self.funcs[key] = fi
        self._by_name.setdefault(node.name, []).append(key)
        return fi

    # -- imports -------------------------------------------------------
    def _resolve_imports(self) -> None:
        have_modules = {f.rel[:-3] for f in self.repo.files}
        for ctx in self.repo.files:
            if ctx.tree is None:
                continue
            scope: Dict[str, tuple] = {}
            # same-module functions/classes first
            for node in ctx.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    scope[node.name] = ("func",
                                        f"{ctx.rel}::{node.name}")
                elif isinstance(node, ast.ClassDef):
                    scope[node.name] = ("class",
                                        f"{ctx.rel}::{node.name}")
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ImportFrom):
                    mod = _module_rel(ctx.rel, node.level,
                                      node.module or "", self.repo)
                    if mod is None:
                        continue
                    for alias in node.names:
                        name = alias.asname or alias.name
                        sub = f"{mod}/{alias.name}"
                        if sub in have_modules:
                            scope[name] = ("module", sub)
                            continue
                        target = self._lookup_module_symbol(
                            mod, alias.name)
                        if target is not None:
                            scope[name] = target
                elif isinstance(node, ast.Import):
                    for alias in node.names:
                        mod = alias.name.replace(".", "/")
                        if mod in have_modules:
                            scope[alias.asname
                                  or alias.name.split(".")[0]] = (
                                "module", mod)
            self._scopes[ctx.rel] = scope

    def _lookup_module_symbol(self, mod: str,
                              name: str) -> Optional[tuple]:
        rel = mod + ".py"
        if not any(f.rel == rel for f in self.repo.files):
            rel = mod + "/__init__.py"
        key = f"{rel}::{name}"
        if key in self.funcs:
            return ("func", key)
        if key in self._class_methods:
            return ("class", key)
        return None

    # -- type inference ------------------------------------------------
    def _class_of_call(self, rel: str, call: ast.Call
                       ) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name):
            tgt = self._scopes.get(rel, {}).get(fn.id)
            if tgt is not None and tgt[0] == "class":
                return tgt[1]
        return None

    def _infer_attr_types(self) -> None:
        for ctx in self.repo.files:
            if ctx.tree is None:
                continue
            for node in ctx.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                attrs: Dict[str, Set[str]] = {}
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign) \
                            or not isinstance(sub.value, ast.Call):
                        continue
                    ck = self._class_of_call(ctx.rel, sub.value)
                    if ck is None:
                        continue
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Attribute) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "self":
                            attrs.setdefault(tgt.attr, set()).add(ck)
                self._attr_types[(ctx.rel, node.name)] = attrs

    # -- edges ---------------------------------------------------------
    def _build_edges(self) -> None:
        for fi in self.funcs.values():
            self.edges[fi.key] = self._edges_of(fi)

    def _own_statements(self, fn: ast.FunctionDef) -> List[ast.AST]:
        """The function's body EXCLUDING nested def/lambda bodies."""
        out: List[ast.AST] = []

        def walk(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                out.append(child)
                walk(child)

        walk(fn)
        return out

    def _edges_of(self, fi: FuncInfo) -> List[Tuple[str, int]]:
        rel = fi.ctx.rel
        scope = self._scopes.get(rel, {})
        # local variable types: var = ClassName(...)
        local_types: Dict[str, Set[str]] = {}
        nodes = self._own_statements(fi.node)
        for node in nodes:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                ck = self._class_of_call(rel, node.value)
                if ck is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            local_types.setdefault(tgt.id,
                                                   set()).add(ck)
        out: List[Tuple[str, int]] = []
        seen: Set[Tuple[str, int]] = set()

        def add(key: Optional[str], line: int) -> None:
            if key is not None and key in self.funcs \
                    and (key, line) not in seen:
                seen.add((key, line))
                out.append((key, line))

        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            line = node.lineno
            if isinstance(fn, ast.Name):
                tgt = scope.get(fn.id)
                if tgt is None:
                    continue
                if tgt[0] == "func":
                    add(tgt[1], line)
                elif tgt[0] == "class":
                    add(self._class_methods.get(tgt[1], {})
                        .get("__init__"), line)
                continue
            if not isinstance(fn, ast.Attribute):
                continue
            meth = fn.attr
            base = fn.value
            resolved = False
            if isinstance(base, ast.Name):
                if base.id == "self" and fi.cls is not None:
                    ckey = f"{rel}::{fi.cls}"
                    key = self._class_methods.get(ckey, {}).get(meth)
                    if key is not None:
                        add(key, line)
                        resolved = True
                    else:
                        resolved = True  # unknown self-attr callable:
                        # callbacks are annotated at their defs
                elif base.id in local_types:
                    for ck in local_types[base.id]:
                        key = self._class_methods.get(ck, {}).get(meth)
                        if key is not None:
                            add(key, line)
                            resolved = True
                else:
                    tgt = scope.get(base.id)
                    if tgt is not None and tgt[0] == "module":
                        for suffix in (".py", "/__init__.py"):
                            key = f"{tgt[1]}{suffix}::{meth}"
                            if key in self.funcs:
                                add(key, line)
                                resolved = True
                                break
                        resolved = True  # module attr either way
            elif isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and fi.cls is not None:
                types = self._attr_types.get((rel, fi.cls), {}) \
                    .get(base.attr)
                if types:
                    for ck in types:
                        key = self._class_methods.get(ck, {}).get(meth)
                        if key is not None:
                            add(key, line)
                            resolved = True
            if not resolved and meth not in _FALLBACK_BLOCKLIST:
                for key in self._by_name.get(meth, ()):
                    cand = self.funcs[key]
                    if cand.cls is not None \
                            and cand.affinity is not None:
                        add(key, line)
        return out
