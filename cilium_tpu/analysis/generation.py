"""CTA009 — generation discipline: active-table writes only through
annotated swap/builder methods; plus the churn bench artifact schema.

The table-versioning tentpole (datapath/tables.py) only guarantees
torn-free swaps if EVERY mutation of the published tables goes
through the builder/publish protocol — one shortcut that pokes a
live tensor or mirror in place re-opens the mid-swap window the
whole design exists to close.  Statically enforced:

1. a class may declare its published-table attrs in a class-body
   annotation::

       # active-tables: state, tensors, _lpm_entries

   Any WRITE to a declared attr — plain/aug/ann assignment, tuple
   unpacking, ``del``, a subscript or dotted store rooted at it
   (``self.tensors.verdict[...] = v``), or a known mutator call
   (``self._lpm_entries.pop(...)``) — outside a method annotated
   ``# table-swap-ok: <reason>`` is a CTA009 finding.  ``__init__``
   is exempt (no published generation exists during construction);
   reads are never flagged (discipline covers mutation, not
   observation).  The reason is MANDATORY: every swap site must say
   what class of swap it is (table publish / CT-only / placement /
   oracle apply).

2. ``cilium_tpu/datapath/loader.py`` must keep the discipline armed:
   a class declaring ``state`` among its active tables, a class
   declaring ``oracle``, and an annotated ``_publish_tables`` swap
   helper — deleting any of the annotations fails tier-1, the same
   presence idiom as the CTA002 tentpole annotations.

3. when ``BENCH_churn.json`` exists at the repo root it carries
   every :data:`BENCH_CHURN_KEYS` entry (the churn bench artifact's
   schema floor, the CTA008 bench-schema idiom; ``check_bench`` is
   the importable validator tests share).
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Set

from .annotations import _def_comment_range
from .core import FileCtx, Finding, Repo

CODE = "CTA009"
NAME = "generation-discipline"

LOADER_MODULE = "cilium_tpu/datapath/loader.py"

BENCH_NAME = "BENCH_churn.json"
BENCH_SCHEMA = "bench-churn-v1"
# the churn bench artifact's schema floor (bench.py --churn)
BENCH_CHURN_KEYS = (
    "schema", "best_of",
    "sustained_pps", "sustained_pps_churn", "churn_ratio",
    "churn_ops", "churn_rate_hz",
    "update_visible_p50_us", "update_visible_p99_us",
    # superbatch-granularity generation pinning (ISSUE 11): the K=8
    # legs' update-visible latency and throughput ride the artifact
    "superbatch_k", "sustained_pps_churn_k8", "churn_ratio_k8",
    "update_visible_p50_us_k8", "update_visible_p99_us_k8",
    "swap_stall_p99_us", "swaps", "generation",
    "ledger_exact", "compile_violations",
)

_ACTIVE_RE = re.compile(
    r"#\s*active-tables:\s*(?P<attrs>[\w,\s]+?)\s*$")
_SWAP_OK_RE = re.compile(
    r"#\s*table-swap-ok\s*(?::\s*(?P<reason>.*))?$")

# method calls that mutate their receiver (the lexical-store
# approximation's blind spot, closed for the common containers)
_MUTATORS = frozenset({
    "pop", "clear", "update", "append", "extend", "insert",
    "setdefault", "add", "remove", "discard", "popitem", "sort",
    "fill",
})


def _class_active_tables(cls: ast.ClassDef,
                         ctx: FileCtx) -> Set[str]:
    """Declared attrs from every ``# active-tables:`` comment line in
    the class range (multiple lines union — the declaration may wrap)."""
    out: Set[str] = set()
    end = getattr(cls, "end_lineno", None) or cls.lineno
    for ln in range(cls.lineno, end + 1):
        for c in ctx.comments.get(ln, ()):
            m = _ACTIVE_RE.match(c.strip())
            if m:
                out.update(a.strip() for a in
                           m.group("attrs").split(",") if a.strip())
    return out


def _swap_ok(node: ast.FunctionDef, ctx: FileCtx,
             findings: List[Finding]) -> bool:
    """True when the def carries ``# table-swap-ok: <reason>``; a
    reason-less annotation is itself a finding (and does NOT arm the
    exemption — an unexplained swap site is the problem)."""
    for ln, c in _def_comment_range(node, ctx):
        m = _SWAP_OK_RE.match(c.strip())
        if m is None:
            continue
        reason = (m.group("reason") or "").strip()
        if not reason:
            if not ctx.suppressed(CODE, ln):
                findings.append(Finding(
                    CODE, ctx.rel, ln,
                    "table-swap-ok needs a reason (`# table-swap-ok: "
                    "<what class of swap this is>`)", checker=NAME))
            return False
        return True
    return False


def _root_self_attr(node: ast.AST) -> Optional[str]:
    """The first attribute above ``self`` in a store-target chain:
    ``self.tensors.verdict[...]`` -> ``tensors``; None when the chain
    is not rooted at self."""
    chain: List[str] = []
    cur = node
    while True:
        if isinstance(cur, ast.Subscript):
            cur = cur.value
        elif isinstance(cur, ast.Attribute):
            chain.append(cur.attr)
            cur = cur.value
        elif isinstance(cur, ast.Name):
            return chain[-1] if cur.id == "self" and chain else None
        else:
            return None


class _WriteVisitor(ast.NodeVisitor):
    """Collect writes to declared attrs anywhere in one method body
    (nested defs/lambdas INCLUDED: a mirror closure defined in an
    annotated builder inherits its exemption lexically)."""

    def __init__(self, declared: Set[str]):
        self.declared = declared
        self.hits: List[tuple] = []  # (lineno, attr, how)

    def _check(self, target: ast.AST, how: str) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._check(e, how)
            return
        attr = _root_self_attr(target)
        if attr is not None and attr in self.declared:
            self.hits.append((target.lineno, attr, how))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check(t, "assigned")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check(node.target, "aug-assigned")
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check(node.target, "assigned")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._check(t, "deleted")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
            attr = _root_self_attr(fn.value)
            if attr is not None and attr in self.declared:
                self.hits.append((node.lineno, attr,
                                  f"mutated via .{fn.attr}()"))
        self.generic_visit(node)


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    loader_declares_state = False
    loader_declares_oracle = False
    loader_publish_ok = False

    for ctx in repo.files:
        if ctx.tree is None:
            continue
        is_loader = ctx.rel == LOADER_MODULE
        for cls in ctx.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            declared = _class_active_tables(cls, ctx)
            if not declared:
                continue
            if is_loader and "state" in declared:
                loader_declares_state = True
            if is_loader and "oracle" in declared:
                loader_declares_oracle = True
            for node in cls.body:
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if node.name == "__init__":
                    continue
                exempt = _swap_ok(node, ctx, findings)
                if exempt:
                    if is_loader and node.name == "_publish_tables":
                        loader_publish_ok = True
                    continue
                v = _WriteVisitor(declared)
                v.visit(node)
                for line, attr, how in v.hits:
                    if ctx.suppressed(CODE, line):
                        continue
                    findings.append(Finding(
                        CODE, ctx.rel, line,
                        f"{cls.name}.{attr} is an active table but "
                        f"{how} in {node.name}() without a "
                        f"`# table-swap-ok: <reason>` annotation — "
                        f"published tables mutate only through the "
                        f"builder/publish protocol "
                        f"(datapath/tables.py)", checker=NAME))

    # 2. the loader keeps the discipline armed
    if repo.by_rel(LOADER_MODULE) is not None:
        if not loader_declares_state:
            findings.append(Finding(
                CODE, LOADER_MODULE, 1,
                "no class declares `state` in an active-tables "
                "annotation — the device loader's generation "
                "discipline is unchecked", checker=NAME))
        if not loader_declares_oracle:
            findings.append(Finding(
                CODE, LOADER_MODULE, 1,
                "no class declares `oracle` in an active-tables "
                "annotation — the interpreter loader's generation "
                "discipline is unchecked", checker=NAME))
        if not loader_publish_ok:
            findings.append(Finding(
                CODE, LOADER_MODULE, 1,
                "no annotated _publish_tables swap helper found — "
                "the single-flip publish protocol has no anchor",
                checker=NAME))

    # 3. bench artifact schema (only when the artifact exists)
    bench_path = os.path.join(repo.root, BENCH_NAME)
    if os.path.exists(bench_path):
        for msg in check_bench(bench_path):
            findings.append(Finding(CODE, BENCH_NAME, 1, msg,
                                    checker=NAME))
    return findings


# -- bench artifact validation (tests + bench share it) ----------------
def check_bench(path: str) -> List[str]:
    """-> list of violation strings (empty = clean)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: does not load as JSON ({e})"]
    if not isinstance(data, dict):
        return [f"{path}: top level is {type(data).__name__}, "
                f"not an object"]
    bad = []
    if data.get("schema") != BENCH_SCHEMA:
        bad.append(f"{path}: schema {data.get('schema')!r} != "
                   f"{BENCH_SCHEMA}")
    for key in BENCH_CHURN_KEYS:
        if key not in data:
            bad.append(f"{path}: missing required key {key!r}")
    return bad
