"""Concurrency & invariant static analysis (the lockdebug-tag + CI
lint layer, made static).

Reference: upstream cilium ships its concurrency discipline as
TOOLING — CI builds with the ``lockdebug`` tag (go-deadlock wrapping
every mutex, ``infra/lockdebug.py`` is this repo's runtime mirror)
and a large golangci-lint/staticcheck pass gates every PR.  This
package is the static half: a pure-stdlib ``ast`` analyzer that
checks, at every call site on every tier-1 run, the invariants the
serving plane's five threads (drain, event-join worker, watchdog,
capture, API) depend on — invariants previously proven only by
runtime monkeypatch tests and hand audits.

Run it::

    python -m cilium_tpu.analysis          # human output, exit != 0 on findings
    python -m cilium_tpu.analysis --json   # machine output
    python scripts/lint.py                 # the CI entry point (same thing)

Checkers (stable codes)
-----------------------

========  ===========================================================
CTA000    analyzer configuration errors: malformed suppression or
          annotation, unknown checker code, unknown affinity token,
          unknown lock name in a ``guarded-by``/``holds`` reference
CTA001    guarded-by lock discipline: an attribute declared
          ``guarded-by: <lock>`` is touched outside ``with
          self.<lock>:`` (``__init__`` exempt; ``# holds:`` methods
          exempt for that lock)
CTA002    thread-affinity: code annotated (or reachable from code
          annotated) with affinity A calls a function whose declared
          affinity set excludes A — e.g. the drain thread reaching
          ``decode_ring_rows`` or ``FlowAnalytics._ingest``
CTA003    hot-path purity: code reachable from a hot domain — the
          serving drain loop (affinity includes ``drain``) or the
          cluster router's enqueue/forwarder path (``router``) —
          calls ``time.sleep``, logs at INFO or above, does file
          I/O (``open``), ``json.dumps``, or
          ``.block_until_ready()`` without a ``hot-path-ok`` waiver
CTA004    sharding-spec spelling: a trailing-``None``
          ``P(axis, None)`` outside a ``shard_map``
          ``in_specs``/``out_specs`` context — the spelling places
          identically to ``P(axis)`` but keys the compile cache
          differently, so fresh ``device_put`` arrays spelled with
          the trailing ``None`` recompile the serve step every
          window swap (the PR 2 retrace trap)
CTA005    reason-code budget: ``REASON_*`` constants must be unique,
          fit the ring's 4-bit wire field (< 16), agree with
          ``N_REASONS``, and every ``DROP_REASON_*`` decode table in
          the repo must cover every nonzero code
CTA006    metrics-registry scatter: prometheus exposition text built
          outside ``obs/registry.py``, or a required operator-
          contract series no longer registered (the former
          ``scripts/check_metrics_registry.py``)
CTA007    sysdump schema sync: ``SYSDUMP_REQUIRED_KEYS`` drifting
          from the daemon's ``_sysdump_collect`` sections (a renamed
          section silently yields ``None`` bundles); also validates
          bundle files passed on the command line (the former
          ``scripts/check_sysdump_schema.py``)
CTA008    cluster-ledger: every ``*_overflow``/``*_dropped``
          increment in ``cilium_tpu/cluster/`` must use a counter
          declared in ``router.DROP_COUNTERS``, each declared
          counter must have its ``cilium_cluster_*_total`` registry
          series, every ``DROP_REASON_*`` table must decode
          ``REASON_CLUSTER_OVERFLOW``, and ``BENCH_cluster.json``
          (when present) must keep its schema
          (``scripts/check_cluster_ledger.py`` is the shim CLI)
CTA009    generation discipline: a class's declared
          ``active-tables`` attrs (the published device tables and
          their host mirrors in ``datapath/loader.py``) may only be
          WRITTEN in methods annotated ``# table-swap-ok: <reason>``
          — every other mutation must go through the versioned
          builder/publish protocol (``datapath/tables.py``); the
          loader module must keep its ``state``/``oracle``
          declarations and annotated ``_publish_tables`` helper, and
          ``BENCH_churn.json`` (when present) must keep its schema
CTA010    scenario contract: every class registered in the
          ``testing/workloads.py`` ``SCENARIOS`` registry declares a
          docstring, a ``name`` literal, a ``criteria`` dict literal
          drawn from the known-criteria vocabulary, and a ``seed``
          constructor parameter (the determinism contract); the
          ``BENCH_scenarios.json`` artifact (when present) must keep
          its schema (``scripts/check_scenarios.py`` is the shim CLI)
CTA011    nodehost control-op discipline: every ``cluster/nodehost``
          ``_OPS`` entry has a positive ``OP_TIMEOUTS`` bound (the
          parent's ``ProcessNode.call`` default — an unbounded RPC
          against a wedged worker parks every later control caller,
          probes included, forever) and is referenced by at least
          one test under ``tests/``; ``OP_TIMEOUTS`` carries no
          stale entries; ``BENCH_obs.json`` (when present) must
          keep its schema
CTA012    proxy-ledger contract: the L7 redirect ledger's counters
          stay declared in ``proxy/worker.py``, surfaced in its
          stats snapshot, registered/floored as ``cilium_l7_*``
          series, and the ``l7.parse`` fault site stays armed;
          ``BENCH_l7.json`` (when present) keeps its schema
CTA013    encryption key hygiene: key material (X25519 private
          keys, derived session keys) never reaches a log call, an
          incident payload, a serializer, a sysdump/obs-collect
          surface, or the exposition/bundle modules; only
          ``NodeKeypair.load_or_create`` may persist a private key
          (``scripts/check_crypto_keys.py`` is the shim CLI)
========  ===========================================================

Annotation grammar
------------------

All annotations are ordinary comments, parsed with ``tokenize`` so
they survive formatting.

``# guarded-by: <lock>: <attr>[, <attr> ...]``
    Class-body declaration (conventionally next to the lock's
    creation in ``__init__``): the listed ``self.<attr>`` names may
    only be touched lexically inside ``with self.<lock>:`` (or a
    ``# holds:`` method).  ``__init__`` is exempt.  ``<lock>`` is a
    lock attribute name (``_lock``), any alias of it (a
    ``threading.Condition(self._lock)`` attribute resolves to the
    wrapped lock), or the runtime name given to
    ``infra.lockdebug.make_lock("<name>")`` — the static lock-alias
    map and the runtime lock registry share identities.

``self.attr = ...  # guarded-by: <lock>``
    Per-attribute trailing form on an ``__init__`` assignment.

``# holds: <lock>[, <lock> ...]``
    On the ``def`` line (trailing), directly above it, or as the
    first comment of the body: every caller guarantees the named
    lock is held, so the method's guarded accesses are exempt for
    that lock.

``# thread-affinity: <aff>[, <aff> ...]``
    Same placement as ``holds``.  Vocabulary: ``drain`` |
    ``event-worker`` | ``watchdog`` | ``capture`` | ``api`` |
    ``cli`` | ``offline`` | ``router`` | ``any``.  A function
    annotated with set S may only (transitively) call functions
    whose declared set is a superset of S (or contains ``any``);
    unannotated functions inherit their callers' affinities during
    the call-graph walk.  Functions whose set includes a hot domain
    (``drain``, or ``router`` — the cluster front end's enqueue path
    and forwarder threads) are the hot-path roots CTA003 scans from;
    ``api`` names the control-plane family (API handlers, CLI,
    tests' main thread, cluster membership/failover orchestration).

``# hot-path-ok: <reason>``
    Trailing waiver on a line CTA003 would flag (e.g. the drain
    loop's bounded idle ``time.sleep``, the load-bearing cursor
    ``block_until_ready`` in ``ring._start_window``).

``# active-tables: <attr>[, <attr> ...]``
    Class-body declaration (CTA009): the listed ``self.<attr>``
    names are published tables / table mirrors.  Any write —
    assignment (including tuple unpacking and stores rooted at the
    attr, e.g. ``self.tensors.verdict[...] = v``), ``del``, or a
    known container-mutator call — outside a ``table-swap-ok``
    method is a finding.  Reads are never flagged; ``__init__`` is
    exempt.  May repeat across lines (the union is declared).

``# table-swap-ok: <reason>``
    Same placement as ``holds``: marks a method as a sanctioned
    table-swap site (the publish helper, a builder, a CT-only or
    placement-only state swap).  The reason is mandatory — every
    swap site must say what class of swap it is.

``# lint: disable=<CODE>[,<CODE>...] -- <reason>``
    Suppress the listed codes on this line (trailing form) or on the
    next line (standalone form).  The reason is mandatory; a
    suppression without one is itself a CTA000 finding.

Baseline
--------

``ANALYSIS_BASELINE.json`` at the repo root grandfathers known
findings (matched by a line-content fingerprint, stable across line
drift).  It is committed EMPTY — every violation the analyzer
surfaced in this repo was fixed, not baselined — and exists so a
future bulk import can land incrementally.  Refresh with
``python -m cilium_tpu.analysis --write-baseline``.
"""

from __future__ import annotations

from .core import (  # noqa: F401
    BASELINE_NAME,
    Baseline,
    FileCtx,
    Finding,
    Repo,
    repo_root,
)
from .driver import CHECKERS, run_analysis  # noqa: F401

__all__ = [
    "BASELINE_NAME",
    "Baseline",
    "CHECKERS",
    "FileCtx",
    "Finding",
    "Repo",
    "repo_root",
    "run_analysis",
]
