"""CTA013 — encryption key hygiene (ISSUE 18).

The encrypted cluster channel's status surfaces expose COUNTERS AND
EPOCHS ONLY; the key material itself (X25519 private keys, derived
session keys) must never be observable.  A private key that leaks
into a sysdump bundle, a metrics exposition, a log line, or an
incident payload outlives the process that held it — bundles are
shipped to operators, scrapes are retained by monitoring stacks, and
neither is covered by rotation.  Four statically-checkable rules:

1. SINK CALLS: no key-bearing expression may appear in the arguments
   of a log call (``log.*``/``logger.*``/``logging.*``), an incident
   recorder (``record_incident``), or a serializer headed for an
   observability surface (``json.dumps`` / ``_jsonable``).
2. SURFACE FUNCTIONS: functions that build operator-visible bundles
   (any ``*sysdump*`` / ``*obs_collect*`` function, the worker's
   ``_crypto_block``, ``worker_crypto``, ``transport_stats``) must
   not reference key-bearing attributes AT ALL — their job is to
   summarize the channel, and a summary never needs the keys.
3. SEALED MODULES: the exposition/bundle modules
   (``obs/registry.py``, ``obs/relay.py``, ``obs/flightrec.py``)
   must not reference key-bearing names and must not import from
   ``encryption`` — key material cannot leak through a module that
   cannot name it.
4. KEY PERSISTENCE: ``NodeKeypair.load_or_create`` is the ONLY
   place allowed to write ``.private`` to disk (0600, the wireguard
   private-key file analogue) — flagged anywhere else.

Key-bearing names: the ``private`` half of a keypair, a channel's
``_send_key``/``_recv_key``/``_local``, the cluster facade's
``_crypto_kp`` keypair, and conventional locals like ``send_key`` /
``shared_secret``.  The PUBLIC key is exempt by design — advertising
it through the node registry is the whole point.

Suppression: the shared grammar
(``# lint: disable=CTA013 -- reason``).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import FileCtx, Finding, Repo

CODE = "CTA013"
NAME = "crypto-hygiene"

# attribute names that hold key material (object.attr accesses)
KEY_ATTRS = {"private", "_send_key", "_recv_key", "_local",
             "_crypto_kp"}
# bare names that conventionally hold key material
KEY_NAMES = {"private_key", "send_key", "recv_key", "session_key",
             "shared_secret"}

LOG_METHODS = {"debug", "info", "warning", "error", "exception",
               "critical", "log"}
INCIDENT_FUNCS = {"record_incident"}
SERIALIZERS = {"dumps", "_jsonable"}

# modules that build operator-facing expositions/bundles: no key
# name may even appear here
SEALED_MODULES = (
    "cilium_tpu/obs/registry.py",
    "cilium_tpu/obs/relay.py",
    "cilium_tpu/obs/flightrec.py",
)

# function-name predicates for rule 2 (operator-visible surfaces)
_SURFACE_EXACT = {"_crypto_block", "worker_crypto",
                  "transport_stats"}
_SURFACE_SUBSTR = ("sysdump", "obs_collect")

# the one sanctioned key writer (rule 4)
_KEYFILE_OWNER = "cilium_tpu/encryption/__init__.py"
_KEYFILE_FUNC = "load_or_create"


def _taint(node: ast.AST) -> Optional[str]:
    """The first key-bearing name referenced under ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in KEY_ATTRS:
            return sub.attr
        if isinstance(sub, ast.Name) and sub.id in KEY_NAMES:
            return sub.id
    return None


def _is_logger_call(call: ast.Call) -> bool:
    f = call.func
    if not (isinstance(f, ast.Attribute)
            and f.attr in LOG_METHODS):
        return False
    base = f.value
    while isinstance(base, ast.Attribute):
        base = base.value
    return isinstance(base, ast.Name) and "log" in base.id.lower()


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _emit(findings: List[Finding], ctx: FileCtx, line: int,
          msg: str, end_line: Optional[int] = None) -> None:
    # a multi-line sink call is waivable from any of its lines (the
    # suppression comment naturally lands next to the offending arg)
    for ln in range(line, (end_line or line) + 1):
        if ctx.suppressed(CODE, ln):
            return
    findings.append(Finding(CODE, ctx.rel, line, msg,
                            checker=NAME))


def _check_sink_calls(ctx: FileCtx,
                      findings: List[Finding]) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if _is_logger_call(node):
            sink = f"log call .{name}()"
        elif name in INCIDENT_FUNCS:
            sink = f"incident payload ({name})"
        elif name in SERIALIZERS:
            sink = f"serializer {name}()"
        else:
            continue
        for arg in [*node.args,
                    *(kw.value for kw in node.keywords)]:
            t = _taint(arg)
            if t is not None:
                _emit(findings, ctx, node.lineno,
                      f"key material ({t!r}) reaches {sink} — "
                      f"keys must never be logged, recorded, or "
                      f"serialized into an observability surface",
                      end_line=getattr(node, "end_lineno", None))
                break


def _check_surface_funcs(ctx: FileCtx,
                         findings: List[Finding]) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        fname = node.name
        if not (fname in _SURFACE_EXACT
                or any(s in fname for s in _SURFACE_SUBSTR)):
            continue
        for stmt in node.body:
            t = _taint(stmt)
            if t is not None:
                _emit(findings, ctx, stmt.lineno,
                      f"operator-visible surface {fname}() "
                      f"references key material ({t!r}) — status "
                      f"surfaces carry counters and epochs only")
                break


def _check_sealed_module(ctx: FileCtx,
                         findings: List[Finding]) -> None:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) \
                and "encryption" in (node.module or ""):
            _emit(findings, ctx, node.lineno,
                  "exposition/bundle module imports from the "
                  "encryption package — key material must not be "
                  "nameable here")
        elif isinstance(node, ast.Attribute) \
                and node.attr in KEY_ATTRS:
            _emit(findings, ctx, node.lineno,
                  f"exposition/bundle module references key "
                  f"material ({node.attr!r})")
        elif isinstance(node, ast.Name) and node.id in KEY_NAMES:
            _emit(findings, ctx, node.lineno,
                  f"exposition/bundle module references key "
                  f"material ({node.id!r})")


def _check_key_writes(ctx: FileCtx,
                      findings: List[Finding]) -> None:
    """``f.write(<something>.private)`` outside the sanctioned
    keyfile writer."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        if ctx.rel == _KEYFILE_OWNER and node.name == _KEYFILE_FUNC:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in ("write", "sendall",
                                          "send", "put", "update") \
                    and any(_taint(a) for a in sub.args):
                _emit(findings, ctx, sub.lineno,
                      f"key material written/sent by "
                      f"{node.name}() — only NodeKeypair."
                      f"{_KEYFILE_FUNC} may persist a private key")


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    for ctx in repo.files:
        if ctx.tree is None:
            continue
        if ctx.rel in SEALED_MODULES:
            _check_sealed_module(ctx, findings)
        _check_sink_calls(ctx, findings)
        _check_surface_funcs(ctx, findings)
        _check_key_writes(ctx, findings)
    return findings
