"""CTA014 — SLO-plane contract: declared objectives stay evaluable.

The SLO engine (``obs/slo.py``) is only as honest as its inputs: an
SLO referencing a series the registry no longer exports, or one the
history ring does not sample, silently degrades to permanent
``no-data`` — the alert that can never fire.  The engine validates
this at construction, but only on the daemon path that actually
builds it; this checker makes the contract a repo invariant:

1. **Every series a shipped SLO references** (``default_slos``'s
   ``bad``/``total``/``series`` fields) must be a registered name in
   ``obs/registry.py`` AND a member of ``HISTORY_SERIES`` (the
   ring's declared sampling subset) — either gap is the
   alert-that-cannot-fire failure mode.
2. **Every HISTORY_SERIES name** must stay registered: the sampler
   drops unknown names silently (a torn registry rename would
   otherwise blank a ring series with no error anywhere).
3. **The ``cilium_slo_*`` exposition floor** must stay registered —
   the burn verdicts themselves are an operator contract
   (:data:`SLO_REQUIRED_SERIES`, the CTA006 floor idiom).

Additionally, when ``BENCH_obs.json`` exists at the repo root it
must carry the v2 observability bench schema
(:data:`BENCH_OBS_KEYS`: the v1 scrape-overhead floor plus the
ISSUE 19 sampler-overhead paired legs and the burn-detection
latency; ``check_bench`` is the importable validator — the CTA008
idiom, migrated here from CTA011 with the v1->v2 bump).
"""

from __future__ import annotations

import ast
import json
import os
from typing import List, Optional, Tuple

from .core import FileCtx, Finding, Repo

CODE = "CTA014"
NAME = "slo-contract"

SLO_MODULE = "cilium_tpu/obs/slo.py"
REGISTRY_MODULE = "cilium_tpu/obs/registry.py"

# the SLO plane's own exposition floor: burn verdicts must stay
# scrapeable (dashboards alert on these, not on the JSON surface)
SLO_REQUIRED_SERIES = (
    "cilium_slo_budget_remaining",
    "cilium_slo_burn_rate",
    "cilium_slo_state",
)

BENCH_NAME = "BENCH_obs.json"
# the observability bench artifact's schema floor (bench.py --obs):
# v1's paired-leg scrape-overhead ratio + rtt percentiles, plus the
# ISSUE 19 additions — the sampler-overhead paired legs (history +
# SLO engine armed vs off) and the burn-detection latency for a
# seeded shed burst
BENCH_OBS_KEYS = (
    "schema", "best_of",
    "sustained_pps_obs", "sustained_pps_noobs",
    "scrape_overhead_ratio", "scrape_overhead_pairs",
    "scrape_rtt_us", "scrapes_total",
    "stitched_spans", "ledger_exact",
    "sampler_overhead_ratio", "sampler_overhead_pairs",
    "burn_detect_s",
)
BENCH_SCHEMA = "bench-obs-v2"

_SLO_SERIES_FIELDS = ("bad", "total", "series")


def _tuple_strs(ctx: FileCtx, name: str) -> Optional[List[Tuple[str,
                                                                int]]]:
    """Module-level ``name = ("a", "b", ...)`` -> [(value, lineno)]."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            out = []
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str):
                    out.append((elt.value, elt.lineno))
            return out
    return None


def _declared_slo_series(ctx: FileCtx) -> List[Tuple[str, str, int]]:
    """-> [(slo_name, series, lineno)] from every ``SLODef(...)``
    call inside ``default_slos`` (keyword fields only — the
    dataclass is keyword-constructed by convention)."""
    fn = None
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "default_slos":
            fn = node
            break
    if fn is None:
        return []
    out: List[Tuple[str, str, int]] = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "SLODef"):
            continue
        slo_name = "?"
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                slo_name = str(kw.value.value)
        for kw in node.keywords:
            if kw.arg not in _SLO_SERIES_FIELDS:
                continue
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, str) and v.value:
                    out.append((slo_name, v.value, v.lineno))
    return out


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    slo = repo.by_rel(SLO_MODULE)
    reg = repo.by_rel(REGISTRY_MODULE)
    if slo is None or slo.tree is None:
        return [Finding(CODE, SLO_MODULE, 1,
                        "SLO module missing", checker=NAME)]
    if reg is None:
        return [Finding(CODE, REGISTRY_MODULE, 1,
                        "registry module missing", checker=NAME)]
    history = _tuple_strs(slo, "HISTORY_SERIES")
    if history is None:
        findings.append(Finding(
            CODE, slo.rel, 1,
            "HISTORY_SERIES tuple literal not found (the ring's "
            "declared sampling subset)", checker=NAME))
        history = []
    history_names = {n for n, _ in history}
    for name, line in history:
        if f'"{name}"' not in reg.source:
            findings.append(Finding(
                CODE, slo.rel, line,
                f"history series {name!r} is not registered in "
                f"obs/registry.py — the sampler would drop it "
                f"silently", checker=NAME))
    refs = _declared_slo_series(slo)
    if not refs:
        findings.append(Finding(
            CODE, slo.rel, 1,
            "no SLODef series references found under default_slos "
            "(the shipped SLO set went invisible to this checker)",
            checker=NAME))
    for slo_name, series, line in refs:
        if f'"{series}"' not in reg.source:
            findings.append(Finding(
                CODE, slo.rel, line,
                f"SLO {slo_name!r} references unregistered series "
                f"{series!r} — an alert that can never fire",
                checker=NAME))
        if history_names and series not in history_names:
            findings.append(Finding(
                CODE, slo.rel, line,
                f"SLO {slo_name!r} references {series!r} which is "
                f"not in HISTORY_SERIES — the ring never samples "
                f"it, so the SLO evaluates to permanent no-data",
                checker=NAME))
    for name in SLO_REQUIRED_SERIES:
        if f'"{name}"' not in reg.source:
            findings.append(Finding(
                CODE, REGISTRY_MODULE, 1,
                f"required series {name!r} is not registered "
                f"(the SLO exposition floor)", checker=NAME))
    # bench artifact schema (only when the artifact exists)
    bench_path = os.path.join(repo.root, BENCH_NAME)
    if os.path.exists(bench_path):
        for msg in check_bench(bench_path):
            findings.append(Finding(CODE, BENCH_NAME, 1, msg,
                                    checker=NAME))
    return findings


# -- bench artifact validation (tests import this) ---------------------
def check_bench(path: str) -> List[str]:
    """-> list of violation strings (empty = clean)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: does not load as JSON ({e})"]
    if not isinstance(data, dict):
        return [f"{path}: top level is {type(data).__name__}, "
                f"not an object"]
    bad = []
    if data.get("schema") != BENCH_SCHEMA:
        bad.append(f"{path}: schema {data.get('schema')!r} != "
                   f"{BENCH_SCHEMA}")
    for key in BENCH_OBS_KEYS:
        if key not in data:
            bad.append(f"{path}: missing required key {key!r}")
    return bad
