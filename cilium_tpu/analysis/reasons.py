"""CTA005 — reason-code budget.

The event ring packs the drop reason into a 4-BIT wire field
(``monitor/ring.py`` w0 bits 5..8), so the ``REASON_*`` space is a
real budget: codes must be unique, fit in [0, 16), agree with
``N_REASONS``, and every decode table that renders them — monitor
(``DROP_REASON_NAMES``), flow/hubble (``DROP_REASON_DESC``), and any
future CLI table matching the ``DROP_REASON_*`` naming convention —
must cover every nonzero code, or a freshly minted reason decodes as
``"reason 13"`` on exactly the surface an operator is staring at
during the incident that minted it.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from .core import FileCtx, Finding, Repo

CODE = "CTA005"
NAME = "reason-codes"

VERDICT_MODULE = "cilium_tpu/datapath/verdict.py"
_TABLE_RE = re.compile(r"^DROP_REASON_[A-Z_]*$")
# the ring's 4-bit wire field (monitor/ring.py)
WIRE_LIMIT = 16


def _collect_reasons(ctx: FileCtx
                     ) -> Tuple[Dict[str, int], Optional[int]]:
    reasons: Dict[str, int] = {}
    n_reasons: Optional[int] = None
    for node in ctx.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        if tgt.id.startswith("REASON_") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int):
            reasons[tgt.id] = node.value.value
        elif tgt.id == "N_REASONS" \
                and isinstance(node.value, ast.Constant):
            n_reasons = node.value.value
    return reasons, n_reasons


def _decode_tables(repo: Repo) -> List[Tuple[FileCtx, str, ast.Dict,
                                             Dict[int, str]]]:
    out = []
    for ctx in repo.files:
        if ctx.tree is None:
            continue
        for node in ctx.tree.body:
            if not isinstance(node, ast.Assign) \
                    or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Name)
                    and _TABLE_RE.match(tgt.id)
                    and isinstance(node.value, ast.Dict)):
                continue
            table: Dict[int, str] = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, int) \
                        and isinstance(v, ast.Constant):
                    table[k.value] = str(v.value)
            out.append((ctx, tgt.id, node.value, table))
    return out


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    ctx = repo.by_rel(VERDICT_MODULE)
    if ctx is None or ctx.tree is None:
        return [Finding(CODE, VERDICT_MODULE, 1,
                        "REASON_* home module missing or unparsable",
                        checker=NAME)]
    reasons, n_reasons = _collect_reasons(ctx)

    def flag(line: int, msg: str) -> None:
        if not ctx.suppressed(CODE, line):
            findings.append(Finding(CODE, ctx.rel, line, msg,
                                    checker=NAME))

    by_value: Dict[int, List[str]] = {}
    for name, value in reasons.items():
        by_value.setdefault(value, []).append(name)
        if not 0 <= value < WIRE_LIMIT:
            flag(1, f"{name} = {value} does not fit the ring's "
                    f"4-bit reason field (codes must be < "
                    f"{WIRE_LIMIT})")
    for value, names in sorted(by_value.items()):
        if len(names) > 1:
            flag(1, f"duplicate reason code {value}: "
                    f"{', '.join(sorted(names))}")
    if reasons:
        expect = max(reasons.values()) + 1
        if n_reasons is None:
            flag(1, "N_REASONS is not defined next to the REASON_* "
                    "constants")
        elif n_reasons != expect:
            flag(1, f"N_REASONS = {n_reasons} but the REASON_* "
                    f"constants cover 0..{expect - 1} (want "
                    f"{expect})")
        elif n_reasons != len(reasons):
            flag(1, f"N_REASONS = {n_reasons} but only "
                    f"{len(reasons)} REASON_* constants exist "
                    f"(holes in the code space)")
    codes = set(range(1, (n_reasons
                          or (max(reasons.values()) + 1
                              if reasons else 1))))
    for tctx, tname, node, table in _decode_tables(repo):
        missing = sorted(codes - set(table))
        extra = sorted(k for k in table
                       if k not in codes and k != 0)
        line = node.lineno
        if missing and not tctx.suppressed(CODE, line):
            findings.append(Finding(
                CODE, tctx.rel, line,
                f"decode table {tname} is missing reason code(s) "
                f"{missing} — a drained row with one of these "
                f"renders as a bare number", checker=NAME))
        if extra and not tctx.suppressed(CODE, line):
            findings.append(Finding(
                CODE, tctx.rel, line,
                f"decode table {tname} names unknown reason "
                f"code(s) {extra} (not in REASON_* / N_REASONS)",
                checker=NAME))
    return findings
