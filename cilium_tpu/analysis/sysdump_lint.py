"""CTA007 — sysdump schema sync (the former
``scripts/check_sysdump_schema.py``, folded in as a registered
checker; the script remains a thin delegating shim).

Two halves:

1. STATIC drift check, run on every analysis pass: every
   ``SYSDUMP_REQUIRED_KEYS`` entry that is not part of the envelope
   the flight recorder writes itself must appear as a section name
   in the daemon's ``_sysdump_collect`` — the writer defaults
   missing keys to ``None``, so a renamed section otherwise degrades
   silently into a bundle full of nulls that still "passes" the old
   schema check.

2. BUNDLE validation (``check_bundle``), used by the shim CLI and
   the flight-recorder tests: the bundle must load as JSON, carry
   every required key and a known schema version, and fit the size
   cap it declares.
"""

from __future__ import annotations

import ast
import json
import os
from typing import List, Optional, Set

from .core import FileCtx, Finding, Repo

CODE = "CTA007"
NAME = "sysdump-schema"

FLIGHTREC_MODULE = "cilium_tpu/obs/flightrec.py"
DAEMON_MODULE = "cilium_tpu/agent/daemon.py"
# keys the recorder's envelope provides regardless of collect_fn
ENVELOPE_KEYS = {"schema", "node", "taken-at", "trigger", "incident",
                 "incidents"}


def _required_keys(ctx: FileCtx) -> Optional[List[str]]:
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SYSDUMP_REQUIRED_KEYS" \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)]
    return None


def _collect_sections(ctx: FileCtx) -> Set[str]:
    """Section names ``_sysdump_collect`` produces: every string
    constant passed as the first argument of a ``section(...)``
    call, plus literal dict keys of its return value."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_sysdump_collect":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Name) \
                        and sub.func.id == "section" and sub.args \
                        and isinstance(sub.args[0], ast.Constant):
                    out.add(str(sub.args[0].value))
                elif isinstance(sub, ast.Dict):
                    for k in sub.keys:
                        if isinstance(k, ast.Constant):
                            out.add(str(k.value))
                elif isinstance(sub, ast.Subscript) \
                        and isinstance(sub.slice, ast.Constant):
                    out.add(str(sub.slice.value))
    return out


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    fr = repo.by_rel(FLIGHTREC_MODULE)
    if fr is None or fr.tree is None:
        return [Finding(CODE, FLIGHTREC_MODULE, 1,
                        "flight-recorder module missing",
                        checker=NAME)]
    required = _required_keys(fr)
    if required is None:
        return [Finding(CODE, fr.rel, 1,
                        "SYSDUMP_REQUIRED_KEYS literal not found",
                        checker=NAME)]
    daemon = repo.by_rel(DAEMON_MODULE)
    if daemon is None or daemon.tree is None:
        return findings
    sections = _collect_sections(daemon)
    if not sections:
        findings.append(Finding(
            CODE, daemon.rel, 1,
            "Daemon._sysdump_collect not found (the sysdump section "
            "producer moved — update the checker's module map)",
            checker=NAME))
        return findings
    for key in required:
        if key in ENVELOPE_KEYS or key in sections:
            continue
        findings.append(Finding(
            CODE, daemon.rel, 1,
            f"sysdump required key {key!r} is not produced by "
            f"Daemon._sysdump_collect — bundles will carry it as "
            f"null", checker=NAME))
    return findings


# -- bundle validation (shim CLI + tests) ------------------------------
def check_bundle(path: str) -> list:
    """-> list of violation strings (empty = clean)."""
    from ..obs.flightrec import SYSDUMP_REQUIRED_KEYS, SYSDUMP_SCHEMA

    bad = []
    try:
        size = os.path.getsize(path)
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    try:
        with open(path) as f:
            bundle = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: does not load as JSON ({e})"]
    if not isinstance(bundle, dict):
        return [f"{path}: top level is {type(bundle).__name__}, "
                f"not an object"]
    if bundle.get("schema") != SYSDUMP_SCHEMA:
        bad.append(f"{path}: schema {bundle.get('schema')!r} != "
                   f"{SYSDUMP_SCHEMA}")
    for key in SYSDUMP_REQUIRED_KEYS:
        if key not in bundle:
            bad.append(f"{path}: missing required key {key!r}")
    cap = bundle.get("max-bytes")
    if isinstance(cap, int) and size > cap:
        bad.append(f"{path}: {size} bytes exceeds its declared "
                   f"cap {cap}")
    return bad
