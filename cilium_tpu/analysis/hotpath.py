"""CTA003 — hot-path purity.

The serving drain loop's latency budget is the product's throughput
ceiling (ROADMAP item 2: Python dispatch overhead IS the bottleneck),
so code reachable from it must not:

- ``time.sleep`` (the bounded idle tick is waived explicitly);
- log at INFO or above (DEBUG is allowed — it is compiled out of hot
  configs; WARNING+ formats strings and may hit handlers/IO);
- do file I/O (``open``);
- ``json.dumps`` / ``json.dump`` (serialization belongs on the
  capture/API planes);
- ``.block_until_ready()`` (a device sync; the one load-bearing
  cursor sync in ``ring._start_window`` is waived with its reason).

Roots are every function whose declared thread-affinity includes a
HOT DOMAIN — ``drain`` (the serving drain loop) or ``router`` (the
cluster front-end's enqueue path + per-node forwarder threads, PR 8:
the cluster tier's submit latency is its admission ceiling exactly
like dispatch latency is the node's).  Reachability follows the call
graph WITHOUT stopping at ``any``-affine boundaries (the hot thread
really executes those bodies) but does not descend into functions
whose declared affinity excludes the domain — that edge is CTA002's
business.

Waive a line with ``# hot-path-ok: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FuncInfo
from .core import Finding, Repo

CODE = "CTA003"
NAME = "hot-path"

_LOG_LEVELS = {"info", "warning", "warn", "error", "critical",
               "exception", "log"}


# the hot thread-affinity domains this checker roots at, and the
# human name each renders with in findings
HOT_DOMAINS = {
    "drain": "serving drain loop",
    "router": "cluster router hot path",
    # the cluster transport I/O threads (ISSUE 13): row-frame
    # send/recv/decode/ack on the forwarders and the node host's
    # data reader — a forward's round trip is cluster admission
    # latency exactly like dispatch latency is the node's
    "transport": "cluster transport I/O",
    # the L7 worker pool (ISSUE 16): parse + fused-tensor verdict on
    # the proxy workers — a redirect's detour latency is serving
    # latency for that flow, so the same no-sleep/no-logging/no-file
    # discipline applies
    "l7": "L7 proxy worker",
}


def domain_roots(graph: CallGraph, domain: str) -> List[str]:
    return [k for k, fi in graph.funcs.items()
            if fi.affinity is not None and domain in fi.affinity]


def drain_roots(graph: CallGraph) -> List[str]:
    """Kept for callers/tests of the original single-domain API."""
    return domain_roots(graph, "drain")


def reachable(graph: CallGraph, domain: str = "drain") -> Set[str]:
    seen: Set[str] = set()
    work = domain_roots(graph, domain)
    while work:
        f = work.pop()
        if f in seen:
            continue
        seen.add(f)
        for g, _line in graph.edges.get(f, ()):
            gi = graph.funcs[g]
            if gi.affinity is not None \
                    and domain not in gi.affinity \
                    and "any" not in gi.affinity:
                continue  # CTA002 territory, not hot-path reach
            if g not in seen:
                work.append(g)
    return seen


def _own_nodes(fn: ast.FunctionDef) -> List[ast.AST]:
    out: List[ast.AST] = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            out.append(child)
            walk(child)

    walk(fn)
    return out


def _violation(node: ast.Call, src: str) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Name):
        if fn.id == "open":
            return "file I/O (open)"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    if fn.attr == "block_until_ready":
        return "device sync (block_until_ready)"
    if fn.attr == "sleep" and isinstance(fn.value, ast.Name) \
            and fn.value.id == "time":
        return "time.sleep"
    if fn.attr in ("dumps", "dump") and isinstance(fn.value, ast.Name) \
            and fn.value.id == "json":
        return f"json.{fn.attr}"
    if fn.attr in _LOG_LEVELS:
        try:
            base = ast.unparse(fn.value)
        except Exception:  # noqa: BLE001 — unparse is best-effort
            base = ""
        if "logg" in base.lower():
            return f"logging.{fn.attr} (>= INFO)"
    return None


def check(repo: Repo, graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    seen_lines: Set[Tuple[str, int]] = set()
    for domain, domain_name in HOT_DOMAINS.items():
        for key in sorted(reachable(graph, domain)):
            fi: FuncInfo = graph.funcs[key]
            for node in _own_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                what = _violation(node, fi.ctx.source)
                if what is None:
                    continue
                line = node.lineno
                if (fi.ctx.rel, line) in seen_lines:
                    continue  # also dedupes across domains: one
                    # violating line is one finding
                seen_lines.add((fi.ctx.rel, line))
                # a waiver may sit on any line of a multi-line call,
                # or anywhere in the contiguous comment block
                # directly above
                end = getattr(node, "end_lineno", None) or line
                if any(ln in fi.ctx.hotpath_ok
                       for ln in range(line, end + 1)):
                    continue
                above = line - 1
                waived = False
                while above >= 1 and fi.ctx.comment_only.get(above):
                    if above in fi.ctx.hotpath_ok:
                        waived = True
                        break
                    above -= 1
                if waived:
                    continue
                if fi.ctx.suppressed(CODE, line):
                    continue
                qual = f"{fi.cls}.{fi.name}" if fi.cls else fi.name
                findings.append(Finding(
                    CODE, fi.ctx.rel, line,
                    f"{what} in {qual}, which is reachable from the "
                    f"{domain_name} (waive with `# hot-path-ok: "
                    f"reason` if intentional)", checker=NAME))
    return findings
