"""CTA012 — proxy-ledger contract: the L7 redirect ledger's counters
stay declared, surfaced, and scrapeable; the L7 bench artifact keeps
its schema.

The L7 plane's no-silent-loss contract (``redirected == l7_allowed +
l7_denied + l7_shed + l7_failed``) is only worth anything while every
leg stays VISIBLE end to end: counter declared in the pool, stat key
in the pool's snapshot, ``cilium_l7_*`` series in the metrics
registry, and that series pinned by CTA006's REQUIRED_SERIES floor.
A refactor that drops any link quietly turns counted loss back into
silent loss, so the chain is enforced statically (the CTA006/CTA010
idiom):

1. every :data:`LEDGER_COUNTERS` name must be DECLARED
   (``self.<name> = 0``) in ``proxy/worker.py`` — the single
   authoritative home of the ledger;
2. every :data:`LEDGER_STAT_KEYS` kebab key must appear as a string
   literal in ``proxy/worker.py`` (the ``stats()`` snapshot every
   surface above reads);
3. every :data:`REQUIRED_L7_SERIES` name must be registered in
   ``obs/registry.py`` AND pinned in ``registry_lint.py``'s
   REQUIRED_SERIES floor (one floor per checker is not enough: THIS
   check fails when someone edits the floor out from under the L7
   family);
4. the ``l7.parse`` fault site must stay declared in
   ``infra/faults.py`` and armed-before-parse in ``proxy/worker.py``
   — the chaos gate's worker-death leg dies silently without it;
5. when ``BENCH_l7.json`` exists at the repo root it carries the
   :data:`BENCH_L7_KEYS` floor (``check_bench`` is the importable
   validator bench and tests share).
"""

from __future__ import annotations

import json
import os
import re
from typing import List

from .core import Finding, Repo

CODE = "CTA012"
NAME = "proxy-ledger"

WORKER_MODULE = "cilium_tpu/proxy/worker.py"
PLANE_MODULE = "cilium_tpu/serving/l7plane.py"
REGISTRY_MODULE = "cilium_tpu/obs/registry.py"
REGISTRY_LINT_MODULE = "cilium_tpu/analysis/registry_lint.py"
FAULTS_MODULE = "cilium_tpu/infra/faults.py"

# the ledger: redirected == l7_allowed + l7_denied + l7_shed +
# l7_failed (rows, exact post-stop)
LEDGER_COUNTERS = (
    "redirected", "l7_allowed", "l7_denied", "l7_shed", "l7_failed",
)
# ...and the kebab keys the pool's stats() snapshot surfaces them as
LEDGER_STAT_KEYS = (
    "redirected", "l7-allowed", "l7-denied", "l7-shed", "l7-failed",
    "ledger-exact",
)
# the scrape-plane floor for the family (mirrored into CTA006's
# REQUIRED_SERIES — both must hold)
REQUIRED_L7_SERIES = (
    "cilium_l7_redirected_total",
    "cilium_l7_allowed_total",
    "cilium_l7_denied_total",
    "cilium_l7_shed_total",
    "cilium_l7_failed_total",
    "cilium_l7_worker_restarts_total",
    "cilium_l7_dns_answers_total",
    "cilium_l7_parse_lag_us",
)

FAULT_SITE = "l7.parse"

BENCH_NAME = "BENCH_l7.json"
BENCH_SCHEMA = "bench-l7-v1"
# top-level keys the L7 bench artifact must carry: the paired-leg
# redirect-overhead ratio, per-plugin parse percentiles, and the
# offline proxy microbench riding along
BENCH_L7_KEYS = (
    "schema", "redirect_overhead", "parse_latency_by_plugin",
    "offline_http",
)
# the paired-leg result keys inside redirect_overhead (the
# bench.paired_legs contract)
BENCH_OVERHEAD_KEYS = (
    "baseline_pps", "candidate_pps", "ratio_median", "ratio_best",
)


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    worker = repo.by_rel(WORKER_MODULE)
    if worker is None:
        findings.append(Finding(
            CODE, WORKER_MODULE, 1,
            "L7 worker-pool module missing (the redirect ledger's "
            "home)", checker=NAME))
    else:
        for name in LEDGER_COUNTERS:
            if not re.search(rf"self\.{name}\s*=\s*0\b",
                             worker.source):
                findings.append(Finding(
                    CODE, worker.rel, 1,
                    f"ledger counter {name!r} is not declared "
                    f"(`self.{name} = 0`) in the worker pool — the "
                    f"redirect ledger cannot close without it",
                    checker=NAME))
        for key in LEDGER_STAT_KEYS:
            if f'"{key}"' not in worker.source:
                findings.append(Finding(
                    CODE, worker.rel, 1,
                    f"ledger stat key {key!r} is not surfaced by the "
                    f"pool's stats() snapshot", checker=NAME))
        if "SITE_L7_PARSE" not in worker.source:
            findings.append(Finding(
                CODE, worker.rel, 1,
                f"the {FAULT_SITE!r} fault site is not armed in the "
                f"worker loop (the chaos gate's worker-death leg)",
                checker=NAME))
    plane = repo.by_rel(PLANE_MODULE)
    if plane is None:
        findings.append(Finding(
            CODE, PLANE_MODULE, 1,
            "L7 plane module missing (the redirect fan-out)",
            checker=NAME))
    reg = repo.by_rel(REGISTRY_MODULE)
    if reg is not None:  # CTA006 owns the missing-module finding
        for name in REQUIRED_L7_SERIES:
            if f'"{name}"' not in reg.source:
                findings.append(Finding(
                    CODE, reg.rel, 1,
                    f"L7 series {name!r} is not registered — a "
                    f"ledger leg went scrape-invisible",
                    checker=NAME))
    lint = repo.by_rel(REGISTRY_LINT_MODULE)
    if lint is not None:
        for name in REQUIRED_L7_SERIES:
            if f'"{name}"' not in lint.source:
                findings.append(Finding(
                    CODE, lint.rel, 1,
                    f"L7 series {name!r} is not pinned in CTA006's "
                    f"REQUIRED_SERIES floor", checker=NAME))
    faults = repo.by_rel(FAULTS_MODULE)
    if faults is not None and f'"{FAULT_SITE}"' not in faults.source:
        findings.append(Finding(
            CODE, FAULTS_MODULE, 1,
            f"fault site {FAULT_SITE!r} is not declared in the "
            f"injector's SITES", checker=NAME))
    bench_path = os.path.join(repo.root, BENCH_NAME)
    if os.path.exists(bench_path):
        for msg in check_bench(bench_path):
            findings.append(Finding(CODE, BENCH_NAME, 1, msg,
                                    checker=NAME))
    return findings


# -- bench artifact validation (bench + tests share it) ----------------
def check_bench(path: str) -> List[str]:
    """-> list of violation strings (empty = clean)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: does not load as JSON ({e})"]
    if not isinstance(data, dict):
        return [f"{path}: top level is {type(data).__name__}, "
                f"not an object"]
    bad = []
    if data.get("schema") != BENCH_SCHEMA:
        bad.append(f"{path}: schema {data.get('schema')!r} != "
                   f"{BENCH_SCHEMA}")
    for key in BENCH_L7_KEYS:
        if key not in data:
            bad.append(f"{path}: missing required key {key!r}")
    ov = data.get("redirect_overhead")
    if isinstance(ov, dict):
        for key in BENCH_OVERHEAD_KEYS:
            if key not in ov:
                bad.append(f"{path}: redirect_overhead missing "
                           f"required key {key!r}")
    elif "redirect_overhead" in data:
        bad.append(f"{path}: redirect_overhead is not an object")
    plat = data.get("parse_latency_by_plugin")
    if isinstance(plat, dict):
        for name, snap in plat.items():
            if not isinstance(snap, dict) or "p99" not in snap:
                bad.append(f"{path}: parse_latency_by_plugin"
                           f"[{name!r}] missing percentile keys")
    elif "parse_latency_by_plugin" in data:
        bad.append(f"{path}: parse_latency_by_plugin is not an "
                   f"object")
    return bad
