"""CTA011 — nodehost control-op discipline: every worker control op
is timeout-bounded and test-referenced.

The process-per-node tier's control channel (``cluster/nodehost.py``
``_OPS``) is the parent's ONLY window into a worker.  Two failure
modes this checker makes impossible to ship:

1. **An unbounded op.**  ``ProcessNode.call`` serializes RPCs under
   the per-node control lock; one call with no deadline against a
   wedged worker parks every later caller (probes included) behind
   it forever — the membership prober can then never declare the
   node dead.  Every ``_OPS`` key must therefore have a positive
   numeric bound in ``nodehost.OP_TIMEOUTS`` (which ``call`` uses as
   its default), and the table must not carry stale entries for ops
   that no longer exist.

2. **An untested op.**  The control vocabulary is a cross-process
   wire contract with no type checker across it; an op nothing
   references from ``tests/`` is a dead letter the next refactor
   breaks silently.  Every ``_OPS`` key must appear as a string
   literal somewhere under ``tests/``.

(The ``BENCH_obs.json`` schema gate that used to ride here moved to
``slo_lint`` (CTA014) with the v1->v2 schema bump.)
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional

from .core import FileCtx, Finding, Repo

CODE = "CTA011"
NAME = "nodehost-ops"

NODEHOST_MODULE = "cilium_tpu/cluster/nodehost.py"
TESTS_DIR = "tests"


def _dict_str_keys(ctx: FileCtx, name: str) -> Optional[Dict[str,
                                                             object]]:
    """Module- or class-level ``name = {"k": v, ...}`` -> {k: value
    node} (string keys only)."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Dict):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, str):
                    out[k.value] = v
            return out
    return None


def _tests_source(root: str) -> str:
    """Concatenated test sources (the reference scan — tests/ sits
    outside the package walk, like the BENCH artifacts)."""
    chunks: List[str] = []
    tests = os.path.join(root, TESTS_DIR)
    for dirpath, dirnames, filenames in os.walk(tests):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirpath, fn),
                          encoding="utf-8", errors="replace") as f:
                    chunks.append(f.read())
            except OSError:
                continue
    return "\n".join(chunks)


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    ctx = repo.by_rel(NODEHOST_MODULE)
    if ctx is None or ctx.tree is None:
        return [Finding(CODE, NODEHOST_MODULE, 1,
                        "nodehost module missing", checker=NAME)]
    ops = _dict_str_keys(ctx, "_OPS")
    timeouts = _dict_str_keys(ctx, "OP_TIMEOUTS")
    if ops is None:
        return [Finding(CODE, ctx.rel, 1,
                        "_OPS dict literal not found", checker=NAME)]
    if timeouts is None:
        return [Finding(
            CODE, ctx.rel, 1,
            "OP_TIMEOUTS dict literal not found (every control op "
            "needs a declared timeout bound)", checker=NAME)]
    for op, vnode in ops.items():
        line = getattr(vnode, "lineno", 1)
        tnode = timeouts.get(op)
        if tnode is None:
            findings.append(Finding(
                CODE, ctx.rel, line,
                f"control op {op!r} has no OP_TIMEOUTS bound — an "
                f"unbounded RPC against a wedged worker parks every "
                f"later control caller (probes included) forever",
                checker=NAME))
        elif not (isinstance(tnode, ast.Constant)
                  and isinstance(tnode.value, (int, float))
                  and tnode.value > 0):
            findings.append(Finding(
                CODE, ctx.rel, getattr(tnode, "lineno", line),
                f"control op {op!r}'s OP_TIMEOUTS entry must be a "
                f"positive numeric literal", checker=NAME))
    for op, tnode in timeouts.items():
        if op not in ops:
            findings.append(Finding(
                CODE, ctx.rel, getattr(tnode, "lineno", 1),
                f"OP_TIMEOUTS carries {op!r} but no such _OPS entry "
                f"exists (stale bound)", checker=NAME))
    tests_src = _tests_source(repo.root)
    for op, vnode in ops.items():
        if f'"{op}"' in tests_src or f"'{op}'" in tests_src:
            continue
        findings.append(Finding(
            CODE, ctx.rel, getattr(vnode, "lineno", 1),
            f"control op {op!r} is referenced by no test under "
            f"tests/ — a cross-process wire contract with no "
            f"coverage is a dead letter", checker=NAME))
    return findings
