"""CTA010 — scenario contract: every registered adversarial scenario
declares its identity and pass criteria; the scenario bench artifact
keeps its schema.

The scenario engine (``testing/workloads.py``) is regression-gated
COVERAGE: tests, the everything-on soak gate, and ``bench.py
--scenarios`` all replay scenarios BY NAME from the ``SCENARIOS``
registry and judge them against criteria the scenario itself
declares.  A registry entry missing its declaration half silently
weakens every consumer, so it is enforced statically (the
CTA008/CTA009 idiom):

1. every class registered in ``SCENARIOS`` must
   - carry a DOCSTRING (what hostile shape it reproduces),
   - bind a ``name`` string literal in its class body (the registry
     key / bench artifact key),
   - bind a ``criteria`` dict literal in its class body (the
     declared pass criteria ``run_scenario`` evaluates), and
   - take a ``seed`` parameter in ``__init__`` (same name+seed =>
     byte-identical streams — the determinism contract);
2. every ``criteria`` key must come from the
   :data:`KNOWN_CRITERIA` vocabulary — ``evaluate_criteria`` fails
   unknown keys at runtime, and this closes the loop at lint time;
3. when ``BENCH_scenarios.json`` exists at the repo root it carries
   the :data:`BENCH_SCENARIO_KEYS` floor per scenario entry
   (``check_bench`` is the importable validator bench and tests
   share; ``scripts/check_scenarios.py`` is the shim CLI).
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional

from .core import FileCtx, Finding, Repo

CODE = "CTA010"
NAME = "scenario-contract"

WORKLOADS_MODULE = "cilium_tpu/testing/workloads.py"

# the criteria vocabulary evaluate_criteria understands (keep in sync
# with testing/workloads.py — a key added there without a branch here
# fails the live-repo gate, which is the point)
KNOWN_CRITERIA = (
    "ledger_exact", "max_shed_frac", "p99_ms",
    "min_ct_insert_drops", "min_nat_failures", "min_drop_frac",
    "l7_ledger_exact", "min_l7_redirected",
    # encrypted-channel rotation floor (ISSUE 18): the cluster leg's
    # landed-epoch-bump count must clear this or the storm rotated
    # nothing (plaintext/thread-mode degrade fails loudly)
    "min_rotations",
)

BENCH_NAME = "BENCH_scenarios.json"
BENCH_SCHEMA = "bench-scenarios-v1"
# per-scenario keys the bench artifact must carry (the acceptance
# surface: sustained pps, shed fraction, pass/fail vs criteria)
BENCH_SCENARIO_KEYS = (
    "seed", "sustained_pps", "shed_frac", "passed", "checks",
    "criteria",
)


def _registry_classes(ctx: FileCtx) -> Optional[List[str]]:
    """Class names registered in the SCENARIOS dict literal (values
    are plain Names; ``Cls.name: Cls`` keys resolve via the value)."""
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SCENARIOS" \
                and isinstance(node.value, ast.Dict):
            return [v.id for v in node.value.values
                    if isinstance(v, ast.Name)]
    return None


def _class_str_attr(cls: ast.ClassDef, name: str) -> Optional[str]:
    for node in cls.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            return node.value.value
    return None


def _class_dict_attr(cls: ast.ClassDef,
                     name: str) -> Optional[ast.Dict]:
    for node in cls.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Dict):
            return node.value
    return None


def _init_has_seed(cls: ast.ClassDef) -> bool:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "__init__":
            args = node.args
            names = [a.arg for a in args.args] \
                + [a.arg for a in args.kwonlyargs]
            return "seed" in names
    return False  # no __init__ at all: no seed parameter


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    ctx = repo.by_rel(WORKLOADS_MODULE)
    if ctx is None or ctx.tree is None:
        return [Finding(CODE, WORKLOADS_MODULE, 1,
                        "scenario module missing", checker=NAME)]
    registered = _registry_classes(ctx)
    if registered is None:
        return [Finding(
            CODE, ctx.rel, 1,
            "SCENARIOS dict literal not found (the scenario "
            "registry tests/bench/the soak gate replay from)",
            checker=NAME)]
    if not registered:
        findings.append(Finding(
            CODE, ctx.rel, 1, "SCENARIOS registry is empty",
            checker=NAME))
    by_name: Dict[str, ast.ClassDef] = {
        node.name: node for node in ctx.tree.body
        if isinstance(node, ast.ClassDef)}
    for cls_name in registered:
        cls = by_name.get(cls_name)
        if cls is None:
            findings.append(Finding(
                CODE, ctx.rel, 1,
                f"SCENARIOS registers {cls_name!r} but no such "
                f"class is defined in the module", checker=NAME))
            continue
        line = cls.lineno
        if ctx.suppressed(CODE, line):
            continue
        if not ast.get_docstring(cls):
            findings.append(Finding(
                CODE, ctx.rel, line,
                f"scenario {cls_name} has no docstring (say what "
                f"hostile shape it reproduces)", checker=NAME))
        if _class_str_attr(cls, "name") is None:
            findings.append(Finding(
                CODE, ctx.rel, line,
                f"scenario {cls_name} does not bind a `name` "
                f"string literal in its class body (the registry "
                f"key)", checker=NAME))
        crit = _class_dict_attr(cls, "criteria")
        if crit is None:
            findings.append(Finding(
                CODE, ctx.rel, line,
                f"scenario {cls_name} does not declare a "
                f"`criteria` dict literal (the pass criteria "
                f"run_scenario evaluates)", checker=NAME))
        else:
            for k in crit.keys:
                if isinstance(k, ast.Constant) \
                        and k.value not in KNOWN_CRITERIA:
                    findings.append(Finding(
                        CODE, ctx.rel, k.lineno,
                        f"scenario {cls_name} declares unknown "
                        f"criterion {k.value!r} (known: "
                        f"{', '.join(KNOWN_CRITERIA)})",
                        checker=NAME))
        if not _init_has_seed(cls):
            findings.append(Finding(
                CODE, ctx.rel, line,
                f"scenario {cls_name}.__init__ has no `seed` "
                f"parameter (the determinism contract: same "
                f"name+seed => byte-identical streams)",
                checker=NAME))

    # the bench artifact schema (only when the artifact exists)
    bench_path = os.path.join(repo.root, BENCH_NAME)
    if os.path.exists(bench_path):
        for msg in check_bench(bench_path):
            findings.append(Finding(CODE, BENCH_NAME, 1, msg,
                                    checker=NAME))
    return findings


# -- bench artifact validation (bench + tests share it) ----------------
def check_bench(path: str) -> List[str]:
    """-> list of violation strings (empty = clean)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: does not load as JSON ({e})"]
    if not isinstance(data, dict):
        return [f"{path}: top level is {type(data).__name__}, "
                f"not an object"]
    bad = []
    if data.get("schema") != BENCH_SCHEMA:
        bad.append(f"{path}: schema {data.get('schema')!r} != "
                   f"{BENCH_SCHEMA}")
    scenarios = data.get("scenarios")
    if not isinstance(scenarios, dict) or not scenarios:
        bad.append(f"{path}: 'scenarios' must be a non-empty object "
                   f"(per-scenario results keyed by name)")
        return bad
    for name, entry in scenarios.items():
        if not isinstance(entry, dict):
            bad.append(f"{path}: scenarios[{name!r}] is not an "
                       f"object")
            continue
        for key in BENCH_SCENARIO_KEYS:
            if key not in entry:
                bad.append(f"{path}: scenarios[{name!r}] missing "
                           f"required key {key!r}")
    if "all_passed" not in data:
        bad.append(f"{path}: missing required key 'all_passed'")
    return bad
